"""Typed, mergeable metrics registry (docs/observability.md "Serving
telemetry").

The reference aggregated per-executor ``Metrics``/``TrainSummary``
accumulators at the driver; the serving fleet (serve/cluster.py) needs
the production analogue: Prometheus-style process-wide instruments whose
snapshots MERGE EXACTLY across replicas and processes.  Three types:

- :class:`Counter` — monotonic; merges by sum (the engine/router
  accepted/shed/completed/failed counters, xcache compiles).
- :class:`Gauge` — last-set value; merges by sum (queue depths,
  inflight) or max (high-water marks, weight versions) per its ``agg``.
- :class:`Histogram` — FIXED log-spaced bucket bounds, pinned at
  declaration (:data:`LATENCY_BUCKETS` spans 100 µs → ~560 s at ratio
  10^0.25 ≈ 1.78x).  Because every replica observes into the SAME
  bounds, merging is element-wise count addition and the merged
  quantiles are *identical* to the quantiles of one histogram that saw
  the pooled stream — the property that makes fleet p99 meaningful
  (``tests/test_obs_metrics.py`` pins it).

Series are keyed by (name, labels): ``registry.counter("serve_requests_total",
engine="local0", outcome="completed")``.  ``snapshot()`` renders the
whole registry to a plain-JSON dict (the wire format child replicas
ship over the frame protocol), :func:`merge` folds any number of
snapshots into one, :func:`render_prometheus` emits the text exposition
format and :func:`parse_prometheus` reads it back (CI asserts the
exposition parses).

The registry is process-wide (:func:`get`); :func:`reset` is for tests
(wired into the suite's autouse fixture, like ``serve.xcache``).
Instruments handed out before a reset keep working — the registry only
forgets them.
"""
from __future__ import annotations

import bisect
import json
import math
import re
import threading

#: pinned latency bucket UPPER bounds (seconds): 100 µs ... ~562 s at a
#: fixed 10^(1/4) ratio.  Histograms merge exactly only when every
#: observer uses identical bounds, so these are module constants, not
#: per-instance choices.  28 bounds -> 29 counts (underflow bucket
#: (0, 1e-4] is index 0's share below the first bound; index 28 is the
#: +Inf overflow).
LATENCY_BUCKETS = tuple(1e-4 * 10 ** (i / 4) for i in range(28))

#: pinned bucket bounds for streamed-decode INTER-TOKEN latency
#: (seconds): 1 µs ... ~5.6 s at the same 10^(1/4) ratio.  On-chip
#: inter-token gaps sit in the tens of microseconds — two decades below
#: LATENCY_BUCKETS' 100 µs floor, which would fold every healthy gap
#: into its underflow bucket and make ITL quantiles meaningless.  Same
#: merge contract: module-pinned bounds, so per-replica ITL histograms
#: add element-wise and fleet quantiles equal pooled quantiles exactly.
ITL_BUCKETS = tuple(1e-6 * 10 ** (i / 4) for i in range(28))

#: pinned bucket bounds for speculative-decode acceptance lengths
#: (serve/decode.py): integers 0..32, one bucket per exact length so the
#: merged histogram reconstructs the full distribution and the fleet
#: acceptance mean/quantiles are exact, not interpolated.  Pinned at
#: module scope for the same reason as LATENCY_BUCKETS — replicas can
#: only merge identical bounds.
SPEC_ACCEPT_BUCKETS = tuple(float(i) for i in range(33))


class Counter:
    """Monotonic counter.  ``inc`` only; merge = sum."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-set value.  ``agg`` ('sum' or 'max') names the cross-replica
    merge rule: queue depths add, high-water marks take the max."""

    __slots__ = ("_lock", "_value", "agg")

    def __init__(self, agg: str = "sum"):
        if agg not in ("sum", "max"):
            raise ValueError(f"gauge agg must be 'sum' or 'max': {agg!r}")
        self._lock = threading.Lock()
        self._value = 0.0
        self.agg = agg

    def set(self, v):
        with self._lock:
            self._value = float(v)

    def add(self, dv):
        with self._lock:
            self._value += dv

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bound bucket histogram.  ``bounds`` are UPPER bucket edges
    (ascending); counts has ``len(bounds) + 1`` slots, the last being
    the +Inf overflow.  Merge = element-wise count addition, legal only
    between identical bounds."""

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds=LATENCY_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError("histogram bounds must be ascending, non-empty")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def _index(self, v: float) -> int:
        # first bound >= v; len(bounds) means the +Inf overflow slot
        return bisect.bisect_left(self.bounds, v)

    def observe(self, v):
        self.observe_n(v, 1)

    def observe_n(self, v, n: int):
        """``n`` observations of ``v`` in one bucket update — the bulk
        path for device-accumulated counts (the speculative decoder
        fetches a per-length acceptance vector once per sync boundary,
        not one observation per window)."""
        if n <= 0:
            return
        v = float(v)
        i = self._index(v)
        with self._lock:
            self._counts[i] += n
            self._sum += v * n
            self._count += n

    def counts(self) -> list:
        with self._lock:
            return list(self._counts)

    def state(self):
        with self._lock:
            return list(self._counts), self._sum, self._count


class Registry:
    """Process-wide instrument registry.  Thread-safe; the same
    (name, labels) pair always resolves the same instrument, and a type
    or bounds conflict on a name is an error (a merge would be
    undefined)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}    # name -> {type, help, agg, bounds, series}
        #: bumped whenever series are dropped (clear/drop_series) —
        #: lets hot-path callers cache resolved instrument handles and
        #: re-resolve only when the registry may have forgotten them
        self.generation = 0

    @staticmethod
    def _label_key(labels: dict):
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def _family(self, name, mtype, help, agg=None, bounds=None):
        fam = self._families.get(name)
        if fam is None:
            fam = {"type": mtype, "help": help, "agg": agg,
                   "bounds": tuple(bounds) if bounds else None,
                   "series": {}}
            self._families[name] = fam
        else:
            if fam["type"] != mtype:
                raise ValueError(
                    f"metric {name!r} is a {fam['type']}, not a {mtype}")
            if mtype == "histogram" and fam["bounds"] != tuple(bounds):
                raise ValueError(
                    f"metric {name!r} re-declared with different bounds "
                    f"— merged quantiles would be undefined")
            if mtype == "gauge" and agg is not None and fam["agg"] != agg:
                raise ValueError(
                    f"metric {name!r} re-declared with agg={agg!r} "
                    f"(family is {fam['agg']!r}) — the cross-replica "
                    f"merge rule would be ambiguous")
        return fam

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        with self._lock:
            fam = self._family(name, "counter", help)
            key = self._label_key(labels)
            inst = fam["series"].get(key)
            if inst is None:
                inst = fam["series"][key] = Counter()
            return inst

    def gauge(self, name: str, help: str = "", agg: str = "sum",
              **labels) -> Gauge:
        with self._lock:
            fam = self._family(name, "gauge", help, agg=agg)
            key = self._label_key(labels)
            inst = fam["series"].get(key)
            if inst is None:
                inst = fam["series"][key] = Gauge(agg=fam["agg"] or agg)
            return inst

    def histogram(self, name: str, help: str = "",
                  bounds=LATENCY_BUCKETS, **labels) -> Histogram:
        with self._lock:
            fam = self._family(name, "histogram", help, bounds=bounds)
            key = self._label_key(labels)
            inst = fam["series"].get(key)
            if inst is None:
                inst = fam["series"][key] = Histogram(bounds=fam["bounds"])
            return inst

    def snapshot(self) -> dict:
        """The whole registry as plain JSON (the frame-protocol wire
        format; also what :func:`merge` and the exporter consume)."""
        out = {}
        with self._lock:
            families = {n: (f, list(f["series"].items()))
                        for n, f in self._families.items()}
        for name, (fam, series) in families.items():
            rows = []
            for key, inst in series:
                row = {"labels": dict(key)}
                if fam["type"] == "histogram":
                    counts, s, n = inst.state()
                    row.update(counts=counts, sum=s, count=n)
                else:
                    row["value"] = inst.value
                rows.append(row)
            out[name] = {"type": fam["type"], "help": fam["help"],
                         "agg": fam["agg"],
                         "bounds": list(fam["bounds"]) if fam["bounds"]
                         else None,
                         "series": rows}
        return out

    def drop_series(self, **labels):
        """Remove every series whose labels contain ``labels`` (and any
        family left empty).  Teardown hook for short-lived instrument
        owners — e.g. each ``continuous_decode`` call's decoder — so
        the process registry does not grow without bound; dropping a
        live instrument just stops it being snapshotted."""
        want = {(str(k), str(v)) for k, v in labels.items()}
        with self._lock:
            for name in list(self._families):
                series = self._families[name]["series"]
                for key in [k for k in series if want <= set(k)]:
                    del series[key]
                if not series:
                    del self._families[name]
            self.generation += 1

    def clear(self):
        with self._lock:
            self._families.clear()
            self.generation += 1


# -- process-wide singleton -------------------------------------------------

_REGISTRY: Registry | None = None
_LOCK = threading.Lock()


def get() -> Registry:
    global _REGISTRY
    if _REGISTRY is None:
        with _LOCK:
            if _REGISTRY is None:
                _REGISTRY = Registry()
    return _REGISTRY


def reset():
    """Drop every family (tests).  Instruments already handed out keep
    counting; the registry just no longer snapshots them."""
    get().clear()


# -- merge / quantiles ------------------------------------------------------

def merge(snapshots, drop_labels=()) -> dict:
    """Fold N registry snapshots into one: counters and sum-gauges add,
    max-gauges take the max, histograms add counts element-wise
    (identical bounds required — a bounds mismatch raises, it cannot be
    papered over).  ``drop_labels`` removes labels (e.g. ``engine``)
    before merging, aggregating across their values."""
    out = {}
    for snap in snapshots:
        if not snap:
            continue
        for name, fam in snap.items():
            dst = out.get(name)
            if dst is None:
                dst = out[name] = {"type": fam["type"], "help": fam["help"],
                                   "agg": fam.get("agg"),
                                   "bounds": fam.get("bounds"),
                                   "series": {}}
            if dst["type"] != fam["type"]:
                raise ValueError(f"merge: {name!r} is both {dst['type']} "
                                 f"and {fam['type']}")
            if dst["type"] == "histogram" and \
                    list(dst["bounds"]) != list(fam["bounds"]):
                raise ValueError(
                    f"merge: {name!r} snapshots carry different bucket "
                    f"bounds — quantiles would be meaningless")
            for row in fam["series"]:
                labels = {k: v for k, v in row["labels"].items()
                          if k not in drop_labels}
                key = tuple(sorted(labels.items()))
                cur = dst["series"].get(key)
                if cur is None:
                    cur = dst["series"][key] = {"labels": labels}
                    if dst["type"] == "histogram":
                        cur.update(counts=[0] * len(row["counts"]),
                                   sum=0.0, count=0)
                    else:
                        cur["value"] = None
                if dst["type"] == "histogram":
                    cur["counts"] = [a + b for a, b in
                                     zip(cur["counts"], row["counts"])]
                    cur["sum"] += row["sum"]
                    cur["count"] += row["count"]
                elif cur["value"] is None:
                    cur["value"] = row["value"]
                elif dst["type"] == "gauge" and dst.get("agg") == "max":
                    cur["value"] = max(cur["value"], row["value"])
                else:
                    cur["value"] += row["value"]
    for fam in out.values():
        fam["series"] = list(fam["series"].values())
    return out


def quantile(bounds, counts, q) -> float | None:
    """The q-th percentile (0..100) from bucket counts.  Deterministic
    rank arithmetic on integer counts, so merged-histogram quantiles
    equal pooled-histogram quantiles EXACTLY (same bounds => counts
    add).  Linear interpolation inside the landing bucket; overflow
    clamps to the last finite bound."""
    total = sum(counts)
    if total == 0:
        return None
    rank = max(1, int(math.ceil(q / 100.0 * total)))
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (rank - cum) / c
            return lo + (hi - lo) * frac
        cum += c
    return bounds[-1]   # pragma: no cover - rank <= total always lands


def merged_histogram(snapshot: dict, name: str, **match):
    """Sum a histogram family's series (those whose labels contain
    ``match``) into one (bounds, counts, sum, count); None when the
    family is absent or empty."""
    fam = snapshot.get(name)
    if fam is None or fam["type"] != "histogram":
        return None
    bounds = list(fam["bounds"])
    counts, total_sum, total_n = None, 0.0, 0
    for row in fam["series"]:
        if any(row["labels"].get(k) != str(v) for k, v in match.items()):
            continue
        if counts is None:
            counts = [0] * len(row["counts"])
        counts = [a + b for a, b in zip(counts, row["counts"])]
        total_sum += row["sum"]
        total_n += row["count"]
    if counts is None:
        return None
    return bounds, counts, total_sum, total_n


def windowed_counts(cur: dict, prev: dict | None, name: str, **match):
    """``(bounds, counts)`` of the observations that landed BETWEEN two
    snapshots: per-series bucket counts are monotonic, so the window's
    histogram is the element-wise count difference, clamped at 0 to
    absorb a replica restart mid-window.  Falls back to the lifetime
    counts when there is no ``prev`` (or its bounds mismatch); None
    when the family is absent.  The ONE windowing rule ``serve_top``'s
    quantile columns and the alert engine's ``quantile``/``baseline``
    rules share — they must judge the same numbers."""
    agg_cur = merged_histogram(cur, name, **match)
    if agg_cur is None:
        return None
    bounds, counts = list(agg_cur[0]), list(agg_cur[1])
    if prev is not None:
        agg_prev = merged_histogram(prev, name, **match)
        if agg_prev is not None and list(agg_prev[0]) == bounds:
            counts = [max(a - b, 0) for a, b in zip(counts, agg_prev[1])]
    return bounds, counts


def histogram_quantiles(snapshot: dict, name: str, qs=(50, 95, 99),
                        **match) -> dict:
    """p50/p95/p99-style dict for a histogram family, summed over its
    matching series (the fleet-pooled view)."""
    agg = merged_histogram(snapshot, name, **match)
    if agg is None:
        return {f"p{int(q)}": None for q in qs}
    bounds, counts, _, _ = agg
    return {f"p{int(q)}": quantile(bounds, counts, q) for q in qs}


def family_total(snapshot: dict, name: str, **match) -> float:
    """Sum of a counter/gauge family's series whose labels contain
    ``match`` (0.0 when absent)."""
    fam = snapshot.get(name)
    if fam is None or fam["type"] == "histogram":
        return 0.0
    total = 0.0
    for row in fam["series"]:
        if any(row["labels"].get(k) != str(v) for k, v in match.items()):
            continue
        total += row["value"]
    return total


def serving_summary(snapshot: dict) -> dict:
    """The fleet roll-up ``ReplicaPool.stats()['merged']`` exposes: the
    four admission counters summed over every engine, total queue
    depth/inflight, and pooled latency quantiles from the merged
    histogram."""
    out = {k: int(family_total(snapshot, "serve_requests_total", outcome=k))
           for k in ("accepted", "shed", "completed", "failed")}
    # router admission-stage sheds happened BEFORE dispatch, so no
    # engine counter saw them; the router's replica-stage sheds are
    # engine max_queue sheds bubbled up and already counted above
    out["shed"] += int(family_total(snapshot, "router_requests_total",
                                    outcome="shed", stage="admission"))
    out["queue_depth"] = int(family_total(snapshot, "serve_queue_depth"))
    out["inflight"] = int(family_total(snapshot, "serve_inflight"))
    out.update(histogram_quantiles(snapshot, "serve_latency_seconds"))
    return out


# -- Prometheus text exposition ---------------------------------------------

def _fmt_value(v) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape_label(v) -> str:
    # exposition-format label escaping: backslash, quote, newline —
    # engine/router names are caller-supplied, so they cannot be
    # trusted to be exposition-clean
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict, extra=()) -> str:
    items = sorted(labels.items()) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


def render_prometheus(snapshot: dict) -> str:
    """Text exposition format (version 0.0.4): HELP/TYPE headers, one
    sample per line, histograms as cumulative ``_bucket`` series plus
    ``_sum``/``_count``."""
    lines = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        if fam.get("help"):
            help_text = (str(fam["help"]).replace("\\", "\\\\")
                         .replace("\n", "\\n"))
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for row in fam["series"]:
            labels = row["labels"]
            if fam["type"] == "histogram":
                cum = 0
                for bound, c in zip(list(fam["bounds"]) + [math.inf],
                                    row["counts"]):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, [('le', _fmt_value(bound))])}"
                        f" {cum}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(row['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{row['count']}")
            else:
                lines.append(f"{name}{_fmt_labels(labels)} "
                             f"{_fmt_value(row['value'])}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return re.sub(r'\\(["\\n])',
                  lambda m: {'"': '"', "\\": "\\", "n": "\n"}[m.group(1)],
                  v)


def parse_prometheus(text: str) -> list:
    """Parse an exposition back to ``(name, labels, value)`` samples;
    raises ValueError on any malformed sample line (the CI drill's
    round-trip check)."""
    samples = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line {lineno}: "
                             f"{line!r}")
        name, labelstr, value = m.groups()
        labels = {k: _unescape_label(v)
                  for k, v in _LABEL_RE.findall(labelstr)} \
            if labelstr else {}
        v = math.inf if value == "+Inf" else float(value)
        samples.append((name, labels, v))
    return samples


def append_snapshot_jsonl(path: str, snapshot: dict, ts: float = None):
    """Append one snapshot as a JSONL line (the exporter's file-based
    sibling of the /snapshot endpoint)."""
    import time
    rec = {"ts": time.time() if ts is None else ts, "snapshot": snapshot}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
