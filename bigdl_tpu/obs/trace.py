"""Per-request trace contexts for the serving stack
(docs/observability.md "Serving telemetry").

Dapper-style (Sigelman et al., 2010) reduced to what a single-host
replica fleet needs: a trace context is an id plus an append-only list
of ``(phase, timestamp)`` hops.  The router mints one at admission (for
a SAMPLED request), every layer that touches the request stamps its
phase — ``admit`` → ``queue`` → ``dispatch`` → ``h2d`` → ``compute`` →
``complete`` on the happy path, ``shed``/``requeue`` on the others —
and the router emits the finished chain as one ``trace`` obs event, so
a postmortem (``tools/obs_report.py`` waterfall) can see exactly where
a slow request's time went, per hop, across process boundaries.

Hop timestamps are ``time.perf_counter()``: on Linux that is
``CLOCK_MONOTONIC``, which is shared by every process on the host, so a
chain stamped partly in the parent router and partly in a subprocess
replica (the context rides the length-prefixed stdio frames —
``serve/cluster.py``) stays monotone and subtractable.  Hops are
host-local times, not wall clock — the enclosing event's ``ts`` carries
wall time.

Sampling: ``BIGDL_OBS_TRACE_SAMPLE`` (default 0 = tracing off) is a
rate in [0, 1].  The :class:`Sampler` is deterministic — an error
accumulator traces exactly the configured fraction of requests (no
snapping to 1/k) — so drills can assert exact trace counts and the
default hot path never pays a single stamp.
"""
from __future__ import annotations

import os
import threading
import time

ENV_SAMPLE = "BIGDL_OBS_TRACE_SAMPLE"

#: the happy-path hop chain a completed request must cover, in order
#: (extra hops — requeue retries — may interleave)
REQUEST_PHASES = ("admit", "queue", "dispatch", "h2d", "compute",
                  "complete")


def sample_rate() -> float:
    """``BIGDL_OBS_TRACE_SAMPLE`` as a clamped [0, 1] rate; malformed or
    unset reads as 0 (tracing off)."""
    try:
        rate = float(os.environ.get(ENV_SAMPLE, "0"))
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


class Trace:
    """One request's trace context: an id and the stamped hops.

    ``to_wire``/``from_wire`` round-trip the context through the
    replica frame protocol; the child stamps onto its copy and ships
    only :meth:`new_hops` back, which the parent :meth:`extend`\\ s onto
    the original — no hop is ever duplicated or lost across the
    process boundary."""

    __slots__ = ("trace_id", "hops", "_wire_base")

    def __init__(self, trace_id: str | None = None, hops=None):
        self.trace_id = trace_id if trace_id else os.urandom(8).hex()
        self.hops = [list(h) for h in (hops or [])]
        self._wire_base = len(self.hops)

    def stamp(self, phase: str, ts: float | None = None) -> "Trace":
        self.hops.append(
            [phase, time.perf_counter() if ts is None else float(ts)])
        return self

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "hops": [list(h) for h in
                                                    self.hops]}

    @classmethod
    def from_wire(cls, wire: dict) -> "Trace":
        return cls(wire["trace_id"], wire.get("hops"))

    def new_hops(self) -> list:
        """Hops stamped since construction-from-wire (what a replica
        child ships back in its reply frame)."""
        return [list(h) for h in self.hops[self._wire_base:]]

    def extend(self, hops) -> "Trace":
        self.hops.extend(list(h) for h in hops or [])
        return self

    def duration_ms(self) -> float | None:
        if len(self.hops) < 2:
            return None
        return (self.hops[-1][1] - self.hops[0][1]) * 1e3

    def emit(self, status: str = "ok", **fields):
        """One ``trace`` obs event carrying the whole chain (the
        terminal emission — call exactly once per trace)."""
        from bigdl_tpu.obs import events
        dur = self.duration_ms()
        if dur is not None:
            fields.setdefault("duration_ms", dur)
        return events.emit("trace", trace_id=self.trace_id, status=status,
                           hops=[list(h) for h in self.hops], **fields)


class Sampler:
    """Deterministic head sampler: an error accumulator adds ``rate``
    per call and mints a :class:`Trace` each time it crosses 1, so the
    sampled fraction equals ANY configured rate in [0, 1] — 1 → every
    request, 0.5 → every 2nd, 0.7 → 7 of every 10, 0 → never — not a
    snap to the nearest 1/k.  The first request is always sampled (the
    accumulator starts one ``rate`` short of the threshold).
    Thread-safe; the unsampled path is one lock + one add."""

    def __init__(self, rate: float | None = None):
        rate = sample_rate() if rate is None else min(max(float(rate),
                                                          0.0), 1.0)
        self.rate = rate
        self._lock = threading.Lock()
        self._acc = 1.0 - rate

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    def next(self) -> Trace | None:
        """A fresh (unstamped) Trace when this request is sampled."""
        if self.rate <= 0:
            return None
        with self._lock:
            self._acc += self.rate
            if self._acc >= 1.0:
                self._acc -= 1.0
                return Trace()
        return None


def hop_deltas(hops) -> list:
    """``[(phase, seconds-since-previous-hop), ...]`` (first hop 0) —
    the waterfall rows ``tools/obs_report.py`` renders."""
    out = []
    prev = None
    for phase, ts in hops:
        out.append((phase, 0.0 if prev is None else ts - prev))
        prev = ts
    return out
