"""Always-on per-request flight recorder — tail-based forensics for the
serving stack (docs/observability.md "Request forensics").

Head sampling (obs/trace.py, ``BIGDL_OBS_TRACE_SAMPLE`` default 0)
cannot answer the on-call question "why was *this* request slow /
failed / wrong": the requests you most need traced — errors, SLO
misses, requeue storms, p99 outliers — are exactly the ones a head
sampler cannot know to keep.  The :class:`FlightRecorder` inverts the
decision: EVERY request gets a cheap record and a perf_counter-stamped
trace, assembled from hooks that already exist at every seam (router
admission/dispatch/shed/requeue, engine submit/complete, decoder
admit/boundary/first-token/retire, fleet prefill-ship/affinity, the
remote frame path), and only at the TERMINAL state does the recorder
decide what the request turned out to be:

* healthy and not head-sampled → the record stays in the bounded ring
  (``BIGDL_OBS_RECORDER_N``, default 512) and nothing is emitted —
  zero trace events, zero per-request file writes;
* head-sampled → the ``trace`` event is emitted as before (the two
  retention policies compose);
* anomalous (error, shed, requeue, deadline/TTFT/e2e SLO miss,
  involvement in a replica death or partition, or latency above
  ``BIGDL_OBS_TAIL_MS`` / the windowed-p99 multiplier
  ``BIGDL_OBS_TAIL_P99X``) → the trace event is emitted AND a schema-v7
  ``forensic`` event carries the full record plus the ring's
  neighboring-request context — the non-fatal analog of the
  ``obs/diagnostics.py`` crash bundle — and
  ``forensic_requests_total{kind=...}`` counts it.

Cost discipline: the recorder never touches the device.  Notes are
plain dict merges under one lock; the decode-side notes ride the step
boundary's ONE existing slab materialization (no added syncs, no
per-token host work); cross-process notes ride the reply frames that
already carry trace hops.  ``BIGDL_OBS_RECORDER=0`` restores the exact
pre-recorder behavior (head sampling only, zero stamps at sample=0).

The recorded decode fields (committed token row, seed length, decode
flags, quant recipe, served weight version) are exactly what
``tools/request_replay.py`` needs to re-execute the request offline
and diff the token stream — greedy replay must be token-identical.
"""
from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from collections import OrderedDict, deque

logger = logging.getLogger("bigdl_tpu.obs")

ENV_RECORDER = "BIGDL_OBS_RECORDER"
ENV_RING = "BIGDL_OBS_RECORDER_N"
ENV_TAIL_MS = "BIGDL_OBS_TAIL_MS"
ENV_TAIL_P99X = "BIGDL_OBS_TAIL_P99X"

#: neighbors on each side shipped as forensic-bundle context
CONTEXT_N = 4
#: latency window for the p99 tail bound (finalized e2e samples)
_P99_WINDOW = 256
#: minimum window fill before the p99 bound judges anybody
_P99_MIN = 20

#: anomaly kinds by precedence — a request that is several things at
#: once (a shed request also missed its deadline) is counted under the
#: most causal kind.  Must stay a subset of events.FORENSIC_KINDS.
KIND_PRECEDENCE = ("error", "shed", "replica_death", "partition",
                   "requeue", "slo_miss", "slow")


def seed_hash(seed) -> str:
    """Stable short hash of a token-id seed (the record carries the
    hash; the committed row carries the actual tokens)."""
    h = hashlib.sha1()
    for t in seed:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()[:16]


def enabled() -> bool:
    return os.environ.get(ENV_RECORDER, "1") != "0"


def _env_float(name: str, default: float = 0.0) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FlightRecorder:
    """Bounded ring of per-request records keyed by trace id.

    Thread-safe: the router dispatch loop, engine compute loop, decoder
    boundary thread and remote read loops all note concurrently.  A
    note for an unknown trace id CREATES the record — subprocess
    replicas accumulate notes without an explicit open and ship them
    back in the reply frame (:meth:`export_notes`)."""

    def __init__(self, ring: int | None = None,
                 tail_ms: float | None = None,
                 tail_p99x: float | None = None):
        if ring is None:
            try:
                ring = int(os.environ.get(ENV_RING, "512"))
            except ValueError:
                ring = 512
        self.ring_n = max(int(ring), 1)
        self.tail_ms = (_env_float(ENV_TAIL_MS) if tail_ms is None
                        else float(tail_ms))
        self.tail_p99x = (_env_float(ENV_TAIL_P99X) if tail_p99x is None
                          else float(tail_p99x))
        self._lock = threading.Lock()
        self._ring: OrderedDict[str, dict] = OrderedDict()
        self._lat = deque(maxlen=_P99_WINDOW)
        self.finalized = 0
        self.anomalies = 0

    # -- assembly ----------------------------------------------------------

    def open(self, trace_id: str, **fields) -> dict:
        """Start (or refresh) the record for one request."""
        with self._lock:
            rec = self._ring.get(trace_id)
            if rec is None:
                rec = {"trace_id": trace_id, "t_open": time.time()}
                self._ring[trace_id] = rec
                while len(self._ring) > self.ring_n:
                    self._ring.popitem(last=False)
            for k, v in fields.items():
                if v is not None:
                    rec[k] = v
            return rec

    def note(self, trace_id: str | None, **fields):
        """Merge fields into a request's record (create on miss — the
        subprocess-replica path).  None values are skipped so call
        sites can pass optionals unconditionally."""
        if not trace_id:
            return None
        return self.open(trace_id, **fields)

    def bump(self, trace_id: str | None, field: str, by: int = 1):
        """Additive note (requeue/attempt counters)."""
        if not trace_id:
            return
        with self._lock:
            rec = self._ring.get(trace_id)
            if rec is None:
                rec = {"trace_id": trace_id, "t_open": time.time()}
                self._ring[trace_id] = rec
                while len(self._ring) > self.ring_n:
                    self._ring.popitem(last=False)
            rec[field] = int(rec.get(field, 0)) + by

    def export_notes(self, trace_id: str | None) -> dict | None:
        """Detach and return one record's accumulated fields (minus the
        open bookkeeping) — what a replica child ships back alongside
        the trace's ``new_hops`` in its reply frame.  The record leaves
        the child's ring: the parent owns the merged record."""
        if not trace_id:
            return None
        with self._lock:
            rec = self._ring.pop(trace_id, None)
        if not rec:
            return None
        rec.pop("trace_id", None)
        rec.pop("t_open", None)
        return rec or None

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            rec = self._ring.get(trace_id)
            return dict(rec) if rec is not None else None

    def records(self) -> list:
        """Ring snapshot, oldest first (obs_report's Forensics source)."""
        with self._lock:
            return [dict(r) for r in self._ring.values()]

    # -- terminal classification -------------------------------------------

    def _p99_bound(self) -> float | None:
        """Windowed p99 × multiplier, or None while the window is thin
        or the multiplier knob is off."""
        if self.tail_p99x <= 0 or len(self._lat) < _P99_MIN:
            return None
        xs = sorted(self._lat)
        p99 = xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))]
        return p99 * self.tail_p99x

    def classify(self, rec: dict) -> tuple[str | None, dict]:
        """The anomaly kind for a finalized record (None = healthy)
        plus the kind's required event fields."""
        status = rec.get("outcome")
        e2e = rec.get("e2e_ms")
        if status == "failed":
            if rec.get("death_replica"):
                return "replica_death", {"replica": rec["death_replica"]}
            return "error", {"error": rec.get("error", "unknown")}
        if status == "shed":
            return "shed", {"stage": rec.get("shed_stage", "admission")}
        if rec.get("blip_replica"):
            return "partition", {"replica": rec["blip_replica"]}
        if rec.get("death_replica"):
            return "replica_death", {"replica": rec["death_replica"]}
        if rec.get("requeues"):
            return "requeue", {"attempts": int(rec["requeues"])}
        if rec.get("slo_miss"):
            return "slo_miss", {"slo": rec["slo_miss"]}
        if e2e is not None:
            if self.tail_ms > 0 and e2e > self.tail_ms:
                return "slow", {"e2e_ms": e2e, "bound_ms": self.tail_ms}
            bound = self._p99_bound()
            if bound is not None and e2e > bound:
                return "slow", {"e2e_ms": e2e, "bound_ms": bound}
        return None, {}

    def _context(self, trace_id: str) -> list:
        """Lightweight summaries of the ring's neighboring requests —
        what else the process was serving around the anomaly (the
        crash-bundle "last N events" analog).  Called under the lock."""
        keys = list(self._ring)
        try:
            i = keys.index(trace_id)
        except ValueError:
            i = len(keys)
        out = []
        lo = max(0, i - CONTEXT_N)
        for k in keys[lo:i] + keys[i + 1:i + 1 + CONTEXT_N]:
            r = self._ring[k]
            out.append({"trace_id": k,
                        "outcome": r.get("outcome"),
                        "e2e_ms": r.get("e2e_ms"),
                        "replica": r.get("replica"),
                        "priority": r.get("priority")})
        return out

    def finalize(self, trace_id: str | None, status: str,
                 trace=None, head_sampled: bool = False,
                 **fields) -> bool:
        """Terminal-state hook: absorb the last fields + the hop
        timeline, classify, emit the forensic bundle when anomalous,
        and return whether the trace event should be emitted (head
        sampled OR anomalous) — the tail-retention decision.

        Never raises: forensics must not break the serving path."""
        if not trace_id:
            return head_sampled
        try:
            with self._lock:
                rec = self._ring.get(trace_id)
                if rec is None:
                    rec = {"trace_id": trace_id, "t_open": time.time()}
                    self._ring[trace_id] = rec
                    while len(self._ring) > self.ring_n:
                        self._ring.popitem(last=False)
                rec["outcome"] = status
                for k, v in fields.items():
                    if v is not None:
                        rec[k] = v
                if trace is not None:
                    rec["hops"] = [list(h) for h in trace.hops]
                    dur = trace.duration_ms()
                    if dur is not None:
                        rec.setdefault("e2e_ms", dur)
                self.finalized += 1
                kind, kind_fields = self.classify(rec)
                if status == "ok" and rec.get("e2e_ms") is not None:
                    self._lat.append(float(rec["e2e_ms"]))
                if kind is None:
                    return head_sampled
                rec["anomaly"] = kind
                self.anomalies += 1
                context = self._context(trace_id)
                record = dict(rec)
            from bigdl_tpu.obs import events, metrics
            reg = metrics.get()
            reg.counter(
                "forensic_requests_total",
                "anomalous requests bundled by the flight recorder",
                kind=kind).inc()
            if record.get("e2e_ms") is not None:
                # max-agg high-water mark: serve_top's anomalies line
                # shows the worst end-to-end among anomalous requests
                g = reg.gauge("forensic_worst_e2e_ms",
                              "worst e2e among anomalous requests",
                              agg="max")
                g.set(max(g.value, float(record["e2e_ms"])))
            events.emit("forensic", kind=kind, trace_id=trace_id,
                        record=record, context=context, **kind_fields)
            return True
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("flight recorder finalize failed: %s", e)
            return head_sampled


# -- process-wide singleton (the events.py get/configure/reset pattern) -----

_REC: FlightRecorder | None = None
_LOADED = False
_LOCK = threading.Lock()


def get() -> FlightRecorder | None:
    """The process flight recorder, or None when off
    (``BIGDL_OBS_RECORDER=0``)."""
    global _REC, _LOADED
    if not _LOADED:
        with _LOCK:
            if not _LOADED:
                if enabled():
                    _REC = FlightRecorder()
                _LOADED = True
    return _REC


def configure(ring: int | None = None, tail_ms: float | None = None,
              tail_p99x: float | None = None) -> FlightRecorder:
    """Install a recorder programmatically (tests, drills)."""
    global _REC, _LOADED
    with _LOCK:
        _REC = FlightRecorder(ring=ring, tail_ms=tail_ms,
                              tail_p99x=tail_p99x)
        _LOADED = True
    return _REC


def reset():
    """Forget the process recorder (re-reads env on next get())."""
    global _REC, _LOADED
    with _LOCK:
        _REC = None
        _LOADED = False


# -- convenience wrappers (no-ops when the recorder is off) -----------------

def note(trace_id: str | None, **fields):
    rec = get()
    if rec is not None:
        rec.note(trace_id, **fields)


def bump(trace_id: str | None, field: str, by: int = 1):
    rec = get()
    if rec is not None:
        rec.bump(trace_id, field, by)


def export_notes(trace_id: str | None) -> dict | None:
    rec = get()
    return rec.export_notes(trace_id) if rec is not None else None


def finalize(trace_id: str | None, status: str, trace=None,
             head_sampled: bool = False, **fields) -> bool:
    """Module-level finalize; with the recorder off the decision
    degrades to plain head sampling."""
    rec = get()
    if rec is None:
        return head_sampled
    return rec.finalize(trace_id, status, trace=trace,
                        head_sampled=head_sampled, **fields)
