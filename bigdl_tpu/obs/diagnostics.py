"""Crash diagnostics: dump a postmortem bundle on the way down
(docs/observability.md).

The failure paths PR 1 built — watchdog peer-death exit, SIGTERM
preemption, the non-finite-gradient abort threshold — all end a run
from code that knows WHY, but until now that knowledge died with the
process (one log line, then ``os._exit``).  ``dump_crash_bundle`` turns
the last moments into a directory an operator (or the next CI run) can
read:

    crash-<reason>-p<proc>-<pid>/
      reason.txt     what tripped, free text
      events.jsonl   the event ring buffer's last N events (obs/events)
      memory.json    per-device HBM stats (utils/profiler)
      config.json    BIGDL_*/JAX_* env, jax version, process topology
      threads.txt    Python stack of every live thread (where was the
                     main thread blocked? usually: inside a dead
                     collective)
      extra.json     caller-provided context (straggler window, streak)

Every step is individually best-effort: a diagnostics bug must never
mask the real failure, so this function cannot raise.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import tempfile
import time
import traceback

logger = logging.getLogger("bigdl_tpu.obs")


def _resolve_dir(run_dir):
    if run_dir:
        return run_dir
    from bigdl_tpu.obs import events as events_mod
    log = events_mod.get()
    if log is not None and log.run_dir:
        return log.run_dir
    env = os.environ.get(events_mod.ENV_DIR, "").strip()
    if env:
        return env
    return tempfile.mkdtemp(prefix="bigdl_obs_")


def thread_stacks() -> str:
    """Python stack of every live thread — the one artifact that tells a
    hung-collective death from a data-loader deadlock."""
    import threading
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sorted(sys._current_frames().items()):
        parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        parts.append("".join(traceback.format_stack(frame)))
    return "\n".join(parts)


def config_snapshot() -> dict:
    """Env flags + versions + topology: enough to reproduce the run's
    configuration from the bundle alone."""
    snap = {"argv": list(sys.argv),
            "python": sys.version.split()[0],
            "cwd": os.getcwd(),
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(("BIGDL_", "JAX_", "XLA_"))}}
    try:
        import jax
        snap["jax"] = jax.__version__
        snap["process_index"] = jax.process_index()
        snap["process_count"] = jax.process_count()
        snap["local_devices"] = [str(d) for d in jax.local_devices()]
    except Exception as e:
        snap["jax"] = f"unavailable: {e!r}"
    return snap


def _write(path, write_fn):
    try:
        with open(path, "w") as f:
            write_fn(f)
    except Exception as e:  # pragma: no cover - disk-full territory
        logger.warning("crash bundle: %s failed: %s", path, e)


def dump_crash_bundle(reason: str, run_dir: str | None = None,
                      extra: dict | None = None,
                      texts: dict | None = None) -> str | None:
    """Write the bundle; returns its path (None only if even the
    directory could not be created).  Safe from signal handlers and
    daemon threads; never raises.  ``texts`` maps extra filenames to
    raw text bodies (e.g. a dead replica's ``stderr.txt`` tail)."""
    try:
        from bigdl_tpu.obs import events as events_mod
        if not events_mod.enabled():
            # BIGDL_OBS=0 is the documented hard-off switch: no stray
            # temp directories from abort/preemption/watchdog paths
            logger.info("crash bundle skipped: obs disabled (%s)", reason)
            return None
        base = _resolve_dir(run_dir)
        log = events_mod.get()
        proc = log.process_index() if log is not None else 0
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:48]
        path = os.path.join(base, f"crash-{slug}-p{proc}-{os.getpid()}")
        os.makedirs(path, exist_ok=True)
    except Exception as e:
        logger.error("crash bundle: could not create directory: %s", e)
        return None

    # the bundle's own event first, so it rides the ring dump below and
    # the surviving JSONL stream points at the directory
    if log is not None:
        log.emit("crash_bundle", reason=reason, path=path)

    _write(os.path.join(path, "reason.txt"),
           lambda f: f.write(f"{reason}\nat {time.strftime('%Y-%m-%dT%H:%M:%S')}\n"))
    if log is not None:
        _write(os.path.join(path, "events.jsonl"), lambda f: f.writelines(
            json.dumps(e, default=events_mod._jsonable) + "\n"
            for e in log.ring_events()))
    _write(os.path.join(path, "threads.txt"),
           lambda f: f.write(thread_stacks()))
    _write(os.path.join(path, "config.json"),
           lambda f: json.dump(config_snapshot(), f, indent=1, default=repr))
    try:
        from bigdl_tpu.utils.profiler import device_memory_stats
        stats = device_memory_stats()
    except Exception as e:
        stats = {"unavailable": repr(e)}
    _write(os.path.join(path, "memory.json"),
           lambda f: json.dump(stats, f, indent=1, default=repr))
    if extra:
        _write(os.path.join(path, "extra.json"),
               lambda f: json.dump(extra, f, indent=1, default=repr))
    for fname, body in (texts or {}).items():
        _write(os.path.join(path, os.path.basename(fname)),
               lambda f, b=body: f.write(b))
    logger.error("crash bundle written: %s (%s)", path, reason)
    return path
