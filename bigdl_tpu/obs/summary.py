"""TensorBoard-compatible scalar export — the ``TrainSummary`` /
``ValidationSummary`` parity piece (later BigDL releases ship
visualization/TrainSummary.scala writing tfevents via an embedded
TensorFlow; SOTA BigDL docs show loss/throughput/lr curves in
TensorBoard).

No tensorflow/tensorboard dependency exists in this container, so the
writer speaks the format directly: a tfevents file is a TFRecord stream
(length, masked-crc32c(length), payload, masked-crc32c(payload)) of
``Event`` protobuf messages, and a scalar point needs exactly four
proto fields (wall_time, step, summary.value.tag,
summary.value.simple_value).  Hand-encoding those ~40 bytes is smaller
than any dependency and byte-compatible with TensorBoard's reader; the
tests round-trip through :func:`read_scalars`.

Usage (the reference's optimizer.setTrainSummary shape)::

    train_summary = TrainSummary(log_dir, app_name="lenet")
    val_summary = ValidationSummary(log_dir, app_name="lenet")
    optimizer.set_train_summary(train_summary)
    optimizer.set_val_summary(val_summary)

Loss/LearningRate/Throughput land per iteration; tap scalars land at
the taps cadence; validation metrics at each validation trigger.
"""
from __future__ import annotations

import os
import socket
import struct
import time

# -- crc32c (Castagnoli, reflected 0x82F63B78) — TFRecord's checksum ------

_CRC_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal proto encoding ------------------------------------------------

def _varint(n: int) -> bytes:
    # protobuf wire: negative int64s ride as 10-byte two's-complement
    # varints (Python's arithmetic shift on a negative n would otherwise
    # never terminate)
    if n < 0:
        n &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _len_field(key: int, payload: bytes) -> bytes:
    return bytes([key << 3 | 2]) + _varint(len(payload)) + payload


def _scalar_event(wall_time: float, step: int, tag: str,
                  value: float) -> bytes:
    # Summary.Value { tag = 1 (string); simple_value = 2 (float) }
    val = (_len_field(1, tag.encode("utf-8"))
           + b"\x15" + struct.pack("<f", value))
    summary = _len_field(1, val)          # Summary { value = 1 repeated }
    return (b"\x09" + struct.pack("<d", wall_time)   # Event.wall_time = 1
            + b"\x10" + _varint(step)                # Event.step = 2
            + _len_field(5, summary))                # Event.summary = 5


def _version_event(wall_time: float) -> bytes:
    # Event.file_version = 3: the "brain.Event:2" header TensorBoard
    # requires as the first record
    return (b"\x09" + struct.pack("<d", wall_time)
            + _len_field(3, b"brain.Event:2"))


class ScalarWriter:
    """One tfevents file of scalar records under ``log_dir``."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        host = socket.gethostname()
        self.path = os.path.join(
            log_dir, f"events.out.tfevents.{int(time.time())}.{host}."
                     f"{os.getpid()}")
        self._fh = open(self.path, "ab")
        self._record(_version_event(time.time()))

    def _record(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._fh.write(header)
        self._fh.write(struct.pack("<I", _masked_crc(header)))
        self._fh.write(payload)
        self._fh.write(struct.pack("<I", _masked_crc(payload)))
        self._fh.flush()

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: float | None = None):
        self._record(_scalar_event(
            time.time() if wall_time is None else wall_time,
            int(step), tag, float(value)))

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class TrainSummary(ScalarWriter):
    """Training-curve sink (ref visualization/TrainSummary.scala):
    ``<log_dir>/<app_name>/train``.  Wire with
    ``optimizer.set_train_summary``."""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = os.path.join(log_dir, app_name, "train")
        super().__init__(self.log_dir)


class ValidationSummary(ScalarWriter):
    """Validation-curve sink (ref ValidationSummary.scala):
    ``<log_dir>/<app_name>/validation``.  Wire with
    ``optimizer.set_val_summary``."""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = os.path.join(log_dir, app_name, "validation")
        super().__init__(self.log_dir)


# -- reader (tests + obs_report) ------------------------------------------

def read_scalars(path: str):
    """Decode a tfevents file back to [(step, tag, value)] — validates
    both CRCs of every record, so the writer above is kept honest."""
    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        header = data[pos:pos + 8]
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", data[pos + 8:pos + 12])
        if hcrc != _masked_crc(header):
            raise ValueError(f"bad length crc at byte {pos}")
        payload = data[pos + 12:pos + 12 + length]
        (pcrc,) = struct.unpack("<I",
                                data[pos + 12 + length:pos + 16 + length])
        if pcrc != _masked_crc(payload):
            raise ValueError(f"bad payload crc at byte {pos}")
        pos += 16 + length
        rec = _decode_event(payload)
        if rec is not None:
            out.append(rec)
    return out


def _read_varint(buf, i):
    n = shift = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _decode_event(buf: bytes):
    """(step, tag, value) from one Event payload, or None for
    non-scalar events (the file_version header)."""
    i, step, summary = 0, 0, None
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
            if field == 2:
                step = val - (1 << 64) if val >= 1 << 63 else val
        elif wire == 1:
            i += 8
        elif wire == 5:
            i += 4
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            if field == 5:
                summary = buf[i:i + ln]
            i += ln
    if summary is None:
        return None
    # Summary -> Value -> (tag, simple_value)
    i = 0
    tag, value = None, None
    while i < len(summary):
        key, i = _read_varint(summary, i)
        if key >> 3 == 1 and key & 7 == 2:
            ln, i = _read_varint(summary, i)
            vbuf = summary[i:i + ln]
            i += ln
            j = 0
            while j < len(vbuf):
                vkey, j = _read_varint(vbuf, j)
                vfield, vwire = vkey >> 3, vkey & 7
                if vfield == 1 and vwire == 2:
                    ln2, j = _read_varint(vbuf, j)
                    tag = vbuf[j:j + ln2].decode("utf-8")
                    j += ln2
                elif vfield == 2 and vwire == 5:
                    (value,) = struct.unpack("<f", vbuf[j:j + 4])
                    j += 4
                elif vwire == 0:
                    _, j = _read_varint(vbuf, j)
                elif vwire == 1:
                    j += 8
                elif vwire == 5:
                    j += 4
                elif vwire == 2:
                    ln2, j = _read_varint(vbuf, j)
                    j += ln2
        else:
            break
    if tag is None or value is None:
        return None
    return (step, tag, value)
