"""In-jit scalar taps: training-health scalars computed INSIDE the
compiled train step (docs/observability.md).

The reference surfaces loss and wall-clock only; gradient explosions or
a silently saturating update show up steps later (or never).  These
taps — gradient global-norm, parameter norm, update/parameter ratio and
the non-finite-element count — are a handful of VPU reductions fused
into the existing backward, returned alongside the step outputs exactly
like PR 1's jit-folded skip-step flag:

- the step stays ONE dispatch (the taps are extra outputs of the same
  executable, not a second program);
- the host does NOT synchronize on them every step: the loop holds the
  device scalars and materializes (blocks + converts) only every
  ``cadence`` steps, so the happy path pays zero extra device→host
  syncs beyond the loss read it already does.

Gating: ``BIGDL_OBS_TAPS`` (default on), cadence ``BIGDL_OBS_TAPS_CADENCE``
(default 10); ``LocalOptimizer.set_taps`` overrides both per run.
"""
from __future__ import annotations

import os
from collections import deque

import numpy as np

ENV_TAPS = "BIGDL_OBS_TAPS"
ENV_CADENCE = "BIGDL_OBS_TAPS_CADENCE"

#: keys of the dict ``compute`` returns, in a fixed order so event
#: consumers and the report tool can rely on the names
TAP_NAMES = ("grad_norm", "param_norm", "update_ratio", "nonfinite_grads")


def enabled(override: bool | None = None) -> bool:
    if override is not None:
        return bool(override)
    return os.environ.get(ENV_TAPS, "1") != "0"


def cadence(override: int | None = None) -> int:
    if override is not None:
        return max(1, int(override))
    return max(1, int(os.environ.get(ENV_CADENCE, "10")))


def compute(grads, params, new_params):
    """The tap dict, traced inside the train step.

    All reductions run in f32 (bf16 squares overflow at ~256) and cost a
    single fused pass over tensors the backward already has in HBM.
    ``new_params`` should be the POST-skip-select values so
    ``update_ratio`` reads 0 on a skipped step.  Under ``shard_map`` the
    caller merges the scalars across replicas (see ``_core_step``'s
    ``taps_merge``) — per-replica values there are local-gradient taps,
    so the merged ``grad_norm`` is the replica-mean of local norms, not
    the norm of the mean gradient (documented in docs/observability.md).
    """
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(grads)
    g2 = jnp.zeros((), jnp.float32)
    bad = jnp.zeros((), jnp.float32)
    for g in leaves:
        gf = g.astype(jnp.float32)
        g2 = g2 + jnp.sum(jnp.square(gf))
        bad = bad + jnp.sum(~jnp.isfinite(gf)).astype(jnp.float32)
    p2 = jnp.zeros((), jnp.float32)
    d2 = jnp.zeros((), jnp.float32)
    for p, q in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(new_params)):
        pf = p.astype(jnp.float32)
        p2 = p2 + jnp.sum(jnp.square(pf))
        d2 = d2 + jnp.sum(jnp.square(q.astype(jnp.float32) - pf))
    pnorm = jnp.sqrt(p2)
    return {
        "grad_norm": jnp.sqrt(g2),
        "param_norm": pnorm,
        "update_ratio": jnp.sqrt(d2) / (pnorm + 1e-12),
        "nonfinite_grads": bad,
    }


class TapsMonitor:
    """Host-side cadence gate for the device tap scalars.

    ``push(step, taps)`` stores the latest DEVICE values (no sync) and
    materializes them to floats only once at least ``cadence``
    iterations have passed since the previous materialization;
    ``flush()`` materializes a pending tail (end of run, so a 4-step
    smoke with cadence 10 still logs one sample).
    ``materialized_steps`` is the audit trail the dispatch-count test
    asserts on: host syncs happen at cadence boundaries, nowhere else.

    The gate is elapsed-iterations, not ``step % cadence == 0``: under
    ``iters_per_dispatch = n`` the pushed step numbers advance by n, and
    for most (n, cadence) pairs an exact-multiple test would NEVER fire
    (neval 1, 9, 17, ... never lands on a multiple of 10) — the same
    chunk-boundary trap ``LocalOptimizer._fired_within`` solves for
    triggers.
    """

    def __init__(self, cadence_override: int | None = None,
                 enabled_override: bool | None = None):
        self.enabled = enabled(enabled_override)
        self.cadence = cadence(cadence_override)
        # bounded: an always-on telemetry path must not grow with run
        # length (a 10M-step run would otherwise bank ~1M samples; the
        # durable record is the event stream, this is the live window)
        self.history = deque(maxlen=1024)  # (step, {name: float})
        self.materialized_steps = deque(maxlen=1024)
        self._pending = None
        self._last_materialized = 0

    def push(self, step: int, taps) -> dict | None:
        """Returns the materialized {name: float} dict at cadence
        boundaries, None otherwise (including when taps are off)."""
        if not taps:
            return None
        self._pending = (int(step), taps)
        if step - self._last_materialized >= self.cadence:
            return self._materialize()
        return None

    def flush(self) -> dict | None:
        if self._pending is None:
            return None
        return self._materialize()

    def _materialize(self) -> dict:
        step, taps = self._pending
        self._pending = None
        self._last_materialized = step
        # chunked dispatch (iters_per_dispatch > 1) stacks (n,) values:
        # report the chunk's LAST step, same convention as state['loss']
        vals = {k: float(np.asarray(v).reshape(-1)[-1])
                for k, v in taps.items()}
        self.materialized_steps.append(step)
        self.history.append((step, vals))
        return vals

    def last(self) -> dict | None:
        return self.history[-1][1] if self.history else None
