"""URL-aware filesystem shim (the HDFS role of ref utils/File.scala:81-116).

The reference reads/writes checkpoints and sequence files through the
Hadoop FileSystem API so `hdfs://` paths work anywhere a local path does.
The TPU-pod equivalent is fsspec: `gs://` (GCS via gcsfs), `s3://`,
`memory://` (tests), `file://`.  Plain paths bypass fsspec entirely and
keep the original os/open semantics (including atomic tmp+rename).

Every consumer in this package (checkpoints utils/file.py, shard folders
dataset/shardfile.py, example CLIs) routes through these helpers, so any
fsspec-registered scheme works end to end.
"""
from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger("bigdl_tpu.utils")


def is_url(path: str) -> bool:
    return isinstance(path, str) and "://" in path


def _fs(path: str):
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "remote path %r needs fsspec (the reference's HDFS role); "
            "pip install fsspec[gcs|s3] or use a local path" % path) from e
    return fsspec.core.url_to_fs(path)  # (fs, stripped_path)


def open_file(path: str, mode: str = "rb"):
    if not is_url(path):
        return open(path, mode)
    fs, p = _fs(path)
    return fs.open(p, mode)


def exists(path: str) -> bool:
    if not is_url(path):
        return os.path.exists(path)
    fs, p = _fs(path)
    return fs.exists(p)


def makedirs(path: str):
    if not path:
        return
    if not is_url(path):
        os.makedirs(path, exist_ok=True)
        return
    fs, p = _fs(path)
    fs.makedirs(p, exist_ok=True)


def listdir(path: str):
    """Names (not full paths) of entries in a directory."""
    if not is_url(path):
        return sorted(os.listdir(path))
    fs, p = _fs(path)
    return sorted(e.rsplit("/", 1)[-1] for e in fs.ls(p, detail=False))


def join(base: str, *parts: str) -> str:
    if not is_url(base):
        return os.path.join(base, *parts)
    return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])


def parent(path: str) -> str:
    if not is_url(path):
        return os.path.dirname(os.path.abspath(path))
    scheme, rest = path.split("://", 1)
    head = rest.rsplit("/", 1)[0]
    return scheme + "://" + head


def _write_once(path: str, data: bytes):
    """Local: tmp + atomic rename (a crashed writer never corrupts the
    target).  Remote object stores upload whole objects, which is already
    atomic-visible, so the tmp dance is skipped there."""
    if not is_url(path):
        makedirs(os.path.dirname(os.path.abspath(path)))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return
    makedirs(parent(path))
    with open_file(path, "wb") as f:
        f.write(data)


def write_bytes_atomic(path: str, data: bytes, attempts: int = 3,
                       backoff: float = 0.1, faultable: bool = True):
    """Atomic write with bounded retry + exponential backoff — transient
    checkpoint-write failures (remote store hiccup, NFS blip) must not
    kill a training run (the HDFS-retry role the reference inherits from
    Hadoop).  After ``attempts`` consecutive OSErrors the last one
    propagates.

    ``faultable=False`` exempts a write from chaos injection — used for
    CRC sidecars, which model the *detector*, not the corruptible
    payload (``bigdl_tpu.resilience.faults``, sites ``ckpt_write_fail``/
    ``ckpt_partial``/``ckpt_bitflip``)."""
    inj = None
    if faultable:
        from bigdl_tpu.resilience import faults
        inj = faults.get()
    last = None
    for attempt in range(max(int(attempts), 1)):
        try:
            if inj is not None and attempt == 0:
                # injected faults fire on the first attempt only: the
                # retry path is exactly what ckpt_write_fail exercises
                if inj.fires("ckpt_write_fail") is not None:
                    raise OSError(f"injected checkpoint write failure: "
                                  f"{path}")
                spec = inj.fires("ckpt_partial")
                if spec is not None:
                    # a crash mid-write: truncated bytes land on the
                    # TARGET (no tmp+rename) — what resume must survive
                    from bigdl_tpu.resilience.faults import truncate
                    short = truncate(data)
                    if is_url(path):
                        _write_once(path, short)
                    else:
                        makedirs(os.path.dirname(os.path.abspath(path)))
                        with open(path, "wb") as f:
                            f.write(short)
                    return
                spec = inj.fires("ckpt_bitflip")
                if spec is not None:
                    from bigdl_tpu.resilience.faults import flip_bit
                    data = flip_bit(data, spec)
            _write_once(path, data)
            return
        except OSError as e:
            last = e
            if attempt == attempts - 1:
                raise
            delay = backoff * (2 ** attempt)
            logger.warning("write %s failed (%s); retry %d/%d in %.2fs",
                           path, e, attempt + 1, attempts - 1, delay)
            if delay > 0:
                time.sleep(delay)
    raise last  # pragma: no cover — loop always returns or raises


def read_bytes(path: str) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()
