"""Model/state persistence (ref utils/File.scala:27).

The reference uses JVM serialization (local + HDFS).  Here checkpoints are a
portable pickle of numpy-converted pytrees: (params, state, metadata) for
modules; plain pytrees for optimizer state Tables.  Orbax-compatible layouts
can be added on top; this format is dependency-free and survives process
restarts, which is the capability being ported (checkpoint/resume,
SURVEY.md §5.4).
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np


def _to_numpy(tree):
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree)


def _to_jax(tree):
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


def save(obj, path, overwrite: bool = True):
    """Save an arbitrary pytree (ref File.save File.scala:63)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        pickle.dump(_to_numpy(obj), f)
    os.replace(tmp, path)


def load(path):
    with open(path, "rb") as f:
        return _to_jax(pickle.load(f))


def save_module(module, path, overwrite: bool = True):
    """Persist a module's (params, state) + class info."""
    save({
        "format": "bigdl_tpu.module.v1",
        "cls": type(module).__name__,
        "params": module.params(),
        "state": module.state(),
    }, path, overwrite=overwrite)


def load_module_into(module, path):
    """Load a checkpoint produced by ``save_module`` into ``module``."""
    blob = load(path)
    module.load_params(blob["params"])
    module.load_state(blob["state"])
    return module
