"""Model/state persistence (ref utils/File.scala:27).

The reference uses JVM serialization (local + HDFS).  Here checkpoints are a
portable pickle of numpy-converted pytrees: (params, state, metadata) for
modules; plain pytrees for optimizer state Tables.  Orbax-compatible layouts
can be added on top; this format is dependency-free and survives process
restarts, which is the capability being ported (checkpoint/resume,
SURVEY.md §5.4).
"""
from __future__ import annotations

import os
import pickle
import zlib

import jax
import numpy as np

from bigdl_tpu.utils import fs

#: sidecar holding "crc32hex length" of the payload bytes — written with
#: every save so resume can reject bit-flipped / truncated snapshots
CRC_SUFFIX = ".crc32"


class ChecksumError(ValueError):
    """Snapshot bytes do not match their CRC32 sidecar (bit rot, partial
    write, torn copy) — the snapshot must not be trusted."""


def _crc_path(path: str) -> str:
    return str(path) + CRC_SUFFIX


def _verify_bytes(path: str, data: bytes):
    """Raise ChecksumError if ``path``'s sidecar disagrees with ``data``.
    Pre-sidecar snapshots (no ``.crc32`` file) pass — unpickling is their
    only integrity check, as before."""
    sc = _crc_path(path)
    if not fs.exists(sc):
        return
    try:
        want_crc_hex, want_len = fs.read_bytes(sc).split()
        want_crc, want_len = int(want_crc_hex, 16), int(want_len)
    except (ValueError, OSError) as e:
        raise ChecksumError(f"{path}: unreadable CRC sidecar {sc}: {e}")
    got_crc = zlib.crc32(data)
    if len(data) != want_len or got_crc != want_crc:
        raise ChecksumError(
            f"{path}: checksum mismatch — sidecar says crc32 "
            f"{want_crc:08x} / {want_len} bytes, payload is "
            f"{got_crc:08x} / {len(data)} bytes (corrupt or partial "
            "snapshot; resume should fall back to an older one)")


def verify(path: str) -> bool:
    """True iff ``path`` holds a loadable snapshot: bytes match the CRC
    sidecar when one exists, else the pickle at least parses.  Used by
    the resume scan (``optim.optimizer.load_latest_checkpoint``) to skip
    corrupt/partial snapshots without aborting."""
    try:
        data = fs.read_bytes(path)
        _verify_bytes(path, data)
        if not fs.exists(_crc_path(path)):
            pickle.loads(data)  # no sidecar: parsing is the only check
        return True
    except Exception:
        return False


def _to_numpy(tree):
    # the "has a shape -> materialize" duck test must not swallow
    # non-array leaves that merely DESCRIBE a shape (the sharded
    # checkpoint writer's ShardRef placeholders) into 0-d object arrays
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x)
        if isinstance(x, (np.ndarray, jax.Array)) else x, tree)


def _to_jax(tree):
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


def save(obj, path, overwrite: bool = True):
    """Save an arbitrary pytree (ref File.save File.scala:63).  ``path``
    may be any fsspec URL (gs://, s3://, memory://) — the HDFS role of
    File.scala:81-116 — or a plain local path (atomic tmp+rename).

    A CRC32 sidecar (``path + ".crc32"``) is written AFTER the payload:
    a crash between the two writes leaves either the old consistent
    pair untouched (payload write died before its atomic rename) or a
    new payload with a stale sidecar, which ``load``/``verify`` reject
    and resume falls back past — never an undetectably torn snapshot,
    and never a still-valid old snapshot poisoned by a fresher
    sidecar."""
    if fs.exists(path) and not overwrite:
        raise FileExistsError(path)
    data = pickle.dumps(_to_numpy(obj))
    fs.write_bytes_atomic(path, data)
    fs.write_bytes_atomic(
        _crc_path(path), b"%08x %d\n" % (zlib.crc32(data), len(data)),
        faultable=False)


def load(path):
    """Load a snapshot, verifying it against its CRC sidecar when one
    exists (raises ChecksumError on mismatch)."""
    data = fs.read_bytes(path)
    _verify_bytes(path, data)
    return _to_jax(pickle.loads(data))


def _pickle_architecture(module):
    """Pickle the module with its weight/buffer/grad dicts emptied: the
    arrays live once in the checkpoint's params/state trees, and a class
    rename only breaks these bytes — never the weight trees."""
    from bigdl_tpu.nn.module import stripped_caches

    stash = []

    def strip(mod):
        stash.append((mod, dict(mod._params), dict(mod._buffers),
                      dict(mod._grads), mod.output, mod.grad_input,
                      mod._last_key))
        mod._params.clear()
        mod._buffers.clear()
        mod._grads.clear()
        # stale eager-mode activations must not ride into checkpoints
        mod.output = None
        mod.grad_input = None
        mod._last_key = None
        for child in mod._modules.values():
            strip(child)

    with stripped_caches(module):  # unpicklable jitted-fn caches leave too
        strip(module)
        try:
            return pickle.dumps(module)
        finally:
            for mod, p, b, g, out, gi, lk in stash:
                mod._params.update(p)
                mod._buffers.update(b)
                mod._grads.update(g)
                mod.output = out
                mod.grad_input = gi
                mod._last_key = lk


def save_module(module, path, overwrite: bool = True):
    """Persist the full module — architecture AND weights (the
    Module.save / Java-serialization role, ref AbstractModule.scala:306,
    File.scala:63).  Weights are stored once, in portable numpy trees;
    the architecture rides along as an opaque pickle so
    ``load_module_into`` keeps working even if the class moves."""
    save({
        "format": "bigdl_tpu.module.v2",
        "cls": type(module).__name__,
        "architecture": _pickle_architecture(module),
        "params": module.params(),
        "state": module.state(),
    }, path, overwrite=overwrite)


def load_module(path):
    """Reconstruct a module saved by ``save_module`` — architecture
    included (ref Module.load Module.scala:27)."""
    blob = load(path)
    arch = blob.get("architecture")
    if arch is None:
        raise ValueError(
            f"{path} is a weights-only (v1) checkpoint: build the "
            f"architecture ({blob.get('cls')}) and use load_module_into")
    module = pickle.loads(arch)
    module.load_params(blob["params"])
    module.load_state(blob["state"])
    # recreate the grad slots the architecture pickle dropped
    module.load_grads(
        jax.tree_util.tree_map(np.zeros_like, blob["params"]))
    return module


def load_module_into(module, path):
    """Load a checkpoint produced by ``save_module`` into ``module``."""
    blob = load(path)
    module.load_params(blob["params"])
    module.load_state(blob["state"])
    return module
