"""Engine — cluster topology + device runtime singleton.

Reference: ``utils/Engine.scala:208``.  There the Engine holds node/core
counts, two JVM thread pools (``Engine.default`` for replica parallelism,
``Engine.model`` for intra-op parallelism) and builds a pinned SparkConf.

On TPU the thread pools dissolve into XLA (intra-op parallelism is the
compiler's job) and Spark's executor topology becomes the JAX process/device
topology.  What remains is the topology bookkeeping that the data and
optimizer layers query: node_number (hosts), core_number (local devices),
plus mesh construction for the distributed optimizer.
"""
from __future__ import annotations

import os
import signal
import threading
import time
import numpy as np
import jax


def set_cpu_device_count(n: int):
    """Pin ``n`` virtual CPU devices (must run before the first backend
    touch).  Newer jax exposes ``jax_num_cpu_devices``; older jaxlibs only
    read ``--xla_force_host_platform_device_count`` from XLA_FLAGS at
    backend init — route through whichever this build supports so the
    no-cluster test meshes (conftest, multiproc workers, BIGDL_CPU_MESH)
    work on both."""
    n = max(int(n), 1)
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        import re
        opt = "--xla_force_host_platform_device_count"
        # replace, don't append: a subprocess inherits its parent's flag
        # (the 8-device test mesh) and must still be able to pin its own
        flags = re.sub(opt + r"=\d+", "",
                       os.environ.get("XLA_FLAGS", "")).strip()
        os.environ["XLA_FLAGS"] = f"{flags} {opt}={n}".strip()


class _Engine:
    def __init__(self):
        self._initialized = False
        self._node_number = 1
        self._core_number = 1
        self._mesh = None
        self._singleton_fd = None
        self._preempted = threading.Event()
        self._preempted_at = None
        self._preempt_armed = False
        self._prev_handlers = {}

    # -- lifecycle (ref Engine.init Engine.scala:339) ---------------------
    def init(self, node_number: int | None = None, core_number: int | None = None,
             distributed: bool = False):
        """Initialize topology.  Defaults to the live JAX topology.

        ``distributed=True`` with multiple hosts expects
        ``jax.distributed.initialize`` to have been called by the launcher
        (one process per TPU VM host — the Spark-executor role in the
        reference, DistriOptimizer.scala).
        """
        # env-var topology (ref DL_NODE_NUMBER/DL_CORE_NUMBER consumed on
        # executors, Engine.scala:234-264) wins over the live JAX topology
        # so launchers can pin it the way scripts/bigdl.sh did
        if node_number is None:
            env = os.environ.get("BIGDL_NODE_NUMBER",
                                 os.environ.get("DL_NODE_NUMBER"))
            node_number = int(env) if env else jax.process_count()
        if core_number is None:
            env = os.environ.get("BIGDL_CORE_NUMBER",
                                 os.environ.get("DL_CORE_NUMBER"))
            core_number = int(env) if env else jax.local_device_count()
        self._node_number = int(node_number)
        self._core_number = int(core_number)
        self._initialized = True
        return self

    def init_distributed(self, coordinator_address: str = None,
                         num_processes: int = None, process_id: int = None):
        """Multi-host bring-up: one JAX process per TPU VM host (the Spark
        executor role, SURVEY.md §2.9/§3.1).  Wraps
        ``jax.distributed.initialize``; with no args, reads the standard
        TPU metadata (works out of the box on Cloud TPU pods).

        With ``BIGDL_ELASTIC=1`` (and explicit coordinates) the bring-up
        routes through ``resilience.elastic.initialize`` instead: same
        coordination service, but with heartbeat windows stretched so the
        runtime never self-terminates on a dead peer — the file watchdog
        is the failure detector, and the training loop re-forms the fleet
        (docs/resilience.md "Elastic training")."""
        kwargs = {}
        if coordinator_address is not None:
            kwargs = dict(coordinator_address=coordinator_address,
                          num_processes=num_processes, process_id=process_id)
        from bigdl_tpu.resilience import elastic
        if elastic.enabled():
            if coordinator_address is None:
                # silently falling through to the stock bring-up would
                # leave the flag a no-op discovered only at the first
                # peer death — fail at init, where it is fixable
                raise ValueError(
                    "BIGDL_ELASTIC=1 requires explicit coordinates "
                    "(coordinator_address/num_processes/process_id): "
                    "the elastic bring-up builds the coordination "
                    "service itself and cannot ride the TPU-metadata "
                    "auto-init — pass the coordinates or unset the flag")
            elastic.initialize(coordinator_address, num_processes,
                               process_id)
        else:
            jax.distributed.initialize(**kwargs)
        return self.init()

    def _ensure_init(self):
        if not self._initialized:
            self.init()

    # -- singleton guard (ref Engine.checkSingleton Engine.scala:222-232) --
    def check_singleton(self) -> bool:
        """Detect a second training process contending for this host's TPU.

        The reference guards against two BigDL tasks landing in one executor
        JVM (they would corrupt the shared thread pools); the TPU analog is
        two processes trying to own the same local chips.  Uses a pid lock
        file per host; stale locks (dead pid) are reclaimed.  Disable with
        ``BIGDL_CHECK_SINGLETON=0`` (the ``bigdl.check.singleton`` knob,
        ref Optimizer.scala:63).
        """
        if os.environ.get("BIGDL_CHECK_SINGLETON", "1") == "0":
            return True
        if self._singleton_fd is not None:
            return True  # this process already holds the lock
        import fcntl
        import tempfile
        path = os.path.join(tempfile.gettempdir(),
                            f"bigdl_tpu_engine_{jax.process_index()}.lock")
        # flock on a long-lived fd: the kernel releases it when the process
        # dies, so there are no stale locks and no pid-file TOCTOU races —
        # exactly one live process can hold LOCK_EX at a time
        try:
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        except PermissionError:
            # lock file owned by another user on a shared host: someone
            # else is (or was) using this host's chips — report contention
            return False
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.truncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())  # diagnostics only
        self._singleton_fd = fd  # keep open for the process lifetime
        return True

    # -- preemption (docs/resilience.md) ----------------------------------
    def install_preemption_handler(self, signals=(signal.SIGTERM,)):
        """Arm SIGTERM-as-preemption: the cluster scheduler's eviction
        notice (GCE preemption, k8s pod termination, SLURM timeout) sets
        a flag instead of killing the process, and the training loop's
        next iteration checkpoints and exits cleanly
        (checkpoint-and-exit, ``LocalOptimizer._checkpoint_and_stop``).

        Multi-host: install on EVERY process (same launcher code path) —
        the distributed loop merges the flag across hosts each iteration
        while armed, so one host's SIGTERM stops all of them at the same
        step and nobody hangs in a half-abandoned collective.  Previous
        handlers are chained.  Idempotent."""
        if self._preempt_armed:
            return self
        for sig in signals:
            def _handler(signum, frame, _sig=sig):
                # flag + timestamp only: anything heavier (logging, I/O)
                # is unsafe here; the obs layer reads preempted_at() from
                # the training loop's clean epilogue instead
                self._preempted_at = time.time()
                self._preempted.set()
                prev = self._prev_handlers.get(_sig)
                if callable(prev):
                    prev(signum, frame)
            self._prev_handlers[sig] = signal.signal(sig, _handler)
        self._preempt_armed = True
        return self

    def preemption_armed(self) -> bool:
        return self._preempt_armed

    def preempted(self) -> bool:
        """True once a preemption notice arrived (signal or
        ``request_preemption``)."""
        return self._preempted.is_set()

    def request_preemption(self):
        """Programmatic preemption notice (tests, custom schedulers) —
        same effect as the armed signal arriving.  Multi-host: the
        distributed loop only merges (and honors) the flag while
        ``install_preemption_handler`` has been called on every process;
        requesting preemption unarmed in a multi-process run is ignored
        with a warning (an unmerged one-host stop would strand the other
        hosts in a dead collective)."""
        self._preempted_at = time.time()
        self._preempted.set()
        return self

    def preempted_at(self) -> float | None:
        """Unix timestamp of the preemption notice (None if never
        preempted) — stamped into the obs ``preempt`` event so the
        postmortem can measure notice-to-checkpoint latency."""
        return self._preempted_at

    def clear_preemption(self):
        """Reset the flag (a new run in the same process)."""
        self._preempted.clear()
        self._preempted_at = None
        return self

    def engine_type(self) -> str:
        """Compute-backend tag (the reference returns MklBlas,
        Engine.scala:273-289); here the backend is XLA on the visible
        platform."""
        return f"Xla:{jax.devices()[0].platform}"

    # -- topology queries (ref Engine.scala:234-264) ----------------------
    def node_number(self) -> int:
        self._ensure_init()
        return self._node_number

    def core_number(self) -> int:
        self._ensure_init()
        return self._core_number

    def device_count(self) -> int:
        return jax.device_count()

    def local_device_count(self) -> int:
        return jax.local_device_count()

    def process_index(self) -> int:
        return jax.process_index()

    # -- mesh construction -------------------------------------------------
    def mesh(self, axis_names=("data",), shape=None, devices=None):
        """Build a ``jax.sharding.Mesh`` over the visible devices.

        With the default single "data" axis this is the topology the
        reference's DistriOptimizer assumes (pure data parallelism, one
        replica per node — DistriOptimizer.scala:361-404).  Pass
        ``axis_names=("data","model")`` + ``shape`` for hybrid shardings.
        """
        if devices is None:
            devices = np.array(jax.devices())
        else:
            devices = np.array(devices)
        if shape is None:
            shape = (len(devices),) if len(axis_names) == 1 else None
        if shape is None:
            raise ValueError("shape required for multi-axis mesh")
        devices = devices.reshape(shape)
        return jax.sharding.Mesh(devices, axis_names)

    def set_mesh(self, mesh):
        self._mesh = mesh

    def get_mesh(self):
        if self._mesh is None:
            self._mesh = self.mesh()
        return self._mesh

    def reset(self):
        if self._singleton_fd is not None:
            os.close(self._singleton_fd)  # releases the flock
        for sig, prev in self._prev_handlers.items():
            try:  # un-arm preemption: restore whatever was there before
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, TypeError, OSError):
                pass  # non-main thread / exotic prior handler
        self.__init__()


Engine = _Engine()
