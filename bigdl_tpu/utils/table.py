"""Torch-style Table: a heterogeneous, 1-indexed keyed container.

Capability parity with the reference's ``utils/Table.scala:35`` (``T(...)``
builder, 1-based integer keys, string keys, ``insert``/``remove``, used for
optimizer config/state and multi-tensor activities).

Registered as a JAX pytree so a Table can flow through ``jit``/``grad`` as a
module input/output (the reference's ``Activity = Tensor | Table`` union,
abstractnn/Activity.scala:26).
"""
from __future__ import annotations

import jax


class Table:
    """Keyed container. Integer keys are 1-based, matching Torch/BigDL."""

    def __init__(self, *args, **kwargs):
        self._store = {}
        for i, v in enumerate(args):
            self._store[i + 1] = v
        for k, v in kwargs.items():
            self._store[k] = v

    # -- mapping interface ------------------------------------------------
    def __getitem__(self, key):
        return self._store[key]

    def __setitem__(self, key, value):
        self._store[key] = value

    def __delitem__(self, key):
        del self._store[key]

    def __contains__(self, key):
        return key in self._store

    def get(self, key, default=None):
        return self._store.get(key, default)

    def get_or_update(self, key, default):
        if key not in self._store:
            self._store[key] = default
        return self._store[key]

    def keys(self):
        return self._store.keys()

    def values(self):
        return self._store.values()

    def items(self):
        return self._store.items()

    def __len__(self):
        return len(self._store)

    def __iter__(self):
        # Iterate array-part values in order (1..n), like Torch ipairs.
        i = 1
        while i in self._store:
            yield self._store[i]
            i += 1

    def length(self):
        """Length of the contiguous 1-based array part."""
        i = 1
        while i in self._store:
            i += 1
        return i - 1

    # -- array-part mutation (Table.scala insert/remove) ------------------
    def insert(self, *args):
        if len(args) == 1:
            self._store[self.length() + 1] = args[0]
        else:
            pos, value = args
            n = self.length()
            for i in range(n, pos - 1, -1):
                self._store[i + 1] = self._store[i]
            self._store[pos] = value
        return self

    def remove(self, pos=None):
        n = self.length()
        if n == 0:
            return None
        if pos is None:
            pos = n
        value = self._store.get(pos)
        for i in range(pos, n):
            self._store[i] = self._store[i + 1]
        del self._store[n]
        return value

    # -- misc -------------------------------------------------------------
    def update(self, other):
        if isinstance(other, Table):
            other = other._store
        self._store.update(other)
        return self

    def copy(self):
        t = Table()
        t._store = dict(self._store)
        return t

    def clear(self):
        self._store.clear()
        return self

    def __eq__(self, other):
        if isinstance(other, Table):
            return self._store == other._store
        return NotImplemented

    def __repr__(self):
        inner = ", ".join(f"{k!r}: {v!r}" for k, v in sorted(
            self._store.items(), key=lambda kv: (isinstance(kv[0], str), str(kv[0]))))
        return f"Table({{{inner}}})"


def T(*args, **kwargs):
    """Builder matching the reference's ``T(...)`` (Table.scala companion)."""
    return Table(*args, **kwargs)


def _table_flatten(t: Table):
    keys = sorted(t._store.keys(), key=lambda k: (isinstance(k, str), str(k)))
    return [t._store[k] for k in keys], tuple(keys)


def _table_unflatten(keys, values):
    t = Table()
    t._store = dict(zip(keys, values))
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
