"""Orbax checkpoint adapter (SURVEY.md §5.4 "TPU equivalent: orbax-style
checkpoint of (params pytree, opt state, step)").

The native checkpoint format (utils/file.py: portable pickle, local or
fsspec URL) stays the default — it is dependency-free and carries the
architecture.  This adapter writes the *weight trees* in the ecosystem-
standard Orbax/TensorStore layout instead, so bigdl_tpu checkpoints can be
consumed by other JAX stacks (and vice versa): sharded, async-capable,
multi-host-aware persistence of (params, net_state, opt_state, step).

    from bigdl_tpu.utils import orbax_io
    orbax_io.save(path, model.params(), model.state(), opt_state, step=12)
    params, net_state, opt_state, step = orbax_io.restore(path)
"""
from __future__ import annotations

import os


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save(path, params, net_state=None, opt_state=None, step: int = 0,
         force: bool = True):
    """Write (params, net_state, opt_state, step) as one Orbax checkpoint.

    ``path`` must be a directory path (absolute local path or gs:// URL —
    TensorStore handles remote stores natively, the HDFS role)."""
    path = os.path.abspath(path) if "://" not in str(path) else str(path)
    ckptr = _checkpointer()
    tree = {"params": params,
            "net_state": net_state if net_state is not None else {},
            "opt_state": opt_state if opt_state is not None else {},
            "step": step}
    ckptr.save(path, tree, force=force)
    ckptr.wait_until_finished()
    return path


def restore(path):
    """Returns (params, net_state, opt_state, step)."""
    path = os.path.abspath(path) if "://" not in str(path) else str(path)
    tree = _checkpointer().restore(path)
    return (tree["params"], tree["net_state"], tree["opt_state"],
            int(tree["step"]))


def save_module(module, path, step: int = 0):
    """Module-level convenience: persists the weight/buffer trees (the
    architecture itself is code — rebuild it and ``load_module``)."""
    return save(path, module.params(), module.state(), step=step)


def load_module(module, path):
    params, net_state, _, step = restore(path)
    module.load_params(params)
    module.load_state(net_state)
    return module, step
