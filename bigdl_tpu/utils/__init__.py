from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils import file as File
from bigdl_tpu.utils import torch_file as TorchFile
from bigdl_tpu.utils import caffe_loader as CaffeLoader
from bigdl_tpu.parallel.broadcast import model_broadcast as ModelBroadcast

__all__ = ["Table", "T", "RandomGenerator", "Engine", "File",
           "TorchFile", "CaffeLoader", "ModelBroadcast", "kth_largest"]


def kth_largest(values, k):
    """k-th largest element (1-based k) — quickselect role of
    ref utils/Util.kthLargest (Util.scala:21), used there for the
    straggler-drop threshold; kept for API parity."""
    import numpy as np
    arr = np.asarray(values).reshape(-1)
    return float(np.partition(arr, len(arr) - k)[len(arr) - k])
