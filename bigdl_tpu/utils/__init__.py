from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils import file as File

__all__ = ["Table", "T", "RandomGenerator", "Engine", "File"]
