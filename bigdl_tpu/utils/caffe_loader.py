"""Caffe .caffemodel weight importer (ref utils/CaffeLoader.scala:40).

The reference ships 96k LoC of protoc-generated Java (caffe/Caffe.java) to
read NetParameter; here a ~100-line protobuf *wire-format* parser extracts
exactly what weight-copy needs — layer names and blobs — with no protobuf
dependency and no generated code.

Field numbers (from caffe.proto):
  NetParameter:    layers = 2 (V1LayerParameter), layer = 100 (LayerParameter)
  V1LayerParameter: name = 4, blobs = 6
  LayerParameter:   name = 1, type = 2, blobs = 7
  BlobProto: num/channels/height/width = 1..4, data = 5 (packed or repeated
             float), shape = 7 (BlobShape.dim = 1, repeated int64)

``load(model, caffemodel_path)`` copies blobs onto modules by matched
``set_name`` (the name-matched copy of CaffeLoader.copyParameters :127),
with ``match_all`` enforcing full coverage (CaffeLoader.load :155).
"""
from __future__ import annotations

import struct

import numpy as np


def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def iter_fields(buf, start=0, end=None):
    """Yield (field_number, wire_type, value) over a protobuf message.
    value: varint int, 8-byte chunk, length-delimited bytes, or 4-byte chunk."""
    pos = start
    end = len(buf) if end is None else end
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            n, pos = _read_varint(buf, pos)
            val = buf[pos:pos + n]
            pos += n
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_blob(buf):
    """BlobProto -> numpy array with caffe shape."""
    dims_old = {}
    shape = None
    data = []
    for field, wire, val in iter_fields(buf):
        if field in (1, 2, 3, 4) and wire == 0:
            dims_old[field] = val
        elif field == 5:
            if wire == 2:  # packed floats
                data.append(np.frombuffer(val, np.float32))
            else:  # unpacked single float
                data.append(np.frombuffer(val, np.float32))
        elif field == 7 and wire == 2:  # BlobShape
            shape = [v for f, w, v in iter_fields(val) if f == 1 and w == 0]
        elif field == 8 and wire == 2:  # double_data
            data.append(np.frombuffer(val, np.float64).astype(np.float32))
    arr = np.concatenate(data) if data else np.zeros(0, np.float32)
    if shape:
        return arr.reshape(shape)
    if dims_old:
        dims = [dims_old.get(i, 1) for i in (1, 2, 3, 4)]
        # squeeze caffe's legacy 4D padding for FC layers
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
        try:
            return arr.reshape(dims)
        except ValueError:
            return arr
    return arr


def _parse_layer(buf, name_field, blobs_field):
    name = None
    blobs = []
    for field, wire, val in iter_fields(buf):
        if field == name_field and wire == 2:
            name = val.decode("utf-8", "replace")
        elif field == blobs_field and wire == 2:
            blobs.append(_parse_blob(val))
    return name, blobs


def read_caffemodel(path):
    """Returns {layer_name: [blob arrays]} from a .caffemodel file."""
    with open(path, "rb") as f:
        buf = f.read()
    layers = {}
    for field, wire, val in iter_fields(buf):
        if wire != 2:
            continue
        if field == 2:       # V1LayerParameter
            name, blobs = _parse_layer(val, name_field=4, blobs_field=6)
        elif field == 100:   # LayerParameter
            name, blobs = _parse_layer(val, name_field=1, blobs_field=7)
        else:
            continue
        if name and blobs:
            layers[name] = blobs
    return layers


def _named_param_modules(model):
    out = {}

    def visit(m):
        if m._params and m.name:
            out[m.name] = m
        for c in m._modules.values():
            visit(c)

    visit(model)
    return out


def read_prototxt(path):
    """Parse a net .prototxt (protobuf TEXT format) minimally: returns
    [{"name": ..., "type": ...}] for every layer/layers block, in order
    (the deploy-net side of ref CaffeLoader.scala:40 — loadCaffe takes
    defPath + modelPath and matches against the *definition*)."""
    with open(path) as f:
        text = f.read()
    layers = []
    i, n = 0, len(text)
    import re
    block_re = re.compile(r"\b(layer|layers)\s*\{")
    kv_re = re.compile(r'\b(name|type)\s*:\s*(?:"([^"]*)"|(\w+))')
    for m in block_re.finditer(text):
        # find the matching close brace of this block
        depth, j = 1, m.end()
        while j < n and depth:
            if text[j] == "{":
                depth += 1
            elif text[j] == "}":
                depth -= 1
            j += 1
        body = text[m.end():j - 1]
        # only top-level keys of the block (strip nested {...} bodies)
        flat, d = [], 0
        for ch in body:
            if ch == "{":
                d += 1
            elif ch == "}":
                d -= 1
            elif d == 0:
                flat.append(ch)
        entry = {}
        for key, quoted, bare in kv_re.findall("".join(flat)):
            entry.setdefault(key, quoted or bare)
        if "name" in entry:
            layers.append(entry)
    return layers


def load(model, caffemodel_path, prototxt_path=None, match_all: bool = True):
    """Copy caffemodel weights onto ``model`` by layer name
    (ref CaffeLoader.load :155; name matching :127).

    ``prototxt_path``: when given, the net definition's layer list is the
    contract — named model modules missing from the prototxt raise (they
    could never be filled), like the reference's defPath-driven matching.
    Blob shapes are always cross-validated: a blob whose element count
    differs from the destination parameter raises with both shapes, never
    a silent mis-reshape; benign layout differences (e.g. Caffe's
    (1,1,out,in) InnerProduct blobs) are reshaped."""
    import jax.numpy as jnp

    blobs_by_name = read_caffemodel(caffemodel_path)
    targets = _named_param_modules(model)
    if prototxt_path is not None:
        proto_names = {l["name"] for l in read_prototxt(prototxt_path)}
        unknown = set(targets) - proto_names
        if unknown:
            raise ValueError(
                "model modules %s are not layers of %s (prototxt layers: "
                "%s...)" % (sorted(unknown), prototxt_path,
                            sorted(proto_names)[:10]))
    copied = set()
    for name, module in targets.items():
        if name not in blobs_by_name:
            if match_all:
                raise ValueError(
                    f"module '{name}' has no blobs in the caffemodel "
                    f"(available: {sorted(blobs_by_name)[:10]}...)")
            continue
        blobs = blobs_by_name[name]
        pnames = [p for p in ("weight", "bias") if p in module._params]
        if len(blobs) < len(pnames):
            raise ValueError(
                f"layer '{name}': caffemodel has {len(blobs)} blobs but the "
                f"module needs {len(pnames)} ({pnames})")
        for pname, blob in zip(pnames, blobs):
            dst = module._params[pname]
            src = np.asarray(blob, np.float32)
            if src.size != dst.size:
                raise ValueError(
                    f"layer '{name}' {pname}: caffemodel blob shape "
                    f"{src.shape} ({src.size} elems) does not match the "
                    f"module parameter {tuple(dst.shape)} ({dst.size} elems)")
            if src.shape != tuple(dst.shape):
                src = src.reshape(dst.shape)
            module._params[pname] = jnp.asarray(src, dst.dtype)
        copied.add(name)
    return model, copied
