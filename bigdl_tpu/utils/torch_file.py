"""Torch7 .t7 binary serialization (ref utils/TorchFile.scala:62).

Pure-Python reader/writer for the Torch serialization wire format
(little-endian; type tags: 0=nil 1=number 2=string 3=table 4=torch-object
5=boolean; REF indices for shared objects — TorchFile.scala:199+).

Capabilities ported:
- ``load(path)``: tensors, storages, tables, numbers, strings, booleans,
  nested objects; returns numpy arrays / dict / scalars.
- ``save(obj, path)``: numpy arrays (-> torch.FloatTensor/DoubleTensor),
  dicts/Tables (-> lua table), scalars, strings.
- module import: ``load_module_weights`` maps a saved Torch module tree's
  weight/bias onto a bigdl_tpu module by traversal order (the role of the
  reference's layer registry TorchFile.scala:136-182).
"""
from __future__ import annotations

import struct

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_TENSOR_DTYPES = {
    "torch.FloatTensor": (np.float32, "torch.FloatStorage"),
    "torch.DoubleTensor": (np.float64, "torch.DoubleStorage"),
    "torch.IntTensor": (np.int32, "torch.IntStorage"),
    "torch.LongTensor": (np.int64, "torch.LongStorage"),
    "torch.ByteTensor": (np.uint8, "torch.ByteStorage"),
}
_STORAGE_DTYPES = {
    "torch.FloatStorage": np.float32,
    "torch.DoubleStorage": np.float64,
    "torch.IntStorage": np.int32,
    "torch.LongStorage": np.int64,
    "torch.ByteStorage": np.uint8,
}


class _Reader:
    def __init__(self, f):
        self.f = f
        self.refs = {}

    def _read(self, fmt, size):
        return struct.unpack(fmt, self.f.read(size))[0]

    def read_int(self):
        return self._read("<i", 4)

    def read_long(self):
        return self._read("<q", 8)

    def read_double(self):
        return self._read("<d", 8)

    def read_string(self):
        n = self.read_int()
        return self.f.read(n).decode("latin-1")

    def read_object(self):
        t = self.read_int()
        if t == TYPE_NIL:
            return None
        if t == TYPE_NUMBER:
            return self.read_double()
        if t == TYPE_STRING:
            return self.read_string()
        if t == TYPE_BOOLEAN:
            return bool(self.read_int())
        if t in (TYPE_TABLE, TYPE_TORCH):
            idx = self.read_int()
            if idx in self.refs:
                return self.refs[idx]
            if t == TYPE_TABLE:
                return self._read_table(idx)
            return self._read_torch(idx)
        raise ValueError(f"unknown .t7 type tag {t}")

    def _read_table(self, idx):
        out = {}
        self.refs[idx] = out
        n = self.read_int()
        for _ in range(n):
            k = self.read_object()
            v = self.read_object()
            out[int(k) if isinstance(k, float) and k.is_integer() else k] = v
        return out

    def _read_torch(self, idx):
        version = self.read_string()
        if version.startswith("V "):
            cls = self.read_string()
        else:
            cls = version  # unversioned legacy
        if cls in _TENSOR_DTYPES:
            obj = self._read_tensor(cls)
        elif cls in _STORAGE_DTYPES:
            obj = self._read_storage(cls)
        else:
            # generic torch object (e.g. nn.Linear): payload is a table
            obj = {"torch_typename": cls}
            self.refs[idx] = obj
            payload = self.read_object()
            if isinstance(payload, dict):
                obj.update(payload)
            return obj
        self.refs[idx] = obj
        return obj

    def _read_tensor(self, cls):
        dtype, _ = _TENSOR_DTYPES[cls]
        ndim = self.read_int()
        size = [self.read_long() for _ in range(ndim)]
        stride = [self.read_long() for _ in range(ndim)]
        offset = self.read_long() - 1  # 1-based
        storage = self.read_object()
        if storage is None or ndim == 0:
            return np.zeros(size, dtype)
        arr = np.lib.stride_tricks.as_strided(
            storage[offset:], shape=size,
            strides=[s * storage.itemsize for s in stride])
        return np.array(arr, dtype=dtype)

    def _read_storage(self, cls):
        dtype = _STORAGE_DTYPES[cls]
        n = self.read_long()
        return np.frombuffer(self.f.read(n * np.dtype(dtype).itemsize),
                             dtype=dtype).copy()


class _Writer:
    def __init__(self, f):
        self.f = f
        self.next_idx = 1

    def write_int(self, v):
        self.f.write(struct.pack("<i", v))

    def write_long(self, v):
        self.f.write(struct.pack("<q", v))

    def write_double(self, v):
        self.f.write(struct.pack("<d", v))

    def write_string(self, s):
        b = s.encode("latin-1")
        self.write_int(len(b))
        self.f.write(b)

    def write_object(self, obj):
        from bigdl_tpu.utils.table import Table
        if obj is None:
            self.write_int(TYPE_NIL)
        elif isinstance(obj, bool):
            self.write_int(TYPE_BOOLEAN)
            self.write_int(int(obj))
        elif isinstance(obj, (int, float)):
            self.write_int(TYPE_NUMBER)
            self.write_double(float(obj))
        elif isinstance(obj, str):
            self.write_int(TYPE_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray) or hasattr(obj, "__array__"):
            self._write_tensor(np.asarray(obj))
        elif isinstance(obj, (dict, Table)):
            items = obj.items() if isinstance(obj, dict) else obj.items()
            self.write_int(TYPE_TABLE)
            self.write_int(self.next_idx)
            self.next_idx += 1
            items = list(items)
            self.write_int(len(items))
            for k, v in items:
                self.write_object(k)
                self.write_object(v)
        else:
            raise TypeError(f"cannot serialize {type(obj)} to .t7")

    def _write_tensor(self, arr):
        if arr.dtype == np.float64:
            cls, scls = "torch.DoubleTensor", "torch.DoubleStorage"
        elif arr.dtype in (np.int64,):
            cls, scls = "torch.LongTensor", "torch.LongStorage"
        else:
            arr = arr.astype(np.float32)
            cls, scls = "torch.FloatTensor", "torch.FloatStorage"
        arr = np.ascontiguousarray(arr)
        self.write_int(TYPE_TORCH)
        self.write_int(self.next_idx)
        self.next_idx += 1
        self.write_string("V 1")
        self.write_string(cls)
        self.write_int(arr.ndim)
        for s in arr.shape:
            self.write_long(s)
        strides = [st // arr.itemsize for st in arr.strides]
        for s in strides:
            self.write_long(s)
        self.write_long(1)  # storage offset, 1-based
        # storage object
        self.write_int(TYPE_TORCH)
        self.write_int(self.next_idx)
        self.next_idx += 1
        self.write_string("V 1")
        self.write_string(scls)
        self.write_long(arr.size)
        self.f.write(arr.tobytes())


def load(path):
    with open(path, "rb") as f:
        return _Reader(f).read_object()


def save(obj, path):
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)


def _iter_torch_modules(obj):
    """Yield torch module dicts (depth-first) from a loaded .t7 object."""
    if isinstance(obj, dict):
        if "torch_typename" in obj and ("weight" in obj or "bias" in obj
                                        or "running_mean" in obj):
            yield obj
        modules = obj.get("modules")
        if isinstance(modules, dict):
            for k in sorted(k for k in modules if isinstance(k, int)):
                yield from _iter_torch_modules(modules[k])
        elif "torch_typename" not in obj:
            for v in obj.values():
                yield from _iter_torch_modules(v)


def load_module_weights(model, path, strict: bool = True):
    """Copy weight/bias AND buffers (BN running stats) from a saved Torch
    module tree onto ``model`` by traversal order of parameterized layers
    (the registry role of TorchFile.scala:136-182)."""
    import jax.numpy as jnp
    from bigdl_tpu.nn.module import Module, Container

    blob = load(path)
    torch_mods = list(_iter_torch_modules(blob))

    def leaves(m):
        if m._params or m._buffers:
            yield m
        for c in m._modules.values():
            yield from leaves(c)

    targets = list(leaves(model))
    if strict and len(torch_mods) != len(targets):
        raise ValueError(
            f"module count mismatch: .t7 has {len(torch_mods)} parameterized "
            f"layers, model has {len(targets)}")

    def copy_into(store, name, tm):
        if name in tm and tm[name] is not None and name in store:
            src = np.asarray(tm[name])
            dst = store[name]
            if src.size != dst.size:
                raise ValueError(
                    f".t7 field '{name}' has {src.size} elems; module "
                    f"expects {tuple(dst.shape)}")
            if src.shape != tuple(dst.shape):
                src = src.reshape(dst.shape)
            store[name] = jnp.asarray(src, dst.dtype)

    missing_params, missing_buffers = [], []
    for tm, tgt in zip(torch_mods, targets):
        names = ("weight", "bias") + tuple(
            k for k in tgt._params if k not in ("weight", "bias"))
        for name in names:
            copy_into(tgt._params, name, tm)
        for name in tuple(tgt._buffers):
            copy_into(tgt._buffers, name, tm)
        for name in tuple(tgt._params):
            if tm.get(name) is None:
                missing_params.append(f"{type(tgt).__name__}.{name}")
        for name in tuple(tgt._buffers):
            if tm.get(name) is None:
                missing_buffers.append(f"{type(tgt).__name__}.{name}")
    if missing_params and strict:
        # a missing PARAMETER means the model would train/predict with
        # random values where the checkpoint was expected to provide them
        raise ValueError(
            f".t7 file lacks {len(missing_params)} parameter field(s): "
            f"{', '.join(missing_params[:8])}"
            + ("..." if len(missing_params) > 8 else "")
            + " (strict=False loads what exists and warns)")
    skipped = missing_params + missing_buffers
    if skipped:
        # buffers stay warn-only even under strict: e.g. legacy torch
        # files store running_std instead of running_var — never silent
        import warnings
        warnings.warn(
            f".t7 file lacks {len(skipped)} field(s) kept at their "
            f"in-model values: {', '.join(skipped[:8])}"
            + ("..." if len(skipped) > 8 else ""))
    return model


# Export class-name registry, mirroring the reference's
# (TorchFile.scala:136-182 maps TYPE_* tags <-> module classes).  Names not
# listed export as nn.<ClassName>.
_TORCH_CLASS_NAMES = {
    "Linear": "nn.Linear",
    "SpatialConvolution": "nn.SpatialConvolution",
    "SpatialShareConvolution": "nn.SpatialConvolution",
    "SpatialFullConvolution": "nn.SpatialFullConvolution",
    "SpatialDilatedConvolution": "nn.SpatialDilatedConvolution",
    "SpatialConvolutionMap": "nn.SpatialConvolutionMap",
    "SpatialMaxPooling": "nn.SpatialMaxPooling",
    "SpatialAveragePooling": "nn.SpatialAveragePooling",
    "BatchNormalization": "nn.BatchNormalization",
    "SpatialBatchNormalization": "nn.SpatialBatchNormalization",
    "SpatialCrossMapLRN": "nn.SpatialCrossMapLRN",
    "SpatialZeroPadding": "nn.SpatialZeroPadding",
    "ReLU": "nn.ReLU", "ReLU6": "nn.ReLU6", "Tanh": "nn.Tanh",
    "Sigmoid": "nn.Sigmoid", "Threshold": "nn.Threshold",
    "PReLU": "nn.PReLU", "LeakyReLU": "nn.LeakyReLU", "ELU": "nn.ELU",
    "HardTanh": "nn.HardTanh", "Clamp": "nn.HardTanh",
    "SoftPlus": "nn.SoftPlus", "SoftSign": "nn.SoftSign",
    "Power": "nn.Power", "Sqrt": "nn.Sqrt", "Square": "nn.Square",
    "Abs": "nn.Abs", "Exp": "nn.Exp", "Log": "nn.Log",
    "LogSoftMax": "nn.LogSoftMax", "SoftMax": "nn.SoftMax",
    "SoftMin": "nn.SoftMin", "LogSigmoid": "nn.LogSigmoid",
    "Dropout": "nn.Dropout", "Reshape": "nn.Reshape", "View": "nn.View",
    "Transpose": "nn.Transpose", "Replicate": "nn.Replicate",
    "Squeeze": "nn.Squeeze", "Unsqueeze": "nn.Unsqueeze",
    "Contiguous": "nn.Contiguous", "Copy": "nn.Copy", "Padding": "nn.Padding",
    "Sequential": "nn.Sequential", "Concat": "nn.Concat",
    "ConcatTable": "nn.ConcatTable", "ParallelTable": "nn.ParallelTable",
    "MapTable": "nn.MapTable", "Bottle": "nn.Bottle",
    "CAddTable": "nn.CAddTable", "CSubTable": "nn.CSubTable",
    "CMulTable": "nn.CMulTable", "CDivTable": "nn.CDivTable",
    "CMaxTable": "nn.CMaxTable", "CMinTable": "nn.CMinTable",
    "JoinTable": "nn.JoinTable", "SelectTable": "nn.SelectTable",
    "NarrowTable": "nn.NarrowTable", "FlattenTable": "nn.FlattenTable",
    "MixtureTable": "nn.MixtureTable", "DotProduct": "nn.DotProduct",
    "PairwiseDistance": "nn.PairwiseDistance",
    "CosineDistance": "nn.CosineDistance",
    "CMul": "nn.CMul", "CAdd": "nn.CAdd", "Mul": "nn.Mul", "Add": "nn.Add",
    "MulConstant": "nn.MulConstant", "AddConstant": "nn.AddConstant",
    "MM": "nn.MM", "MV": "nn.MV", "Cosine": "nn.Cosine",
    "Euclidean": "nn.Euclidean", "Bilinear": "nn.Bilinear",
    "Mean": "nn.Mean", "Sum": "nn.Sum", "Max": "nn.Max", "Min": "nn.Min",
    "Select": "nn.Select", "Narrow": "nn.Narrow",
    "Identity": "nn.Identity", "LookupTable": "nn.LookupTable",
    "Recurrent": "nn.Recurrent", "TimeDistributed": "nn.TimeDistributed",
}

# constructor attributes exported per class so a Lua-side loader (or our
# own load_module) can rebuild geometry — the serialized-field role of the
# reference registry (kW/kH/dW/dH/padW/padH etc.)
_EXPORT_ATTRS = {
    "SpatialConvolution": [("kernel_w", "kW"), ("kernel_h", "kH"),
                           ("stride_w", "dW"), ("stride_h", "dH"),
                           ("pad_w", "padW"), ("pad_h", "padH"),
                           ("n_input_plane", "nInputPlane"),
                           ("n_output_plane", "nOutputPlane"),
                           ("n_group", "nGroup")],
    "SpatialMaxPooling": [("kw", "kW"), ("kh", "kH"), ("dw", "dW"),
                          ("dh", "dH"), ("pad_w", "padW"), ("pad_h", "padH"),
                          ("ceil_mode", "ceil_mode")],
    "SpatialAveragePooling": [("kw", "kW"), ("kh", "kH"), ("dw", "dW"),
                              ("dh", "dH"), ("pad_w", "padW"),
                              ("pad_h", "padH"),
                              ("count_include_pad", "count_include_pad")],
    "BatchNormalization": [("n_output", "nOutput"), ("eps", "eps"),
                           ("momentum", "momentum"), ("affine", "affine")],
    "SpatialBatchNormalization": [("n_output", "nOutput"), ("eps", "eps"),
                                  ("momentum", "momentum"),
                                  ("affine", "affine")],
    "SpatialCrossMapLRN": [("size", "size"), ("alpha", "alpha"),
                           ("beta", "beta"), ("k", "k")],
    "Linear": [("input_size", "inputSize"), ("output_size", "outputSize")],
    "Dropout": [("p", "p")],
    "LookupTable": [("n_index", "nIndex"), ("n_output", "nOutput")],
}


def save_module(model, path):
    """Export a module tree to .t7 (the saveTorch role,
    ref AbstractModule.saveTorch :312 + TorchFile module registry :136-182).

    Best-effort object graph: each module becomes a lua table with
    ``torch_typename`` (mapped class name) + weight/bias + child
    ``modules`` — readable back via ``load_module_weights``."""

    def encode(m):
        cls = type(m).__name__
        out = {"torch_typename": _TORCH_CLASS_NAMES.get(cls, f"nn.{cls}")}
        for pname, arr in m._params.items():
            out[pname] = np.asarray(arr)
        for bname, arr in m._buffers.items():
            out[bname] = np.asarray(arr)
        for attr, lua_name in _EXPORT_ATTRS.get(cls, []):
            if hasattr(m, attr):
                out[lua_name] = getattr(m, attr)
        if m._modules:
            out["modules"] = {i + 1: encode(c)
                              for i, c in enumerate(m._modules.values())}
        return out

    save(encode(model), path)
