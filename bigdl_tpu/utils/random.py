"""Seeded RNG for the framework.

The reference carries a hand-written Torch-compatible Mersenne-Twister
(utils/RandomGenerator.scala:50) because bit-exact Torch streams mattered for
its golden tests.  On TPU we port the *reproducibility guarantee* (seeded
determinism), not the generator (SURVEY.md §7 "hard parts"): host-side
initialization uses a numpy MT19937 stream, device-side randomness (dropout)
uses JAX's counter-based PRNG keyed off the same seed.
"""
from __future__ import annotations

import numpy as np
import jax


class RandomGenerator:
    """Global, seedable RNG. ``RNG`` below is the process-wide instance."""

    def __init__(self, seed: int = 1):
        self.set_seed(seed)

    def set_seed(self, seed: int):
        self._seed = int(seed)
        self._np = np.random.RandomState(self._seed)
        self._key_counter = 0
        return self

    def get_seed(self) -> int:
        return self._seed

    # -- host-side (parameter init, shuffles) -----------------------------
    def uniform(self, a=0.0, b=1.0, size=None):
        return self._np.uniform(a, b, size)

    def normal(self, mean=0.0, stdv=1.0, size=None):
        return self._np.normal(mean, stdv, size)

    def bernoulli(self, p=0.5, size=None):
        return (self._np.uniform(0.0, 1.0, size) < p).astype(np.float32)

    def randperm(self, n):
        """1-based random permutation, like Torch randperm."""
        return self._np.permutation(n) + 1

    def shuffle(self, array):
        self._np.shuffle(array)
        return array

    def np_rng(self) -> np.random.RandomState:
        return self._np

    # -- device-side key stream (dropout etc.) ----------------------------
    def next_key(self):
        """A fresh JAX PRNG key; successive calls give independent keys."""
        self._key_counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), self._key_counter)


RNG = RandomGenerator(seed=1)


def set_seed(seed: int):
    RNG.set_seed(seed)
    return RNG
