"""Seeded RNG for the framework.

The reference carries a hand-written Torch-compatible Mersenne-Twister
(utils/RandomGenerator.scala:50) because bit-exact Torch streams mattered for
its golden tests.  On TPU we port the *reproducibility guarantee* (seeded
determinism), not the generator (SURVEY.md §7 "hard parts"): host-side
initialization uses a numpy MT19937 stream, device-side randomness (dropout)
uses JAX's counter-based PRNG keyed off the same seed.

Like the reference (RandomGenerator.scala:24-34 is a thread-local), host
streams are per-thread: worker threads (MTLabeledImgToBatch, PreFetch
pipelines) each get an independent stream derived from the global seed, so
concurrent augmentation neither races on Mersenne state nor loses seeded
determinism on the main thread.
"""
from __future__ import annotations

import threading

import numpy as np
import jax


class RandomGenerator:
    """Global, seedable RNG. ``RNG`` below is the process-wide instance."""

    def __init__(self, seed: int = 1):
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._device_impl = None
        self.set_seed(seed)

    def set_seed(self, seed: int):
        self._seed = int(seed)
        # bump epoch so previously-created thread streams reinitialize
        self._epoch = getattr(self, "_epoch", 0) + 1
        self._thread_counter = 0
        self._main_thread = threading.get_ident()
        self._np = np.random.RandomState(self._seed)
        self._key_counter = 0
        return self

    def get_seed(self) -> int:
        return self._seed

    # -- snapshot/restore (checkpoint payload + scoped borrowing) ---------
    def snapshot(self) -> dict:
        """Portable copy of the host-stream state: seed, epoch, derived-
        thread counter, device-key counter and the full numpy MT state.
        Rides the checkpoint payload (``state.N["rng"]``) so a resumed
        run replays the uninterrupted run's shuffle/augmentation stream;
        also the supported way for helpers to borrow the process RNG
        (``scoped``) instead of poking privates."""
        with self._lock:
            return {
                "seed": self._seed,
                "epoch": self._epoch,
                "thread_counter": self._thread_counter,
                "key_counter": self._key_counter,
                "np_state": self._np.get_state(),
                "device_impl": self._device_impl,
            }

    def restore(self, snap: dict):
        """Inverse of ``snapshot``.  ``_epoch`` is restored too, so live
        worker threads whose derived streams postdate the snapshot
        re-derive (same ordinals -> same streams) on their next draw.
        The restoring thread becomes the seed-stream owner."""
        with self._lock:
            self._seed = int(snap["seed"])
            self._epoch = int(snap["epoch"])
            self._thread_counter = int(snap["thread_counter"])
            self._key_counter = int(snap["key_counter"])
            self._device_impl = snap.get("device_impl")
            self._main_thread = threading.get_ident()
            st = snap["np_state"]
            # checkpoint round-trips may hand the 624-word key back as a
            # jax array; RandomState.set_state wants numpy uint32
            st = (st[0], np.asarray(st[1], np.uint32)) + tuple(st[2:])
            self._np = np.random.RandomState()
            self._np.set_state(st)
        return self

    def own_seed_stream(self):
        """Make the CALLING thread the owner of the process seed stream.

        The serial training loop draws shuffles/augmentations from the
        seeding thread's stream; a prefetch pipeline moves those exact
        draws onto its single producer thread (``dataset/prefetch.py``).
        For the draw sequence to stay bit-identical to the serial path,
        the producer must continue the seed stream itself rather than a
        derived per-thread stream — this is the supported handoff.  Any
        other thread's ``np_rng()`` then returns a derived stream, so
        the previous owner must not draw host randomness until it takes
        the stream back (``own_seed_stream`` again, or ``restore``)."""
        with self._lock:
            self._main_thread = threading.get_ident()
        return self

    def seed_stream_owner(self) -> int:
        """Thread ident currently owning the seed stream (tests/debug)."""
        return self._main_thread

    def key_counter(self) -> int:
        """Current device-key ordinal (``next_key`` calls so far).  The
        prefetch checkpoint path splices this LIVE value into a
        producer-side stream snapshot: np draws happen at fetch time (on
        the producer) while keys are minted at consume time (on the
        loop), so the two counters advance on different threads."""
        with self._lock:
            return self._key_counter

    def scoped(self):
        """Context manager: snapshot on entry, restore on exit — for
        helpers that reseed mid-run (bench drills, data peeks) and must
        leave the caller's stream untouched."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            snap = self.snapshot()
            try:
                yield self
            finally:
                self.restore(snap)
        return _scope()

    # -- host-side (parameter init, shuffles) -----------------------------
    def uniform(self, a=0.0, b=1.0, size=None):
        return self.np_rng().uniform(a, b, size)

    def normal(self, mean=0.0, stdv=1.0, size=None):
        return self.np_rng().normal(mean, stdv, size)

    def bernoulli(self, p=0.5, size=None):
        return (self.np_rng().uniform(0.0, 1.0, size) < p).astype(np.float32)

    def randperm(self, n):
        """1-based random permutation, like Torch randperm."""
        return self.np_rng().permutation(n) + 1

    def shuffle(self, array):
        self.np_rng().shuffle(array)
        return array

    def np_rng(self) -> np.random.RandomState:
        """This thread's stream: the seed stream on the seeding thread,
        a derived independent stream on every other thread."""
        if threading.get_ident() == self._main_thread:
            return self._np
        tls = self._tls
        if getattr(tls, "epoch", None) != self._epoch:
            with self._lock:
                self._thread_counter += 1
                ordinal = self._thread_counter
            derived = (self._seed + 0x9E3779B1 * ordinal) % (2 ** 32)
            tls.rng = np.random.RandomState(derived)
            tls.epoch = self._epoch
        return tls.rng

    # -- device-side key stream (dropout etc.) ----------------------------
    def set_device_prng(self, impl):
        """Select the device PRNG implementation for keys minted here.

        ``None`` (default) keeps JAX's default threefry2x32 — a
        deterministic, splittable stream.  ``"rbg"`` routes mask
        generation through XLA's hardware RngBitGenerator: measured
        -15.7%% device-busy on the dropout-heavy VGG-CIFAR train step
        (threefry counter math is pure VPU work; the hardware generator
        is ~free).  Same Bernoulli/uniform distributions, different
        stream — seeded determinism is preserved per impl, but streams
        are NOT comparable across impls (like the reference's
        MKL-VSL-vs-Torch-MT split, RandomGenerator.scala:50)."""
        if impl not in (None, "threefry2x32", "rbg", "unsafe_rbg"):
            raise ValueError(f"unknown device PRNG impl {impl!r}")
        self._device_impl = None if impl == "threefry2x32" else impl
        return self

    def next_key(self):
        """A fresh JAX PRNG key; successive calls give independent keys."""
        with self._lock:
            self._key_counter += 1
            counter = self._key_counter
        if self._device_impl is not None:
            base = jax.random.key(self._seed, impl=self._device_impl)
        else:
            base = jax.random.PRNGKey(self._seed)
        return jax.random.fold_in(base, counter)


RNG = RandomGenerator(seed=1)


def set_seed(seed: int):
    RNG.set_seed(seed)
    return RNG


def set_device_prng(impl):
    """Process-wide device PRNG selection (see
    ``RandomGenerator.set_device_prng``)."""
    RNG.set_device_prng(impl)
    return RNG
