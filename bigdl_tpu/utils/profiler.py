"""Profiling utilities (SURVEY.md §5.1).

The reference stacks per-module wall-clock timers (AbstractModule
forwardTime/backwardTime), phase metrics (optim/Metrics.scala) and
throughput logs.  Those exist here too (Module.get_times, optim.Metrics);
this module adds the TPU-native layer: ``jax.profiler`` device traces and
annotated step ranges viewable in XProf/TensorBoard.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax


def start_trace(log_dir: str):
    """Begin a device trace (open in xprof / tensorboard-profile)."""
    jax.profiler.start_trace(log_dir)


def stop_trace():
    jax.profiler.stop_trace()


@contextmanager
def trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


@contextmanager
def step_annotation(name: str):
    """Annotate a host range so steps are findable in the trace viewer."""
    with jax.profiler.StepTraceAnnotation(name):
        yield


@contextmanager
def annotation(name: str):
    """Plain named trace range (non-step): phase spans (obs/spans.py)
    use this so data-load/dispatch/validate line up in XProf under the
    same names as the event log."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats():
    """Per-device HBM usage, when the backend exposes it."""
    stats = {}
    for d in jax.devices():
        try:
            stats[str(d)] = d.memory_stats()
        except Exception:
            stats[str(d)] = None
    return stats


def format_module_times(model, top_n: int = 20) -> str:
    """Pretty per-module forward/backward table
    (ref Container.getTimes Container.scala:71-78)."""
    rows = sorted(model.get_times(), key=lambda r: -(r[1] + r[2]))[:top_n]
    lines = [f"{'module':<40} {'fwd_s':>10} {'bwd_s':>10}"]
    for mod, fwd, bwd in rows:
        lines.append(f"{mod.get_name():<40} {fwd:>10.4f} {bwd:>10.4f}")
    return "\n".join(lines)
