"""Logging configuration (the log4j.properties role,
ref dl/src/main/resources/log4j.properties + the driver progress line
Optimizer.header, Optimizer.scala:132-135).

The reference configures log4j once per JVM; here ``init_logging`` sets up
the root ``bigdl_tpu`` logger with the same shape of output: timestamped
console lines, optional file sink, INFO default.
"""
from __future__ import annotations

import logging
import os
import sys
import time

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

_LAST_WARN: dict = {}


def reset_warn_cache():
    """Forget every ``warn_every`` timestamp.  The cache is process-
    global, so without this a warning rate-limited in one run (or test)
    stays suppressed in the next — test fixtures call it between cases
    (tests/conftest.py), long-lived drivers call it between runs."""
    _LAST_WARN.clear()


_BAD_OVERRIDES_WARNED: set = set()


def warn_interval(logger: logging.Logger, interval: float) -> float:
    """The effective rate-limit interval for ``logger``: the env
    override ``BIGDL_WARN_INTERVAL_<LOGGER_NAME, dots as underscores,
    uppercased>`` wins, then the global ``BIGDL_WARN_INTERVAL``, then
    the call site's default.  Lets an operator silence (large value) or
    un-rate-limit (0) one subsystem's warnings without a code change."""
    per_logger = "BIGDL_WARN_INTERVAL_" + \
        logger.name.upper().replace(".", "_")
    v = os.environ.get(per_logger, os.environ.get("BIGDL_WARN_INTERVAL"))
    if v:
        try:
            return float(v)
        except ValueError:
            # complain ONCE per bad value: this runs inside warn_every's
            # hot path, and an unthrottled complaint would be exactly
            # the log flood warn_every exists to prevent
            if v not in _BAD_OVERRIDES_WARNED:
                _BAD_OVERRIDES_WARNED.add(v)
                logger.warning("ignoring non-numeric warn-interval "
                               "override %r", v)
    return interval


def warn_every(logger: logging.Logger, key: str, interval: float,
               msg: str, *args) -> bool:
    """Rate-limited warning: at most one ``key`` warning per ``interval``
    seconds (the first always fires).  A chaos run skipping thousands of
    non-finite steps must not drown the progress log; returns whether the
    line was emitted.  ``interval`` is a default — see ``warn_interval``
    for the per-logger env override."""
    now = time.monotonic()
    interval = warn_interval(logger, interval)
    last = _LAST_WARN.get(key)
    if last is not None and now - last < interval:
        return False
    _LAST_WARN[key] = now
    logger.warning(msg, *args)
    return True


def init_logging(level=logging.INFO, log_file: str = None, fmt: str = _FORMAT):
    """Configure the framework's loggers (idempotent)."""
    logger = logging.getLogger("bigdl_tpu")
    logger.setLevel(level)
    if not any(isinstance(h, logging.StreamHandler) for h in logger.handlers):
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(fmt))
        logger.addHandler(h)
    if log_file and not any(
            isinstance(h, logging.FileHandler) and
            getattr(h, "baseFilename", None) == log_file
            for h in logger.handlers):
        fh = logging.FileHandler(log_file)
        fh.setFormatter(logging.Formatter(fmt))
        logger.addHandler(fh)
    return logger


def header(epoch: int, count: int, total: int, neval: int, wall: float) -> str:
    """The reference's driver progress-line prefix
    (Optimizer.header Optimizer.scala:132-135)."""
    return (f"[Epoch {epoch} {count}/{total}][Iteration {neval}]"
            f"[Wall Clock {wall:.6f}s]")
