"""Logging configuration (the log4j.properties role,
ref dl/src/main/resources/log4j.properties + the driver progress line
Optimizer.header, Optimizer.scala:132-135).

The reference configures log4j once per JVM; here ``init_logging`` sets up
the root ``bigdl_tpu`` logger with the same shape of output: timestamped
console lines, optional file sink, INFO default.
"""
from __future__ import annotations

import logging
import sys
import time

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

_LAST_WARN: dict = {}


def warn_every(logger: logging.Logger, key: str, interval: float,
               msg: str, *args) -> bool:
    """Rate-limited warning: at most one ``key`` warning per ``interval``
    seconds (the first always fires).  A chaos run skipping thousands of
    non-finite steps must not drown the progress log; returns whether the
    line was emitted."""
    now = time.monotonic()
    last = _LAST_WARN.get(key)
    if last is not None and now - last < interval:
        return False
    _LAST_WARN[key] = now
    logger.warning(msg, *args)
    return True


def init_logging(level=logging.INFO, log_file: str = None, fmt: str = _FORMAT):
    """Configure the framework's loggers (idempotent)."""
    logger = logging.getLogger("bigdl_tpu")
    logger.setLevel(level)
    if not any(isinstance(h, logging.StreamHandler) for h in logger.handlers):
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(fmt))
        logger.addHandler(h)
    if log_file and not any(
            isinstance(h, logging.FileHandler) and
            getattr(h, "baseFilename", None) == log_file
            for h in logger.handlers):
        fh = logging.FileHandler(log_file)
        fh.setFormatter(logging.Formatter(fmt))
        logger.addHandler(fh)
    return logger


def header(epoch: int, count: int, total: int, neval: int, wall: float) -> str:
    """The reference's driver progress-line prefix
    (Optimizer.header Optimizer.scala:132-135)."""
    return (f"[Epoch {epoch} {count}/{total}][Iteration {neval}]"
            f"[Wall Clock {wall:.6f}s]")
