"""bigdl_tpu — a TPU-native deep learning framework.

A ground-up reimplementation of the capabilities of Intel BigDL v0.1.0
(reference: MikeTam1021/BigDL) designed for TPU hardware:

- Compute path: JAX/XLA (jnp ops compile onto the MXU; Pallas for custom
  kernels).  The reference's native MKL/JNI layer (native/mkl/src/main/c/jni/
  mkl.c) dissolves into XLA-compiled kernels.
- Module system: Torch-style ergonomics (`forward`/`backward`/`parameters`)
  over a pure functional core (`apply(params, input, state, ctx)`) so the
  same model object works eagerly AND under `jax.jit`/`pjit`.
- Distributed: `jax.sharding.Mesh` + collectives over ICI replace the
  reference's Spark BlockManager parameter all-reduce
  (parameters/AllReduceParameter.scala).

Package layout (mirrors the reference's package inventory, SURVEY.md §2):
  nn/        layer + criterion inventory  (ref: dl/.../bigdl/nn)
  tensor/    dtype policy + tensor helpers (ref: dl/.../bigdl/tensor)
  dataset/   DataSet/Transformer/Sample    (ref: dl/.../bigdl/dataset)
  optim/     Optimizer/OptimMethod/Trigger (ref: dl/.../bigdl/optim)
  parallel/  mesh, collectives, sharded training (ref: dl/.../bigdl/parameters)
  models/    LeNet/VGG/Inception/ResNet/... (ref: dl/.../bigdl/models)
  utils/     Engine, Table, File, RandomGenerator (ref: dl/.../bigdl/utils)
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("BIGDL_CPU_MESH"):
    # virtual N-device CPU mesh for sharding tests without TPU hardware
    # (the reference's local-SparkContext multi-node test trick, SURVEY.md
    # §4; set by scripts/bigdl_tpu.sh --cpu-mesh N).  Must run before the
    # first backend touch; a no-op with a warning if jax already started.
    try:
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        from bigdl_tpu.utils.engine import set_cpu_device_count \
            as _set_cpu_device_count
        _set_cpu_device_count(int(_os.environ["BIGDL_CPU_MESH"]))
    except (RuntimeError, ValueError) as _e:
        # backend already initialized, or a non-integer value
        import warnings as _warnings
        _warnings.warn(f"BIGDL_CPU_MESH ignored: {_e}")

from bigdl_tpu.utils.table import Table, T  # noqa: F401
from bigdl_tpu.utils.engine import Engine  # noqa: F401
