"""LeNet-5 (ref models/lenet/LeNet5.scala:24) — the canonical end-to-end
slice (SURVEY.md §7, BASELINE config 1).
"""
from __future__ import annotations

import bigdl_tpu.nn as nn


def LeNet5(class_num: int = 10):
    """Layer-for-layer the reference graph (LeNet5.scala:24-41):
    reshape -> conv(1,6,5x5) -> tanh -> maxpool -> tanh? ... -> log_softmax."""
    return nn.Sequential(
        nn.Reshape([1, 28, 28]),
        nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Tanh(),
        nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([12 * 4 * 4]),
        nn.Linear(12 * 4 * 4, 100).set_name("fc_1"),
        nn.Tanh(),
        nn.Linear(100, class_num).set_name("fc_2"),
        nn.LogSoftMax(),
    )
