"""Synthetic-data throughput harnesses — the LocalOptimizerPerf /
DistriOptimizerPerf CLIs (ref models/utils/DistriOptimizerPerf.scala:41-138,
LocalOptimizerPerf.scala).

Usage:
  python -m bigdl_tpu.models.utils.perf --model inception_v1 -b 128 -i 20
  python -m bigdl_tpu.models.utils.perf --model vgg16 -b 64 --distributed

Flags mirror the reference's scopt options: --batchSize/-b, --iteration/-i,
--model/-m (alexnet | alexnetowt | googlenet_v1 | inception_v1 |
googlenet_v2 | inception_v2 | vgg16 | vgg19 | lenet5), --dataType
(float | bf16 compute).
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


MODELS = {}


def _register():
    from bigdl_tpu.models.alexnet import AlexNet, AlexNet_OWT
    from bigdl_tpu.models.inception import Inception_v1, Inception_v2
    from bigdl_tpu.models.vgg import Vgg_16, Vgg_19
    from bigdl_tpu.models.lenet import LeNet5
    MODELS.update({
        "alexnet": (lambda: AlexNet(1000), (3, 227, 227), 1000),
        "alexnetowt": (lambda: AlexNet_OWT(1000), (3, 224, 224), 1000),
        "googlenet_v1": (lambda: Inception_v1(1000), (3, 224, 224), 1000),
        "inception_v1": (lambda: Inception_v1(1000), (3, 224, 224), 1000),
        "googlenet_v2": (lambda: Inception_v2(1000), (3, 224, 224), 1000),
        "inception_v2": (lambda: Inception_v2(1000), (3, 224, 224), 1000),
        "vgg16": (lambda: Vgg_16(1000), (3, 224, 224), 1000),
        "vgg19": (lambda: Vgg_19(1000), (3, 224, 224), 1000),
        "lenet5": (lambda: LeNet5(10), (1, 28, 28), 10),
    })


def run_perf(model_name: str, batch_size: int, iterations: int,
             warmup: int = 3, distributed: bool = False,
             data_type: str = "bf16", iters_per_dispatch: int = 1) -> dict:
    """``iters_per_dispatch > 1`` uses the device-side training loop
    (n scanned steps per dispatch over distinct stacked minibatches, the
    set_iterations_per_dispatch feature) — on dispatch-latency-bound
    setups this reports the device-limited rate."""
    import jax
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu import tensor as bt
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.utils.random import set_seed

    _register()
    set_seed(1)
    if data_type == "bf16":
        bt.set_policy(bt.BF16_COMPUTE)
    else:
        bt.set_policy(bt.FP32)
    build, shape, n_classes = MODELS[model_name]
    model = build()
    criterion = nn.ClassNLLCriterion()
    method = SGD()
    # copy before the donating jit step — donate_argnums would otherwise
    # leave the live module holding deleted buffers (same guard as
    # LocalOptimizer/DistriOptimizer)
    params = jax.tree_util.tree_map(jnp.copy, model.params())
    net_state = jax.tree_util.tree_map(jnp.copy, model.state())
    opt_state = method.init_state(params)
    hyper = {"lr": 0.01, "momentum": 0.9, "dampening": 0.0,
             "weight_decay": 0.0, "nesterov": False}

    def train_step(params, net_state, opt_state, x, y, key):
        def loss_fn(p):
            out, ns = model.apply(p, x, net_state, Context(training=True, key=key))
            return criterion.apply_loss(out, y), ns

        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = method.update(grads, opt_state, params, hyper)
        return new_params, ns, new_opt, loss

    rs = np.random.RandomState(0)
    n_disp = max(1, int(iters_per_dispatch))
    if n_disp > 1:
        from jax import lax
        per_step = train_step

        def train_step(params, net_state, opt_state, xs, ys, key):
            keys = jax.random.split(key, n_disp)

            def body(carry, xyk):
                p, ns, o = carry
                bx, by, k = xyk
                p, ns, o, loss = per_step(p, ns, o, bx, by, k)
                return (p, ns, o), loss

            (params, net_state, opt_state), losses = lax.scan(
                body, (params, net_state, opt_state), (xs, ys, keys))
            return params, net_state, opt_state, losses[-1]

        x = jnp.asarray(rs.randn(n_disp, batch_size, *shape), jnp.float32)
        y = jnp.asarray(rs.randint(1, n_classes + 1, (n_disp, batch_size)))
    else:
        x = jnp.asarray(rs.randn(batch_size, *shape), jnp.float32)
        y = jnp.asarray(rs.randint(1, n_classes + 1, (batch_size,)))
    key = jax.random.PRNGKey(0)

    if distributed:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from bigdl_tpu.parallel.mesh import data_parallel_mesh
        mesh = data_parallel_mesh()
        rep = NamedSharding(mesh, P())
        data_s = NamedSharding(
            mesh, P(None, "data") if n_disp > 1 else P("data"))
        reps = lambda tree: jax.tree_util.tree_map(lambda _: rep, tree)
        step = jax.jit(train_step,
                       in_shardings=(reps(params), reps(net_state),
                                     reps(opt_state), data_s, data_s, rep),
                       out_shardings=(reps(params), reps(net_state),
                                      reps(opt_state), rep),
                       donate_argnums=(0, 1, 2))
        x = jax.device_put(x, data_s)
        y = jax.device_put(y, data_s)
    else:
        step = jax.jit(train_step, donate_argnums=(0, 1, 2))

    compile_t0 = time.perf_counter()
    out = step(params, net_state, opt_state, x, y, key)
    float(out[3])  # device->host copy = hard sync (see bench.py)
    compile_time = time.perf_counter() - compile_t0
    params, net_state, opt_state, _ = out

    loss = out[3]
    for _ in range(warmup - 1):
        params, net_state, opt_state, loss = step(params, net_state, opt_state, x, y, key)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iterations):
        params, net_state, opt_state, loss = step(params, net_state, opt_state, x, y, key)
    last_loss = float(loss)  # syncs the sequential step chain
    dt = (time.perf_counter() - t0) / (iterations * n_disp)

    return {
        "model": model_name,
        "batch_size": batch_size,
        "iters_per_dispatch": n_disp,
        "distributed": distributed,
        "devices": jax.device_count() if distributed else 1,
        "step_time_ms": round(dt * 1e3, 3),
        "throughput_records_per_sec": round(batch_size / dt, 2),
        "compile_time_s": round(compile_time, 2),
        "loss": last_loss,
    }


def main(argv=None, force_distributed=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", "-m", default="inception_v1")
    p.add_argument("--batchSize", "-b", type=int, default=128)
    p.add_argument("--iteration", "-i", type=int, default=10)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--dataType", choices=["float", "bf16"], default="bf16")
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--distributed", action="store_true")
    args = p.parse_args(argv)
    if force_distributed is not None and args.distributed != force_distributed:
        p.error("--distributed conflicts with this entry point; use "
                "`python -m bigdl_tpu.models.utils.perf --distributed` instead")
    distributed = (force_distributed if force_distributed is not None
                   else args.distributed)
    result = run_perf(args.model, args.batchSize, args.iteration,
                      args.warmup, distributed, args.dataType,
                      iters_per_dispatch=args.iterationsPerDispatch)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
