"""Single-host synthetic-data throughput CLI
(ref models/utils/LocalOptimizerPerf.scala).

  python -m bigdl_tpu.models.utils.local_optimizer_perf --model vgg16 -b 128
"""
from bigdl_tpu.models.utils.perf import main as _main


def main(argv=None):
    return _main(argv, force_distributed=False)


if __name__ == "__main__":
    main()
