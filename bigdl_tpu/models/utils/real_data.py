"""Real-data train + evaluate loop over an image class-folder.

The role of the reference's per-model ``Test`` mains and
``example/loadmodel/ModelValidator.scala:114-146``: every reference model
ships an entry point that decodes REAL image files and reports top-1/
top-5 through the validation apparatus (models/lenet/Test.scala,
models/inception/Test.scala).  This helper drives the same loop end to
end on any class-per-subfolder image directory — decode through the
framework pipeline, train a small conv net on-chip, evaluate with
``Top1Accuracy``/``Top5Accuracy`` — so accuracy numbers in tests and in
the bench artifact come from actually-decoded images, not synthetic
tensors.  The reference's shipped CIFAR PNG folders
(dl/src/test/resources/cifar/) are the canonical input.
"""
from __future__ import annotations

import numpy as np


def small_convnet(n_classes: int, image_size: int):
    """Conv-pool-conv-pool-linear classifier, LeNet-scale (the smallest
    member of the reference's conv zoo, models/lenet/LeNet5.scala)."""
    import bigdl_tpu.nn as nn
    after_pool = image_size // 4
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    m.add(nn.SpatialConvolution(8, 16, 3, 3, 1, 1, 1, 1))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    m.add(nn.Reshape([16 * after_pool * after_pool]))
    m.add(nn.Linear(16 * after_pool * after_pool, n_classes))
    m.add(nn.LogSoftMax())
    return m


def _byte_record_dataset(folder: str, image_size: int):
    """ImageFolder paths -> decoded/normalized/batched dataset + counts."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.image import BytesToImg, ImgNormalizer
    from bigdl_tpu.dataset.sample import ByteRecord

    paths = list(DataSet.image_folder(folder).data(train=False))
    kept = [(p, lab) for p, lab in paths
            if p.lower().endswith((".png", ".jpeg", ".jpg", ".bmp"))]
    if not kept:
        raise ValueError(f"no decodable images under {folder}")
    # re-densify labels: filtering can empty a class folder, and a gap in
    # the 1-based label range would let NLL's take_along_axis silently
    # clamp out-of-range targets onto the wrong class
    remap = {lab: float(i + 1)
             for i, lab in enumerate(sorted({lab for _, lab in kept}))}
    recs = []
    for path, label in kept:
        with open(path, "rb") as f:
            recs.append(ByteRecord(f.read(), remap[label]))
    n_classes = len(remap)
    ds = (DataSet.array(recs)
          >> BytesToImg(scale_to=image_size)
          >> ImgNormalizer(125.0, 62.0))
    return ds, recs, n_classes


def train_and_eval_image_folder(folder: str, image_size: int = 32,
                                iterations: int = 120,
                                learning_rate: float = 0.05,
                                seed: int = 5, model=None):
    """Decode -> train -> validate on one image class-folder.

    Returns ``{"top1", "top5", "n_records", "n_classes", "loss",
    "iterations"}`` where top1/top5 come from the shared ``validate``
    loop (ref Validator.scala:24) over the same decoded records the
    model trained on — a tiny-dataset overfit drill, so a healthy
    decode/label path yields top1 near 1.0 while broken label plumbing
    pins it at chance."""
    from bigdl_tpu import nn
    from bigdl_tpu.dataset.image import ImgToBatch
    from bigdl_tpu.optim import (LocalOptimizer, Top1Accuracy, Top5Accuracy,
                                 max_iteration, validate)
    from bigdl_tpu.utils.random import set_seed
    from bigdl_tpu.utils.table import T

    from bigdl_tpu.utils.random import RNG
    # this helper runs mid-bench / mid-suite: borrow the process RNG via
    # the snapshot/restore API (epoch included, so worker-thread derived
    # streams re-derive correctly) and hand it back on exit
    with RNG.scoped():
        set_seed(seed)
        ds, recs, n_classes = _byte_record_dataset(folder, image_size)
        if model is None:
            model = small_convnet(n_classes, image_size)
        batched = ds >> ImgToBatch(len(recs))
        opt = LocalOptimizer(model, batched, nn.ClassNLLCriterion())
        opt.set_state(T(learningRate=learning_rate, momentum=0.9))
        opt.set_end_when(max_iteration(iterations))
        opt.optimize()
        results = validate(model, model.params(), model.state(), batched,
                           [Top1Accuracy(), Top5Accuracy()])
        (_, top1), (_, top5) = results
    return {"top1": round(top1.result()[0], 4),
            "top5": round(top5.result()[0], 4),
            "n_records": len(recs), "n_classes": n_classes,
            "loss": round(float(opt.state["loss"]), 6),
            "iterations": iterations}
