"""Mesh-distributed synthetic-data throughput CLI
(ref models/utils/DistriOptimizerPerf.scala:41-138: inception_v1/v2,
vgg16/19, default batch 128, -n nodes x -c cores -> here the device mesh).

  python -m bigdl_tpu.models.utils.distri_optimizer_perf --model inception_v1 -b 128
"""
from bigdl_tpu.models.utils.perf import main as _main


def main(argv=None):
    return _main(argv, force_distributed=True)


if __name__ == "__main__":
    main()
