"""Inception/GoogLeNet models (ref models/inception/Inception_v1.scala:96,
Inception_v2.scala) — the distributed-training flagship (BASELINE config 3:
Inception-v1 ImageNet sync-SGD).
"""
from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn import Xavier


def _conv(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0):
    return nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                                 init_method=Xavier)


def inception_module(input_size, c1, c3r, c3, c5r, c5, pool_proj):
    """4-branch inception block (ref Inception_v1.scala inception():
    Concat over channel dim of 1x1 / 1x1-3x3 / 1x1-5x5 / pool-1x1)."""
    return nn.Concat(
        2,
        nn.Sequential(_conv(input_size, c1, 1, 1), nn.ReLU(True)),
        nn.Sequential(_conv(input_size, c3r, 1, 1), nn.ReLU(True),
                      _conv(c3r, c3, 3, 3, 1, 1, 1, 1), nn.ReLU(True)),
        nn.Sequential(_conv(input_size, c5r, 1, 1), nn.ReLU(True),
                      _conv(c5r, c5, 5, 5, 1, 1, 2, 2), nn.ReLU(True)),
        nn.Sequential(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil(),
                      _conv(input_size, pool_proj, 1, 1), nn.ReLU(True)),
    )


def Inception_v1_NoAuxClassifier(class_num: int = 1000):
    """GoogLeNet without aux heads (ref Inception_v1.scala:96 main path)."""
    m = nn.Sequential()
    m.add(_conv(3, 64, 7, 7, 2, 2, 3, 3).set_name("conv1/7x7_s2"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(_conv(64, 64, 1, 1).set_name("conv2/3x3_reduce"))
    m.add(nn.ReLU(True))
    m.add(_conv(64, 192, 3, 3, 1, 1, 1, 1).set_name("conv2/3x3"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(inception_module(192, 64, 96, 128, 16, 32, 32))    # 3a -> 256
    m.add(inception_module(256, 128, 128, 192, 32, 96, 64))  # 3b -> 480
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(inception_module(480, 192, 96, 208, 16, 48, 64))   # 4a -> 512
    m.add(inception_module(512, 160, 112, 224, 24, 64, 64))  # 4b -> 512
    m.add(inception_module(512, 128, 128, 256, 24, 64, 64))  # 4c -> 512
    m.add(inception_module(512, 112, 144, 288, 32, 64, 64))  # 4d -> 528
    m.add(inception_module(528, 256, 160, 320, 32, 128, 128))  # 4e -> 832
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(inception_module(832, 256, 160, 320, 32, 128, 128))  # 5a -> 832
    m.add(inception_module(832, 384, 192, 384, 48, 128, 128))  # 5b -> 1024
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.Dropout(0.4))
    m.add(nn.View(1024))
    m.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


# main entry matching the reference's default training graph
def Inception_v1(class_num: int = 1000):
    return Inception_v1_NoAuxClassifier(class_num)


def _conv_bn(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0):
    return nn.Sequential(
        nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                              init_method=Xavier),
        nn.SpatialBatchNormalization(n_out, 1e-3),
        nn.ReLU(True))


def inception_v2_module(input_size, c1, c3r, c3, d3r, d3, pool_proj,
                        pool_type="avg", stride=1):
    """BN-Inception block (ref Inception_v2.scala): 5x5 branch factorized
    into double-3x3; stride-2 variants drop the 1x1 branch and pass the
    pool through (c1 == 0)."""
    branches = []
    if c1 > 0:
        branches.append(_conv_bn(input_size, c1, 1, 1))
    branches.append(nn.Sequential(
        _conv_bn(input_size, c3r, 1, 1),
        _conv_bn(c3r, c3, 3, 3, stride, stride, 1, 1)))
    branches.append(nn.Sequential(
        _conv_bn(input_size, d3r, 1, 1),
        _conv_bn(d3r, d3, 3, 3, 1, 1, 1, 1),
        _conv_bn(d3, d3, 3, 3, stride, stride, 1, 1)))
    pool = (nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1, ceil_mode=True)
            if pool_type == "avg"
            else nn.SpatialMaxPooling(3, 3, stride, stride,
                                      1 if stride == 1 else 0,
                                      1 if stride == 1 else 0).ceil())
    if pool_proj > 0:
        branches.append(nn.Sequential(pool, _conv_bn(input_size, pool_proj, 1, 1)))
    else:
        branches.append(nn.Sequential(pool))
    return nn.Concat(2, *branches)


def Inception_v2(class_num: int = 1000):
    """BN-Inception (ref Inception_v2.scala)."""
    m = nn.Sequential()
    m.add(_conv_bn(3, 64, 7, 7, 2, 2, 3, 3))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(_conv_bn(64, 64, 1, 1))
    m.add(_conv_bn(64, 192, 3, 3, 1, 1, 1, 1))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(inception_v2_module(192, 64, 64, 64, 64, 96, 32, "avg"))     # 3a -> 256
    m.add(inception_v2_module(256, 64, 64, 96, 64, 96, 64, "avg"))     # 3b -> 320
    m.add(inception_v2_module(320, 0, 128, 160, 64, 96, 0, "max", 2))  # 3c -> 576
    m.add(inception_v2_module(576, 224, 64, 96, 96, 128, 128, "avg"))  # 4a -> 576
    m.add(inception_v2_module(576, 192, 96, 128, 96, 128, 128, "avg")) # 4b -> 576
    m.add(inception_v2_module(576, 160, 128, 160, 128, 160, 96, "avg"))  # 4c -> 576
    m.add(inception_v2_module(576, 96, 128, 192, 160, 192, 96, "avg"))   # 4d -> 576
    m.add(inception_v2_module(576, 0, 128, 192, 192, 256, 0, "max", 2))  # 4e -> 1024
    m.add(inception_v2_module(1024, 352, 192, 320, 160, 224, 128, "avg"))  # 5a -> 1024
    m.add(inception_v2_module(1024, 352, 192, 320, 192, 224, 128, "max"))  # 5b -> 1024
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.View(1024))
    m.add(nn.Linear(1024, class_num))
    m.add(nn.LogSoftMax())
    return m
