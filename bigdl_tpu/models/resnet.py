"""ResNet (ref models/resnet/ResNet.scala:59).

The reference's ``shareGradInput`` memory trick (ResNet.scala:62-100) is
obsolete under XLA buffer assignment; the MSRA init (``modelInit``
:102-132) is preserved via init_method=MSRA on convs + BN gamma init.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn import MSRA


def _shortcut(n_in, n_out, stride, shortcut_type="B"):
    """(ref ResNet.scala shortcut) A: identity/pad, B: 1x1 conv when shape
    changes."""
    use_conv = shortcut_type == "C" or (shortcut_type == "B" and n_in != n_out)
    if use_conv:
        return nn.Sequential(
            nn.SpatialConvolution(n_in, n_out, 1, 1, stride, stride,
                                  init_method=MSRA, with_bias=False),
            nn.SpatialBatchNormalization(n_out))
    if n_in != n_out:
        # type A: stride then zero-pad channels
        return nn.Sequential(
            nn.SpatialAveragePooling(1, 1, stride, stride),
            nn.Concat(2, nn.Identity(), nn.MulConstant(0.0)))
    return nn.Identity()


def basic_block(n_in, n_out, stride=1, shortcut_type="B"):
    """(ref ResNet.scala basicBlock :162)"""
    s = nn.Sequential(
        nn.SpatialConvolution(n_in, n_out, 3, 3, stride, stride, 1, 1,
                              init_method=MSRA, with_bias=False),
        nn.SpatialBatchNormalization(n_out),
        nn.ReLU(True),
        nn.SpatialConvolution(n_out, n_out, 3, 3, 1, 1, 1, 1,
                              init_method=MSRA, with_bias=False),
        nn.SpatialBatchNormalization(n_out),
    )
    return nn.Sequential(
        nn.ConcatTable(s, _shortcut(n_in, n_out, stride, shortcut_type)),
        nn.CAddTable(True),
        nn.ReLU(True))


def bottleneck(n_in, n_mid, stride=1, shortcut_type="B"):
    """(ref ResNet.scala bottleneck :182) — expansion 4."""
    n_out = n_mid * 4
    s = nn.Sequential(
        nn.SpatialConvolution(n_in, n_mid, 1, 1, init_method=MSRA, with_bias=False),
        nn.SpatialBatchNormalization(n_mid), nn.ReLU(True),
        nn.SpatialConvolution(n_mid, n_mid, 3, 3, stride, stride, 1, 1,
                              init_method=MSRA, with_bias=False),
        nn.SpatialBatchNormalization(n_mid), nn.ReLU(True),
        nn.SpatialConvolution(n_mid, n_out, 1, 1, init_method=MSRA, with_bias=False),
        nn.SpatialBatchNormalization(n_out),
    )
    return nn.Sequential(
        nn.ConcatTable(s, _shortcut(n_in, n_out, stride, shortcut_type)),
        nn.CAddTable(True),
        nn.ReLU(True))


def _layer(block, n_in, n_mid, count, stride, shortcut_type="B", expansion=1):
    m = nn.Sequential()
    for i in range(count):
        m.add(block(n_in if i == 0 else n_mid * expansion, n_mid,
                    stride if i == 0 else 1, shortcut_type))
    return m


def ResNetCifar(depth: int = 20, class_num: int = 10, shortcut_type: str = "A"):
    """CIFAR-10 ResNet, depth = 6n+2 (ref ResNet.scala cifar path)."""
    assert (depth - 2) % 6 == 0, "depth must be 6n+2"
    n = (depth - 2) // 6
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1,
                                init_method=MSRA, with_bias=False))
    m.add(nn.SpatialBatchNormalization(16))
    m.add(nn.ReLU(True))
    m.add(_layer(basic_block, 16, 16, n, 1, shortcut_type))
    m.add(_layer(basic_block, 16, 32, n, 2, shortcut_type))
    m.add(_layer(basic_block, 32, 64, n, 2, shortcut_type))
    m.add(nn.SpatialAveragePooling(8, 8, 1, 1))
    m.add(nn.View(64))
    m.add(nn.Linear(64, class_num))
    m.add(nn.LogSoftMax())
    _zero_init_final_bn(m)
    return m


def ResNet(depth: int = 50, class_num: int = 1000, shortcut_type: str = "B"):
    """ImageNet ResNet (ref ResNet.scala imagenet path)."""
    cfgs = {18: (basic_block, [2, 2, 2, 2], 1, 512),
            34: (basic_block, [3, 4, 6, 3], 1, 512),
            50: (bottleneck, [3, 4, 6, 3], 4, 2048),
            101: (bottleneck, [3, 4, 23, 3], 4, 2048),
            152: (bottleneck, [3, 8, 36, 3], 4, 2048)}
    block, counts, expansion, n_features = cfgs[depth]
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                                init_method=MSRA, with_bias=False))
    m.add(nn.SpatialBatchNormalization(64))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1))
    m.add(_layer(block, 64, 64, counts[0], 1, shortcut_type, expansion))
    m.add(_layer(block, 64 * expansion, 128, counts[1], 2, shortcut_type, expansion))
    m.add(_layer(block, 128 * expansion, 256, counts[2], 2, shortcut_type, expansion))
    m.add(_layer(block, 256 * expansion, 512, counts[3], 2, shortcut_type, expansion))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.View(n_features))
    m.add(nn.Linear(n_features, class_num))
    m.add(nn.LogSoftMax())
    _zero_init_final_bn(m)
    return m


def _zero_init_final_bn(model):
    """MSRA-style: zero the last BN gamma of each residual branch
    (ref ResNet.modelInit ResNet.scala:102-132)."""
    def visit(mod):
        if isinstance(mod, nn.Sequential):
            mods = mod.modules
            for i, child in enumerate(mods):
                if (isinstance(child, nn.SpatialBatchNormalization)
                        and i == len(mods) - 1
                        and "weight" in child._params):
                    child._params["weight"] = jnp.zeros_like(child._params["weight"])
            for child in mods:
                visit(child)
        elif isinstance(mod, nn.Container):
            for child in mod.modules:
                visit(child)

    visit(model)
    return model
