"""Autoencoder on MNIST (ref models/autoencoder/Autoencoder.scala:28)."""
from __future__ import annotations

import bigdl_tpu.nn as nn

ROW_N = 28
COL_N = 28
FEATURE_SIZE = ROW_N * COL_N


def Autoencoder(class_num: int = 32):
    """(ref Autoencoder.scala:28-36): 784 -> classNum -> 784 with sigmoid
    reconstruction; trained with MSE against the input."""
    return nn.Sequential(
        nn.Reshape([FEATURE_SIZE]),
        nn.Linear(FEATURE_SIZE, class_num),
        nn.ReLU(True),
        nn.Linear(class_num, FEATURE_SIZE),
        nn.Sigmoid(),
    )
