"""Text classifiers over word embeddings.

- ``TextClassifierConv``: the reference's temporal conv net
  (example/textclassification/TextClassifier.scala:119-140 — three
  conv5-relu-maxpool stages as SpatialConvolution over the (1, seq,
  embed) plane, then a linear head).
- ``TextClassifierBiLSTM``: BASELINE.md config 4 — a bidirectional LSTM
  (BiRecurrent(LSTMCell, LSTMCell), recurrence as lax.scan) with
  mean-over-time pooling and the same linear head.  Not in the reference
  (it has no LSTM — SURVEY.md §2.3 "Recurrent"); capability extension
  required by the benchmark config.

Both take pre-embedded input (batch, seq_len, embed_dim): the reference
also embeds on the data side (GloVe lookup in the Spark pipeline,
TextClassifier.scala; here dataset/news20.embed_samples).
"""
from __future__ import annotations

import bigdl_tpu.nn as nn


def TextClassifierConv(class_num: int, seq_len: int = 200, embed_dim: int = 50):
    """(ref TextClassifier.buildModel :119-140).  The reference hardcodes
    the last pooling to 35 for its 1000-token sequences; here the final
    pool consumes whatever extent remains, so any seq_len that survives
    the first two stages (>= 149) works."""
    h1 = seq_len - 4          # conv kh=5
    h2 = (h1 - 5) // 5 + 1    # pool 5/5
    h3 = h2 - 4               # conv kh=5
    h4 = (h3 - 5) // 5 + 1    # pool 5/5
    h5 = h4 - 4               # conv kh=5
    if h5 < 1:
        raise ValueError(f"seqLength {seq_len} too short for 3 conv stages")
    m = nn.Sequential()
    m.add(nn.Reshape([1, seq_len, embed_dim]))
    m.add(nn.SpatialConvolution(1, 128, embed_dim, 5))   # kw=embed, kh=5
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(1, 5, 1, 5))
    m.add(nn.SpatialConvolution(128, 128, 1, 5))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(1, 5, 1, 5))
    m.add(nn.SpatialConvolution(128, 128, 1, 5))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(1, h5, 1, h5))            # ref: 35 @ seq 1000
    m.add(nn.Reshape([128]))
    m.add(nn.Linear(128, 100))
    m.add(nn.ReLU())
    m.add(nn.Linear(100, class_num))
    m.add(nn.LogSoftMax())
    return m


def TextClassifierBiLSTM(class_num: int, embed_dim: int = 50,
                         hidden_size: int = 128):
    """Bi-LSTM classifier (BASELINE.md config 4).

    (B, T, E) -> BiRecurrent(LSTM fwd, LSTM bwd) -> (B, T, 2H)
    -> mean over time -> Linear(2H, 100) -> ReLU -> Linear -> LogSoftMax.
    Works for any sequence length (the head has no T dependence).
    """
    m = nn.Sequential()
    m.add(nn.BiRecurrent(nn.LSTMCell(embed_dim, hidden_size),
                         nn.LSTMCell(embed_dim, hidden_size)))
    m.add(nn.Mean(1, n_input_dims=2))   # time = dim 1 of unbatched (T, 2H)
    m.add(nn.Linear(2 * hidden_size, 100))
    m.add(nn.ReLU())
    m.add(nn.Linear(100, class_num))
    m.add(nn.LogSoftMax())
    return m
