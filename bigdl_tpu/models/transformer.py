"""Transformer encoder classifier — the attention-family flagship.

No counterpart in the reference (its sequence model zoo stops at
RNN/LSTM text classifiers, models/textclassifier); this family exists to
exercise the long-context machinery end to end: `nn.MultiHeadSelfAttention`
(ring attention under ``DistriOptimizer(sequence_parallel=True)``),
`nn.LayerNorm` (per-token — no cross-device stats under any sharding),
and optionally `nn.MoE` FFN blocks (expert-parallel under
``expert_parallel=True``).

Structure per block (pre-LN): x + Attn(LN(x)); x + FFN(LN(x)) — the
residuals use the reference's ConcatTable(Identity, branch) + CAddTable
idiom (same as its ResNet shortcut spelling).
"""
from __future__ import annotations

import collections

import bigdl_tpu.nn as nn

# Round-7 Mosaic paged-attention kernels (ops/pallas_kernels.py
# paged_attention / paged_spec_verify): walk the slot→page table
# in-kernel with an online softmax and the int8 dequantize fused into
# the QK/PV loops, instead of materializing the gathered `pool[ptab]`
# view (and a separate dequantize pass) in HBM each decode step.
# `_PALLAS_PAGED_ATTN` gates the S == 1 continuous-decode step,
# `_PALLAS_SPEC_VERIFY` the speculative (k+1)-query verify window.
# PR-2 adoption discipline: no chip verdict yet → both default OFF;
# True adopts on TPU, "interpret" forces the Pallas interpreter
# (CPU equivalence tests and the perf_smoke drill).  The staged A/Bs
# live in tools/ab_device_clock.py and `tools/bench_serve.py
# --decode-sweep --attn-kernel`.
_PALLAS_PAGED_ATTN = False
_PALLAS_SPEC_VERIFY = False


def _residual(branch: nn.Module) -> nn.Module:
    return nn.Sequential(nn.ConcatTable(nn.Identity(), branch),
                         nn.CAddTable())


def _ffn(d_model: int, hidden: int, dropout: float,
         moe_experts: int) -> nn.Module:
    if moe_experts > 0:
        return nn.Sequential(nn.MoE(d_model, hidden, moe_experts),
                             nn.Dropout(dropout))
    return nn.Sequential(
        nn.TimeDistributed(nn.Linear(d_model, hidden)),
        nn.ReLU(True),
        nn.Dropout(dropout),
        nn.TimeDistributed(nn.Linear(hidden, d_model)),
    )


def encoder_block(d_model: int, n_heads: int, hidden: int,
                  dropout: float = 0.1, causal: bool = False,
                  moe_experts: int = 0) -> nn.Module:
    return nn.Sequential(
        _residual(nn.Sequential(
            nn.LayerNorm(d_model),
            nn.MultiHeadSelfAttention(d_model, n_heads, causal=causal),
            nn.Dropout(dropout),
        )),
        _residual(nn.Sequential(
            nn.LayerNorm(d_model),
            _ffn(d_model, hidden, dropout, moe_experts),
        )),
    )


def TransformerLM(vocab_size: int, d_model: int = 128, n_heads: int = 4,
                  n_layers: int = 2, hidden: int = 256,
                  dropout: float = 0.1):
    """Causal word LM over (B, T, vocab) one-hot input -> per-token class
    log-probs — the attention-family counterpart of models/rnn.SimpleRNN
    (ref SimpleRNN.scala:23-38): same input/output contract, so it trains
    with ``TimeDistributedCriterion(ClassNLLCriterion)`` and generates
    with ``models.rnn.generate`` unchanged.  Sequence order comes from
    ``nn.SinusoidalPositionalEncoding`` (attention is permutation-
    equivariant; the RNN's recurrence is replaced, not imitated)."""
    m = nn.Sequential(
        nn.TimeDistributed(nn.Linear(vocab_size, d_model)),
        nn.SinusoidalPositionalEncoding(d_model),
    )
    for _ in range(n_layers):
        m.add(encoder_block(d_model, n_heads, hidden, dropout,
                            causal=True))
    m.add(nn.LayerNorm(d_model))
    m.add(nn.TimeDistributed(nn.Sequential(
        nn.Linear(d_model, vocab_size), nn.LogSoftMax())))
    return m


_LMHandles = collections.namedtuple(
    "_LMHandles", ["mods", "n_layers", "emb", "d_model", "blocks",
                   "block_eps", "n_heads", "hd", "ln_f", "eps_f", "head",
                   "vocab"])


def _lm_handles(model):
    """Structural handle extraction shared by ``lm_decode`` and
    ``lm_beam_search``: walk each block for its LayerNorm/attention/
    Linear instances (count-checked) so refactors of ``encoder_block``'s
    container nesting fail loudly instead of silently diverging through
    stale hard-coded param paths."""
    from bigdl_tpu.nn.attention import (MultiHeadSelfAttention,
                                        SinusoidalPositionalEncoding)
    from bigdl_tpu.nn.linear import Linear
    from bigdl_tpu.nn.moe import MoE
    from bigdl_tpu.nn.normalization import LayerNorm

    def _walk(mod, path=()):
        yield path, mod
        for i, ch in enumerate(getattr(mod, "modules", None) or []):
            yield from _walk(ch, path + (str(i),))

    def _find(mod, cls):
        return [(p, m) for p, m in _walk(mod) if isinstance(m, cls)]

    def _param_at(tree, path):
        for k in path:
            tree = tree[k]
        return tree

    mods = model.modules
    n_layers = len(mods) - 4
    if (n_layers < 1
            or not isinstance(mods[1], SinusoidalPositionalEncoding)):
        raise ValueError("lm_decode expects a TransformerLM-built model "
                         "(embedding, positional encoding, blocks, final "
                         "LayerNorm, head)")
    params = model.params()
    emb_mods = _find(mods[0], Linear)
    if len(emb_mods) != 1:
        raise ValueError("lm_decode: embedding stage must hold exactly "
                         "one Linear")
    emb = _param_at(params["0"], emb_mods[0][0])["~"]  # weight (d, vocab)
    d_model = int(emb["weight"].shape[0])
    blocks, block_eps = [], []
    n_heads = None
    for li in range(n_layers):
        blk, pb = mods[2 + li], params[str(2 + li)]
        if _find(blk, MoE):
            raise NotImplementedError(
                "lm_decode does not support MoE FFN blocks")
        attn = _find(blk, MultiHeadSelfAttention)
        lns = _find(blk, LayerNorm)
        ffn_lins = _find(blk, Linear)
        if len(attn) != 1 or len(lns) != 2 or len(ffn_lins) != 2:
            raise ValueError(
                f"lm_decode: block {li} must hold exactly one attention, "
                f"two LayerNorms and two FFN Linears; found {len(attn)}/"
                f"{len(lns)}/{len(ffn_lins)} — was encoder_block "
                f"restructured?")
        n_heads = attn[0][1].n_heads
        blocks.append((
            _param_at(pb, lns[0][0]),        # attention-branch LN
            _param_at(pb, attn[0][0])["~"],  # MHSA weights
            _param_at(pb, lns[1][0]),        # FFN-branch LN
            _param_at(pb, ffn_lins[0][0])["~"],  # d_model -> hidden
            _param_at(pb, ffn_lins[1][0])["~"],  # hidden -> d_model
        ))
        block_eps.append((lns[0][1].eps, lns[1][1].eps))
    hd = d_model // n_heads
    ln_f = params[str(2 + n_layers)]["~"]
    eps_f = mods[2 + n_layers].eps
    head_mods = _find(mods[3 + n_layers], Linear)
    if len(head_mods) != 1:
        raise ValueError("lm_decode: head stage must hold exactly one "
                         "Linear")
    head = _param_at(params[str(3 + n_layers)],
                     head_mods[0][0])["~"]   # weight (vocab, d)
    vocab = int(head["weight"].shape[0])
    return _LMHandles(mods, n_layers, emb, d_model, blocks, block_eps,
                      n_heads, hd, ln_f, eps_f, head, vocab)


def _lm_forward_window(tok, i, caches, handles, pe, pages, valid=None,
                       tp_axis=None, view_pages=None):
    """Paged multi-position forward: token ids (B, S) at per-row
    positions ``i`` (B, S) against block-paged KV pools.

    ``pages`` is ``(page_table, page_size)``: the pools in ``caches``
    are shaped (layers, n_pages, page_size, H, hd) and ``page_table``
    (B, P) maps each row's logical page ``t // page_size`` to a pool
    page, so a row's attention span is the gathered view
    ``pool[layer][page_table[b]]`` — (P * page_size) positions in
    logical order.  The window's K/V scatter runs BEFORE the gather, so
    window position j attends window positions j' <= j and the
    committed past through one causal mask (``t <= i[b, j]``): this is
    both the speculative-verify batch step (S = k+1 drafted positions
    judged in one pass) and, at S = 1, the paged continuous-decode
    step.

    ``valid`` (B, S) gates the scatter: invalid positions — a frozen
    row, or window positions past the row's page allocation — are
    routed out of bounds, where XLA DROPS the update.  That gate is a
    correctness contract, not hygiene: pages can outlive their request
    through the prefix cache (serve/prefix.py), so a stale write from a
    finished row would corrupt K/V another request later trusts.

    ``caches`` of FOUR arrays — ``(kpool, vpool, kscale, vscale)`` —
    selects int8 KV storage (``BIGDL_SERVE_KV_QUANT``, docs/serving.md
    "Quantized serving"): the pools are int8 and the scale arrays
    ``(layers, n_pages, page_size, H)`` carry one float scale per
    written head-row, pool-indexed exactly like the values (so prefix
    page donation ships scales with pages).  The scatter quantizes
    (``quant/kv.py``: per-head amax/127), the page-gathered attention
    view dequantizes; scales ride the SAME ``phys`` coordinates, so
    invalid lanes drop both writes together.

    ``tp_axis`` has `_lm_forward_one`'s Megatron semantics: handles
    carry LOCAL shards, the pools (and scale arrays) shard on their
    head dim, one psum merges each branch's output projection.

    ``view_pages`` (static int) bounds the attention view to the first
    that many page-table columns — the caller promises every live
    position in this window sits below ``view_pages * page_size``
    (serve/decode.py tracks the fleet-wide live page horizon), so the
    gather, mask and softmax shrink from the full reservation to the
    pages actually in use.  Scatter coordinates are unaffected: a valid
    position's logical page is < ``view_pages`` by the same promise,
    and invalid positions were already routed out of bounds.

    When `_PALLAS_PAGED_ATTN` (S == 1) or `_PALLAS_SPEC_VERIFY`
    (S > 1) is set, the gather + dequantize + attention stack is
    replaced by the fused Mosaic page-walk kernel
    (ops/pallas_kernels.py paged_attention); the K/V scatter is
    unchanged.  Flag value "interpret" forces the Pallas interpreter
    off-TPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.quant import kv as kvq

    h_ = handles
    ptab, page_size = pages
    if view_pages is not None:
        ptab = ptab[:, :view_pages]
    quantized = len(caches) == 4
    if quantized:
        kpool, vpool, kscale, vscale = caches
    else:
        kpool, vpool = caches
    bsz, S = tok.shape
    n_pool_pages = int(kpool.shape[1])
    n_view = int(ptab.shape[1]) * int(page_size)
    rows = jnp.arange(bsz)[:, None]                      # (B, 1)
    scale = 1.0 / np.sqrt(h_.hd)
    if valid is None:
        valid = jnp.ones(tok.shape, bool)
    # scatter coordinates: logical page -> physical pool page; invalid
    # positions target page id n_pool_pages (out of bounds -> dropped)
    phys = jnp.where(valid, ptab[rows, i // page_size], n_pool_pages)
    off = i % page_size
    use_kernel = _PALLAS_SPEC_VERIFY if S > 1 else _PALLAS_PAGED_ATTN
    if use_kernel:
        from bigdl_tpu.ops import pallas_kernels as pk
        kernel_interp = (use_kernel == "interpret") or not pk._on_tpu()
    mask = (jnp.arange(n_view)[None, None, None, :]
            <= i[:, None, :, None])                      # (B, 1, S, T)

    def layernorm(x, p, eps):
        mean = x.mean(axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(x.var(axis=-1, keepdims=True) + eps)
        return (x - mean) * inv * p["~"]["weight"] + p["~"]["bias"]

    def merge(partial):
        return (partial if tp_axis is None
                else jax.lax.psum(partial, tp_axis))

    x = h_.emb["weight"].T[tok] + h_.emb["bias"] + pe[i]   # (B, S, d)
    for li, (ln1, m, ln2, lin1, lin2) in enumerate(h_.blocks):
        a = layernorm(x, ln1, h_.block_eps[li][0])
        q = (a @ m["wq"] + m["bq"]).reshape(bsz, S, h_.n_heads, h_.hd)
        k = (a @ m["wk"] + m["bk"]).reshape(bsz, S, h_.n_heads, h_.hd)
        v = (a @ m["wv"] + m["bv"]).reshape(bsz, S, h_.n_heads, h_.hd)
        if quantized:
            qk, sk = kvq.quantize_rows(k)
            qv, sv = kvq.quantize_rows(v)
            kpool = kpool.at[li, phys, off].set(qk)
            vpool = vpool.at[li, phys, off].set(qv)
            kscale = kscale.at[li, phys, off].set(sk)
            vscale = vscale.at[li, phys, off].set(sv)
        else:
            kpool = kpool.at[li, phys, off].set(k)
            vpool = vpool.at[li, phys, off].set(v)
        if use_kernel:
            # fused page-walk attention: no gathered view, no HBM
            # dequantize pass — scatter above is unchanged.
            o = pk.paged_attention(
                q, kpool[li], vpool[li], ptab, i,
                kscale[li] if quantized else None,
                vscale[li] if quantized else None,
                interpret=kernel_interp,
            ).reshape(bsz, S, h_.n_heads * h_.hd)
        else:
            if quantized:
                kview = kvq.dequantize_view(kpool[li][ptab],
                                            kscale[li][ptab])
                vview = kvq.dequantize_view(vpool[li][ptab],
                                            vscale[li][ptab])
                kview = kview.reshape(bsz, n_view, h_.n_heads, h_.hd)
                vview = vview.reshape(bsz, n_view, h_.n_heads, h_.hd)
            else:
                kview = kpool[li][ptab].reshape(bsz, n_view, h_.n_heads,
                                                h_.hd)
                vview = vpool[li][ptab].reshape(bsz, n_view, h_.n_heads,
                                                h_.hd)
            s = jnp.einsum("bshd,bthd->bhst", q, kview) * scale
            s = jnp.where(mask, s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhst,bthd->bshd", p,
                           vview).reshape(bsz, S, h_.n_heads * h_.hd)
        x = x + merge(o @ m["wo"]) + m["bo"]
        a2 = layernorm(x, ln2, h_.block_eps[li][1])
        h = jax.nn.relu(a2 @ lin1["weight"].T + lin1["bias"])
        x = x + merge(h @ lin2["weight"].T) + lin2["bias"]
    xf = ((x - x.mean(axis=-1, keepdims=True))
          * jax.lax.rsqrt(x.var(axis=-1, keepdims=True) + h_.eps_f)
          * h_.ln_f["weight"] + h_.ln_f["bias"])
    logp = jax.nn.log_softmax(xf @ h_.head["weight"].T + h_.head["bias"])
    if quantized:
        return logp, (kpool, vpool, kscale, vscale)
    return logp, (kpool, vpool)


def _lm_forward_one(tok, i, caches, handles, n_pos, pe, tp_axis=None,
                    pages=None, valid=None, view_pages=None):
    """One decode position for all rows: token ids (B,) at position i
    with per-layer KV caches (layers, B, n_pos, H, hd) -> (log-probs
    (B, vocab), updated caches).  The shared inner body of lm_decode,
    lm_beam_search and the continuous-batching decoder.

    ``pages=(page_table, page_size)`` switches the cache layout to the
    block-paged pools of :func:`_lm_forward_window` (gather/scatter
    through the slot→page table, ``valid`` gating the write) — the same
    math at that row's position, storage indirected through pages.  A
    four-array ``caches`` tuple (int8 pools + per-page-row scales,
    ``BIGDL_SERVE_KV_QUANT``) passes through opaquely to the window's
    quantized storage path.

    ``i`` is either a scalar position (every row at the same step — the
    lock-step scans here) or a per-row (B,) vector (``serve/decode.py``
    slots at independent positions): the cache write scatters per row
    and the causal mask compares against each row's own position, so
    the math per row is IDENTICAL to the scalar path at that row's
    position — the bit-parity contract ``tests/test_serve.py`` holds
    the decoder to.

    ``tp_axis`` names a mesh axis when this body runs INSIDE shard_map
    with Megatron-style tensor parallelism (serve/decode.py TP path):
    ``handles`` then carries the LOCAL shard of each block — attention
    heads split over the axis (wq/wk/wv columns, wo rows, and the KV
    caches on their head dim) and the FFN hidden dim likewise (lin1
    rows, lin2 columns).  The only cross-shard communication is one
    psum after each branch's output projection, with the replicated
    bias added after the sum — per-head/per-hidden-unit math is
    untouched, so the TP decode stays token-identical to one device."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if pages is not None:
        v = None if valid is None else valid[:, None]
        logp, caches = _lm_forward_window(
            tok[:, None], i[:, None], caches, handles, pe, pages,
            valid=v, tp_axis=tp_axis, view_pages=view_pages)
        return logp[:, 0], caches

    h_ = handles
    emb, blocks, block_eps = h_.emb, h_.blocks, h_.block_eps
    n_heads, hd, d_model = h_.n_heads, h_.hd, h_.d_model
    ln_f, eps_f, head = h_.ln_f, h_.eps_f, h_.head
    kcache, vcache = caches
    bsz = tok.shape[0]
    per_row = getattr(i, "ndim", 0) == 1
    rows = jnp.arange(bsz)
    limit = i[:, None, None] if per_row else i
    scale = 1.0 / np.sqrt(hd)

    def layernorm(x, p, eps):
        mean = x.mean(axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(x.var(axis=-1, keepdims=True) + eps)
        return (x - mean) * inv * p["~"]["weight"] + p["~"]["bias"]

    def merge(partial):
        return (partial if tp_axis is None
                else jax.lax.psum(partial, tp_axis))

    x = emb["weight"][:, tok].T + emb["bias"] + pe[i]
    for li, (ln1, m, ln2, lin1, lin2) in enumerate(blocks):
        a = layernorm(x, ln1, block_eps[li][0])
        q = (a @ m["wq"] + m["bq"]).reshape(bsz, n_heads, hd)
        k = (a @ m["wk"] + m["bk"]).reshape(bsz, n_heads, hd)
        v = (a @ m["wv"] + m["bv"]).reshape(bsz, n_heads, hd)
        if per_row:
            kcache = kcache.at[li, rows, i].set(k)
            vcache = vcache.at[li, rows, i].set(v)
        else:
            kcache = kcache.at[li, :, i].set(k)
            vcache = vcache.at[li, :, i].set(v)
        s = jnp.einsum("bhd,bthd->bht", q, kcache[li]) * scale
        s = jnp.where(jnp.arange(n_pos)[None, None, :] <= limit, s,
                      -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bht,bthd->bhd", p,
                       vcache[li]).reshape(bsz, n_heads * hd)
        x = x + merge(o @ m["wo"]) + m["bo"]
        a2 = layernorm(x, ln2, block_eps[li][1])
        h = jax.nn.relu(a2 @ lin1["weight"].T + lin1["bias"])
        x = x + merge(h @ lin2["weight"].T) + lin2["bias"]
    xf = ((x - x.mean(axis=-1, keepdims=True))
          * jax.lax.rsqrt(x.var(axis=-1, keepdims=True) + eps_f)
          * ln_f["weight"] + ln_f["bias"])
    logp = jax.nn.log_softmax(xf @ head["weight"].T + head["bias"])
    return logp, (kcache, vcache)


def lm_decode(model, seed_ids, n_words, greedy: bool = True, key=None,
              temperature: float = 1.0, top_k: int = 0,
              top_p: float = 0.0):
    """KV-cached incremental decoding for a ``TransformerLM`` model.

    Same math as re-forwarding the whole prefix per token
    (``models.rnn.generate``): causal attention at position i reads only
    positions <= i, so the per-layer K/V projections are computed ONCE
    and cached.  The entire decode — seed consumption and generation —
    is a single ``lax.scan`` with static shapes (fixed-size caches
    written via ``.at[i].set``), so it compiles to one TPU program with
    no host round-trip per token; the reference's generation loop
    (rnn/Test.scala:58-90) re-forwards the growing sentence from
    scratch each word.

    ``greedy=True`` takes the argmax; otherwise ``key`` (a JAX PRNG key)
    drives ``jax.random.categorical`` — a different draw stream from
    ``generate``'s host inverse-CDF, same distribution — with optional
    ``temperature`` scaling plus ``top_k`` / ``top_p`` truncation
    through the ONE shared sampler
    (:func:`bigdl_tpu.serve.sampling.sample_tokens` — the served
    continuous decoder filters logits with the same function, so the
    offline and serving paths cannot drift).  Pre-existing
    (temperature, top_k) draws are byte-identical to the historical
    inline math; ``top_p`` in (0, 1) additionally keeps only the
    smallest descending-probability prefix reaching that mass.

    ``seed_ids`` is a flat list of ids (returns the extended flat list)
    or a rectangular batch of B seed rows (returns B extended rows) —
    batched decoding shares ONE scan, with independent draws per row.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.serve.sampling import sample_tokens

    if not greedy and key is None:
        raise ValueError("sampling (greedy=False) needs a PRNG key")
    if temperature <= 0:
        raise ValueError("temperature must be > 0")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError("top_p must be in [0, 1] (0 or 1 = off)")
    handles = _lm_handles(model)
    mods, n_layers = handles.mods, handles.n_layers
    n_heads, hd, vocab = handles.n_heads, handles.hd, handles.vocab

    if len(seed_ids) == 0:
        raise ValueError("lm_decode needs at least one seed token")
    try:
        seed_np = np.asarray(seed_ids, np.int32)
    except (ValueError, TypeError) as e:   # ragged rows
        raise ValueError("seed_ids must be a flat id list or a "
                         "RECTANGULAR batch of seed rows") from e
    flat = seed_np.ndim == 1
    seed_np = np.atleast_2d(seed_np)
    if seed_np.ndim != 2 or seed_np.shape[1] == 0:
        raise ValueError("seed_ids must be a flat id list or a "
                         "rectangular batch of non-empty seed rows")
    seed = jnp.asarray(seed_np)
    bsz, n_seed = int(seed.shape[0]), int(seed.shape[1])
    n_pos = n_seed + int(n_words) - 1      # positions fed through
    pe = jnp.asarray(mods[1].table(n_pos))

    def step(carry, i):
        kcache, vcache, tok, k_rng = carry
        tok = jnp.where(i < n_seed, seed[:, jnp.minimum(i, n_seed - 1)],
                        tok)
        logp, (kcache, vcache) = _lm_forward_one(
            tok, i, (kcache, vcache), handles, n_pos, pe)
        if greedy:
            nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        else:
            k_rng, sub = jax.random.split(k_rng)
            nxt = sample_tokens(logp, sub, temperature, top_k,
                                top_p).astype(jnp.int32)
        return (kcache, vcache, nxt, k_rng), nxt

    k0 = jnp.zeros((n_layers, bsz, n_pos, n_heads, hd), jnp.float32)
    rng0 = key if key is not None else jax.random.PRNGKey(0)
    (_, _, _, _), preds = jax.lax.scan(
        step, (k0, jnp.zeros_like(k0),
               jnp.zeros((bsz,), jnp.int32), rng0),
        jnp.arange(n_pos))
    gen = np.asarray(preds[n_seed - 1:])        # (n_words, B)
    rows = [[int(t) for t in seed_np[b]] + [int(t) for t in gen[:, b]]
            for b in range(bsz)]
    return rows[0] if flat else rows


def lm_beam_search(model, seed_ids, n_words, beam_size: int = 4,
                   return_all: bool = False):
    """Beam-search decoding over the same KV-cache scan as ``lm_decode``.

    Two compiled scans, no host round-trip per token: the seed is
    consumed at batch 1 (beams share the prefix, so a K-wide seed pass
    would be K-times redundant), the caches tile to ``beam_size`` rows,
    and the beam scan does a joint top-k over ``beam_size * vocab``
    continuations plus a beam-reordering gather of every layer's KV
    cache per step.  Beams have equal length (``n_words``
    continuations), so the winner is the highest total log-probability;
    ``return_all=True`` additionally returns every beam's token row and
    score, best first.

    The reference has no beam search (its generation loop samples one
    path, rnn/Test.scala:58-90); this extends the attention family's
    decoder the TPU-native way: the beam dimension is just the batch
    dimension of the cached decode, and reordering is a device-side
    gather.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    if beam_size < 1:
        raise ValueError("beam_size must be >= 1")
    seed_np = np.asarray(seed_ids, np.int32)
    if seed_np.ndim != 1 or seed_np.size == 0:
        raise ValueError("lm_beam_search takes one flat non-empty seed "
                         "id list")
    handles = _lm_handles(model)
    mods, n_layers = handles.mods, handles.n_layers
    n_heads, hd, vocab = handles.n_heads, handles.hd, handles.vocab
    K = int(beam_size)
    n_seed = int(seed_np.size)
    n_pos = n_seed + int(n_words) - 1
    pe = jnp.asarray(mods[1].table(n_pos))
    seed = jnp.asarray(seed_np)

    # ---- seed pass at batch 1: all beams share the prefix
    k0 = jnp.zeros((n_layers, 1, n_pos, n_heads, hd), jnp.float32)

    def seed_step(caches, i):
        _, caches = _lm_forward_one(seed[i][None], i, caches, handles,
                                    n_pos, pe)
        return caches, None

    (kc, vc), _ = jax.lax.scan(seed_step, (k0, jnp.zeros_like(k0)),
                               jnp.arange(n_seed - 1))
    kc = jnp.repeat(kc, K, axis=1)
    vc = jnp.repeat(vc, K, axis=1)

    # ---- beam scan over the generated positions
    def step(carry, i):
        kcache, vcache, tok, scores, gen = carry
        logp, (kcache, vcache) = _lm_forward_one(
            tok, i, (kcache, vcache), handles, n_pos, pe)
        total = (scores[:, None] + logp).reshape(-1)
        scores, flat_idx = jax.lax.top_k(total, K)
        beam_idx = flat_idx // vocab
        nxt = (flat_idx % vocab).astype(jnp.int32)
        # reorder every beam-indexed carry to the surviving beams
        kcache = kcache[:, beam_idx]
        vcache = vcache[:, beam_idx]
        gen = gen[beam_idx].at[:, i - (n_seed - 1)].set(nxt)
        return (kcache, vcache, nxt, scores, gen), None

    # only beam 0 is live at the first expansion, else the top-k would
    # pick the same token K times from identical beams
    scores0 = jnp.full((K,), -jnp.inf).at[0].set(0.0)
    gen0 = jnp.zeros((K, int(n_words)), jnp.int32)
    tok0 = jnp.full((K,), seed[-1], jnp.int32)
    (_, _, _, scores, gen), _ = jax.lax.scan(
        step, (kc, vc, tok0, scores0, gen0),
        jnp.arange(n_seed - 1, n_pos))
    order = np.argsort(-np.asarray(scores))
    rows = [[int(t) for t in seed_np] + [int(t) for t in np.asarray(gen)[b]]
            for b in order]
    if return_all:
        return rows, [float(scores[b]) for b in order]
    return rows[0]


def TransformerClassifier(class_num: int, d_model: int = 128,
                          n_heads: int = 4, n_layers: int = 2,
                          hidden: int = 256, dropout: float = 0.1,
                          causal: bool = False, moe_experts: int = 0):
    """(B, T, d_model) embeddings -> class log-probs.

    The head mirrors the Bi-LSTM text classifier's (mean over time ->
    linear -> LogSoftMax), so the two families slot into the same
    training CLIs and datasets.  ``causal=True`` masks attention
    autoregressively in every block.
    """
    m = nn.Sequential()
    for _ in range(n_layers):
        m.add(encoder_block(d_model, n_heads, hidden, dropout,
                            causal=causal, moe_experts=moe_experts))
    m.add(nn.LayerNorm(d_model))
    m.add(nn.Mean(1, n_input_dims=2))
    m.add(nn.Linear(d_model, class_num))
    m.add(nn.LogSoftMax())
    return m
