"""Transformer encoder classifier — the attention-family flagship.

No counterpart in the reference (its sequence model zoo stops at
RNN/LSTM text classifiers, models/textclassifier); this family exists to
exercise the long-context machinery end to end: `nn.MultiHeadSelfAttention`
(ring attention under ``DistriOptimizer(sequence_parallel=True)``),
`nn.LayerNorm` (per-token — no cross-device stats under any sharding),
and optionally `nn.MoE` FFN blocks (expert-parallel under
``expert_parallel=True``).

Structure per block (pre-LN): x + Attn(LN(x)); x + FFN(LN(x)) — the
residuals use the reference's ConcatTable(Identity, branch) + CAddTable
idiom (same as its ResNet shortcut spelling).
"""
from __future__ import annotations

import bigdl_tpu.nn as nn


def _residual(branch: nn.Module) -> nn.Module:
    return nn.Sequential(nn.ConcatTable(nn.Identity(), branch),
                         nn.CAddTable())


def _ffn(d_model: int, hidden: int, dropout: float,
         moe_experts: int) -> nn.Module:
    if moe_experts > 0:
        return nn.Sequential(nn.MoE(d_model, hidden, moe_experts),
                             nn.Dropout(dropout))
    return nn.Sequential(
        nn.TimeDistributed(nn.Linear(d_model, hidden)),
        nn.ReLU(True),
        nn.Dropout(dropout),
        nn.TimeDistributed(nn.Linear(hidden, d_model)),
    )


def encoder_block(d_model: int, n_heads: int, hidden: int,
                  dropout: float = 0.1, causal: bool = False,
                  moe_experts: int = 0) -> nn.Module:
    return nn.Sequential(
        _residual(nn.Sequential(
            nn.LayerNorm(d_model),
            nn.MultiHeadSelfAttention(d_model, n_heads, causal=causal),
            nn.Dropout(dropout),
        )),
        _residual(nn.Sequential(
            nn.LayerNorm(d_model),
            _ffn(d_model, hidden, dropout, moe_experts),
        )),
    )


def TransformerLM(vocab_size: int, d_model: int = 128, n_heads: int = 4,
                  n_layers: int = 2, hidden: int = 256,
                  dropout: float = 0.1):
    """Causal word LM over (B, T, vocab) one-hot input -> per-token class
    log-probs — the attention-family counterpart of models/rnn.SimpleRNN
    (ref SimpleRNN.scala:23-38): same input/output contract, so it trains
    with ``TimeDistributedCriterion(ClassNLLCriterion)`` and generates
    with ``models.rnn.generate`` unchanged.  Sequence order comes from
    ``nn.SinusoidalPositionalEncoding`` (attention is permutation-
    equivariant; the RNN's recurrence is replaced, not imitated)."""
    m = nn.Sequential(
        nn.TimeDistributed(nn.Linear(vocab_size, d_model)),
        nn.SinusoidalPositionalEncoding(d_model),
    )
    for _ in range(n_layers):
        m.add(encoder_block(d_model, n_heads, hidden, dropout,
                            causal=True))
    m.add(nn.LayerNorm(d_model))
    m.add(nn.TimeDistributed(nn.Sequential(
        nn.Linear(d_model, vocab_size), nn.LogSoftMax())))
    return m


def TransformerClassifier(class_num: int, d_model: int = 128,
                          n_heads: int = 4, n_layers: int = 2,
                          hidden: int = 256, dropout: float = 0.1,
                          causal: bool = False, moe_experts: int = 0):
    """(B, T, d_model) embeddings -> class log-probs.

    The head mirrors the Bi-LSTM text classifier's (mean over time ->
    linear -> LogSoftMax), so the two families slot into the same
    training CLIs and datasets.  ``causal=True`` masks attention
    autoregressively in every block.
    """
    m = nn.Sequential()
    for _ in range(n_layers):
        m.add(encoder_block(d_model, n_heads, hidden, dropout,
                            causal=causal, moe_experts=moe_experts))
    m.add(nn.LayerNorm(d_model))
    m.add(nn.Mean(1, n_input_dims=2))
    m.add(nn.Linear(d_model, class_num))
    m.add(nn.LogSoftMax())
    return m
