"""SimpleRNN language model (ref models/rnn/SimpleRNN.scala:23-38) plus a
Bi-LSTM classifier head (BASELINE config 4).
"""
from __future__ import annotations

import bigdl_tpu.nn as nn


def SimpleRNN(input_size: int = 4000, hidden_size: int = 40,
              output_size: int = 4000, bptt_truncate: int = 4):
    """(ref SimpleRNN.scala:23-38) Recurrent(RnnCell+Tanh) -> per-timestep
    Linear -> LogSoftMax over (N, T, vocab) one-hot input."""
    return nn.Sequential(
        nn.Recurrent(bptt_truncate).add(
            nn.RnnCell(input_size, hidden_size, nn.Tanh())),
        nn.TimeDistributed(nn.Sequential(
            nn.Linear(hidden_size, output_size),
            nn.LogSoftMax())),
    )


def BiLSTMClassifier(input_size: int, hidden_size: int, class_num: int):
    """Bi-LSTM text classifier (BASELINE config 4).  Canonical builder:
    models/textclassifier.TextClassifierBiLSTM (used by the example, the
    bench, and the convergence test); this alias keeps the round-1 name."""
    from bigdl_tpu.models.textclassifier import TextClassifierBiLSTM
    return TextClassifierBiLSTM(class_num, input_size, hidden_size)
