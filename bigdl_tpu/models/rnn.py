"""SimpleRNN language model (ref models/rnn/SimpleRNN.scala:23-38) plus a
Bi-LSTM classifier head (BASELINE config 4).
"""
from __future__ import annotations

import numpy as np

import bigdl_tpu.nn as nn


def SimpleRNN(input_size: int = 4000, hidden_size: int = 40,
              output_size: int = 4000, bptt_truncate: int = 4):
    """(ref SimpleRNN.scala:23-38) Recurrent(RnnCell+Tanh) -> per-timestep
    Linear -> LogSoftMax over (N, T, vocab) one-hot input."""
    return nn.Sequential(
        nn.Recurrent(bptt_truncate).add(
            nn.RnnCell(input_size, hidden_size, nn.Tanh())),
        nn.TimeDistributed(nn.Sequential(
            nn.Linear(hidden_size, output_size),
            nn.LogSoftMax())),
    )


def generate(model, dictionary, seed_ids, n_words, rng=None):
    """Autoregressive word sampling — the reference's rnn/Test.scala
    generation loop (:58-90): forward the sentence, inverse-CDF-sample
    the next word from the last timestep's distribution, append, repeat.

    ``seed_ids``: list of 0-based word ids; returns the extended list.
    The reference samples with ``cumsum.filter(_ < rand).length - 1`` on
    its cumulative array — an off-by-one that can yield -1 when the
    first bucket already exceeds the draw; here the standard inverse-CDF
    index ``(cumsum < rand).sum()`` is used (a documented divergence,
    PARITY.md).  ``rng`` defaults to the framework host stream."""
    import jax.numpy as jnp
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.utils.random import RNG

    if rng is None:
        rng = RNG.np_rng()
    vocab = dictionary.vocab_size() + 1   # + OOV bucket
    ids = [int(i) for i in seed_ids]
    params, state = model.params(), model.state()
    for _ in range(int(n_words)):
        x = np.zeros((1, len(ids), vocab), np.float32)
        x[0, np.arange(len(ids)), ids] = 1.0
        out, _ = model.apply(params, jnp.asarray(x), state,
                             Context(training=False))
        probs = np.exp(np.asarray(out[0, -1], np.float64))
        probs /= probs.sum()
        # clamp: fp rounding can leave cumsum[-1] a hair under 1.0, and
        # a draw above it would index one past the last class
        idx = int((np.cumsum(probs) < rng.uniform()).sum())
        ids.append(min(idx, vocab - 1))
    return ids


def BiLSTMClassifier(input_size: int, hidden_size: int, class_num: int):
    """Bi-LSTM text classifier (BASELINE config 4).  Canonical builder:
    models/textclassifier.TextClassifierBiLSTM (used by the example, the
    bench, and the convergence test); this alias keeps the round-1 name."""
    from bigdl_tpu.models.textclassifier import TextClassifierBiLSTM
    return TextClassifierBiLSTM(class_num, input_size, hidden_size)
