"""SimpleRNN language model (ref models/rnn/SimpleRNN.scala:23-38) plus a
Bi-LSTM classifier head (BASELINE config 4).
"""
from __future__ import annotations

import numpy as np

import bigdl_tpu.nn as nn


def SimpleRNN(input_size: int = 4000, hidden_size: int = 40,
              output_size: int = 4000, bptt_truncate: int = 4):
    """(ref SimpleRNN.scala:23-38) Recurrent(RnnCell+Tanh) -> per-timestep
    Linear -> LogSoftMax over (N, T, vocab) one-hot input."""
    return nn.Sequential(
        nn.Recurrent(bptt_truncate).add(
            nn.RnnCell(input_size, hidden_size, nn.Tanh())),
        nn.TimeDistributed(nn.Sequential(
            nn.Linear(hidden_size, output_size),
            nn.LogSoftMax())),
    )


def adjust_logprobs(logp, temperature: float = 1.0, top_k: int = 0):
    """Renormalized log-probs after temperature scaling and top-k
    truncation (no reference counterpart — rnn/Test.scala samples the
    raw distribution; both knobs default to that behavior)."""
    logp = np.asarray(logp, np.float64)
    if temperature != 1.0:
        if temperature <= 0:
            raise ValueError("temperature must be > 0 (use a small value "
                             "like 1e-3 to approach greedy)")
        logp = logp / temperature
    if top_k and top_k < logp.size:
        kth = np.partition(logp, -top_k)[-top_k]
        logp = np.where(logp >= kth, logp, -np.inf)
    logp = logp - logp.max()
    return logp - np.log(np.exp(logp).sum())


def generate(model, dictionary, seed_ids, n_words, rng=None,
             temperature: float = 1.0, top_k: int = 0):
    """Autoregressive word sampling — the reference's rnn/Test.scala
    generation loop (:58-90): forward the sentence, inverse-CDF-sample
    the next word from the last timestep's distribution, append, repeat.

    ``seed_ids``: list of 0-based word ids; returns the extended list.
    The reference samples with ``cumsum.filter(_ < rand).length - 1`` on
    its cumulative array — an off-by-one that can yield -1 when the
    first bucket already exceeds the draw; here the standard inverse-CDF
    index ``(cumsum < rand).sum()`` is used (a documented divergence,
    PARITY.md).  ``rng`` defaults to the framework host stream;
    ``temperature``/``top_k`` reshape the distribution (defaults = the
    reference's raw sampling)."""
    import jax.numpy as jnp
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.utils.random import RNG

    if rng is None:
        rng = RNG.np_rng()
    vocab = dictionary.vocab_size() + 1   # + OOV bucket
    ids = [int(i) for i in seed_ids]
    params, state = model.params(), model.state()
    for _ in range(int(n_words)):
        x = np.zeros((1, len(ids), vocab), np.float32)
        x[0, np.arange(len(ids)), ids] = 1.0
        out, _ = model.apply(params, jnp.asarray(x), state,
                             Context(training=False))
        logp = adjust_logprobs(out[0, -1], temperature, top_k)
        probs = np.exp(logp)
        probs /= probs.sum()
        # clamp: fp rounding can leave cumsum[-1] a hair under 1.0, and
        # a draw above it would index past the last class — land on the
        # last SUPPORTED class (top_k may have zeroed the tail)
        idx = int((np.cumsum(probs) < rng.uniform()).sum())
        ids.append(min(idx, int(np.flatnonzero(probs)[-1])))
    return ids


def BiLSTMClassifier(input_size: int, hidden_size: int, class_num: int):
    """Bi-LSTM text classifier (BASELINE config 4).  Canonical builder:
    models/textclassifier.TextClassifierBiLSTM (used by the example, the
    bench, and the convergence test); this alias keeps the round-1 name."""
    from bigdl_tpu.models.textclassifier import TextClassifierBiLSTM
    return TextClassifierBiLSTM(class_num, input_size, hidden_size)
