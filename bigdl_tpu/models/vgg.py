"""VGG models (ref models/vgg/VggForCifar10.scala:25, Vgg_16/Vgg_19 :74+)."""
from __future__ import annotations

import bigdl_tpu.nn as nn


def _conv_bn_relu(model, n_in, n_out):
    """convBNReLU helper (ref VggForCifar10.scala convBNReLU)."""
    model.add(nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
    model.add(nn.SpatialBatchNormalization(n_out, 1e-3))
    model.add(nn.ReLU(True))
    return model


def VggForCifar10(class_num: int = 10):
    """(ref VggForCifar10.scala:25-72)"""
    m = nn.Sequential()
    _conv_bn_relu(m, 3, 64).add(nn.Dropout(0.3))
    _conv_bn_relu(m, 64, 64)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    _conv_bn_relu(m, 64, 128).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 128, 128)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    _conv_bn_relu(m, 128, 256).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 256, 256).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 256, 256)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    _conv_bn_relu(m, 256, 512).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 512, 512).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 512, 512)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    _conv_bn_relu(m, 512, 512).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 512, 512).add(nn.Dropout(0.4))
    _conv_bn_relu(m, 512, 512)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    m.add(nn.View(512))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(512, 512))
    m.add(nn.BatchNormalization(512))
    m.add(nn.ReLU(True))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(512, class_num))
    m.add(nn.LogSoftMax())
    return m


def _vgg_block(model, n_in, n_out, n_convs):
    for i in range(n_convs):
        model.add(nn.SpatialConvolution(n_in if i == 0 else n_out, n_out,
                                        3, 3, 1, 1, 1, 1))
        model.add(nn.ReLU(True))
    model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    return model


def _vgg_head(model, class_num):
    model.add(nn.View(512 * 7 * 7))
    model.add(nn.Linear(512 * 7 * 7, 4096))
    model.add(nn.Threshold(0, 1e-6))
    model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096))
    model.add(nn.Threshold(0, 1e-6))
    model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num))
    model.add(nn.LogSoftMax())
    return model


def Vgg_16(class_num: int = 1000):
    """(ref VggForCifar10.scala Vgg_16 :74+) — 224x224 ImageNet VGG-16."""
    m = nn.Sequential()
    _vgg_block(m, 3, 64, 2)
    _vgg_block(m, 64, 128, 2)
    _vgg_block(m, 128, 256, 3)
    _vgg_block(m, 256, 512, 3)
    _vgg_block(m, 512, 512, 3)
    return _vgg_head(m, class_num)


def Vgg_19(class_num: int = 1000):
    """(ref Vgg_19)"""
    m = nn.Sequential()
    _vgg_block(m, 3, 64, 2)
    _vgg_block(m, 64, 128, 2)
    _vgg_block(m, 128, 256, 4)
    _vgg_block(m, 256, 512, 4)
    _vgg_block(m, 512, 512, 4)
    return _vgg_head(m, class_num)
