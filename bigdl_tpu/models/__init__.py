from bigdl_tpu.models import lenet, vgg, inception, resnet, autoencoder, rnn, alexnet, textclassifier
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.models.vgg import VggForCifar10, Vgg_16, Vgg_19
from bigdl_tpu.models.inception import (
    Inception_v1, Inception_v1_NoAuxClassifier, Inception_v2,
)
from bigdl_tpu.models.resnet import ResNet, ResNetCifar, basic_block, bottleneck
from bigdl_tpu.models.autoencoder import Autoencoder
from bigdl_tpu.models.rnn import SimpleRNN, BiLSTMClassifier
from bigdl_tpu.models.alexnet import AlexNet, AlexNet_OWT
from bigdl_tpu.models.textclassifier import (TextClassifierConv,
                                             TextClassifierBiLSTM)

__all__ = [
    "LeNet5", "VggForCifar10", "Vgg_16", "Vgg_19",
    "Inception_v1", "Inception_v1_NoAuxClassifier", "Inception_v2",
    "ResNet", "ResNetCifar", "basic_block", "bottleneck",
    "Autoencoder", "SimpleRNN", "BiLSTMClassifier", "AlexNet", "AlexNet_OWT",
    "TextClassifierConv", "TextClassifierBiLSTM",
]
