from bigdl_tpu.models import lenet, vgg, inception, resnet, autoencoder, rnn, alexnet
