"""AlexNet (ref example/loadmodel/AlexNet.scala — AlexNet + AlexNet_OWT)."""
from __future__ import annotations

import bigdl_tpu.nn as nn


def AlexNet(class_num: int = 1000):
    """Caffe-style AlexNet with grouped convs (ref AlexNet.scala)."""
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 96, 11, 11, 4, 4).set_name("conv1"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2).set_name("conv2"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2).set_name("conv4"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2).set_name("conv5"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.View(256 * 6 * 6))
    m.add(nn.Linear(256 * 6 * 6, 4096).set_name("fc6"))
    m.add(nn.ReLU(True))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096).set_name("fc7"))
    m.add(nn.ReLU(True))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num).set_name("fc8"))
    m.add(nn.LogSoftMax())
    return m


def AlexNet_OWT(class_num: int = 1000, has_dropout: bool = True):
    """One-weird-trick variant without groups/LRN (ref AlexNet.scala)."""
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 64, 11, 11, 4, 4, 2, 2))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.View(256 * 6 * 6))
    m.add(nn.Linear(256 * 6 * 6, 4096))
    m.add(nn.ReLU(True))
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096))
    m.add(nn.ReLU(True))
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num))
    m.add(nn.LogSoftMax())
    return m
