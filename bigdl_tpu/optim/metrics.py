"""Phase metrics (ref optim/Metrics.scala:25).

Named counters for per-iteration phase breakdown ("computing time for each
node", "aggregate gradient time", "get weights average" —
DistriOptimizer.scala:114-118).  The reference keeps THREE kinds of entry
(Metrics.scala: local / aggregate / distributed, where "distributed"
carries one value per node via Spark accumulators); here values are
host-side floats per process, and entries marked distributed gather one
mean per jax process on demand (the accumulator role is
``multihost_utils.process_allgather``).
"""
from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._sums = defaultdict(float)
        self._counts = defaultdict(int)
        self._distributed = set()
        self._per_node_cache = {}

    def set(self, name: str, value: float, distributed: bool = False):
        self._sums[name] = value
        self._counts[name] = 1
        if distributed:
            self._distributed.add(name)

    def add(self, name: str, value: float, distributed: bool = False):
        self._sums[name] += value
        self._counts[name] += 1
        if distributed:
            self._distributed.add(name)

    def accumulate(self, name: str, value: float, count: int = 1,
                   distributed: bool = False):
        """``add`` with an explicit sample count — for intervals timed on
        a background thread and drained in lumps (``count=0`` folds more
        seconds into samples already counted, keeping the mean honest)."""
        self._sums[name] += value
        self._counts[name] += count
        if distributed:
            self._distributed.add(name)

    def get(self, name: str):
        return self._sums[name], self._counts[name]

    def mean(self, name: str) -> float:
        return self._sums[name] / max(self._counts[name], 1)

    def per_node(self, name: str):
        """One mean per jax PROCESS (the reference's per-node accumulator
        readout, Metrics.scala "computing time for each node" consumed by
        DistriOptimizer.scala:114-118).  Single-process: a 1-list.

        Multi-process this is a COLLECTIVE unless a cached snapshot
        exists: DistriOptimizer calls :meth:`collect_per_node` at the end
        of ``optimize()`` — a point every process reaches — so post-
        training ``per_node``/``summary(per_node=True)`` from process 0
        alone reads the cache instead of deadlocking the other hosts
        waiting in ``process_allgather``."""
        import jax
        local = self.mean(name)
        if jax.process_count() == 1:
            return [local]
        if name in self._per_node_cache:
            return list(self._per_node_cache[name])
        import numpy as np
        from jax.experimental import multihost_utils
        vals = multihost_utils.process_allgather(
            np.asarray(local, np.float64))
        return [float(v) for v in np.asarray(vals).reshape(-1)]

    def collect_per_node(self):
        """Eagerly gather the per-process snapshot of every distributed
        entry (collective — every process must call this together); later
        ``per_node``/``summary(per_node=True)`` calls are then local."""
        import jax
        if jax.process_count() == 1:
            return self
        for name in sorted(self._distributed):
            self._per_node_cache.pop(name, None)
            self._per_node_cache[name] = self.per_node(name)
        return self

    def declare(self, name: str, distributed: bool = True):
        """Register an entry with no samples yet (sum 0, count 0).

        Multi-process: ``collect_per_node`` walks THIS process's
        distributed-name set — if a name only ever gets samples on some
        processes (e.g. the checkpoint span: process 0 writes, the rest
        return early), the gather counts would diverge and the
        processes deadlock mid-allgather.  Declaring the full fixed
        name set on every process up front (obs.SpanTracker does this
        for its phase names) keeps the collective schedule identical
        everywhere; undeclared processes simply report a 0.0 mean."""
        self._sums[name] += 0.0
        self._counts[name] += 0
        if distributed:
            self._distributed.add(name)
        return self

    @contextmanager
    def timer(self, name: str, distributed: bool = False):
        # try/finally: a timed body that raises (a failing dispatch, a
        # KeyboardInterrupt mid-fetch) must still record its elapsed
        # time, or the postmortem phase breakdown silently loses exactly
        # the phase that broke
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0,
                     distributed=distributed)

    def summary(self, unit_scale: float = 1.0,
                per_node: bool = False) -> str:
        """(ref Metrics.summary) one line per metric, averaged.

        ``per_node=True`` adds the per-process breakdown for entries
        marked distributed.  CAUTION: that path calls
        ``process_allgather`` — a COLLECTIVE, so with per_node=True every
        jax process must call summary() at the same point or the callers
        deadlock (same contract as any collective).  The default is
        purely local and safe to call from one process."""
        lines = ["========== Metrics Summary =========="]
        for name in sorted(self._sums):
            lines.append(f"{name} : {self.mean(name) * unit_scale}")
            if per_node and name in self._distributed:
                nodes = self.per_node(name)
                if len(nodes) > 1:
                    per = ", ".join(f"{v * unit_scale:.6g}" for v in nodes)
                    lines.append(f"  per node : [{per}]")
        lines.append("=====================================")
        return "\n".join(lines)

    def reset(self):
        self._sums.clear()
        self._counts.clear()
        self._distributed.clear()
        self._per_node_cache.clear()
