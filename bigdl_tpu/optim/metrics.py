"""Phase metrics (ref optim/Metrics.scala:25).

Named counters for per-iteration phase breakdown ("computing time for each
node", "aggregate gradient time", "get weights average" —
DistriOptimizer.scala:114-118).  The reference aggregates via Spark
accumulators; here values are host-side floats (per-process), merged across
hosts by the distributed optimizer when needed.
"""
from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._sums = defaultdict(float)
        self._counts = defaultdict(int)

    def set(self, name: str, value: float):
        self._sums[name] = value
        self._counts[name] = 1

    def add(self, name: str, value: float):
        self._sums[name] += value
        self._counts[name] += 1

    def get(self, name: str):
        return self._sums[name], self._counts[name]

    def mean(self, name: str) -> float:
        return self._sums[name] / max(self._counts[name], 1)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        yield
        self.add(name, time.perf_counter() - t0)

    def summary(self, unit_scale: float = 1.0) -> str:
        """(ref Metrics.summary) one line per metric, averaged."""
        lines = ["========== Metrics Summary =========="]
        for name in sorted(self._sums):
            lines.append(f"{name} : {self.mean(name) * unit_scale}")
        lines.append("=====================================")
        return "\n".join(lines)

    def reset(self):
        self._sums.clear()
        self._counts.clear()
