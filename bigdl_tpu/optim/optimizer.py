"""Optimizer factory (ref optim/Optimizer.scala:30,151-186): picks Local vs
Distri from the dataset type, exactly as the reference dispatches on
LocalDataSet vs DistributedDataSet.
"""
from __future__ import annotations

from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, ShardedDataSet, TransformedDataSet,
)
from bigdl_tpu.optim.local_optimizer import LocalOptimizer
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer


def _root_dataset(ds):
    while isinstance(ds, TransformedDataSet):
        ds = ds.base
    return ds


def is_distributed_dataset(ds):
    """ONE predicate for "this dataset shards per process" — shared by the
    Optimizer factory's Local/Distri routing and the multi-host pipeline
    guard."""
    root = _root_dataset(ds)
    return isinstance(root, ShardedDataSet) or getattr(
        root, "distributed", False)


def Optimizer(model, dataset=None, criterion=None, *, training_rdd=None,
              optim_method=None, state=None, end_trigger=None,
              batch_size=None, **kwargs):
    """(ref Optimizer.apply :151-186) — also accepts the reference's
    Python-API keyword signature (python/optim/optimizer.py):
    Optimizer(model=..., training_rdd=samples, criterion=...,
    optim_method=..., state=T(...), end_trigger=MaxEpoch(n), batch_size=b).
    """
    if training_rdd is not None:
        from bigdl_tpu.dataset.transformer import SampleToBatch
        from bigdl_tpu.dataset.dataset import DataSet
        if batch_size is None:
            raise ValueError("batch_size is required with training_rdd")
        dataset = (DataSet.array(list(training_rdd), distributed=True)
                   >> SampleToBatch(batch_size, drop_last=True))
    if is_distributed_dataset(dataset):
        opt = DistriOptimizer(model, dataset, criterion, **kwargs)
    else:
        opt = LocalOptimizer(model, dataset, criterion)
    if optim_method is not None:
        opt.set_optim_method(optim_method)
    if state is not None:
        opt.set_state(state)
    if end_trigger is not None:
        opt.set_end_when(end_trigger)
    return opt


def list_checkpoints(path):
    """Iteration labels of the ``model.N``/``state.N`` snapshot pairs
    under ``path``, newest first (CRC sidecars and temp files ignored).
    Pairs only: a ``model.N`` whose ``state.N`` is missing (crash between
    the two writes) is not a resumable snapshot."""
    from bigdl_tpu.utils import fs
    try:
        names = fs.listdir(path)
    except (FileNotFoundError, OSError):
        return []

    def labels(prefix):
        return {int(f[len(prefix):]) for f in names
                if f.startswith(prefix) and f[len(prefix):].isdigit()}

    return sorted(labels("model.") & labels("state."), reverse=True)


def load_latest_checkpoint(path, restore_rng: bool = False):
    """Newest VALID snapshot under ``path`` — the resume entry point.

    Scans ``model.N``/``state.N`` pairs newest-first; the loads verify
    against the CRC sidecars (and unpickle), so corrupt or partial
    snapshots (bit flips, truncated writes, crash between the pair's two
    writes) are logged and skipped, falling back to the next older pair —
    a chaos-injected checkpoint failure costs at most one checkpoint
    interval of retraining, never the run.  Each candidate is read once
    (no separate verify pre-pass: checkpoints can be multi-GB).

    Returns ``(module, state_blob, neval)`` or ``None`` when no valid
    snapshot exists (caller starts fresh).  ``restore_rng=True`` also
    restores the host RNG stream snapshotted into the payload
    (``RandomGenerator.restore``), so resumed data augmentation replays
    the uninterrupted run's stream.
    """
    import logging

    from bigdl_tpu.utils import file as File
    from bigdl_tpu.utils import fs
    logger = logging.getLogger("bigdl_tpu.optim")
    for neval in list_checkpoints(path):
        mp = fs.join(path, f"model.{neval}")
        sp = fs.join(path, f"state.{neval}")
        try:
            module = File.load_module(mp)
            blob = File.load(sp)
        except File.ChecksumError as e:
            logger.warning("resume: snapshot %d under %s is corrupt or "
                           "partial (%s) — skipping to an older one",
                           neval, path, e)
            continue
        except Exception as e:
            logger.warning("resume: snapshot %d under %s failed to load "
                           "(%s) — skipping to an older one", neval, path, e)
            continue
        if restore_rng and blob.get("rng") is not None:
            from bigdl_tpu.utils.random import RNG
            RNG.restore(blob["rng"])
        logger.info("resume: loaded snapshot %d from %s", neval, path)
        return module, blob, neval
    return None


def save_model(model, path, overwrite: bool = False):
    """(ref Optimizer.saveModel Optimizer.scala:137-143; like the
    reference, refuses to clobber an existing file unless asked)"""
    from bigdl_tpu.utils import file as File
    File.save_module(model, path, overwrite=overwrite)
    return path


def save_state(state, path, overwrite: bool = False):
    """(ref Optimizer.saveState Optimizer.scala:145-149; refuses to
    clobber an existing file unless asked)"""
    from bigdl_tpu.utils import file as File
    File.save(state, path, overwrite=overwrite)
    return path
