"""Optimizer factory (ref optim/Optimizer.scala:30,151-186): picks Local vs
Distri from the dataset type, exactly as the reference dispatches on
LocalDataSet vs DistributedDataSet.
"""
from __future__ import annotations

from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, ShardedDataSet, TransformedDataSet,
)
from bigdl_tpu.optim.local_optimizer import LocalOptimizer
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer


def _root_dataset(ds):
    while isinstance(ds, TransformedDataSet):
        ds = ds.base
    return ds


def Optimizer(model, dataset, criterion, **kwargs):
    """(ref Optimizer.apply :151-186)"""
    root = _root_dataset(dataset)
    if isinstance(root, ShardedDataSet) or getattr(root, "distributed", False):
        return DistriOptimizer(model, dataset, criterion, **kwargs)
    return LocalOptimizer(model, dataset, criterion)
