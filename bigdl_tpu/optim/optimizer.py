"""Optimizer factory (ref optim/Optimizer.scala:30,151-186): picks Local vs
Distri from the dataset type, exactly as the reference dispatches on
LocalDataSet vs DistributedDataSet.
"""
from __future__ import annotations

import os

from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, ShardedDataSet, TransformedDataSet,
)
from bigdl_tpu.optim.local_optimizer import LocalOptimizer
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer


def _root_dataset(ds):
    while isinstance(ds, TransformedDataSet):
        ds = ds.base
    return ds


def is_distributed_dataset(ds):
    """ONE predicate for "this dataset shards per process" — shared by the
    Optimizer factory's Local/Distri routing and the multi-host pipeline
    guard."""
    root = _root_dataset(ds)
    return isinstance(root, ShardedDataSet) or getattr(
        root, "distributed", False)


def Optimizer(model, dataset=None, criterion=None, *, training_rdd=None,
              optim_method=None, state=None, end_trigger=None,
              batch_size=None, **kwargs):
    """(ref Optimizer.apply :151-186) — also accepts the reference's
    Python-API keyword signature (python/optim/optimizer.py):
    Optimizer(model=..., training_rdd=samples, criterion=...,
    optim_method=..., state=T(...), end_trigger=MaxEpoch(n), batch_size=b).
    """
    if training_rdd is not None:
        from bigdl_tpu.dataset.transformer import SampleToBatch
        from bigdl_tpu.dataset.dataset import DataSet
        if batch_size is None:
            raise ValueError("batch_size is required with training_rdd")
        dataset = (DataSet.array(list(training_rdd), distributed=True)
                   >> SampleToBatch(batch_size, drop_last=True))
    if is_distributed_dataset(dataset):
        opt = DistriOptimizer(model, dataset, criterion, **kwargs)
    else:
        opt = LocalOptimizer(model, dataset, criterion)
    if optim_method is not None:
        opt.set_optim_method(optim_method)
    if state is not None:
        opt.set_state(state)
    if end_trigger is not None:
        opt.set_end_when(end_trigger)
    return opt


def list_checkpoints(path):
    """Iteration labels of the ``model.N``/``state.N`` snapshot pairs
    under ``path``, newest first (CRC sidecars and temp files ignored).
    Pairs only: a ``model.N`` whose ``state.N`` is missing (crash between
    the two writes) is not a resumable snapshot."""
    from bigdl_tpu.utils import fs
    try:
        names = fs.listdir(path)
    except (FileNotFoundError, OSError):
        return []

    def labels(prefix):
        return {int(f[len(prefix):]) for f in names
                if f.startswith(prefix) and f[len(prefix):].isdigit()}

    return sorted(labels("model.") & labels("state."), reverse=True)


def load_latest_checkpoint(path, restore_rng: bool = False):
    """Newest VALID snapshot under ``path`` — the resume entry point.

    Scans ``model.N``/``state.N`` pairs newest-first; the loads verify
    against the CRC sidecars (and unpickle), so corrupt or partial
    snapshots (bit flips, truncated writes, crash between the pair's two
    writes) are logged and skipped, falling back to the next older pair —
    a chaos-injected checkpoint failure costs at most one checkpoint
    interval of retraining, never the run.  Each candidate is read once
    (no separate verify pre-pass: checkpoints can be multi-GB).

    Sharded snapshots (``blob["opt_shards"] == n``, written by the async
    sharded path — ``resilience/checkpoint.py``) additionally load their
    ``state.N.shard<r>of<n>`` files and reassemble the FULL optimizer
    state, so the returned blob is world-size-agnostic: a checkpoint
    taken at dp=4 restores at dp=3 or dp=1 (the restoring optimizer
    re-partitions over its own mesh).  A corrupt or missing shard fails
    the whole snapshot (optimizer state must be complete or absent,
    never silently partial) and the scan falls back to an older pair.

    Returns ``(module, state_blob, neval)`` or ``None`` when no valid
    snapshot exists (caller starts fresh).  ``restore_rng=True`` also
    restores the host RNG stream snapshotted into the payload
    (``RandomGenerator.restore``), so resumed data augmentation replays
    the uninterrupted run's stream.
    """
    import logging

    from bigdl_tpu.utils import file as File
    from bigdl_tpu.utils import fs
    logger = logging.getLogger("bigdl_tpu.optim")
    for neval in list_checkpoints(path):
        mp = fs.join(path, f"model.{neval}")
        sp = fs.join(path, f"state.{neval}")
        try:
            module = File.load_module(mp)
            blob = File.load(sp)
            n_shards = int(blob.get("opt_shards") or 0)
            if n_shards:
                from bigdl_tpu.resilience.checkpoint import (
                    assemble_sharded_state, shard_file)
                shards = [File.load(shard_file(path, neval, r, n_shards))
                          for r in range(n_shards)]
                blob["opt_state"] = assemble_sharded_state(
                    blob["opt_state"], shards)
        except File.ChecksumError as e:
            logger.warning("resume: snapshot %d under %s is corrupt or "
                           "partial (%s) — skipping to an older one",
                           neval, path, e)
            continue
        except Exception as e:
            logger.warning("resume: snapshot %d under %s failed to load "
                           "(%s) — skipping to an older one", neval, path, e)
            continue
        if restore_rng and blob.get("rng") is not None:
            from bigdl_tpu.utils.random import RNG
            RNG.restore(blob["rng"])
        logger.info("resume: loaded snapshot %d from %s", neval, path)
        return module, blob, neval
    return None


def snapshot_files(path, neval):
    """Every file belonging to snapshot ``neval`` under ``path`` (model,
    state, shard files, CRC sidecars) — the unit retention deletes."""
    from bigdl_tpu.utils import fs
    try:
        names = fs.listdir(path)
    except (FileNotFoundError, OSError):
        return []
    prefixes = (f"model.{neval}", f"state.{neval}")
    out = []
    for f in names:
        stem = f[:-len(".crc32")] if f.endswith(".crc32") else f
        if stem in prefixes or stem.startswith(f"state.{neval}.shard"):
            out.append(f)
    return out


def shard_set_complete(path, neval, names=None) -> bool:
    """True when snapshot ``neval``'s shard files form a complete set.
    The expected count is parsed from the ``shard<r>of<n>`` names (the
    same writer emits its own shard before ``state.N``, so a sharded
    snapshot with a ``state.N`` always has at least one shard file to
    read ``n`` from) — no payload unpickling.  A snapshot with no shard
    files is trivially complete (whole-tree path)."""
    from bigdl_tpu.utils import fs
    if names is None:
        try:
            names = fs.listdir(path)
        except (FileNotFoundError, OSError):
            return False
    prefix = f"state.{neval}.shard"
    shards = [f for f in names
              if f.startswith(prefix) and not f.endswith(".crc32")]
    if not shards:
        return True
    try:
        n = int(shards[0].rsplit("of", 1)[1])
    except (IndexError, ValueError):
        return False
    want = {f"{prefix}{r}of{n}" for r in range(n)}
    return want <= set(names)


def snapshot_valid(path, neval) -> bool:
    """CRC-verify every file of snapshot ``neval`` (model, state, and
    any shard files) without unpickling the payloads twice.  A sharded
    snapshot missing any of its shard files (a rank died before its
    write landed) is invalid — it can never reassemble."""
    from bigdl_tpu.utils import file as File
    from bigdl_tpu.utils import fs
    files = [f for f in snapshot_files(path, neval)
             if not f.endswith(File.CRC_SUFFIX)]
    if not files:
        return False
    if not shard_set_complete(path, neval):
        return False
    return all(File.verify(fs.join(path, f)) for f in files)


def prune_checkpoints(path, keep: int, just_written=None):
    """Keep-last-``keep`` retention over the ``model.N``/``state.N``
    snapshot pairs (shard files and CRC sidecars ride along with their
    label).  The newest CRC-VALID snapshot is always retained even when
    it falls outside the keep window — a corrupt latest snapshot must
    never leave the directory with nothing to resume from.
    ``just_written``: the label the caller wrote (and checksummed)
    moments ago — when it is the newest, the full read-back CRC scan is
    skipped (retention after every snapshot must not double the
    checkpoint I/O).  Deletion failures are logged, not raised
    (retention is housekeeping; the training run matters more)."""
    import logging

    from bigdl_tpu.utils import fs
    logger = logging.getLogger("bigdl_tpu.optim")
    keep = int(keep or 0)
    if keep <= 0:
        return []
    labels = list_checkpoints(path)   # newest first
    if not labels:
        return []
    victims = []
    if len(labels) > keep:
        # just_written vouches only for THIS rank's files — a sharded
        # snapshot still needs every other rank's shard on disk before
        # it can anchor retention (a rank killed mid-write must not let
        # the last complete snapshot be pruned)
        if just_written is not None and \
                int(just_written) == labels[0] and \
                shard_set_complete(path, labels[0]):
            newest_valid = labels[0]
        else:
            newest_valid = next(
                (n for n in labels if snapshot_valid(path, n)), None)
        victims = [n for n in labels[keep:] if n != newest_valid]
    # orphan sweep: shard files whose model/state pair is already gone
    # (a failed delete in an earlier prune, or a lagging rank's async
    # writer landing after the pair was pruned) never reappear in
    # list_checkpoints, so without this they would leak forever.  Only
    # labels OLDER than the newest pair qualify — a shard landing ahead
    # of its still-in-flight state.N must not be swept.
    try:
        names = fs.listdir(path)
    except (FileNotFoundError, OSError):
        names = []
    known = set(labels)
    for f in names:
        stem = f[:-len(".crc32")] if f.endswith(".crc32") else f
        if stem.startswith("state.") and ".shard" in stem:
            lab = stem[len("state."):stem.index(".shard")]
            if lab.isdigit() and int(lab) not in known \
                    and int(lab) < labels[0] and int(lab) not in victims:
                victims.append(int(lab))
    removed = []
    for n in victims:
        for f in snapshot_files(path, n):
            full = fs.join(path, f)
            try:
                if fs.is_url(full):  # pragma: no cover - object stores
                    fsys, p = fs._fs(full)
                    fsys.rm(p)
                else:
                    os.remove(full)
                removed.append(f)
            except OSError as e:
                logger.warning("checkpoint retention: could not remove "
                               "%s: %s", full, e)
    if removed:
        logger.info("checkpoint retention: pruned %d file(s) beyond the "
                    "newest %d snapshot(s) under %s",
                    len(removed), keep, path)
    return removed


def save_model(model, path, overwrite: bool = False):
    """(ref Optimizer.saveModel Optimizer.scala:137-143; like the
    reference, refuses to clobber an existing file unless asked)"""
    from bigdl_tpu.utils import file as File
    File.save_module(model, path, overwrite=overwrite)
    return path


def save_state(state, path, overwrite: bool = False):
    """(ref Optimizer.saveState Optimizer.scala:145-149; refuses to
    clobber an existing file unless asked)"""
    from bigdl_tpu.utils import file as File
    File.save(state, path, overwrite=overwrite)
    return path
