"""Optimizer factory (ref optim/Optimizer.scala:30,151-186): picks Local vs
Distri from the dataset type, exactly as the reference dispatches on
LocalDataSet vs DistributedDataSet.
"""
from __future__ import annotations

from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, ShardedDataSet, TransformedDataSet,
)
from bigdl_tpu.optim.local_optimizer import LocalOptimizer
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer


def _root_dataset(ds):
    while isinstance(ds, TransformedDataSet):
        ds = ds.base
    return ds


def is_distributed_dataset(ds):
    """ONE predicate for "this dataset shards per process" — shared by the
    Optimizer factory's Local/Distri routing and the multi-host pipeline
    guard."""
    root = _root_dataset(ds)
    return isinstance(root, ShardedDataSet) or getattr(
        root, "distributed", False)


def Optimizer(model, dataset=None, criterion=None, *, training_rdd=None,
              optim_method=None, state=None, end_trigger=None,
              batch_size=None, **kwargs):
    """(ref Optimizer.apply :151-186) — also accepts the reference's
    Python-API keyword signature (python/optim/optimizer.py):
    Optimizer(model=..., training_rdd=samples, criterion=...,
    optim_method=..., state=T(...), end_trigger=MaxEpoch(n), batch_size=b).
    """
    if training_rdd is not None:
        from bigdl_tpu.dataset.transformer import SampleToBatch
        from bigdl_tpu.dataset.dataset import DataSet
        if batch_size is None:
            raise ValueError("batch_size is required with training_rdd")
        dataset = (DataSet.array(list(training_rdd), distributed=True)
                   >> SampleToBatch(batch_size, drop_last=True))
    if is_distributed_dataset(dataset):
        opt = DistriOptimizer(model, dataset, criterion, **kwargs)
    else:
        opt = LocalOptimizer(model, dataset, criterion)
    if optim_method is not None:
        opt.set_optim_method(optim_method)
    if state is not None:
        opt.set_state(state)
    if end_trigger is not None:
        opt.set_end_when(end_trigger)
    return opt


def save_model(model, path, overwrite: bool = False):
    """(ref Optimizer.saveModel Optimizer.scala:137-143; like the
    reference, refuses to clobber an existing file unless asked)"""
    from bigdl_tpu.utils import file as File
    File.save_module(model, path, overwrite=overwrite)
    return path


def save_state(state, path, overwrite: bool = False):
    """(ref Optimizer.saveState Optimizer.scala:145-149; refuses to
    clobber an existing file unless asked)"""
    from bigdl_tpu.utils import file as File
    File.save(state, path, overwrite=overwrite)
    return path
