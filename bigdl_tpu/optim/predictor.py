"""Batch inference (the DLClassifier / Module.predict role:
ref org/apache/spark/ml/DLClassifier.scala:37-140 and
PythonBigDL.modelPredictRDD :231).

The reference wraps a trained Module as a Spark ML Transformer for
DataFrame batch scoring; here ``Predictor`` is a thin SYNCHRONOUS
wrapper over :class:`bigdl_tpu.serve.ServeEngine` — there is exactly one
compiled-forward inference path in the codebase (docs/serving.md).  The
engine buckets and zero-pads batches (the old standalone loop padded the
tail chunk with host-side ``np.repeat`` copies of the last row), keeps
the weights pinned on device, and never cold-compiles after warmup.

**Capture semantics**: parameters and state are captured ONCE, at
construction (matching the reference, whose DLClassifier holds a trained
Module snapshot).  Training the model afterwards does NOT change what
``predict`` returns until :meth:`refresh` re-captures the module tree's
current weights (same shapes, so nothing recompiles).

**Behavior change vs the old standalone loop**: rows containing
non-finite values now raise ``serve.PoisonedRequestError`` from
``predict`` (the engine fails poisoned rows' futures instead of
forwarding NaN/Inf into the model silently); finite rows are unaffected.
"""
from __future__ import annotations

import numpy as np


class Predictor:
    def __init__(self, model, batch_size: int = 128, policy=None):
        from bigdl_tpu.serve import ServeEngine
        self.model = model
        self.batch_size = batch_size
        self._engine = ServeEngine(model, max_batch=batch_size,
                                   policy=policy)

    def refresh(self):
        """Re-capture the model's CURRENT params/state (see the module
        docstring for the capture contract)."""
        self._engine.refresh()
        return self

    def predict(self, features) -> np.ndarray:
        """Forward all rows; returns stacked outputs (n, ...)."""
        features = np.asarray(features)
        futs = self._engine.submit_many(features)
        return np.stack([f.result() for f in futs])

    def predict_class(self, features) -> np.ndarray:
        """Argmax class, 1-based (the DLClassifier 'predict' column)."""
        return self.predict(features).argmax(axis=-1) + 1

    def close(self):
        self._engine.close()

    def __del__(self):  # pragma: no cover - gc-timing dependent
        try:
            self._engine.close(drain=False)
        except Exception:
            pass


class DLClassifier(Predictor):
    """API-parity alias: ``transform(rows)`` returns (rows, predictions)
    pairs, the DataFrame-ish shape of DLClassifier.process :72-130."""

    def transform(self, rows):
        feats = np.asarray([r[0] if isinstance(r, (tuple, list)) else r
                            for r in rows])
        preds = self.predict_class(feats)
        return list(zip(rows, preds.tolist()))
