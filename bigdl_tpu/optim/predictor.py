"""Batch inference (the DLClassifier / Module.predict role:
ref org/apache/spark/ml/DLClassifier.scala:37-140 and
PythonBigDL.modelPredictRDD :231).

The reference wraps a trained Module as a Spark ML Transformer for
DataFrame batch scoring; here ``Predictor`` maps any array / iterable of
features through a jit-compiled forward in fixed-size batches (the last
partial batch is padded, then trimmed — keeping one compiled shape).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Context


class Predictor:
    def __init__(self, model, batch_size: int = 128):
        self.model = model
        self.batch_size = batch_size
        params = model.params()
        state = model.state()

        @jax.jit
        def fwd(x):
            out, _ = model.apply(params, x, state,
                                 Context(training=False, key=jax.random.PRNGKey(0)))
            return out

        self._fwd = fwd

    def predict(self, features) -> np.ndarray:
        """Forward all rows; returns stacked outputs (n, ...)."""
        features = np.asarray(features)
        n = features.shape[0]
        outs = []
        for start in range(0, n, self.batch_size):
            chunk = features[start:start + self.batch_size]
            pad = self.batch_size - chunk.shape[0]
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, 0)])
            out = np.asarray(self._fwd(jnp.asarray(chunk)))
            outs.append(out[:out.shape[0] - pad] if pad else out)
        return np.concatenate(outs)

    def predict_class(self, features) -> np.ndarray:
        """Argmax class, 1-based (the DLClassifier 'predict' column)."""
        return self.predict(features).argmax(axis=-1) + 1


class DLClassifier(Predictor):
    """API-parity alias: ``transform(rows)`` returns (rows, predictions)
    pairs, the DataFrame-ish shape of DLClassifier.process :72-130."""

    def transform(self, rows):
        feats = np.asarray([r[0] if isinstance(r, (tuple, list)) else r
                            for r in rows])
        preds = self.predict_class(feats)
        return list(zip(rows, preds.tolist()))
