from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adagrad,
    LearningRateSchedule, Default, Step, Poly, EpochDecay, EpochStep,
    EpochSchedule,
)
from bigdl_tpu.optim.lbfgs import LBFGS
from bigdl_tpu.optim import trigger as Trigger
from bigdl_tpu.optim.trigger import (
    every_epoch, several_iteration, max_epoch, max_iteration, min_loss,
)
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, AccuracyResult, LossResult,
    Top1Accuracy, Top5Accuracy, Loss, EvaluateMethods,
)
from bigdl_tpu.optim.validator import Validator, LocalValidator, DistriValidator
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.local_optimizer import (
    LocalOptimizer, NonFiniteGradError, validate, distri_validate,
)
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.optim.optimizer import (
    Optimizer, list_checkpoints, load_latest_checkpoint, save_model,
    save_state,
)
from bigdl_tpu.optim.predictor import Predictor, DLClassifier

__all__ = [
    "OptimMethod", "SGD", "Adagrad", "LBFGS",
    "LearningRateSchedule", "Default", "Step", "Poly", "EpochDecay",
    "EpochStep", "EpochSchedule",
    "Trigger", "every_epoch", "several_iteration", "max_epoch",
    "max_iteration", "min_loss",
    "ValidationMethod", "ValidationResult", "AccuracyResult", "LossResult",
    "Top1Accuracy", "Top5Accuracy", "Loss", "EvaluateMethods", "Metrics",
    "Validator", "LocalValidator", "DistriValidator",
    "LocalOptimizer", "DistriOptimizer", "Optimizer", "validate",
    "distri_validate", "Predictor", "DLClassifier",
    "save_model", "save_state", "list_checkpoints",
    "load_latest_checkpoint", "NonFiniteGradError",
]
