"""Validator classes (ref optim/Validator.scala:24, LocalValidator.scala:30,
DistriValidator.scala:32).

The reference exposes evaluation as ``Validator(model, dataset).test(methods)``
with a Local/Distri split chosen by dataset type; the computation itself lives
in :func:`bigdl_tpu.optim.local_optimizer.validate` /
:func:`~bigdl_tpu.optim.local_optimizer.distri_validate`.  These classes keep
that API shape for users coming from the reference.

Both paths route the last PARTIAL batch through the serve bucket
pad-and-trim helper (``serve/bucketing.py``), so an eval pass compiles
exactly one forward shape — the odd tail no longer costs a second XLA
compile (docs/serving.md).
"""
from __future__ import annotations

from bigdl_tpu.optim.local_optimizer import validate, distri_validate


class Validator:
    """Evaluate a model over a dataset (ref Validator.scala:24).

    ``Validator(model, dataset)`` picks Local vs Distri semantics the way the
    reference's ``Validator()`` factory does (Validator.scala:44–52): a
    dataset that reports itself as distributed/sharded evaluates with
    cross-host result merging.
    """

    def __init__(self, model, dataset):
        self.model = model
        self.dataset = dataset

    def _fn(self):
        from bigdl_tpu.dataset.dataset import DistributedDataSet, ShardedDataSet
        if isinstance(self.dataset, (DistributedDataSet, ShardedDataSet)):
            return distri_validate
        return validate

    def test(self, methods, params=None, net_state=None):
        """Run every ValidationMethod over the dataset; returns
        ``[(method, result)]`` (ref Validator.test)."""
        params = params if params is not None else self.model.params()
        net_state = net_state if net_state is not None else self.model.state()
        return self._fn()(self.model, params, net_state, self.dataset, methods)


class LocalValidator(Validator):
    """Single-process evaluation (ref LocalValidator.scala:30)."""

    def _fn(self):
        return validate


class DistriValidator(Validator):
    """Multi-host evaluation with result merge (ref DistriValidator.scala:32)."""

    def _fn(self):
        return distri_validate
