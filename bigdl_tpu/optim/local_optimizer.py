"""LocalOptimizer — single-host training with one compiled step
(ref optim/LocalOptimizer.scala:40, call stack SURVEY.md §3.2).

The reference clones coreNumber model replicas on JVM threads and reduces
their gradients slice-wise; on TPU one ``jit``-compiled
forward+loss+grad+update over the full local batch saturates the chip, so
the replica machinery dissolves (SURVEY.md §2.9: intra-node splitting is a
JVM-thread artifact).  What is kept, capability-for-capability:

- iteration loop with epoch/neval state Table (keys match the reference for
  checkpoint parity),
- throughput + data-fetch vs train-time logging (LocalOptimizer.scala:151),
- Trigger-driven validation and checkpointing,
- OptimMethod with Table config (SGD schedules update the lr host-side;
  the scalar feeds the compiled step as an argument, so no retrace).
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset import prefetch as prefetch_mod
from bigdl_tpu.nn.module import Context
from bigdl_tpu.obs import events as obs_events
from bigdl_tpu.obs import taps as obs_taps
from bigdl_tpu.obs.spans import SpanTracker
from bigdl_tpu.optim.optim_method import SGD, OptimMethod, Default
from bigdl_tpu.optim import trigger as triggers
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.log import warn_every
from bigdl_tpu.utils.random import RNG

logger = logging.getLogger("bigdl_tpu.optim")


class NonFiniteGradError(RuntimeError):
    """Training aborted: non-finite gradients for more consecutive steps
    than the abort threshold (``set_nonfinite_policy`` /
    ``BIGDL_NONFINITE_ABORT``) — the run has diverged and skipping can no
    longer save it."""


def _finite_all(loss, grads):
    """One scalar: loss AND every gradient leaf finite.  Computed inside
    the existing jit step (a handful of VPU reductions fused into the
    backward), so the happy path pays no extra dispatch."""
    finite = jnp.all(jnp.isfinite(loss))
    for g in jax.tree_util.tree_leaves(grads):
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return finite


def _where_finite(finite, new_tree, old_tree):
    """Skip-step select: keep the pre-step value on every leaf when the
    step produced non-finite gradients (the update, optimizer state and
    BN running stats are all poisoned by one NaN)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(finite, n, o), new_tree, old_tree)


class _PendingStep:
    """One dispatched-but-not-yet-synced iteration: device scalars (loss,
    finite flag, tap dict) plus the host-side bookkeeping captured at
    dispatch time, held until the next cadence flush."""

    __slots__ = ("neval0", "epoch", "count", "loss", "finite", "taps",
                 "lr", "records", "fetch_t", "train_t", "extra")

    def __init__(self, neval0, epoch, count, loss, finite, taps, lr,
                 records, fetch_t, train_t, extra):
        self.neval0 = neval0
        self.epoch = epoch
        self.count = count
        self.loss = loss
        self.finite = finite
        self.taps = taps
        self.lr = lr
        self.records = records
        self.fetch_t = fetch_t
        self.train_t = train_t
        self.extra = extra


class _HostSyncWindow:
    """Cadence-gated device→host synchronization for the training loops
    (docs/observability.md "host pipeline").

    The serial loop ended every iteration in ``float(loss)`` — an
    80–120 ms device→host round-trip on relay-attached chips
    (PERF_NOTES).  Instead the loop now parks each step's device scalars
    here and materializes them in one blocking batch every ``cadence``
    iterations (the same elapsed-iterations gate, and therefore the same
    boundaries, as ``obs.taps.TapsMonitor``), at epoch/validation/
    checkpoint boundaries, on preemption, and at run end.  The in-jit
    skip-step guard (PR 1) keeps params safe between syncs.

    ``flush_steps``/``flush_reasons`` are the audit trail the sync-count
    test asserts on: host syncs happen at flush boundaries, nowhere else.
    """

    def __init__(self, cadence: int):
        self.cadence = max(1, int(cadence))
        self.pending: list[_PendingStep] = []
        self._last_flush = 0
        self._t0 = None
        self.flush_steps = deque(maxlen=1024)
        self.flush_reasons = deque(maxlen=1024)

    def arm(self):
        """Start the window wall clock — called at the top of the first
        iteration the window covers, so the flushed throughput spans
        fetch + dispatch + sync like the serial per-step number did."""
        if self._t0 is None:
            self._t0 = time.perf_counter()

    def push(self, entry: _PendingStep):
        self.arm()
        self.pending.append(entry)

    def due(self) -> bool:
        """Same chunk-safe gate as ``TapsMonitor``: at least ``cadence``
        iterations have begun since the last flushed step."""
        return bool(self.pending) and \
            (self.pending[-1].neval0 - self._last_flush) >= self.cadence

    def flush(self):
        """Materialize every pending step (the only device→host block in
        the loop).  Returns (entries, losses, finites, window_wall)."""
        entries, self.pending = self.pending, []
        losses = [np.asarray(e.loss) for e in entries]
        finites = [np.asarray(e.finite) for e in entries]
        wall = (time.perf_counter() - self._t0) if self._t0 else 0.0
        self._t0 = None
        if entries:
            self._last_flush = entries[-1].neval0
        return entries, losses, finites, wall


class LocalOptimizer:
    def __init__(self, model, dataset, criterion):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method: OptimMethod = SGD()
        self.state = T()
        self.end_when = triggers.max_epoch(10)
        self.validation_trigger = None
        self.validation_dataset = None
        self.validation_methods = None
        self.checkpoint_trigger = None
        self.checkpoint_path = None
        self.metrics = Metrics()
        self.remat = False
        self._resume_opt_state = None
        self.iters_per_dispatch = 1
        # non-finite-grad policy: skip the update (params/opt-state/BN
        # stats keep their pre-step values), count, abort after this many
        # CONSECUTIVE bad steps (0/None = never abort)
        self.nonfinite_abort = int(
            os.environ.get("BIGDL_NONFINITE_ABORT", "10"))
        self._nonfinite_skips = 0
        self._nonfinite_streak = 0
        # observability (docs/observability.md): in-jit taps (None =
        # BIGDL_OBS_TAPS / _CADENCE env defaults), phase spans, optional
        # TensorBoard sinks
        self._taps_enabled = None
        self._taps_cadence = None
        self._taps_monitor = None
        self._train_summary = None
        self._val_summary = None
        self.spans = SpanTracker(self.metrics)
        # async host pipeline (dataset/prefetch.py): live runner + the
        # cadence window, both set up per optimize() run
        self._train_pipeline = None
        self._window = None
        # async/sharded checkpointing (resilience/checkpoint.py): lazy
        # writer thread + the non-donated device-copy jit it feeds from
        self._ckpt_writer = None
        self._ckpt_copy_fn = None
        # elastic recovery session (resilience/elastic.py) — armed by the
        # DistriOptimizer loop when BIGDL_ELASTIC=1 on a multi-process run
        self._elastic = None

    def set_taps(self, enabled: bool | None = None,
                 cadence: int | None = None):
        """Override the in-jit tap gating for this run (None defers to
        ``BIGDL_OBS_TAPS`` / ``BIGDL_OBS_TAPS_CADENCE``).  Takes effect
        at the next ``optimize()`` — the taps are part of the compiled
        step."""
        self._taps_enabled = enabled
        self._taps_cadence = cadence
        return self

    def set_train_summary(self, summary):
        """TensorBoard training-curve sink (``obs.TrainSummary``):
        Loss/LearningRate/Throughput per iteration, tap scalars at the
        taps cadence.  Multi-host: attach on process 0 only (the
        reference's driver-side TrainSummary)."""
        self._train_summary = summary
        return self

    def set_val_summary(self, summary):
        """TensorBoard validation sink (``obs.ValidationSummary``): one
        scalar per validation method at each validation trigger."""
        self._val_summary = summary
        return self

    def set_nonfinite_policy(self, abort_after: int | None = 10):
        """Abort training (NonFiniteGradError) after ``abort_after``
        consecutive skipped steps; 0/None keeps skipping forever.  The
        detection itself is always on — it folds into the jit step for
        free (ref has no equivalent: a NaN there poisons the
        AllReduceParameter weights silently)."""
        self.nonfinite_abort = int(abort_after or 0)
        return self

    def set_gradient_checkpointing(self, enabled: bool = True):
        """Rematerialize the forward inside backward (``jax.checkpoint``):
        trades FLOPs for HBM — the TPU-native replacement for the
        reference's shared-buffer memory tricks (SpatialShareConvolution,
        ResNet.shareGradInput)."""
        self.remat = enabled
        return self

    # -- builder config (ref Optimizer.scala:66-124) ----------------------
    def set_state(self, state: Table):
        self.state.update(state)
        return self

    def set_optim_method(self, method: OptimMethod):
        self.optim_method = method
        return self

    def set_iterations_per_dispatch(self, n: int):
        """Device-side training loop: ONE dispatch runs ``n`` train steps
        via ``lax.scan``, each consuming a DISTINCT minibatch from a
        stacked host transfer.  On dispatch-latency-bound setups this
        recovers the device-limited rate (VGG-16/CIFAR on the relay
        v5e: 4,988 -> 24,208 img/s, PERF_NOTES round 3).  Semantics:
        triggers/validation/checkpoint/lr updates happen at dispatch
        (n-step) granularity, and ``state['loss']`` is the chunk's last
        step.  Batches inside a chunk must share one shape (the standard
        looped training iterators guarantee this)."""
        self.iters_per_dispatch = max(1, int(n))
        return self

    def set_optim_state(self, opt_state):
        """Restore the optimizer's internal state (momentum velocity
        etc.) from a ``state.N`` snapshot's ``opt_state`` entry — without
        this a momentum run resumes with zeroed velocity and diverges
        from the uninterrupted trajectory (ref: state Table + internal
        buffers both persist through Optimizer.saveState,
        OptimMethod.scala clearHistory/state)."""
        self._resume_opt_state = opt_state
        return self

    def set_end_when(self, end_when):
        self.end_when = end_when
        return self

    def _initial_opt_state(self, params):
        """Fresh optimizer state, or the restored snapshot from
        set_optim_state.  The snapshot is COPIED: the donating jit step
        would otherwise delete the caller's buffers after one dispatch
        (same guard as the params/net_state copies in optimize())."""
        if self._resume_opt_state is not None:
            return jax.tree_util.tree_map(lambda v: jnp.array(v),
                                          self._resume_opt_state)
        return self.optim_method.init_state(params)

    def set_validation(self, trigger, dataset, methods):
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = methods
        return self

    def set_checkpoint(self, path, trigger):
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    # -- hyper extraction --------------------------------------------------
    def _hyper(self, lr):
        s = self.state
        return {
            "lr": lr,
            "weight_decay": float(s.get("weightDecay", 0.0)),
            "momentum": float(s.get("momentum", 0.0)),
            "dampening": float(s.get("dampening", s.get("momentum", 0.0))),
            "nesterov": bool(s.get("nesterov", False)),
            "lr_decay": float(s.get("learningRateDecay", 0.0)),
            # per-param lr multipliers shaped like model.params()
            # (ref SGD.scala "learningRates"); baked into the trace
            "lr_scales": s.get("learningRates", None),
        }

    def _current_lr(self):
        schedule = self.state.get("learningRateSchedule", Default())
        schedule.update_hyper_parameter(self.state, self.state)
        return -self.state.get("currentLearningRate", -self.state.get("learningRate", 1e-3))

    def _setup_lr_scales(self, static_hyper):
        """Per-param lr multipliers flow in as a jit ARGUMENT (not a baked
        constant, which would duplicate a model-sized tree in the
        executable); a scalar dummy stands in when unused."""
        has_scales = static_hyper.pop("lr_scales", None) is not None
        if has_scales:
            if not isinstance(self.optim_method, SGD):
                raise ValueError(
                    "state['learningRates'] (per-param lr scales) is only "
                    f"supported by SGD, not {type(self.optim_method).__name__}"
                    " — it would be silently ignored")
            self._lr_scales_arg = jax.tree_util.tree_map(
                jnp.asarray, self.state["learningRates"])
        else:
            self._lr_scales_arg = jnp.zeros(())
        return has_scales

    def _build_step(self):
        model, criterion, method = self.model, self.criterion, self.optim_method
        # non-lr hypers are fixed for the run: bake them in as trace-time
        # constants (nesterov/momentum branches resolve at compile time);
        # only the scheduled lr flows in as a traced scalar.
        static_hyper = self._hyper(None)
        del static_hyper["lr"]
        has_scales = self._setup_lr_scales(static_hyper)

        remat = self.remat
        taps_on = obs_taps.enabled(self._taps_enabled)

        def step(params, net_state, opt_state, x, y, lr, key, lr_scales):
            hyper = dict(static_hyper, lr=lr)
            if has_scales:
                hyper["lr_scales"] = lr_scales

            def loss_fn(p):
                apply = model.apply
                if remat:
                    apply = jax.checkpoint(
                        lambda p_, x_: model.apply(
                            p_, x_, net_state, Context(training=True, key=key)))
                    out, ns = apply(p, x)
                else:
                    out, ns = apply(p, x, net_state, Context(training=True, key=key))
                return criterion.apply_loss(out, y), ns

            (loss, new_net_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            finite = _finite_all(loss, grads)
            new_params, new_opt_state = method.update(grads, opt_state, params, hyper)
            new_params = _where_finite(finite, new_params, params)
            new_opt_state = _where_finite(finite, new_opt_state, opt_state)
            new_net_state = _where_finite(finite, new_net_state, net_state)
            # in-jit taps: extra outputs of the SAME dispatch, post-skip-
            # select so update_ratio reads 0 on a skipped step
            taps = (obs_taps.compute(grads, params, new_params)
                    if taps_on else {})
            return (new_params, new_net_state, new_opt_state, loss, finite,
                    taps)

        # donate the carried state: the old params/opt-state buffers are
        # dead after each step, so XLA reuses them instead of allocating a
        # second copy of the model per step (lr_scales is reused each call
        # and must NOT be donated).  Dispatches register in the shared
        # executable cache (serve/xcache.py) keyed on the batch operands
        # only, so train rides the same compile accounting as eval/serve.
        from bigdl_tpu.serve import xcache
        fn_key = ("train_step", _model_fingerprint(self.model),
                  type(self.optim_method).__name__)
        n = self.iters_per_dispatch
        if n <= 1:
            return xcache.tracked_jit(step, fn_key, key_argnums=(3, 4),
                                      donate_argnums=(0, 1, 2))
        return xcache.tracked_jit(self._scan_chunk(step, n),
                                  fn_key + ("chunk%d" % n,),
                                  key_argnums=(3, 4),
                                  donate_argnums=(0, 1, 2))

    @staticmethod
    def _scan_chunk(step, n):
        """Wrap a per-step train fn in the device-side n-step loop
        (shared by Local and Distri builders)."""
        from jax import lax

        def chunk(params, net_state, opt_state, xs, ys, lr, key, lr_scales):
            keys = jax.random.split(key, n)

            def body(carry, xyk):
                p, ns, o = carry
                x, y, k = xyk
                p, ns, o, loss, finite, taps = step(p, ns, o, x, y, lr, k,
                                                    lr_scales)
                return (p, ns, o), (loss, finite, taps)

            ((params, net_state, opt_state),
             (losses, finites, taps)) = lax.scan(
                body, (params, net_state, opt_state), (xs, ys, keys))
            # taps leaves arrive stacked (n,); the host monitor reports
            # the chunk's last step, matching state['loss']
            return params, net_state, opt_state, losses, finites, taps

        return chunk

    @staticmethod
    def _next_chunk(data_iter, n):
        """Draw n uniform-shape batches and stack them host-side (each
        batch converted once — see ``prefetch.stack_chunk``)."""
        return prefetch_mod.stack_chunk([next(data_iter) for _ in range(n)])

    def _device_put_batch(self, x, y, stacked: bool = False):
        """Host batch → device arrays.  The Distri override shards over
        the mesh; the prefetch transfer thread calls this off the main
        thread to overlap H2D with compute."""
        del stacked
        return jnp.asarray(x), jnp.asarray(y)

    def _global_records_factor(self) -> int:
        """Host-batch → global-record multiplier for the producer's epoch
        arithmetic (multi-host data sharding overrides this)."""
        return 1

    def _sync_cadence(self) -> int:
        """Iterations between host materializations of loss/finite — the
        taps cadence (``BIGDL_OBS_TAPS_CADENCE`` / ``set_taps``), or 1
        under the ``BIGDL_SYNC_EVERY_STEP`` escape hatch."""
        if prefetch_mod.sync_every_step():
            return 1
        return obs_taps.cadence(self._taps_cadence)

    def _make_train_pipeline(self, n_disp: int, epoch_size: int):
        """The background input pipeline for this run, or None (prefetch
        disabled, or a mode that needs per-iteration host feedback).
        With a FaultInjector installed the runner stays host-side so
        ``_chaos_prestep`` keys every site by the CONSUMING step and H2D
        happens after poisoning — ``BIGDL_FAULTS`` drills are unchanged."""
        from bigdl_tpu.resilience import faults
        if not prefetch_mod.enabled():
            return None
        if getattr(self, "_straggler", None) is not None:
            # straggler drop accepts/rejects and re-times every iteration
            # on the host; producing ahead would decouple its clock
            return None
        to_device = None
        if faults.get() is None:
            stacked = n_disp > 1
            to_device = lambda xh, yh: self._device_put_batch(
                xh, yh, stacked=stacked)
        return prefetch_mod.PipelineRunner(
            self.dataset, train=True, chunk=n_disp, epoch_size=epoch_size,
            to_device=to_device,
            records_scale=self._global_records_factor())

    def _drain_pipeline_obs(self, pipeline, item, waited, neval0):
        """Book the background threads' telemetry onto the main-thread
        spans/events: producer fetch + H2D walls, and a prefetch_stall
        event when the queue failed to hide the fetch."""
        sec, n = pipeline.take_h2d()
        if n:
            self.spans.record("h2d", sec, count=n)
        sec, n = pipeline.take_fetch()
        if n:
            self.spans.record("data-load/fetch", sec, count=n)
        if waited > 0.01 and item.seq >= pipeline.depth:
            obs_events.emit("prefetch_stall", step=int(neval0),
                            seconds=round(waited, 6),
                            queue_depth=int(item.queue_depth))

    def _flush_window(self, state, monitor, reason: str):
        """Materialize the pending window: one blocking device→host sync
        (the ``host-wait`` span), then the per-step host work the serial
        loop did eagerly — loss logging, the non-finite ledger, step
        events and TensorBoard scalars.  An abort raised by the ledger is
        deferred until every pending step's events are out."""
        w = self._window
        if w is None or not w.pending:
            return
        with self.spans.span("host-wait"):
            entries, losses, finites, wall = w.flush()
        w.flush_steps.append(entries[-1].neval0)
        w.flush_reasons.append(reason)
        records = sum(e.records for e in entries)
        rate = records / max(wall, 1e-9)
        self._note_window_utilization(entries, wall)
        epoch_size = self.dataset.size()
        abort = None
        for e, lv, fv in zip(entries, losses, finites):
            loss_f = float(lv.reshape(-1)[-1])
            state["loss"] = loss_f
            logger.info(
                "Epoch %d %d/%d loss %.6f lr %.5g throughput %.1f "
                "records/s (fetch %.4fs dispatch %.4fs, synced %s)",
                e.epoch, e.count, epoch_size, loss_f, e.lr, rate,
                e.fetch_t, e.train_t, reason)
            if abort is None:
                try:
                    self._note_finite(fv, state)
                except NonFiniteGradError as exc:
                    abort = exc  # emit the remaining step events first
            self._emit_step_event(e.neval0, loss_f, e.lr, rate,
                                  monitor.push(e.neval0, e.taps), **e.extra)
        if abort is not None:
            raise abort

    def _note_window_utilization(self, entries, wall):
        """Windowed ``train_mfu`` + ``train_step_wall_seconds`` gauges,
        published at flush boundaries ONLY (the host-sync cadence — the
        warm path never pays this): ledger flops for the compiled step
        x iterations in the window / (window wall x datasheet peak).
        The flops come from the compile-time capture of THIS loop's
        tracked-jit step (``obs/ledger.py``; a scanned chunk's scan
        body is counted once by XLA, so the chunk entry is already the
        per-iteration count), which is the same number ``bench.py``
        resolves — live MFU and bench MFU cannot silently diverge.
        Best-effort: absent ledger/flops just skips the gauge."""
        fn_key = getattr(self, "_step_fn_key", None)
        if fn_key is None or wall <= 0 or not entries:
            return
        try:
            from bigdl_tpu.obs import ledger as obs_ledger
            from bigdl_tpu.obs import metrics as obs_metrics
            iters = len(entries) * max(
                1, int(getattr(self, "iters_per_dispatch", 1)))
            label = ("distri" if type(self).__name__.startswith("Distri")
                     else "local")
            reg = obs_metrics.get()
            reg.gauge("train_step_wall_seconds",
                      "windowed mean train-step wall (fetch + dispatch "
                      "+ sync)", agg="max",
                      optimizer=label).set(wall / iters)
            flops = obs_ledger.get().flops_for(fn_key)
            if flops:
                mfu = (flops * iters
                       / (wall * obs_ledger.device_peak_flops()))
                reg.gauge("train_mfu",
                          "windowed model flops utilization of the "
                          "training loop (ledger flops x step rate / "
                          "datasheet peak)", agg="max",
                          optimizer=label).set(mfu)
        except Exception as e:  # pragma: no cover - obs mid-teardown
            logger.warning("train utilization gauge failed: %s", e)

    # -- main loop (ref LocalOptimizer.optimize :77) ----------------------
    def optimize(self):
        state = self.state
        state.get_or_update("epoch", 1)
        state.get_or_update("neval", 1)
        # a resumed state blob may carry the previous run's preemption
        # mark; this run hasn't been preempted (yet)
        state["preempted"] = False

        # copy the model's arrays: the jit step donates its carried state,
        # and donating the module's own buffers would leave the user's model
        # holding deleted arrays mid-training
        params = jax.tree_util.tree_map(jnp.copy, self.model.params())
        net_state = jax.tree_util.tree_map(jnp.copy, self.model.state())
        opt_state = self._initial_opt_state(params)
        step_fn = self._build_step()
        # the ledger key the MFU gauge resolves flops through (the
        # tracked-jit wrapper captured cost at its compiling dispatch)
        self._step_fn_key = getattr(step_fn, "fn_key", None)
        monitor = self._start_obs_run()

        count = 0
        epoch_size = self.dataset.size()
        n_disp = self.iters_per_dispatch
        pipeline = self._make_train_pipeline(n_disp, epoch_size)
        self._train_pipeline = pipeline
        data_iter = None if pipeline is not None \
            else self.dataset.data(train=True)
        self._window = _HostSyncWindow(self._sync_cadence())
        wall_start = time.perf_counter()

        try:
            while not self.end_when(state):
                neval0 = int(state["neval"])
                epoch0 = int(state["epoch"])
                self._window.arm()
                fetch_start = time.perf_counter()
                dev = qdepth = None
                with self.spans.span("data-load"):
                    if pipeline is not None:
                        # the span measures the CONSUMER's wait only; the
                        # producer's transform wall rides data-load/fetch
                        item, waited = pipeline.get()
                        self._drain_pipeline_obs(pipeline, item, waited,
                                                 neval0)
                        qdepth = item.queue_depth
                        if item.device is not None:
                            dev = item.device
                    elif n_disp <= 1:
                        batch = next(data_iter)
                        xh = self._chaos_prestep(batch.data, neval0)
                        yh = batch.labels
                    else:
                        xh, yh = self._next_chunk(data_iter, n_disp)
                        xh = self._chaos_prestep(xh, neval0)
                if dev is None:
                    if pipeline is not None:
                        # chaos host mode: poison at CONSUME time, so
                        # every site stays keyed by the consuming step
                        xh = self._chaos_prestep(item.x, neval0)
                        yh = item.y
                    with self.spans.span("h2d"):
                        dev = self._device_put_batch(xh, yh,
                                                     stacked=n_disp > 1)
                x, y = dev
                fetch_time = time.perf_counter() - fetch_start

                train_start = time.perf_counter()
                with self.spans.span("dispatch"):
                    lr = self._current_lr()
                    key = RNG.next_key()
                    params, net_state, opt_state, loss, finite, taps = \
                        step_fn(params, net_state, opt_state, x, y,
                                jnp.float32(lr), key, self._lr_scales_arg)
                train_time = time.perf_counter() - train_start

                b = x.shape[0] * x.shape[1] if n_disp > 1 else x.shape[0]
                count += b
                state["neval"] = neval0 + n_disp
                state["evalCounter"] = state.get("evalCounter", 0) + n_disp
                self.metrics.add("data fetch time", fetch_time)
                self.metrics.add("train time", train_time)
                extra = ({"queue_depth": int(qdepth)}
                         if qdepth is not None else {})
                # loss/finite/taps stay ON DEVICE; the window materializes
                # them at the next cadence/boundary flush (no per-step
                # device→host sync — the tentpole of this layer)
                self._window.push(_PendingStep(
                    neval0, epoch0, count, loss, finite, taps, lr, b,
                    fetch_time, train_time, extra))

                rolled = count >= epoch_size
                count, data_iter = self._advance_epochs(
                    state, count, epoch_size, n_disp, data_iter, pipeline)
                if self._window.due() or rolled:
                    self._flush_window(state, monitor,
                                       "epoch" if rolled else "cadence")
                # trigger predicates are host-only (no device sync); a
                # firing one forces its own flush below so validation/
                # checkpoint always see materialized loss + finite ledger
                ne_val = self._fired_within(self.validation_trigger, state,
                                            n_disp)
                ne_ck = self._fired_within(self.checkpoint_trigger, state,
                                           n_disp)
                preempt = self._preemption_pending()
                if preempt or ne_val is not None or ne_ck is not None:
                    self._flush_window(state, monitor,
                                       "preempt" if preempt else "trigger")
                if ne_val is not None:
                    self._maybe_validate(params, net_state, state,
                                         force=True)
                if ne_ck is not None:
                    self._maybe_checkpoint(params, net_state, opt_state,
                                           state, force=True,
                                           neval_label=ne_ck)
                if preempt:
                    self._checkpoint_and_stop(params, net_state, opt_state,
                                              state)
                    break
            self._flush_window(state, monitor, "run-end")
        finally:
            try:
                # best-effort: an exception between cadence boundaries
                # (fault, dispatch error, watchdog exit) must not lose
                # the already-dispatched steps' events + finite ledger —
                # the postmortem needs the steps NEAREST the crash.  A
                # no-op on clean exit (run-end already flushed); never
                # masks the propagating exception.
                self._flush_window(state, monitor, "exception")
            except Exception as e:
                logger.warning("pending-step flush during unwind "
                               "failed: %s", e)
            if pipeline is not None:
                pipeline.close()
            self._train_pipeline = None
            # leaving optimize() with snapshots still in flight would
            # let the process exit before they are durable
            self._flush_ckpt_writer("run end")

        self.model.load_params(params)
        self.model.load_state(net_state)
        self._end_obs_run(state, wall_start)
        logger.info("Training finished in %.1fs", time.perf_counter() - wall_start)
        return self.model

    # -- resilience hooks (docs/resilience.md) ----------------------------
    def _chaos_prestep(self, x_host, neval: int):
        """FaultInjector sites threaded through the train loop: NaN/Inf
        batch poisoning (drives the non-finite guard end-to-end through
        the real backward), slow-worker delay, induced process death.
        Returns the (possibly poisoned) host batch; a no-op None-check
        when chaos is off."""
        from bigdl_tpu.resilience import faults
        inj = faults.get()
        if inj is None:
            return x_host
        spec = inj.fires("slow_worker", step=neval)
        if spec is not None:
            time.sleep(spec.delay)
        if inj.fires("proc_kill", step=neval) is not None:
            logger.error("FaultInjector: induced process death at "
                         "iteration %d", neval)
            os._exit(1)
        poison = None
        if inj.fires("nan_grad", step=neval) is not None:
            poison = np.nan
        elif inj.fires("inf_grad", step=neval) is not None:
            poison = np.inf
        if poison is not None:
            x_host = np.array(x_host, dtype=np.float32, copy=True)
            x_host.reshape(-1)[0] = poison
        return x_host

    def _note_finite(self, finite, state):
        """Host-side accounting for the jit-folded finite flag(s): count
        skipped steps, track the consecutive streak, abort past the
        threshold.  ``finite`` is a scalar (or (n,) per-chunk array —
        the streak then continues across dispatch boundaries)."""
        flags = np.atleast_1d(np.asarray(finite)).astype(bool)
        n_bad = int((~flags).sum())
        if n_bad == 0:
            self._nonfinite_streak = 0
            return
        self._nonfinite_skips += n_bad
        # longest consecutive bad run, seeded with the streak carried in
        # from earlier dispatches — a >=threshold run INSIDE one chunk
        # must abort even if the chunk's last step recovered
        streak = self._nonfinite_streak
        worst = streak
        for f in flags:
            streak = 0 if f else streak + 1
            worst = max(worst, streak)
        self._nonfinite_streak = streak
        state["nonFiniteSkips"] = self._nonfinite_skips
        warn_every(
            logger, "nonfinite", 5.0,
            "non-finite gradients at iteration %d: update skipped, "
            "params/optimizer state kept (%d skipped total, %d "
            "consecutive, abort threshold %s)",
            int(state["neval"]), self._nonfinite_skips,
            worst, self.nonfinite_abort or "off")
        if self.nonfinite_abort and worst >= self.nonfinite_abort:
            # postmortem before the raise: the abort event + crash bundle
            # are what explains this death from the run directory alone
            from bigdl_tpu.obs import diagnostics
            obs_events.emit("abort", step=int(state["neval"]),
                            reason="nonfinite",
                            skips=int(self._nonfinite_skips),
                            streak=int(worst))
            diagnostics.dump_crash_bundle(
                "nonfinite-abort",
                extra={"neval": int(state["neval"]), "streak": int(worst),
                       "skips": int(self._nonfinite_skips),
                       "threshold": int(self.nonfinite_abort)})
            raise NonFiniteGradError(
                f"{worst} consecutive non-finite-gradient "
                f"steps (threshold {self.nonfinite_abort}, iteration "
                f"{int(state['neval'])}): loss has diverged — lower the "
                "learning rate or resume from an earlier checkpoint")

    def _preemption_pending(self) -> bool:
        """SIGTERM arrived (``Engine.install_preemption_handler``)?  The
        distributed loop overrides this with an any-process merge so every
        host agrees to stop at the same iteration."""
        return Engine.preempted()

    def _checkpoint_and_stop(self, params, net_state, opt_state, state):
        """Preemption epilogue: force one final checkpoint (when a
        checkpoint path is configured) and mark the state so callers can
        tell a preempted run from a completed one — flag first, so it
        rides the snapshot payload."""
        state["preempted"] = True
        obs_events.emit("preempt", step=int(state["neval"]),
                        signal_at=Engine.preempted_at())
        if self.checkpoint_path:
            self._maybe_checkpoint(params, net_state, opt_state, state,
                                   force=True)
            # the eviction deadline is real: the final snapshot must
            # be on disk before the exit, async mode or not
            self._flush_ckpt_writer("preemption checkpoint-and-stop")
        # the exit is clean, but the bundle records WHERE the notice
        # landed (docs/observability.md: preemption postmortems)
        from bigdl_tpu.obs import diagnostics
        diagnostics.dump_crash_bundle(
            "preemption", extra={"neval": int(state["neval"]),
                                 "signal_at": Engine.preempted_at()})
        # the notice has been honored; a LATER optimize() in this process
        # (restart after resume) must not stop on the stale flag — a new
        # SIGTERM sets it again
        Engine.clear_preemption()
        logger.warning(
            "preemption: checkpointed at iteration %d, leaving the "
            "training loop (resume with load_latest_checkpoint)",
            int(state["neval"]))

    def _advance_epochs(self, state, count, epoch_size, n_disp, data_iter,
                        pipeline=None):
        """Epoch rollover shared by both optimizers' loops.  Single-step
        keeps the historical semantics (leftover count resets — it came
        from the discarded iterator); a chunk can span several epochs of
        a small dataset, so it rolls the epoch counter through.  With a
        prefetch pipeline the PRODUCER already performed the shuffle and
        iterator rebuild at the same point of the draw stream
        (``PipelineRunner._advance_epoch``); only the counters move here."""
        if n_disp <= 1:
            if count >= epoch_size:
                state["epoch"] = state["epoch"] + 1
                count = 0
                if pipeline is None:
                    self.dataset.shuffle()
                    data_iter = self.dataset.data(train=True)
                self.spans.emit_phase_events(obs_events.get(),
                                             int(state["neval"]))
            return count, data_iter
        rolled = count >= epoch_size
        while count >= epoch_size:
            state["epoch"] = state["epoch"] + 1
            count -= epoch_size
            if pipeline is None:
                self.dataset.shuffle()
                data_iter = self.dataset.data(train=True)
        if rolled:
            self.spans.emit_phase_events(obs_events.get(),
                                         int(state["neval"]))
        return count, data_iter

    @staticmethod
    def _fired_within(trig, state, n):
        """The first neval in this dispatch's (neval-n, neval] interval
        at which ``trig`` would have fired, or None — periodic triggers
        (several_iteration(k)) must not be skipped because neval jumps by
        n per dispatch, and the probe keeps trigger evaluation host-only
        so a non-firing iteration costs no device sync.  Probes a shallow
        state copy per intermediate iteration (triggers are cheap
        predicates); the caller then invokes the action with force=True
        (stateful triggers like every_epoch must be probed exactly
        once)."""
        if trig is None:
            return None
        neval = state["neval"]
        for ne in range(neval - n + 1, neval + 1):
            probe = T()
            probe.update(state)
            probe["neval"] = ne
            if trig(probe):
                return ne
        return None

    # -- observability plumbing (docs/observability.md) -------------------
    def _obs_flags(self) -> dict:
        """The run-configuration snapshot stamped into the run_start
        event — enough to tell two runs apart in a pile of JSONL."""
        flags = {"optimizer": type(self).__name__,
                 "taps": obs_taps.enabled(self._taps_enabled),
                 "taps_cadence": obs_taps.cadence(self._taps_cadence),
                 "iters_per_dispatch": self.iters_per_dispatch,
                 "nonfinite_abort": self.nonfinite_abort,
                 "prefetch": prefetch_mod.enabled(),
                 "prefetch_depth": prefetch_mod.depth(),
                 "sync_cadence": self._sync_cadence(),
                 "optim_method": type(self.optim_method).__name__}
        mesh = getattr(self, "mesh", None)
        if mesh is not None:
            flags["mesh"] = {k: int(v) for k, v in dict(mesh.shape).items()}
        return flags

    def _start_obs_run(self):
        """Fresh taps monitor + run_start event at each optimize()."""
        self._taps_monitor = obs_taps.TapsMonitor(self._taps_cadence,
                                                  self._taps_enabled)
        try:
            # BIGDL_OBS_HBM_SAMPLE=<s>: cadence HBM sampler for the
            # run (process-wide, started once; obs/ledger.py)
            from bigdl_tpu.obs import ledger as obs_ledger
            obs_ledger.maybe_start_sampler_from_env()
        except Exception:   # pragma: no cover - obs layer unavailable
            pass
        obs_events.emit("run_start", flags=self._obs_flags())
        return self._taps_monitor

    def _end_obs_run(self, state, wall_start):
        """Flush the tap tail (short runs still log one sample), emit
        the cumulative phase breakdown and the run_end event."""
        ev = obs_events.get()
        tail = self._taps_monitor.flush() if self._taps_monitor else None
        if ev is not None:
            self.spans.emit_phase_events(ev, int(state["neval"]))
            fields = {"steps": int(state["neval"]) - 1,
                      "wall": time.perf_counter() - wall_start}
            if tail:
                fields["taps"] = tail
            ev.emit("run_end", **fields)

    def _emit_step_event(self, neval, loss, lr, throughput, tap_vals,
                         **extra):
        """One structured step event + TensorBoard scalars.  ``tap_vals``
        is the monitor's cadence-gated dict (None off-boundary)."""
        ev = obs_events.get()
        if ev is None and self._train_summary is None:
            return
        fields = dict(step=int(neval), loss=float(loss), lr=float(lr),
                      throughput=float(throughput))
        if tap_vals:
            fields["taps"] = tap_vals
        if self._nonfinite_skips:
            fields["skips"] = int(self._nonfinite_skips)
        fields.update(extra)
        if ev is not None:
            ev.emit("step", **fields)
        ts = self._train_summary
        if ts is not None:
            ts.add_scalar("Loss", loss, neval)
            ts.add_scalar("LearningRate", lr, neval)
            ts.add_scalar("Throughput", throughput, neval)
            if tap_vals:
                for k, v in tap_vals.items():
                    ts.add_scalar("Taps/" + k, v, neval)

    # -- validation (ref LocalOptimizer.scala:196-242) --------------------
    def _maybe_validate(self, params, net_state, state, force=False):
        if not force and (self.validation_trigger is None
                          or not self.validation_trigger(state)):
            return
        pipeline = self._train_pipeline
        if pipeline is not None:
            # hold the producer before its next draw: validation may
            # iterate the same backing store an epoch shuffle mutates
            pipeline.pause()
        try:
            with self.spans.span("validate"):
                results = validate(self.model, params, net_state,
                                   self.validation_dataset,
                                   self.validation_methods)
        finally:
            if pipeline is not None:
                pipeline.resume()
        for method, result in results:
            logger.info("%s is %s", method, result)
            val = result.result()[0]
            state[str(method)] = val
            obs_events.emit("validation", step=int(state["neval"]),
                            method=str(method), value=float(val))
            if self._val_summary is not None:
                self._val_summary.add_scalar(str(method), val,
                                             int(state["neval"]))

    def _maybe_checkpoint(self, params, net_state, opt_state, state,
                          force=False, neval_label=None):
        if not force and (self.checkpoint_trigger is None
                          or not self.checkpoint_trigger(state)):
            return
        neval = state["neval"] if neval_label is None else neval_label
        from bigdl_tpu.resilience import checkpoint as ckpt_mod
        # the classic (synchronous, whole-tree) path cannot express
        # optimizer state sharded ACROSS processes — those leaves are not
        # addressable from one writer — so zero1 multi-host snapshots ride
        # the sharded writer even with the async flag off
        sharded = jax.process_count() > 1 and any(
            ckpt_mod.is_cross_process_sharded(l)
            for l in jax.tree_util.tree_leaves(opt_state))
        if ckpt_mod.async_enabled() or sharded:
            with self.spans.span("checkpoint"):
                self._emit_checkpoint(params, net_state, opt_state, state,
                                      neval,
                                      asynchronous=ckpt_mod.async_enabled())
            return
        if jax.process_count() > 1 and jax.process_index() != 0:
            # replicated state, shared checkpoint dir: exactly one writer
            # (the reference's driver-side getModel + File.save)
            return
        with self.spans.span("checkpoint"):
            # load host copies: loading the live pytree would leave the
            # module referencing buffers the next (donating) step deletes
            self.model.load_params(jax.device_get(params))
            self.model.load_state(jax.device_get(net_state))
            File.save_module(self.model,
                             f"{self.checkpoint_path}/model.{neval}")
            # "neval": the file label (= the nominal firing iteration under
            # the device-side loop, which may be < state['neval']); kept in
            # the payload so resume tooling can detect the chunked case.
            # "rng": host-stream snapshot so a resume can replay the
            # uninterrupted run's shuffle/augmentation draws
            # (load_latest_checkpoint(restore_rng=True)).  With the
            # prefetch pipeline the stream has advanced past the batches
            # merely PREFETCHED; the runner's snapshot is pinned to the
            # last CONSUMED batch so the resumed trajectory matches.
            pipeline = self._train_pipeline
            rng_snap = (pipeline.rng_snapshot() if pipeline is not None
                        else RNG.snapshot())
            File.save({"state": state, "opt_state": opt_state,
                       "neval": neval, "rng": rng_snap},
                      f"{self.checkpoint_path}/state.{neval}")
            keep = ckpt_mod.keep_count()
            if keep:
                from bigdl_tpu.optim.optimizer import prune_checkpoints
                prune_checkpoints(self.checkpoint_path, keep,
                                  just_written=neval)
        obs_events.emit("checkpoint", step=int(neval),
                        path=f"{self.checkpoint_path}/model.{neval}")

    def _flush_ckpt_writer(self, context: str, timeout: float = 120.0):
        """Drain the async checkpoint writer, LOUDLY: a flush that times
        out at a preemption/run-end epilogue means the newest snapshot
        may be missing at resume — that must be in the log, not silently
        indistinguishable from success."""
        if self._ckpt_writer is None:
            return True
        ok = self._ckpt_writer.flush(timeout=timeout)
        if not ok:
            logger.error(
                "async checkpoint writer did not drain within %.0fs at "
                "%s — the newest snapshot may be missing or partial on "
                "resume (the CRC scan will fall back past it)",
                timeout, context)
        return ok

    def _ckpt_copy(self, params, net_state, opt_state):
        """Fresh (never-donated) device copies of the carried state in one
        dispatch, shardings preserved — what makes handing the trees to a
        background writer safe against the next step's donation."""
        if self._ckpt_copy_fn is None:
            copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
            self._ckpt_copy_fn = jax.jit(
                lambda p, s, o: (copy(p), copy(s), copy(o)))
        return self._ckpt_copy_fn(params, net_state, opt_state)

    def _emit_checkpoint(self, params, net_state, opt_state, state, neval,
                         asynchronous: bool):
        """The sharded/async snapshot builder (docs/resilience.md "Async
        checkpoints").  Device trees are copied on this thread (cheap,
        on-device); the device→host materialization and every byte of
        pickling/IO happen on the writer thread when ``asynchronous`` —
        the loop's checkpoint-step cost collapses to one copy dispatch +
        an enqueue.  Optimizer-state leaves sharded across processes
        become one ``state.N.shard<r>of<n>`` file (+ CRC sidecar) per
        process; ``load_latest_checkpoint`` reassembles the full tree,
        making the snapshot world-size-agnostic."""
        from bigdl_tpu.resilience import checkpoint as ckpt_mod
        from bigdl_tpu.utils.file import _pickle_architecture

        params_c, net_c, opt_c = self._ckpt_copy(params, net_state,
                                                 opt_state)
        marked, slices = ckpt_mod.split_sharded_state(opt_c)
        nproc = jax.process_count()
        rank = jax.process_index()
        sharded = bool(slices) and nproc > 1
        pipeline = self._train_pipeline
        rng_snap = (pipeline.rng_snapshot() if pipeline is not None
                    else RNG.snapshot())
        files = []
        if sharded:
            files.append((ckpt_mod.shard_file(self.checkpoint_path, neval,
                                              rank, nproc),
                          {"rank": int(rank), "world": int(nproc),
                           "slices": slices}))
        meta = {}
        if rank == 0:
            state_copy = T()
            state_copy.update(state)
            blob = {"state": state_copy,
                    "opt_state": marked if sharded else opt_c,
                    "neval": neval, "rng": rng_snap}
            if sharded:
                blob["opt_shards"] = int(nproc)
            files.append((f"{self.checkpoint_path}/model.{neval}",
                          {"format": "bigdl_tpu.module.v2",
                           "cls": type(self.model).__name__,
                           "architecture": _pickle_architecture(self.model),
                           "params": params_c, "state": net_c}))
            files.append((f"{self.checkpoint_path}/state.{neval}", blob))
            meta = {"event_path": f"{self.checkpoint_path}/model.{neval}",
                    "step": int(neval),
                    "shards": int(nproc) if sharded else 0,
                    "keep": ckpt_mod.keep_count() or None,
                    "ckpt_dir": self.checkpoint_path}
        if not files:
            return
        if asynchronous:
            if self._ckpt_writer is None:
                self._ckpt_writer = ckpt_mod.AsyncCheckpointWriter()
            self._ckpt_writer.submit(files, meta)
            return
        # sharded-but-sync (zero1 multi-host with BIGDL_CKPT_ASYNC=0):
        # write inline, same files, same sidecars
        for path, blob in files:
            File.save(blob, path)
        if meta:
            obs_events.emit("checkpoint", step=int(neval),
                            path=meta["event_path"],
                            shards=meta["shards"])
            if meta.get("keep"):
                from bigdl_tpu.optim.optimizer import prune_checkpoints
                prune_checkpoints(self.checkpoint_path, meta["keep"],
                                  just_written=meta.get("step"))


def _model_fingerprint(model):
    """Cheap structure+hyper fingerprint: module tree paths, class names,
    and scalar attributes.  Guards the cached eval jit against in-place
    architecture edits between validations (swap a layer, change a bound)."""
    parts = []

    hyper_types = (int, float, bool, str, bytes, type(None), tuple, list,
                   np.integer, np.floating, np.bool_)
    # runtime-mutable attrs that don't change the compiled computation —
    # including them would recompile on every eager call / mode flip
    skip = {"forward_time", "backward_time", "training_mode", "output",
            "grad_input", "_last_key", "name"}

    def walk(mod, path):
        scalars = tuple(sorted(
            (k, repr(v)) for k, v in mod.__dict__.items()
            if isinstance(v, hyper_types) and k not in skip and
            not k.startswith("_cached_")))
        parts.append((path, type(mod).__name__, scalars))
        for name, child in mod._modules.items():
            walk(child, f"{path}/{name}")

    walk(model, "")
    return tuple(parts)


def _eval_fn(model):
    """One eval forward per model instance, cached on the model (a fresh
    closure per validate() call would recompile at every validation
    trigger; the model->fn->model cycle is ordinary gc fodder) and
    routed through the shared executable cache (``serve/xcache.py``):
    the returned callable resolves an AOT executable per batch shape
    keyed by the model FINGERPRINT, so a process that validates AND
    serves the same (model, shape) pair compiles it exactly once."""
    fp = _model_fingerprint(model)
    cached = getattr(model, "_cached_eval_fn", None)
    if cached is not None and cached[0] == fp:
        return cached[1]
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.serve import xcache

    @jax.jit
    def fwd(p, s, x):
        out, _ = model.apply(p, x, s,
                             Context(training=False, key=jax.random.PRNGKey(0)))
        return out

    wrapped = xcache.ShapedCallable(fwd, fn_key=("eval", fp))
    model._cached_eval_fn = (fp, wrapped)
    return wrapped


def validate(model, params, net_state, dataset, methods, batch_to_device=jnp.asarray):
    """Shared evaluation loop (ref Validator.scala:24 / LocalValidator.scala:30).

    Returns [(method, merged_result)].  Logs eval throughput, the
    reference's "validate model throughput is %.2f records / second"
    line (LocalOptimizer.scala:231-233).

    Validation batches ride the background prefetcher too (bounded, one
    pass): batch k+1 decodes while batch k's forward + host-side compare
    run.  ``BIGDL_PREFETCH=0`` restores the serial iterator, and a chain
    with RNG-bearing stages (unconventional for eval) stays serial so
    its draws come from the calling thread's stream, not a fresh derived
    stream per validation pass.

    The last PARTIAL batch is zero-padded back to the full batch's row
    count through the serve bucket helper (``serve/bucketing.pad_rows``)
    and its outputs trimmed, so an odd tail reuses the executable the
    first batch compiled instead of paying a second XLA compile per
    distinct tail shape (docs/serving.md).
    """
    from bigdl_tpu.serve import bucketing
    fwd = _eval_fn(model)
    totals = [None] * len(methods)
    count = timed_count = 0
    t0 = None
    full_rows = None
    batches = dataset.data(train=False)
    if prefetch_mod.enabled() and not prefetch_mod.has_stochastic_stage(
            dataset):
        batches = prefetch_mod.background(batches, prefetch_mod.depth())
    for batch in batches:
        data = np.asarray(batch.data)   # converted ONCE: shape probe,
        rows = int(data.shape[0])       # pad and device transfer all
        if full_rows is None:           # reuse the same array
            full_rows = rows
        if rows < full_rows:
            data, _ = bucketing.pad_rows(data, full_rows)
        out = fwd(params, net_state, batch_to_device(data))
        if rows < full_rows:
            out = bucketing.trim(out, rows)
        b = int(np.asarray(batch.labels).shape[0])
        count += b
        for i, m in enumerate(methods):
            r = m(out, batch.labels)  # host-side compare = hard sync
            totals[i] = r if totals[i] is None else totals[i] + r
        if t0 is None:
            # start the throughput clock AFTER the first batch: its jit
            # compile (tens of seconds cold on TPU) would otherwise
            # deflate the logged number ~1000x
            t0 = time.perf_counter()
        else:
            timed_count += b
    dt = time.perf_counter() - (t0 or time.perf_counter())
    if timed_count:
        logger.info("validate model throughput is %.2f records / second "
                    "(%d records in %.3fs, excluding the first batch)",
                    timed_count / max(dt, 1e-9), timed_count, dt)
    else:
        logger.info("validate model throughput unavailable: single-batch "
                    "dataset (first batch carries the compile); "
                    "%d records validated", count)
    return list(zip(methods, totals))


def distri_validate(model, params, net_state, dataset, methods):
    """Distributed evaluation (ref DistriValidator.scala:32): each process
    evaluates its dataset shard, results merge across hosts via the
    ValidationResult ``+`` algebra (the reference reduces driver-side)."""
    local = validate(model, params, net_state, dataset, methods)
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils
    merged = []
    for method, result in local:
        if hasattr(result, "correct"):
            vec = np.asarray([result.correct, result.count], np.float32)
        else:
            vec = np.asarray([result.loss, result.count], np.float32)
        total = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(vec))).sum(axis=0)
        merged.append((method, type(result)(total[0], int(total[1]))))
    return merged
