"""Validation methods + result algebra (ref optim/ValidationMethod.scala:26-230,
EvaluateMethods.scala:23).

Top1Accuracy / Top5Accuracy / Loss, each producing a mergeable result
(AccuracyResult/LossResult ``+`` algebra for reduction across batches and
across hosts).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class ValidationResult:
    def result(self):
        """(value, count)"""
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError


class AccuracyResult(ValidationResult):
    def __init__(self, correct: int, count: int):
        self.correct = int(correct)
        self.count = int(count)

    def result(self):
        return (self.correct / max(self.count, 1), self.count)

    def __add__(self, other):
        return AccuracyResult(self.correct + other.correct, self.count + other.count)

    def __eq__(self, other):
        return (isinstance(other, AccuracyResult)
                and self.correct == other.correct and self.count == other.count)

    def __repr__(self):
        acc, n = self.result()
        return f"Accuracy(correct: {self.correct}, count: {n}, accuracy: {acc})"


class LossResult(ValidationResult):
    def __init__(self, loss: float, count: int):
        self.loss = float(loss)
        self.count = int(count)

    def result(self):
        return (self.loss / max(self.count, 1), self.count)

    def __add__(self, other):
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self):
        l, n = self.result()
        return f"Loss(sum: {self.loss}, count: {n}, mean: {l})"


class ValidationMethod:
    def __call__(self, output, target) -> ValidationResult:
        raise NotImplementedError


def _topk_correct(output, target, k):
    """#samples whose 1-based target is within top-k of output rows
    (ref EvaluateMethods.scala:23)."""
    output = np.asarray(output)
    if output.ndim == 1:
        output = output[None]
    target = np.reshape(np.asarray(target), (output.shape[0],))
    tgt0 = target.astype(np.int64) - 1
    topk = np.argsort(-output, axis=1)[:, :k]
    correct = (topk == tgt0[:, None]).any(axis=1).sum()
    return int(correct), int(output.shape[0])


class EvaluateMethods:
    """Raw tensor accuracy helpers (ref EvaluateMethods.scala:23): return
    ``(correct, count)`` without the result-object wrapper."""

    @staticmethod
    def calc_accuracy(output, target):
        return _topk_correct(output, target, 1)

    @staticmethod
    def calc_top5_accuracy(output, target):
        return _topk_correct(output, target, 5)


class Top1Accuracy(ValidationMethod):
    def __call__(self, output, target):
        return AccuracyResult(*_topk_correct(output, target, 1))

    def __repr__(self):
        return "Top1Accuracy"


class Top5Accuracy(ValidationMethod):
    def __call__(self, output, target):
        return AccuracyResult(*_topk_correct(output, target, 5))

    def __repr__(self):
        return "Top5Accuracy"


class Loss(ValidationMethod):
    """Mean criterion loss over the validation set (ref ValidationMethod.Loss)."""

    def __init__(self, criterion):
        self.criterion = criterion

    def __call__(self, output, target):
        l = float(self.criterion.apply_loss(output, target))
        n = output.shape[0] if hasattr(output, "shape") and output.ndim > 1 else 1
        return LossResult(l * n, n)

    def __repr__(self):
        return "Loss"
