"""OptimMethod interface + SGD / Adagrad (ref optim/OptimMethod.scala:98,
SGD.scala:26, Adagrad.scala:26).

Dual interface:
- ``optimize(feval, x, config, state)`` — the reference's functional
  interface over any pytree ``x`` (feval returns (loss, grad-pytree)).
- ``init_state(params)`` + ``update(grads, opt_state, params, hyper)`` —
  pure pytree functions the trainers close over inside ``jit``; all
  branches resolved at trace time, all arithmetic jnp, so the whole
  optimizer fuses into the train step (the reference instead runs SGD on
  each node's weight slice after all-reduce, DistriOptimizer.scala:232).

Config/state live in ``Table``s keyed exactly as the reference
(learningRate, weightDecay, momentum, dampening, nesterov, learningRateDecay,
learningRateSchedule, evalCounter, epoch...) for checkpoint parity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.utils.table import Table, T


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class OptimMethod:
    def optimize(self, feval, x, config: Table = None, state: Table = None):
        raise NotImplementedError

    def clear_history(self, state: Table):
        raise NotImplementedError

    def get_hyper_parameter(self, config: Table) -> str:
        return ""

    def update_hyper_parameter(self, config: Table, state: Table):
        pass

    # pure-pytree interface
    def init_state(self, params):
        return {}

    def update(self, grads, opt_state, params, hyper):
        """Returns (new_params, new_opt_state). ``hyper`` is a dict of
        scalars (may be traced values for schedules inside jit)."""
        raise NotImplementedError


class SGD(OptimMethod):
    """SGD with weight decay / momentum / dampening / nesterov + LR schedules
    (ref SGD.scala:26; schedules :128-210).

    ``fused=True`` runs the update as a single-pass Pallas kernel over HBM
    (read p,g,v -> write p',v' once) instead of the unfused tree_map chain.
    Default off: measured ~2x slower than the unfused path on v5e (XLA
    already fuses the elementwise update into the backward pass, which the
    opaque Pallas call prevents — PERF_NOTES.md); kept for kernel-authoring
    reference and for backends where XLA's fusion is weaker.
    """

    def __init__(self, fused: bool = False):
        self.fused = fused

    def optimize(self, feval, x, config: Table = None, state: Table = None):
        config = config if config is not None else T()
        state = state if state is not None else config

        schedule = config.get("learningRateSchedule", Default())
        schedule.update_hyper_parameter(config, state)
        clr = -config.get("currentLearningRate", -config.get("learningRate", 1e-3))
        # schedule writes currentLearningRate as a negative value (Torch habit)

        wd = config.get("weightDecay", 0.0)
        mom = config.get("momentum", 0.0)
        damp = config.get("dampening", mom)  # Torch default: dampening = momentum
        nesterov = config.get("nesterov", False)
        lrs = config.get("learningRates", None)

        loss, dfdx = feval(x)
        if wd != 0:
            dfdx = _tree_map(lambda g, p: g + wd * p, dfdx, x)
        if mom != 0:
            if "dfdx" not in state:
                state["dfdx"] = _tree_map(lambda g: g, dfdx)
            else:
                state["dfdx"] = _tree_map(lambda v, g: mom * v + (1 - damp) * g,
                                          state["dfdx"], dfdx)
            if nesterov:
                dfdx = _tree_map(lambda g, v: g + mom * v, dfdx, state["dfdx"])
            else:
                dfdx = state["dfdx"]
        if lrs is not None:
            x = _tree_map(lambda p, g, s: p - clr * s * g, x, dfdx, lrs)
        else:
            x = _tree_map(lambda p, g: p - clr * g, x, dfdx)
        state["evalCounter"] = state.get("evalCounter", 0) + 1
        return x, [loss]

    def clear_history(self, state: Table):
        if "dfdx" in state:
            del state["dfdx"]
        return state

    def get_hyper_parameter(self, config: Table) -> str:
        lr = -config.get("currentLearningRate", -config.get("learningRate", 1e-3))
        return f"Current learning rate is {lr}. "

    def update_hyper_parameter(self, config: Table, state: Table):
        schedule = config.get("learningRateSchedule", Default())
        schedule.update_hyper_parameter(config, state)

    # -- pure interface ----------------------------------------------------
    def init_state(self, params):
        return {"velocity": _tree_map(jnp.zeros_like, params)}

    def update(self, grads, opt_state, params, hyper):
        lr = hyper.get("lr", 1e-3)
        wd = hyper.get("weight_decay", 0.0)
        mom = hyper.get("momentum", 0.0)
        damp = hyper.get("dampening", 0.0)
        nesterov = hyper.get("nesterov", False)
        lr_scales = hyper.get("lr_scales")  # per-param lr multipliers
        # (ref SGD.scala "learningRates" Tensor: per-weight lr scaling)
        if self.fused and lr_scales is None:
            # one-HBM-pass Pallas update (ops/pallas_kernels.fused_sgd);
            # matches the unfused math bit-for-bit per leaf
            from bigdl_tpu.ops.pallas_kernels import fused_sgd
            new_params, vel = fused_sgd(
                params, grads, opt_state["velocity"], lr, momentum=mom,
                weight_decay=wd, dampening=damp, nesterov=nesterov)
            return new_params, {"velocity": vel}
        if wd != 0.0:
            grads = _tree_map(lambda g, p: g + wd * p, grads, params)
        vel = opt_state["velocity"]
        if mom != 0.0:
            vel = _tree_map(lambda v, g: mom * v + (1 - damp) * g, vel, grads)
            step_dir = (_tree_map(lambda g, v: g + mom * v, grads, vel)
                        if nesterov else vel)
        else:
            step_dir = grads
        if lr_scales is not None:
            step_dir = _tree_map(lambda d, s: d * s, step_dir, lr_scales)
        new_params = _tree_map(lambda p, d: p - lr * d, params, step_dir)
        return new_params, {"velocity": vel}


class Adagrad(OptimMethod):
    """(ref Adagrad.scala:26)"""

    def optimize(self, feval, x, config: Table = None, state: Table = None):
        config = config if config is not None else T()
        state = state if state is not None else config
        lr = config.get("learningRate", 1e-3)
        lrd = config.get("learningRateDecay", 0.0)
        wd = config.get("weightDecay", 0.0)

        loss, dfdx = feval(x)
        if wd != 0:
            dfdx = _tree_map(lambda g, p: g + wd * p, dfdx, x)
        n_eval = state.get("evalCounter", 0)
        clr = lr / (1 + n_eval * lrd)
        if "paramVariance" not in state:
            state["paramVariance"] = _tree_map(jnp.zeros_like, dfdx)
        state["paramVariance"] = _tree_map(lambda v, g: v + g * g,
                                           state["paramVariance"], dfdx)
        std = _tree_map(lambda v: jnp.sqrt(v) + 1e-10, state["paramVariance"])
        x = _tree_map(lambda p, g, s: p - clr * g / s, x, dfdx, std)
        state["evalCounter"] = n_eval + 1
        return x, [loss]

    def clear_history(self, state: Table):
        for k in ("paramVariance",):
            if k in state:
                del state[k]
        return state

    def init_state(self, params):
        return {"variance": _tree_map(jnp.zeros_like, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, grads, opt_state, params, hyper):
        lr = hyper.get("lr", 1e-3)
        lrd = hyper.get("lr_decay", 0.0)
        wd = hyper.get("weight_decay", 0.0)
        if wd != 0.0:
            grads = _tree_map(lambda g, p: g + wd * p, grads, params)
        step = opt_state["step"]
        clr = lr / (1 + step.astype(jnp.float32) * lrd)
        var = _tree_map(lambda v, g: v + g * g, opt_state["variance"], grads)
        new_params = _tree_map(
            lambda p, g, v: p - clr * g / (jnp.sqrt(v) + 1e-10), params, grads, var)
        return new_params, {"variance": var, "step": step + 1}


# ---------------------------------------------------------------------------
# learning-rate schedules (ref SGD.scala:128-210)
# ---------------------------------------------------------------------------

class LearningRateSchedule:
    def update_hyper_parameter(self, config: Table, state: Table):
        raise NotImplementedError

    def scale_at(self, step: int, config: Table) -> float:
        """Pure variant for jitted trainers: multiplicative factor at step."""
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + evalCounter * learningRateDecay) (ref SGD.scala Default)."""

    def update_hyper_parameter(self, config: Table, state: Table):
        lr = config.get("learningRate", 1e-3)
        lrd = config.get("learningRateDecay", 0.0)
        n = state.get("evalCounter", 0)
        config["currentLearningRate"] = -lr / (1 + n * lrd)

    def scale_at(self, step, config):
        lrd = config.get("learningRateDecay", 0.0)
        return 1.0 / (1.0 + step * lrd)


class Step(LearningRateSchedule):
    """lr * gamma^(floor(evalCounter / stepSize)) (ref SGD.Step)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def update_hyper_parameter(self, config: Table, state: Table):
        lr = config.get("learningRate", 1e-3)
        n = state.get("evalCounter", 0)
        config["currentLearningRate"] = -lr * self.gamma ** (n // self.step_size)

    def scale_at(self, step, config):
        return self.gamma ** (step // self.step_size)


class Poly(LearningRateSchedule):
    """lr * (1 - iter/maxIter)^power (ref SGD.Poly — used by Inception
    Train.scala:39-51)."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def update_hyper_parameter(self, config: Table, state: Table):
        lr = config.get("learningRate", 1e-3)
        n = state.get("evalCounter", 0)
        if n > self.max_iteration:
            config["currentLearningRate"] = 0.0
        else:
            config["currentLearningRate"] = -lr * (1 - n / self.max_iteration) ** self.power

    def scale_at(self, step, config):
        import jax.numpy as jnp
        frac = jnp.clip(1.0 - step / self.max_iteration, 0.0, 1.0)
        return frac ** self.power


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decayFn(epoch) (ref SGD.EpochDecay)."""

    def __init__(self, decay_fn):
        self.decay_fn = decay_fn

    def update_hyper_parameter(self, config: Table, state: Table):
        lr = config.get("learningRate", 1e-3)
        epoch = state.get("epoch", 1)
        config["currentLearningRate"] = -lr * 0.1 ** self.decay_fn(epoch)


class EpochStep(LearningRateSchedule):
    """lr * gamma^floor((epoch-1)/stepSize) (ref SGD.EpochStep)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def update_hyper_parameter(self, config: Table, state: Table):
        lr = config.get("learningRate", 1e-3)
        epoch = state.get("epoch", 1)
        config["currentLearningRate"] = -lr * self.gamma ** ((epoch - 1) // self.step_size)


class EpochSchedule(LearningRateSchedule):
    """Explicit per-epoch-range rates (ref SGD.EpochSchedule / Regime)."""

    class Regime:
        def __init__(self, start_epoch, end_epoch, config: Table):
            self.start_epoch = start_epoch
            self.end_epoch = end_epoch
            self.config = config

    def __init__(self, regimes):
        self.regimes = regimes

    def update_hyper_parameter(self, config: Table, state: Table):
        epoch = state.get("epoch", 1)
        for r in self.regimes:
            if r.start_epoch <= epoch <= r.end_epoch:
                config.update(r.config)
        config["currentLearningRate"] = -config.get("learningRate", 1e-3)
