"""Straggler mitigation — the reference's drop-slowest-tasks machinery
(ref optim/DistriOptimizer.scala:154-172 timeout drop, :245-278 threshold
computation; knobs from Optimizer.setDropMoudleProperty, Optimizer.scala:
116-124), re-designed for a bulk-synchronous SPMD step.

The reference cancels in-flight gradient tasks that exceed a timeout
(``invokeAndWait2``), zeroes their gradients, and divides the gradient
sum by the number of tasks that finished.  An XLA collective cannot be
cancelled mid-dispatch, so the TPU-native design masks instead of
cancels: each data-parallel replica is one "task"; a replica whose
measured step time exceeded the threshold on the PREVIOUS iteration is
masked out of the CURRENT iteration's aggregation —
``grads = psum(w_i * g_i) / sum(w)`` — which is exactly the reference's
``gradientPartition.div(finishedModelNum)`` math (DistriOptimizer.scala:
231-234), one dispatch later.  Everything else mirrors the reference
line for line:

- the threshold is recomputed every ``compute_threshold_batch_size``
  accepted iterations after ``warmup_iteration``, as the k-th largest of
  the window's per-task times with ``k = drop_percentage * window *
  n_tasks``, discounted by the tasks already dropped in the window
  (Util.kthLargest, DistriOptimizer.scala:250-262);
- when the window already dropped >= k, the threshold relaxes by 1%
  (``threshold * 1.01``, :259);
- masked tasks contribute a zero time slot to the window, like the
  reference's cancelled tasks whose ``moduleTimeList`` slot stays 0;
- an iteration whose surviving-task count would fall below
  ``n * (1 - max_drop_percentage)`` is REJECTED: no update, no ``neval``
  advance, the batch is consumed (DistriOptimizer.scala:224 guard).  On
  rejection the policy forgets its last measurements so the next
  dispatch runs unmasked and re-measures every task — the analogue of
  the reference re-running all tasks under the same timeout.

Timing source: per-task (= per data-replica) step seconds.  The
production default maps each process's measured dispatch wall time onto
the replicas that process owns (a host-level straggler — the realistic
failure mode under a single-controller runtime — shows up on all of its
replicas); tests inject synthetic schedules via ``time_source``.
"""
from __future__ import annotations

import logging
import math

import numpy as np

from bigdl_tpu.utils import kth_largest

logger = logging.getLogger("bigdl_tpu.optim")


class StragglerPolicy:
    """Host-side mask/threshold state for straggler dropping.

    Parameters mirror ``Optimizer.setDropMoudleProperty`` (ref
    Optimizer.scala:116-124, defaults :48-51): ``drop_percentage`` <=
    ``max_drop_percentage``, window ``compute_threshold_batch_size``
    (ref computeThresholdbatchSize, default 100), ``warmup_iteration``
    (default 200).
    """

    def __init__(self, n_tasks: int, drop_percentage: float,
                 max_drop_percentage: float,
                 compute_threshold_batch_size: int = 100,
                 warmup_iteration: int = 200,
                 time_source=None):
        if not (0.0 <= drop_percentage <= max_drop_percentage <= 1.0):
            raise ValueError(
                "need 0 <= drop_percentage <= max_drop_percentage <= 1 "
                f"(ref Optimizer.scala:120), got {drop_percentage}, "
                f"{max_drop_percentage}")
        if n_tasks < 1 or compute_threshold_batch_size < 1:
            raise ValueError("n_tasks and compute_threshold_batch_size "
                             "must be >= 1")
        self.n_tasks = int(n_tasks)
        self.drop_percentage = float(drop_percentage)
        self.max_drop_percentage = float(max_drop_percentage)
        self.batch_size = int(compute_threshold_batch_size)
        self.warmup = int(warmup_iteration)
        self.time_source = time_source
        if self.drop_percentage > 0 and self._k_per_window() == 0:
            # k rounds to 0 every window -> the threshold stays inf and
            # dropping can never engage; tell the user at configuration
            # time instead of silently doing nothing
            logger.warning(
                "straggler dropping cannot arm: drop_percentage (%g) * "
                "compute_threshold_batch_size (%d) * n_tasks (%d) rounds "
                "to 0 slow slots per window; raise drop_percentage or "
                "the window size", self.drop_percentage, self.batch_size,
                self.n_tasks)
        # ref: threshold starts at Long.MaxValue (Util.kthLargest k=0)
        self.threshold = math.inf
        self.iteration = 0          # accepted iterations, ref `iteration`
        self._window: list[float] = []   # ref moduleTimeList (flattened)
        self._dropped_in_window = 0      # ref dropModelNumBatch
        self._last_times: np.ndarray | None = None

    def _k_per_window(self) -> int:
        """Slow slots per threshold window (ref DistriOptimizer.scala:
        250: ``dropPercentage * computeThresholdbatchSize * n``) — the
        ONE k formula shared by the threshold update and the cannot-arm
        configuration check."""
        return int(self.drop_percentage * self.batch_size * self.n_tasks)

    # ------------------------------------------------------------- mask
    @property
    def armed(self) -> bool:
        """Dropping engages only after warmup + one full threshold window
        (ref DistriOptimizer.scala:154: ``iteration > warmupIterationNum
        + computeThresholdbatchSize - 1``)."""
        return (self.drop_percentage > 0
                and self.iteration > self.warmup + self.batch_size - 1)

    def mask(self) -> np.ndarray:
        """(n_tasks,) float32 of 0/1 — 1 keeps the task's gradient.

        A task is dropped only when it is over the threshold AND slower
        than the fastest cohort: the threshold is a quantile over TIME,
        so a uniformly slow iteration (GC pause, relay hiccup — every
        task's wall identical) would otherwise mask ALL tasks and
        spuriously reject the iteration.  A straggler is slow RELATIVE
        to its peers (the reference's timeout fires while other tasks
        finish); uniform slowness has no straggler to drop."""
        if (not self.armed or self._last_times is None
                or not math.isfinite(self.threshold)):
            return np.ones(self.n_tasks, np.float32)
        t = self._last_times
        return ((t <= self.threshold) | (t <= t.min())).astype(np.float32)

    def accepts(self, mask: np.ndarray) -> bool:
        """Ref DistriOptimizer.scala:224: the update runs only when
        ``finishedModelNum >= n * (1 - maxDropPercentage)`` — plus a
        floor of one finished task, or the masked mean would divide by
        zero (the reference would divide lossSum by finishedModelNum=0
        here too; we reject instead of NaN-ing the params)."""
        s = float(mask.sum())
        return s >= max(self.n_tasks * (1.0 - self.max_drop_percentage),
                        1.0)

    # ------------------------------------------------------- accounting
    def reject(self, mask: np.ndarray):
        """Iteration rejected (too many stragglers): count the drops
        (ref :223 ``dropModelNumBatch +=``), forget the stale
        measurements so the next dispatch runs unmasked, advance
        nothing."""
        self._dropped_in_window += int(self.n_tasks - mask.sum())
        self._last_times = None
        logger.warning(
            "straggler drop REJECTED iteration: %d/%d tasks under "
            "threshold %.4gs < required %.1f (maxDropPercentage=%s); "
            "batch consumed, no update (ref DistriOptimizer.scala:224)",
            int(mask.sum()), self.n_tasks, self.threshold,
            self.n_tasks * (1 - self.max_drop_percentage),
            self.max_drop_percentage)

    def record(self, times, mask: np.ndarray):
        """After an ACCEPTED iteration: store per-task seconds for the
        next mask, append the window slots (masked tasks contribute 0
        like the reference's cancelled tasks), and recompute the
        threshold at window boundaries (ref DistriOptimizer.scala:
        245-278)."""
        times = np.asarray(times, np.float64).reshape(-1)
        if times.shape != (self.n_tasks,):
            raise ValueError(
                f"need {self.n_tasks} per-task times, got {times.shape}")
        self._last_times = times
        self._window.extend(np.where(mask > 0, times, 0.0).tolist())
        # ref moduleTimeList is a FIXED array of batchSize*n slots written
        # circularly (index ``(iteration % computeThresholdbatchSize) *
        # _subModelNumber``) — before warmup ends it only ever holds the
        # most recent window, so trim to one window here too
        cap = self.batch_size * self.n_tasks
        if len(self._window) > cap:
            del self._window[:len(self._window) - cap]
        self._dropped_in_window += int(self.n_tasks - mask.sum())
        self.iteration += 1
        if (self.drop_percentage > 0 and self.iteration > self.warmup
                and self.iteration % self.batch_size == 0):
            k = self._k_per_window()
            if k > self._dropped_in_window:
                self.threshold = kth_largest(
                    np.asarray(self._window),
                    k - self._dropped_in_window)
            else:
                # window already dropped its share: relax 1% (ref :259)
                self.threshold = self.threshold * 1.01
            logger.info("straggler threshold: %.6gs", self.threshold)
            self._window.clear()
            self._dropped_in_window = 0

    # ---------------------------------------------------------- timing
    def task_times(self, local_wall: float) -> np.ndarray:
        """Per-task seconds for this iteration.  ``time_source`` (tests /
        custom instrumentation) wins; the production default assigns the
        local process's dispatch wall time to every task (single
        process: no skew observable — dropping never engages, which is
        correct: one host's replicas cannot straggle independently under
        one XLA dispatch)."""
        if self.time_source is not None:
            return np.asarray(self.time_source(local_wall),
                              np.float64).reshape(-1)
        return np.full(self.n_tasks, float(local_wall), np.float64)
