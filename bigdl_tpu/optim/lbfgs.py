"""L-BFGS with Wolfe line search (ref optim/LBFGS.scala:39,
LineSearch.scala:44 lswolfe).

Operates on flat vectors (history pairs are rank-1), with pytree
ravel/unravel at the boundary.  The two-loop recursion and line search are
host-driven (each feval may itself be a jitted function) — matching the
reference's full-batch second-order usage, not a per-step jit path.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.utils.table import Table, T


def ls_wolfe(feval, x, t, d, f, g, gtd, c1=1e-4, c2=0.9, tolX=1e-9,
             max_iter=20):
    """Wolfe line search (bracket + zoom), ref LineSearch.lswolfe
    (LineSearch.scala:44).  Returns (f_new, g_new, x_new, t, n_feval)."""
    d_norm = float(jnp.abs(d).max())
    g = g.copy()
    # evaluate at initial step
    f_new, g_new = feval(x + t * d)
    ls_func_evals = 1
    gtd_new = float(jnp.dot(g_new, d))

    t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
    done = False
    ls_iter = 0
    bracket = None

    while ls_iter < max_iter:
        if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
            bracket = [(t_prev, f_prev, g_prev, gtd_prev), (t, f_new, g_new, gtd_new)]
            break
        if abs(gtd_new) <= -c2 * gtd:
            done = True
            bracket = [(t, f_new, g_new, gtd_new)] * 2
            break
        if gtd_new >= 0:
            bracket = [(t_prev, f_prev, g_prev, gtd_prev), (t, f_new, g_new, gtd_new)]
            break
        # extrapolate
        tmp = t
        t = min(10 * t, t + (t - t_prev) * 10)
        t_prev, f_prev, g_prev, gtd_prev = tmp, f_new, g_new, gtd_new
        f_new, g_new = feval(x + t * d)
        ls_func_evals += 1
        gtd_new = float(jnp.dot(g_new, d))
        ls_iter += 1

    if bracket is None:
        bracket = [(0.0, f, g, gtd), (t, f_new, g_new, gtd_new)]

    # zoom phase
    while not done and ls_iter < max_iter:
        (t_lo, f_lo, g_lo, gtd_lo), (t_hi, f_hi, g_hi, gtd_hi) = bracket
        if abs(t_hi - t_lo) * d_norm < tolX:
            break
        t = (t_lo + t_hi) / 2.0
        f_new, g_new = feval(x + t * d)
        ls_func_evals += 1
        gtd_new = float(jnp.dot(g_new, d))
        if f_new > (f + c1 * t * gtd) or f_new >= f_lo:
            bracket = [(t_lo, f_lo, g_lo, gtd_lo), (t, f_new, g_new, gtd_new)]
        else:
            if abs(gtd_new) <= -c2 * gtd:
                done = True
                bracket = [(t, f_new, g_new, gtd_new)] * 2
            elif gtd_new * (t_hi - t_lo) >= 0:
                bracket = [(t, f_new, g_new, gtd_new), (t_lo, f_lo, g_lo, gtd_lo)]
            else:
                bracket = [(t, f_new, g_new, gtd_new), (t_hi, f_hi, g_hi, gtd_hi)]
        ls_iter += 1

    t_res, f_res, g_res, _ = min(bracket, key=lambda b: b[1])
    return f_res, g_res, x + t_res * d, t_res, ls_func_evals


class LBFGS(OptimMethod):
    """(ref LBFGS.scala:39) — config keys: maxIter, maxEval, tolFun, tolX,
    nCorrection, learningRate, lineSearch ('wolfe' or None)."""

    def optimize(self, feval, x, config: Table = None, state: Table = None):
        config = config if config is not None else T()
        state = state if state is not None else config
        max_iter = config.get("maxIter", 20)
        max_eval = config.get("maxEval", int(max_iter * 1.25))
        tol_fun = config.get("tolFun", 1e-5)
        tol_x = config.get("tolX", 1e-9)
        n_correction = config.get("nCorrection", 100)
        lr = config.get("learningRate", 1.0)
        use_wolfe = config.get("lineSearch", True)

        x_flat, unravel = ravel_pytree(x)

        def feval_flat(xf):
            loss, grad = feval(unravel(xf))
            gf, _ = ravel_pytree(grad)
            return float(loss), gf

        f, g = feval_flat(x_flat)
        f_hist = [f]
        current_f_evals = 1
        state["funcEval"] = state.get("funcEval", 0) + 1

        if float(jnp.abs(g).sum()) <= 1e-12 * g.size:
            return unravel(x_flat), f_hist

        old_dirs = state.get("old_dirs", [])
        old_stps = state.get("old_stps", [])
        g_prev = state.get("g_prev", None)
        d = state.get("d", None)
        t = 1.0
        H_diag = state.get("H_diag", 1.0)

        n_iter = 0
        while n_iter < max_iter:
            n_iter += 1
            if g_prev is None:
                d = -g
            else:
                y = g - g_prev
                s = d * t
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(old_dirs) == n_correction:
                        old_dirs.pop(0)
                        old_stps.pop(0)
                    old_dirs.append(s)
                    old_stps.append(y)
                    H_diag = ys / float(jnp.dot(y, y))
                # two-loop recursion
                k = len(old_dirs)
                ro = [1.0 / float(jnp.dot(old_stps[i], old_dirs[i])) for i in range(k)]
                al = [0.0] * k
                q = -g
                for i in range(k - 1, -1, -1):
                    al[i] = float(jnp.dot(old_dirs[i], q)) * ro[i]
                    q = q - al[i] * old_stps[i]
                d = q * H_diag
                for i in range(k):
                    be = float(jnp.dot(old_stps[i], d)) * ro[i]
                    d = d + old_dirs[i] * (al[i] - be)
            g_prev = g

            gtd = float(jnp.dot(g, d))
            if gtd > -tol_x:
                break
            t = min(1.0, 1.0 / float(jnp.abs(g).sum())) if n_iter == 1 else lr

            if use_wolfe:
                f, g, x_flat, t, ls_evals = ls_wolfe(feval_flat, x_flat, t, d, f, g, gtd)
                current_f_evals += ls_evals
            else:
                x_flat = x_flat + t * d
                f, g = feval_flat(x_flat)
                current_f_evals += 1
            f_hist.append(f)
            state["funcEval"] = state.get("funcEval", 0) + 1

            if current_f_evals >= max_eval:
                break
            if float(jnp.abs(g).sum()) <= 1e-12 * g.size:
                break
            if float(jnp.abs(t * d).sum()) <= tol_x:
                break
            if len(f_hist) > 1 and abs(f_hist[-1] - f_hist[-2]) < tol_fun:
                break

        state["old_dirs"] = old_dirs
        state["old_stps"] = old_stps
        state["g_prev"] = g_prev
        state["d"] = d
        state["H_diag"] = H_diag
        return unravel(x_flat), f_hist

    def clear_history(self, state: Table):
        for k in ("old_dirs", "old_stps", "g_prev", "d", "H_diag", "funcEval"):
            if k in state:
                del state[k]
        return state
