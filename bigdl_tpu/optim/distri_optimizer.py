"""DistriOptimizer — synchronous data-parallel training over a device mesh
(ref optim/DistriOptimizer.scala, call stack SURVEY.md §3.1).

Mapping from the reference, piece by piece:

- Spark partition per node + model replica     -> mesh axis ``data``; the
  (initThreadModels :344-410)                     model is written once, XLA
                                                  replicates per device
- AllReduceParameter reduce-scatter/all-gather -> XLA all-reduce over ICI,
  (putGradients/getWeights)                       emitted by jit from the
                                                  sharded-batch mean loss
- FP16 wire compression                        -> bf16 compute policy
  (FP16CompressedTensor)                          (on-chip cast, no wire)
- per-partition weight update                  -> optional ZeRO-1 optimizer
  (optimMethod.optimize on MY slice :232)         state sharding
- straggler dropping (invokeAndWait2 timeout)  -> gradient masking: an XLA
  (DistriOptimizer.scala:154-172, threshold        dispatch cannot be
  :245-278)                                        cancelled, so replicas
                                                  over the kth-largest
                                                  time threshold are
                                                  masked out of the NEXT
                                                  aggregation instead —
                                                  psum(w*g)/sum(w), the
                                                  reference's div-by-
                                                  finishedModelNum (see
                                                  optim/straggler.py)
- Metrics phase breakdown :114-118             -> step metrics below

Multi-host: each process feeds its local batch shard;
``jax.make_array_from_process_local_data`` assembles the global array
(the Spark-RDD locality role, ZippedPartitionsWithLocalityRDD).
"""
from __future__ import annotations

import logging
import os
import time

import jax

from bigdl_tpu.parallel.compat import shard_map
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Context
from bigdl_tpu.obs import events as obs_events
from bigdl_tpu.optim.local_optimizer import (LocalOptimizer,
                                             _HostSyncWindow, _PendingStep,
                                             _finite_all,
                                             _model_fingerprint,
                                             _where_finite, validate)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.parallel.mesh import data_parallel_mesh
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random import RNG
from bigdl_tpu.utils.table import T

logger = logging.getLogger("bigdl_tpu.optim")


def _put_host(arr, sharding):
    """Host array → device array under ``sharding``, multi-host-safe:
    every process holds the FULL host copy (replicated state, or a
    checkpoint/anchor restore) and contributes its addressable slices —
    the one placement primitive this jax supports for arbitrary
    cross-process shardings."""
    import jax as _jax
    arr = np.asarray(arr)
    return _jax.make_array_from_callback(arr.shape, sharding,
                                         lambda idx: arr[idx])


class DistriOptimizer(LocalOptimizer):
    def __init__(self, model, dataset, criterion, mesh=None,
                 drop_percentage: float = 0.0, tensor_parallel: bool = False,
                 zero1: bool = False, gradient_compression: str = None,
                 pipeline_stages: int = None, pipeline_schedule: str = "1f1b",
                 pipeline_microbatches: int = None,
                 expert_parallel: bool = False,
                 sequence_parallel: bool = False):
        """``tensor_parallel=True`` with a mesh containing a ``model`` axis
        shards eligible weights (and their optimizer state) over that axis
        via ``parallel.sharding.shard_params_rule`` — hybrid DP x TP with
        the same user API as pure DP.

        ``zero1=True`` shards optimizer state over the ``data`` axis
        (ZeRO-1) — the direct analogue of the reference's owner-partition
        update (each AllReduceParameter partition updates only its weight
        slice, DistriOptimizer.scala:232); XLA moves the state shards as
        needed and HBM per chip drops by ~|opt_state|*(1-1/N).

        ``gradient_compression="bf16"`` is the reference's FP16 wire codec
        (parameters/FP16CompressedTensor.scala: gradients truncated to 16
        bits before crossing the network): the step is built with
        ``shard_map`` so each device computes local grads, casts them to
        bf16, and the cross-device all-reduce moves bf16 — halving
        ICI/DCN gradient traffic — before the f32 update.

        ``pipeline_stages=P`` trains a ``Sequential`` model with pipeline
        parallelism over a ``pipe`` mesh axis — the model is stage-
        partitioned automatically (``parallel/pipeline_model.py``) and the
        batch streams through as ``pipeline_microbatches`` microbatches
        (default 2·P) under ``pipeline_schedule``: ``"1f1b"`` (bounded
        activation memory) or ``"gpipe"`` (optionally with
        ``set_gradient_checkpointing``).  Same front door as every other
        distribution mode (ref Optimizer.scala:151-186).  Stage sharding
        owns the whole mesh, so it composes with none of
        tensor_parallel/zero1/gradient_compression — and gradients never
        cross ranks under PP (each stage's grads stay home), so there is
        no wire to compress."""
        super().__init__(model, dataset, criterion)
        if gradient_compression not in (None, "bf16"):
            raise ValueError("gradient_compression must be None or 'bf16'")
        if pipeline_stages is not None:
            if tensor_parallel or zero1 or gradient_compression \
                    or expert_parallel or sequence_parallel:
                raise ValueError(
                    "pipeline_stages owns the mesh; it does not combine "
                    "with tensor_parallel/zero1/gradient_compression/"
                    "expert_parallel/sequence_parallel")
            if pipeline_schedule not in ("1f1b", "gpipe"):
                raise ValueError("pipeline_schedule must be '1f1b' or "
                                 "'gpipe'")
            if jax.process_count() > 1:
                # multi-host pipeline: stages span hosts over DCN.  Every
                # process must feed the IDENTICAL global batch (operands
                # ride replicated), so a per-process-sharded dataset
                # cannot drive it — fail at construction, not at
                # optimize() after the user's setup work
                from bigdl_tpu.optim.optimizer import is_distributed_dataset
                if is_distributed_dataset(dataset):
                    raise ValueError(
                        "multi-host pipeline_stages needs a replicated "
                        "(non-distributed) dataset: every process feeds "
                        "the identical global batch")
            if mesh is None:
                from bigdl_tpu.parallel.mesh import make_mesh
                devs = jax.devices()
                if len(devs) < pipeline_stages:
                    raise ValueError(
                        f"pipeline_stages={pipeline_stages} needs that "
                        f"many devices, have {len(devs)}")
                if jax.process_count() > 1 and len(devs) != pipeline_stages:
                    # devs[:P] would be a host-0-only mesh while every
                    # process must join the pipeline collectives — the
                    # multi-host spanning layout needs an explicit choice
                    raise ValueError(
                        f"multi-host pipeline with {len(devs)} global "
                        f"devices and pipeline_stages={pipeline_stages}: "
                        "pass an explicit mesh (e.g. make_mesh({'data': "
                        f"{len(devs) // pipeline_stages}, 'pipe': "
                        f"{pipeline_stages}}})) so every process holds "
                        "mesh devices")
                # default mesh: the first P devices as a pure pipe axis
                # (pass an explicit {'data': d, 'pipe': P} mesh to use
                # the rest for hybrid dp x pp)
                mesh = make_mesh({"pipe": pipeline_stages},
                                 devs[:pipeline_stages])
            if "pipe" not in mesh.axis_names or \
                    mesh.shape["pipe"] != pipeline_stages:
                raise ValueError(
                    f"mesh needs a 'pipe' axis of size {pipeline_stages}, "
                    f"got {dict(mesh.shape)}")
            if set(mesh.axis_names) - {"pipe", "data"}:
                raise ValueError(
                    "pipeline meshes support 'pipe' plus an optional "
                    f"'data' axis (hybrid dp x pp), got {mesh.axis_names}")
        elif expert_parallel:
            if tensor_parallel or zero1 or gradient_compression \
                    or sequence_parallel:
                raise ValueError(
                    "expert_parallel composes with data parallelism only "
                    "(mesh {'data': d, 'expert': e}); tensor_parallel/"
                    "zero1/gradient_compression/sequence_parallel assume "
                    "replicated or data-sharded params, not expert-"
                    "sharded ones")
            if mesh is None or "expert" not in mesh.axis_names:
                raise ValueError(
                    "expert_parallel needs a mesh with an 'expert' axis")
        elif sequence_parallel:
            if tensor_parallel or zero1 or gradient_compression:
                raise ValueError(
                    "sequence_parallel composes with data parallelism "
                    "only (mesh {'data': d, 'seq': s})")
            if mesh is None or "seq" not in mesh.axis_names \
                    or "data" not in mesh.axis_names:
                raise ValueError(
                    "sequence_parallel needs a mesh with 'data' and "
                    "'seq' axes (pure SP: use {'data': 1, 'seq': s})")
        elif gradient_compression and tensor_parallel:
            raise ValueError(
                "gradient_compression composes with DP and zero1, not "
                "tensor_parallel: TP grads are per-leaf sharded over the "
                "model axis, so there is no single flat gradient wire to "
                "compress (the reference has no TP at all)")
        self.gradient_compression = gradient_compression
        self._z1c_flat = None  # padded flat-param length (compressed ZeRO-1)
        self.pipeline_stages = pipeline_stages
        self.pipeline_schedule = pipeline_schedule
        self.pipeline_microbatches = pipeline_microbatches
        self._pipe_plan = None
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.tensor_parallel = tensor_parallel
        self.zero1 = zero1
        self.expert_parallel = expert_parallel
        self.sequence_parallel = sequence_parallel
        self._straggler = None
        if drop_percentage:
            # constructor shorthand: drop and cap at the same fraction
            # (the reference arms both through setDropMoudleProperty,
            # Optimizer.scala:116-124)
            self.set_drop_module_property(drop_percentage, drop_percentage)

    def set_drop_module_property(self, drop_percentage: float,
                                 max_drop_percentage: float,
                                 batch_size: int = 100,
                                 warmup_iteration: int = 200,
                                 time_source=None):
        """Arm straggler dropping (ref Optimizer.setDropMoudleProperty,
        Optimizer.scala:116-124; drop/threshold machinery
        DistriOptimizer.scala:154-172, :245-278).  Each data replica is
        one reference "task": replicas whose measured step time exceeded
        the kth-largest threshold are masked out of the gradient
        aggregation — ``psum(w*g)/sum(w)``, the reference's
        ``gradientPartition.div(finishedModelNum)`` — one dispatch after
        the measurement (an XLA collective cannot be cancelled mid-
        flight the way ``invokeAndWait2`` cancels a JVM task).
        ``time_source(local_wall) -> (n_tasks,) seconds`` overrides the
        per-process wall-clock default (tests inject synthetic
        schedules); see optim/straggler.py."""
        from bigdl_tpu.optim.straggler import StragglerPolicy
        if not drop_percentage:
            self._straggler = None
            return self
        if (self.pipeline_stages is not None or self.expert_parallel
                or self.sequence_parallel or self.tensor_parallel):
            raise ValueError(
                "straggler drop masks per-DATA-replica gradients; it "
                "composes with DP, zero1 and gradient_compression only "
                "(the reference's tasks are data-parallel model clones)")
        if "data" not in self.mesh.axis_names:
            raise ValueError("straggler drop needs a 'data' mesh axis")
        self._straggler = StragglerPolicy(
            n_tasks=self.mesh.shape["data"],
            drop_percentage=drop_percentage,
            max_drop_percentage=max_drop_percentage,
            compute_threshold_batch_size=batch_size,
            warmup_iteration=warmup_iteration,
            time_source=time_source)
        return self

    def _straggler_task_times(self, fetch_wall: float,
                              step_wall: float) -> np.ndarray:
        """Per-task (= per data-replica) seconds for this iteration.

        Multi-host: the signal is each process's HOST-SIDE wall (data
        fetch + preprocessing), assigned to the replicas that process
        owns.  The dispatch wall itself is useless here — the collective
        is bulk-synchronous, so every process's step ENDS at the same
        instant and a process that entered late (because its fetch was
        slow) measures a SHORTER dispatch than the healthy hosts; the
        fetch wall is the part of the iteration where a straggling host
        actually spends its excess time.  Single host: no skew is
        observable within one XLA dispatch, so every task reads the same
        total wall and dropping never engages."""
        pol = self._straggler
        if pol.time_source is not None:
            times = pol.task_times(fetch_wall + step_wall)
            if jax.process_count() > 1:
                # every process must hold IDENTICAL policy state or they
                # disagree on accept/reject and deadlock the collective:
                # merge the per-process views (any process seeing a task
                # slow counts)
                from jax.experimental import multihost_utils
                allv = np.asarray(multihost_utils.process_allgather(
                    np.asarray(times, np.float64)))
                times = allv.reshape(jax.process_count(), -1).max(axis=0)
            return times
        if jax.process_count() == 1:
            return pol.task_times(fetch_wall + step_wall)
        from jax.experimental import multihost_utils
        walls = np.asarray(multihost_utils.process_allgather(
            np.asarray(fetch_wall, np.float64))).reshape(-1)
        ax = list(self.mesh.axis_names).index("data")
        devs = np.moveaxis(self.mesh.devices, ax, 0).reshape(
            pol.n_tasks, -1)
        return np.array([walls[row[0].process_index] for row in devs],
                        np.float64)

    def _maybe_validate(self, params, net_state, state, force=False):
        # triggers first (every_epoch is stateful — probe exactly once),
        # THEN the pipeline unpack: validation consumes module-tree
        # pytrees, but unpacking the stage-stacked arrays is a full-model
        # host gather that must not run on every non-firing iteration
        if not force and (self.validation_trigger is None
                          or not self.validation_trigger(state)):
            return
        if self._pipe_plan is not None:
            params = self._pipe_plan.unpack_params(params)
            net_state = self._pipe_plan.unpack_state(net_state)
        super()._maybe_validate(params, net_state, state, force=True)

    def _maybe_checkpoint(self, params, net_state, opt_state, state,
                          force=False, neval_label=None):
        if not force and (self.checkpoint_trigger is None
                          or not self.checkpoint_trigger(state)):
            return
        if self._pipe_plan is not None:
            # unpack only when actually firing (full-model host gather),
            # and BEFORE the process gate: multi-host stage gathering is
            # a collective every process must join.  opt_state stays
            # stage-stacked — a resumed run re-packs the same partition,
            # so set_optim_state round-trips.
            params = self._pipe_plan.unpack_params(params)
            net_state = self._pipe_plan.unpack_state(net_state)
            # opt_state leaves are stage-stacked too: bring host copies
            # so process 0 can pickle them (a multi-host sharded array
            # is not picklable)
            opt_state = jax.tree_util.tree_map(
                self._pipe_plan._gather_stacked, opt_state)
            # params are replicated post-unpack, so exactly one process
            # writes — the reference gathers slices to the driver and
            # saves once (getModel + File.save, DistriOptimizer.scala:
            # 320-342); writing from every host would race on a shared
            # checkpoint path.
            if jax.process_index() != 0:
                return
        # non-pipeline: the base decides per snapshot — replicated state
        # writes from process 0 only; zero1 state sharded across
        # processes writes one shard file per process
        # (resilience/checkpoint.py, docs/resilience.md)
        super()._maybe_checkpoint(params, net_state, opt_state, state,
                                  force=True, neval_label=neval_label)

    def _preemption_pending(self) -> bool:
        """Multi-host preemption barrier: ANY process's SIGTERM stops all
        of them at the same iteration (one host exiting alone would
        strand the rest in a dead collective).  The merge is a tiny
        allgather per iteration, paid only while the handler is armed —
        install it on EVERY process (``Engine.install_preemption_handler``
        from the shared launcher path) or the collective deadlocks."""
        if jax.process_count() == 1:
            return Engine.preempted()
        if not Engine.preemption_armed():
            if Engine.preempted():
                from bigdl_tpu.utils.log import warn_every
                warn_every(
                    logger, "preempt-unarmed", 30.0,
                    "preemption requested but the handler is not armed: "
                    "a multi-host run only honors the notice when "
                    "Engine.install_preemption_handler() ran on EVERY "
                    "process (the stop flag must merge as a collective); "
                    "ignoring it")
            return False
        from jax.experimental import multihost_utils
        flags = self._guarded(lambda: np.asarray(
            multihost_utils.process_allgather(
                np.asarray(1.0 if Engine.preempted() else 0.0,
                           np.float32))))
        return bool(flags.max() > 0)

    def _expert_param_specs(self, params):
        """Path-aware sharding tree: the expert-stacked leaves of ``MoE``
        modules (w1/b1/w2/b2, leading dim = n_experts) shard dim 0 over
        the ``expert`` axis — the reference has no EP at all (SURVEY.md
        §2.9); the GSPMD partitioning of the MoE dispatch einsums is the
        all-to-all the hand-scheduled parallel/moe.moe_apply spells out.
        Router and every non-MoE param replicate."""
        from bigdl_tpu.nn.moe import MoE
        mesh = self.mesh
        rep = NamedSharding(mesh, P())
        exp = NamedSharding(mesh, P("expert"))
        esize = mesh.shape["expert"]

        def walk(mod, ptree):
            out = {"~": {}}
            is_moe = isinstance(mod, MoE)
            for k, v in ptree.get("~", {}).items():
                shard = (is_moe and k != "router"
                         and np.ndim(v) >= 1 and v.shape[0] % esize == 0)
                out["~"][k] = exp if shard else rep
            for name, child in mod._modules.items():
                out[name] = walk(child, ptree[name])
            return out

        return walk(self.model, params)

    def _mirror_opt_specs(self, opt_state, params, pspec, rep):
        """Optimizer-state subtrees that mirror the param tree (SGD
        velocity, Adagrad variance) inherit the param shardings; anything
        else (scalar counters) replicates."""
        ptd = jax.tree_util.tree_structure(params)
        if not isinstance(opt_state, dict):
            return jax.tree_util.tree_map(lambda _: rep, opt_state)
        out = {}
        for k, sub in opt_state.items():
            if jax.tree_util.tree_structure(sub) == ptd:
                out[k] = pspec
            else:
                out[k] = jax.tree_util.tree_map(lambda _: rep, sub)
        return out

    def _shardings(self, params, net_state, opt_state):
        mesh = self.mesh
        rep = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data")
                             if "data" in mesh.axis_names else P())
        reps = lambda tree: jax.tree_util.tree_map(lambda _: rep, tree)
        if self.expert_parallel:
            pspec = self._expert_param_specs(params)
            ospec = self._mirror_opt_specs(opt_state, params, pspec, rep)
            return pspec, reps(net_state), ospec, data
        if self.tensor_parallel and "model" in mesh.axis_names:
            from bigdl_tpu.parallel.sharding import (shard_params_rule,
                                                     zero1_tp_rule)
            rule = shard_params_rule(mesh, "model")
            orule = zero1_tp_rule(mesh, "data", "model") if self.zero1 else rule
            return (jax.tree_util.tree_map(rule, params), reps(net_state),
                    jax.tree_util.tree_map(orule, opt_state), data)
        if self.zero1:
            from bigdl_tpu.parallel.sharding import zero1_rule
            zrule = zero1_rule(mesh, "data")
            return (reps(params), reps(net_state),
                    jax.tree_util.tree_map(zrule, opt_state), data)
        return reps(params), reps(net_state), reps(opt_state), data

    def _core_step(self, fold_axis=None, grad_transform=None,
                   state_merge=None, update_transform=None,
                   finite_merge=None, taps_merge=None):
        """The train step both builders share: loss_fn, value_and_grad,
        optimizer update.  ``fold_axis`` decorrelates the dropout key per
        replica; ``grad_transform``/``state_merge`` hook the compressed
        path's collectives in; ``update_transform`` replaces the plain
        ``method.update`` (the compressed-ZeRO-1 owner-partition path).
        ``finite_merge`` reconciles the non-finite-guard flag across
        replicas inside shard_map (local grads can be finite on one
        replica and not another; a divergent skip decision would fork the
        replicated params).  ``taps_merge`` does the same for the in-jit
        tap scalars (obs/taps.py): under shard_map they are computed from
        LOCAL gradients, so the shard_map builder pmean-merges them —
        divergent per-replica values behind a replicated out_spec would
        silently report one arbitrary replica."""
        from bigdl_tpu.obs import taps as obs_taps
        model, criterion, method = self.model, self.criterion, self.optim_method
        static_hyper = self._hyper(None)
        del static_hyper["lr"]
        has_scales = self._setup_lr_scales(static_hyper)
        taps_on = obs_taps.enabled(self._taps_enabled)
        # sequence-parallel trainers hand attention layers the mesh so
        # they route through the exact ring collective (nn/attention.py)
        seq_mesh = self.mesh if self.sequence_parallel else None

        def step(params, net_state, opt_state, x, y, lr, key, lr_scales):
            hyper = dict(static_hyper, lr=lr)
            if has_scales:
                hyper["lr_scales"] = lr_scales
            if fold_axis is not None:
                # independent dropout masks per replica (the reference's
                # thread-local RNG per model clone)
                key = jax.random.fold_in(key, jax.lax.axis_index(fold_axis))

            def loss_fn(p):
                out, ns = model.apply(p, x, net_state,
                                      Context(training=True, key=key,
                                              seq_mesh=seq_mesh))
                # in the plain jit path: mean over the GLOBAL batch — with x
                # sharded over "data" and params replicated, jax.grad makes
                # XLA emit the cross-ICI all-reduce; this line IS
                # AllReduceParameter
                return criterion.apply_loss(out, y), ns

            (loss, new_net_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if grad_transform is not None:
                grads, loss = grad_transform(grads, loss)
            if state_merge is not None:
                new_net_state = state_merge(new_net_state)
            finite = _finite_all(loss, grads)
            if finite_merge is not None:
                finite = finite_merge(finite)
            if update_transform is not None:
                new_params, new_opt_state = update_transform(
                    grads, opt_state, params, hyper)
            else:
                new_params, new_opt_state = method.update(
                    grads, opt_state, params, hyper)
            new_params = _where_finite(finite, new_params, params)
            new_opt_state = _where_finite(finite, new_opt_state, opt_state)
            new_net_state = _where_finite(finite, new_net_state, net_state)
            taps = (obs_taps.compute(grads, params, new_params)
                    if taps_on else {})
            if taps and taps_merge is not None:
                taps = taps_merge(taps)
            return (new_params, new_net_state, new_opt_state, loss, finite,
                    taps)

        return step

    def _jit_step(self, step, ps, ns, os_, data_s, x_s=None,
                  x_chunk_s=None, extra_in=()):
        """Shared jit wiring: carried state is donated (buffers recycled in
        place); optimize() passes copies so the module's arrays survive.
        The trailing lr_scales argument rides replicated (prefix sharding
        broadcasts over its pytree) and is never donated.

        ``x_s``/``x_chunk_s`` override the INPUT sharding when it differs
        from the label sharding (sequence parallelism also shards dim T).

        With ``iters_per_dispatch > 1`` the step is wrapped in a
        lax.scan over stacked (n, B, ...) batches — same device-side
        training loop as LocalOptimizer (set_iterations_per_dispatch),
        batch sharded over "data" on dim 1."""
        from bigdl_tpu.serve import xcache
        fn_key = ("distri_step", _model_fingerprint(self.model),
                  type(self.optim_method).__name__)
        rep = NamedSharding(self.mesh, P())
        n = self.iters_per_dispatch
        if n <= 1:
            return xcache.tracked_jit(
                step, fn_key, key_argnums=(3, 4), mesh=self.mesh,
                in_shardings=(ps, ns, os_, x_s or data_s, data_s,
                              rep, rep, rep) + tuple(extra_in),
                out_shardings=(ps, ns, os_, rep, rep, rep),
                donate_argnums=(0, 1, 2),
            )

        if extra_in:
            raise ValueError("extra step operands are single-dispatch "
                             "only (no chunked-scan wiring for them)")
        chunk_data_s = NamedSharding(self.mesh, P(None, "data"))
        return xcache.tracked_jit(
            self._scan_chunk(step, n), fn_key + ("chunk%d" % n,),
            key_argnums=(3, 4), mesh=self.mesh,
            in_shardings=(ps, ns, os_, x_chunk_s or chunk_data_s,
                          chunk_data_s, rep, rep, rep),
            out_shardings=(ps, ns, os_, rep, rep, rep),
            donate_argnums=(0, 1, 2),
        )

    def _build_step_compressed(self):
        """shard_map step with bf16 gradient all-reduce (the FP16 wire codec
        role, ref FP16CompressedTensor.scala:29/parAdd :173-268: compress,
        ship, add).  Params stay replicated f32; only the gradient crossing
        the mesh is 16-bit.

        BatchNorm running stats are computed per shard and pmean-merged —
        the reference's replicas likewise each update their own running
        stats on their sub-batch (BatchNormalization.scala under
        _subModelNumber clones); the global-batch stats of the plain jit
        path are a (slightly tighter) superset of that behavior.

        ``zero1=True`` composes, reproducing the reference's single
        mechanism where the fp16 codec and the owner-partition update are
        one code path (AllReduceParameter.scala:162-235: compressed
        gradient slices land on their owner, which runs optimMethod on
        its slice and serves the updated weights back):

        - local grads ravel to ONE flat vector (the reference's flattened
          getParameters storage), padded to a multiple of the data-axis
          size;
        - ``psum_scatter`` in bf16 — each device receives only its owned
          slice of the summed gradient, and only bf16 bytes cross the
          mesh (vs pmean moving the full vector to every device);
        - the optimizer updates the owned slice with opt state that
          lives data-sharded (ZeRO-1: HBM per chip for optimizer state
          drops by 1/N);
        - ``all_gather`` redistributes the updated f32 slices (the
          reference's getWeights).
        """
        mesh = self.mesh
        method = self.optim_method
        # straggler drop rides this same shard_map path with a f32 wire
        # when compression is off: tasks = data replicas, and the masked
        # aggregation needs the per-replica gradients this builder has
        wire = jnp.bfloat16 if self.gradient_compression else jnp.float32
        masked = self._straggler is not None
        # (w, msum) for the current trace, pushed by the masked step
        # wrapper below so the hooks — whose (grads, loss) signature is
        # fixed by _core_step — can see the mask operand
        mask_cell = []

        def wmean(x, dtype):
            """Weighted replica mean computed in ``dtype`` — with w == 1
            this is exactly pmean(x.astype(dtype)): psum then divide."""
            w, msum = mask_cell[-1]
            return (jax.lax.psum((x * w).astype(dtype), "data")
                    / msum.astype(dtype))

        def loss_mean(grads, loss):
            if mask_cell:
                # the reference's lossSum / finishedModelNum (:226)
                return grads, wmean(loss, loss.dtype)
            return grads, jax.lax.pmean(loss, "data")

        def grad_transform(grads, loss):
            # compress -> all-reduce(mean) over the wire dtype -> f32;
            # masked: psum(w*g)/sum(w) — the reference's div-by-
            # finishedModelNum (DistriOptimizer.scala:231-234)
            if mask_cell:
                grads = jax.tree_util.tree_map(
                    lambda g: wmean(g, wire).astype(g.dtype), grads)
                return grads, wmean(loss, loss.dtype)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g.astype(wire),
                                        "data").astype(g.dtype), grads)
            return grads, jax.lax.pmean(loss, "data")

        def state_merge(net_state):
            def merge(s):
                if not jnp.issubdtype(jnp.asarray(s).dtype, jnp.floating):
                    return s
                if mask_cell:
                    # dropped replicas' BN stats are excluded, like the
                    # reference's cancelled tasks never touching theirs
                    return wmean(s, s.dtype)
                return jax.lax.pmean(s, "data")
            return jax.tree_util.tree_map(merge, net_state)

        update_transform = None
        if self.zero1:
            from jax.flatten_util import ravel_pytree
            if self.state.get("learningRates", None) is not None:
                raise ValueError(
                    "state['learningRates'] (per-param lr scales) is not "
                    "supported with zero1 + gradient_compression: the "
                    "owner-partition update runs on a flat slice, not the "
                    "param tree")
            ndata = mesh.shape["data"]
            # concrete ravel builds the unravel closure; the flat copy is
            # transient (freed after this scope)
            flat0, unravel = ravel_pytree(self.model.params())
            total = int(flat0.size)
            pad = (-total) % ndata
            self._z1c_flat = total + pad
            slice_len = self._z1c_flat // ndata
            del flat0

            def update_transform(grads, opt_state, params, hyper):
                gflat, _ = ravel_pytree(grads)
                if mask_cell:
                    # masked replica contributes zeros; divide by the
                    # finished count instead of ndata
                    gflat = gflat * mask_cell[-1][0]
                gflat = jnp.pad(gflat, (0, pad)).astype(wire)
                gslice = jax.lax.psum_scatter(gflat, "data", tiled=True)
                gslice = gslice.astype(jnp.float32) / (
                    mask_cell[-1][1] if mask_cell else ndata)
                pflat, _ = ravel_pytree(params)
                pflat = jnp.pad(pflat, (0, pad))
                rank = jax.lax.axis_index("data")
                pslice = jax.lax.dynamic_slice_in_dim(
                    pflat, rank * slice_len, slice_len)
                new_pslice, new_opt = method.update(
                    gslice, opt_state, pslice, hyper)
                new_flat = jax.lax.all_gather(new_pslice, "data", tiled=True)
                return unravel(new_flat[:total]), new_opt

        core = self._core_step(
            fold_axis="data",
            grad_transform=loss_mean if self.zero1 else grad_transform,
            state_merge=state_merge, update_transform=update_transform,
            # non-finite guard: replicas see LOCAL grads here (the zero1
            # path aggregates inside update_transform), so one replica's
            # NaN must veto the update on every replica or the
            # where-select forks the replicated params
            finite_merge=lambda f: jax.lax.pmin(
                f.astype(jnp.int32), "data").astype(jnp.bool_),
            # tap scalars are per-replica inside shard_map: pmean to a
            # truly replicated value (grad_norm then reads as the
            # replica-mean of local-gradient norms — docs/observability.md)
            taps_merge=lambda t: {k: jax.lax.pmean(v, "data")
                                  for k, v in t.items()})
        if masked:
            # 9th operand: the (n_tasks,) 0/1 drop mask, replicated —
            # push (w_this_replica, finished_count) for the hooks above
            def step(params, ns, os_, x, y, lr, key, lr_scales, mask):
                w = mask[jax.lax.axis_index("data")]
                mask_cell.append((w, mask.sum()))
                try:
                    return core(params, ns, os_, x, y, lr, key, lr_scales)
                finally:
                    mask_cell.pop()
        else:
            step = core
        rep, data = P(), P("data")
        if self.zero1:
            # flat mirrors of the parameter vector shard over data; scalar
            # leaves (e.g. Adagrad's 0-d step counter, identical on every
            # rank) stay replicated — same guard as zero1_rule
            ospec = jax.tree_util.tree_map(
                self._z1c_leaf_spec, self._z1c_opt_shape())
        else:
            ospec = rep
        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(rep, rep, ospec, data, data, rep, rep, rep)
            + ((rep,) if masked else ()),
            out_specs=(rep, rep, ospec, rep, rep, rep),
            check_vma=False,
        )
        params, net_state, opt_state = self._state_trees()
        rep_s = NamedSharding(mesh, rep)
        data_s = NamedSharding(mesh, data)
        reps = lambda tree: jax.tree_util.tree_map(lambda _: rep_s, tree)
        if self.zero1:
            opt_s = jax.tree_util.tree_map(
                lambda l: NamedSharding(mesh, self._z1c_leaf_spec(l)),
                self._z1c_opt_shape())
        else:
            opt_s = reps(opt_state)
        if masked:
            if self.iters_per_dispatch > 1:
                raise ValueError(
                    "straggler drop recomputes the mask every iteration "
                    "(ref DistriOptimizer.scala:154: the timeout applies "
                    "per invokeAndWait2 round); it does not combine with "
                    "set_iterations_per_dispatch > 1")
            return self._jit_step(sharded, reps(params), reps(net_state),
                                  opt_s, data_s, extra_in=(rep_s,))
        return self._jit_step(sharded, reps(params), reps(net_state),
                              opt_s, data_s)

    def _z1c_opt_shape(self):
        """Abstract optimizer-state tree for the flat compressed-ZeRO-1
        parameter vector."""
        return jax.eval_shape(
            self.optim_method.init_state,
            jax.ShapeDtypeStruct((self._z1c_flat,), jnp.float32))

    def _z1c_leaf_spec(self, leaf):
        ndata = self.mesh.shape["data"]
        if leaf.ndim >= 1 and leaf.shape[0] % ndata == 0:
            return P("data")
        return P()

    def _initial_opt_state(self, params):
        """Compressed ZeRO-1 keeps optimizer state as data-sharded slices
        of the flat parameter vector (the reference's per-partition
        optimMethod state, AllReduceParameter.scala:162-235) — init it
        flat; everything else defers to the base builder."""
        z1c = ((self.gradient_compression or self._straggler is not None)
               and self.zero1)
        if z1c and self._resume_opt_state is None:
            state = self.optim_method.init_state(
                jnp.zeros((self._z1c_flat,), jnp.float32))
            return jax.tree_util.tree_map(
                lambda v: jax.device_put(
                    v, NamedSharding(self.mesh, self._z1c_leaf_spec(v))),
                state)
        if z1c and self._resume_opt_state is not None:
            return self._adapt_z1c_state(self._resume_opt_state)
        if self.zero1 and self._resume_opt_state is not None:
            # world-size-agnostic restore: the snapshot holds the FULL
            # logical tree (load_latest_checkpoint reassembles shards);
            # partition it over THIS mesh's data axis — which may differ
            # from the saving run's (dp=4 checkpoint, dp=3 restore)
            from bigdl_tpu.parallel.sharding import zero1_rule
            rule = zero1_rule(self.mesh, "data")
            if self.tensor_parallel and "model" in self.mesh.axis_names:
                from bigdl_tpu.parallel.sharding import zero1_tp_rule
                rule = zero1_tp_rule(self.mesh, "data", "model")
            return jax.tree_util.tree_map(
                lambda v: _put_host(np.asarray(v), rule(np.asarray(v))),
                self._resume_opt_state)
        return super()._initial_opt_state(params)

    def _adapt_z1c_state(self, host_state):
        """Restore flat compressed-ZeRO-1 optimizer state saved at ANY
        world size: the stored flat mirrors carry the saving run's
        padding (flat param count rounded up to ITS data-axis size), so
        leaves are trimmed to the model's true flat length and re-padded
        for this mesh before sharding.  Scalar leaves (step counters)
        pass through."""
        from jax.flatten_util import ravel_pytree
        total = int(ravel_pytree(self.model.params())[0].size)

        def adapt(v):
            arr = np.asarray(v)
            if arr.ndim >= 1 and arr.shape[0] >= total:
                arr = np.pad(arr[:total],
                             [(0, self._z1c_flat - total)]
                             + [(0, 0)] * (arr.ndim - 1))
            return _put_host(
                arr, NamedSharding(self.mesh, self._z1c_leaf_spec(arr)))

        return jax.tree_util.tree_map(adapt, host_state)

    def _state_trees(self):
        # used only to derive sharding specs: opt_state as abstract
        # ShapeDtypeStructs (the rules read .ndim/.shape), so building the
        # step never materializes a second model-sized state tree in HBM
        params = self.model.params()
        net_state = self.model.state()
        opt_state = jax.eval_shape(self.optim_method.init_state, params)
        return params, net_state, opt_state

    def _build_step_pipeline(self):
        """Pipeline-parallel train step through the same Optimizer front
        door (ref Optimizer.scala:151-186): partition the Sequential model
        into P stages, stream the batch as M microbatches under the chosen
        schedule, update each stage's params with the stage-local grads.
        Params/opt-state/net-state live stage-sharded on the ``pipe`` axis
        — per-device model memory is O(|model|/P), the point of PP."""
        from bigdl_tpu.parallel.pipeline import (pipeline_apply,
                                                 pipeline_train_1f1b)
        from bigdl_tpu.parallel.pipeline_model import partition_sequential


        # Shape peek from the TRAIN stream (the eval pass may end with a
        # partial batch and its first batch can differ from the looped
        # train batch size), with the host RNG snapshotted/restored: the
        # peek's shuffle permutation and augmentation draws must not
        # advance the stream, or every later batch would shift and the
        # trajectory would silently diverge from an identical
        # non-pipeline run.  (A PreFetch stage in the pipeline draws from
        # per-thread derived streams this snapshot cannot cover.)
        rng_state = RNG.np_rng().get_state()
        peek = next(iter(self.dataset.data(train=True)))
        RNG.np_rng().set_state(rng_state)
        xb = np.asarray(peek.data)
        B = xb.shape[0]
        M = self.pipeline_microbatches or 2 * self.pipeline_stages
        if B % M:
            raise ValueError(
                f"batch size {B} is not divisible by "
                f"pipeline_microbatches={M}")
        data_axis = ("data" if "data" in self.mesh.axis_names
                     and self.mesh.shape["data"] > 1 else None)
        if data_axis and (B // M) % self.mesh.shape["data"]:
            raise ValueError(
                f"microbatch size {B // M} is not divisible by the data "
                f"axis ({self.mesh.shape['data']}) — hybrid dp x pp "
                "shards each microbatch across the data replicas")
        plan = partition_sequential(self.model, self.pipeline_stages,
                                    (B // M,) + xb.shape[1:], axis="pipe")
        self._pipe_plan = plan
        logger.info("pipeline partition (schedule=%s, %d microbatches):\n%s",
                    self.pipeline_schedule, M, plan.describe())

        criterion, method = self.criterion, self.optim_method
        static_hyper = self._hyper(None)
        del static_hyper["lr"]
        if self._setup_lr_scales(static_hyper):
            raise ValueError("state['learningRates'] (per-param lr scales) "
                             "is not supported with pipeline_stages")
        mesh, schedule, remat = self.mesh, self.pipeline_schedule, self.remat
        loss_fn = plan.make_loss_fn(criterion)
        from bigdl_tpu.obs import taps as obs_taps
        taps_on = obs_taps.enabled(self._taps_enabled)

        def step(stacked_p, stacked_s, opt_state, x, y, lr, key, lr_scales):
            hyper = dict(static_hyper, lr=lr)
            xf = plan.pack_input(x.reshape((M, plan.mb) + x.shape[1:]))
            tm = y.reshape((M, plan.mb) + y.shape[1:])
            stage_fn = plan.make_stage_fn(key, fold_axis=data_axis)
            if schedule == "1f1b":
                loss, grads, new_s = pipeline_train_1f1b(
                    stage_fn, loss_fn, stacked_p, xf, tm, mesh, "pipe",
                    stage_state=stacked_s, data_axis=data_axis)
            else:
                def gpipe_loss(p, s):
                    outs, ns = pipeline_apply(stage_fn, p, xf, mesh, "pipe",
                                              remat=remat, stage_state=s,
                                              data_axis=data_axis)
                    return jax.vmap(loss_fn)(outs, tm).mean(), ns

                (loss, new_s), grads = jax.value_and_grad(
                    gpipe_loss, has_aux=True)(stacked_p, stacked_s)
            finite = _finite_all(loss, grads)
            new_p, new_opt = method.update(grads, opt_state, stacked_p,
                                           hyper)
            new_p = _where_finite(finite, new_p, stacked_p)
            new_opt = _where_finite(finite, new_opt, opt_state)
            new_s = _where_finite(finite, new_s, stacked_s)
            # taps over the stage-stacked trees: norms cover every
            # stage's params/grads at once (the stacking is just layout)
            taps = (obs_taps.compute(grads, stacked_p, new_p)
                    if taps_on else {})
            return new_p, new_s, new_opt, loss, finite, taps

        pipe = NamedSharding(mesh, P("pipe"))
        rep = NamedSharding(mesh, P())
        # opt-state leaves mirror the (P, max) stacked params and shard
        # over "pipe"; scalar leaves (Adagrad's step counter) replicate
        opt_shape = jax.eval_shape(
            method.init_state,
            jax.ShapeDtypeStruct((plan.n_stages, plan.max_p), jnp.float32))
        opt_s = jax.tree_util.tree_map(
            lambda l: pipe if l.ndim >= 1
            and l.shape[0] % plan.n_stages == 0 else rep, opt_shape)
        n = self.iters_per_dispatch
        fn = step if n <= 1 else self._scan_chunk(step, n)
        from bigdl_tpu.serve import xcache
        return xcache.tracked_jit(
            fn, ("pipeline_step", _model_fingerprint(self.model),
                 type(method).__name__, plan.n_stages,
                 "chunk%d" % n if n > 1 else "single"),
            key_argnums=(3, 4), mesh=mesh,
            in_shardings=(pipe, pipe, opt_s, rep, rep, rep, rep, rep),
            out_shardings=(pipe, pipe, opt_s, rep, rep, rep),
            donate_argnums=(0, 1, 2),
        )

    def _build_step(self):
        if self.pipeline_stages is not None:
            return self._build_step_pipeline()
        if self.gradient_compression or self._straggler is not None:
            # straggler drop needs the per-replica gradients only the
            # shard_map builder sees; it rides that path with a f32 wire
            # when compression is off
            return self._build_step_compressed()
        step = self._core_step()
        params, net_state, opt_state = self._state_trees()
        ps, ns, os_, data_s = self._shardings(params, net_state, opt_state)
        x_s = x_chunk_s = None
        if self.sequence_parallel:
            x_s = NamedSharding(self.mesh, P("data", "seq"))
            x_chunk_s = NamedSharding(self.mesh, P(None, "data", "seq"))
        return self._jit_step(step, ps, ns, os_, data_s, x_s, x_chunk_s)

    def _device_put_batch(self, x, y, stacked: bool = False):
        """Assemble the global sharded batch from this process's local
        shard.  ``stacked=True``: (n, local_B, ...) chunk for the
        device-side loop — sharded over "data" on dim 1."""
        mesh = self.mesh
        if self.pipeline_stages is not None:
            # pipeline operands arrive replicated and the engine's
            # shard_map reshards them (pure pp: in_specs P(); hybrid:
            # P(None, "data") — so hybrid pays a d-times-larger host
            # transfer than strictly needed; acceptable at current batch
            # sizes, revisit with a reshaped device_put if it shows up)
            spec = P()
        elif "data" in mesh.axis_names:
            spec = P(None, "data") if stacked else P("data")
        else:
            spec = P()   # e.g. a pure-EP mesh: batch replicates
        xspec = spec
        if self.sequence_parallel and spec != P():
            # inputs additionally shard their time dim over "seq"
            t_dim = 2 if stacked else 1
            xa = np.asarray(x)
            if xa.ndim <= t_dim or xa.shape[t_dim] % mesh.shape["seq"]:
                raise ValueError(
                    f"sequence_parallel needs input dim {t_dim} (time) "
                    f"divisible by the seq axis ({mesh.shape['seq']}); "
                    f"got shape {xa.shape}")
            xspec = (P(None, "data", "seq") if stacked
                     else P("data", "seq"))
        xsh = NamedSharding(mesh, xspec)
        ysh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return (jax.device_put(jnp.asarray(x), xsh),
                    jax.device_put(jnp.asarray(y), ysh))
        return (jax.make_array_from_process_local_data(xsh, np.asarray(x)),
                jax.make_array_from_process_local_data(ysh, np.asarray(y)))

    def _global_records_factor(self) -> int:
        """Host-batch → global-record multiplier for the prefetch
        producer's epoch arithmetic: multi-host data-sharded batches
        assemble ``process_count`` local shards into one global array
        (``make_array_from_process_local_data``); pipeline operands ride
        replicated, so their global batch equals the local one."""
        if jax.process_count() == 1 or self.pipeline_stages is not None:
            return 1
        if "data" in self.mesh.axis_names:
            return jax.process_count()
        return 1

    # -- elastic recovery (resilience/elastic.py, docs/resilience.md) ------

    def _elastic_session(self):
        """Arm recover-in-place for this run, or return None (and train
        with the historical fail-fast contract).  Armed only when every
        parameter bit is redundant across the surviving processes — pure
        data-parallel layouts (plain DP, zero1, gradient compression):
        pipeline/tensor/expert/sequence parallelism shard params across
        processes, so a dead peer takes the only copy of its slice."""
        from bigdl_tpu.resilience import elastic
        if not elastic.enabled() or jax.process_count() == 1:
            return None
        rt = elastic.runtime()
        if not rt.armed:
            logger.warning(
                "BIGDL_ELASTIC=1 but the job was not brought up through "
                "the elastic runtime (Engine.init_distributed with the "
                "flag set, or resilience.elastic.initialize): recover-in-"
                "place disabled — the stock runtime's heartbeat defaults "
                "abort survivors before any re-form could run")
            return None
        mode = None
        if self.pipeline_stages is not None:
            mode = "pipeline_stages"
        elif self.tensor_parallel:
            mode = "tensor_parallel"
        elif self.expert_parallel:
            mode = "expert_parallel"
        elif self.sequence_parallel:
            mode = "sequence_parallel"
        elif self._straggler is not None:
            mode = "straggler dropping"
        if mode is not None:
            logger.warning(
                "BIGDL_ELASTIC=1 ignored: %s is keyed to the original "
                "process world (params or policy state are not redundant "
                "across survivors); this run keeps the fail-fast "
                "watchdog contract", mode)
            return None
        try:
            cadence = max(1, int(os.environ.get("BIGDL_ELASTIC_ANCHOR",
                                                "1")))
        except ValueError:
            cadence = 1
        return {"keeper": elastic.AnchorKeeper(), "gather": None,
                "cadence": cadence}

    def _elastic_gather_fn(self):
        """The anchor gather: one dispatch producing fresh REPLICATED
        copies of (params, net_state, opt_state) — zero1 shards all-
        gather back to full leaves, so every survivor holds a complete
        host snapshot after the background D2H (the redundancy recovery
        reshards from)."""
        rep = NamedSharding(self.mesh, P())
        copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
        return jax.jit(lambda p, s, o: (copy(p), copy(s), copy(o)),
                       out_shardings=(rep, rep, rep))

    def _elastic_offer(self, params, net_state, opt_state, state, count):
        """Enqueue a consistent anchor snapshot (async: the collective
        dispatches here, the D2H lands on the keeper's thread)."""
        es = self._elastic
        if es["gather"] is None:
            es["gather"] = self._elastic_gather_fn()
        pipeline = self._train_pipeline
        snap = T()
        snap.update(state)
        payload = {"state": snap, "neval": int(state["neval"]),
                   "epoch": int(state["epoch"]), "count": int(count),
                   "rng": (pipeline.rng_snapshot() if pipeline is not None
                           else RNG.snapshot())}
        # abandonable: on sync-dispatch backends the gather collective
        # runs right here, and a dead peer must not wedge the loop
        trees = self._guarded(
            lambda: es["gather"](params, net_state, opt_state))
        es["keeper"].offer(trees, payload)

    def _guarded(self, fn):
        """Host-blocking work (window flush, validation, checkpoint,
        preemption merge) runs abandonably while elastic is armed: a
        collective with a dead peer hangs forever on this backend, and
        the loop must reach its recovery point instead."""
        if self._elastic is None:
            return fn()
        from bigdl_tpu.resilience import elastic
        return elastic.guarded_sync(fn)

    def _flush_window(self, state, monitor, reason):
        if self._elastic is not None:
            from bigdl_tpu.resilience import elastic
            if elastic.tripped() is not None:
                # the pending scalars ride collectives the dead peer will
                # never join; PARK them (freeing a doomed buffer blocks
                # in the PJRT destructor) — the anchor is the resume truth
                if self._window is not None and self._window.pending:
                    elastic.runtime().leaked.append(
                        list(self._window.pending))
                    self._window.pending.clear()
                if reason == "exception":
                    return
                elastic.check()
            return self._guarded(
                lambda: super(DistriOptimizer, self)._flush_window(
                    state, monitor, reason))
        return super()._flush_window(state, monitor, reason)

    def _elastic_recover(self, trip):
        """The recovery protocol between two ``_optimize_run`` attempts:
        quiesce (the unwind already abandoned in-flight work), re-form
        the fleet at the reduced world size, restore the training state
        from the newest complete host anchor, re-partition the dataset,
        and hand back to the loop — which rebuilds mesh-keyed
        executables through the (reset) xcache registry on re-entry.
        Raises ``ReformAbort`` when recovery is impossible; the caller
        falls back to the fail-fast exit."""
        from bigdl_tpu.resilience import elastic
        es = self._elastic
        started = time.monotonic() - (elastic.trip_age() or 0.0)
        obs_events.emit("recover", kind="quiesce",
                        step=int(self.state["neval"]),
                        stale=sorted(trip.stale))
        anchor = es["keeper"].latest()
        world_before = int(elastic.runtime().world or jax.process_count())
        elastic.reform(trip.stale)   # ReformAbort propagates to caller
        world_after = jax.process_count()
        self.mesh = data_parallel_mesh()
        Engine.init()                # refresh node/core counts
        # training state: the anchor is the last consistent step
        self.model.load_params(anchor.params)
        self.model.load_state(anchor.net_state)
        self._resume_opt_state = anchor.opt_state
        self.state.update(anchor.state)
        self.state["neval"] = anchor.neval
        self.state["epoch"] = anchor.epoch
        RNG.restore(anchor.rng)
        self._elastic_resume_count = anchor.count
        self._reshard_dataset()
        # executables, device copies and writer threads are keyed to the
        # abandoned runtime; drop them (xcache was reset in the reform)
        self._ckpt_copy_fn = None
        self._ckpt_writer = None
        self._lr_scales_arg = None
        # the old keeper's drain thread may be wedged on a doomed gather
        # (and its queue holds doomed buffers) — park it with the rest of
        # the old runtime and seed a fresh one from the anchor on host
        elastic.runtime().leaked.append((es["keeper"], es["gather"]))
        keeper = elastic.AnchorKeeper()
        keeper.capture_sync(
            (anchor.params, anchor.net_state, anchor.opt_state),
            {"state": anchor.state, "neval": anchor.neval,
             "epoch": anchor.epoch, "count": anchor.count,
             "rng": anchor.rng})
        es["keeper"] = keeper
        es["gather"] = None
        obs_events.emit("recover", kind="reshard", step=int(anchor.neval),
                        world_after=int(world_after))
        pause = time.monotonic() - started
        obs_events.emit("recover", kind="resume", step=int(anchor.neval),
                        world_before=int(world_before),
                        world_after=int(world_after),
                        pause_s=round(pause, 4))
        logger.warning(
            "elastic: resuming from in-memory anchor at neval=%d epoch=%d "
            "(world %d -> %d, recovery pause %.2fs, no checkpoint read)",
            anchor.neval, anchor.epoch, world_before, world_after, pause)

    def _reshard_dataset(self):
        """Walk the dataset chain and re-key every world-size-dependent
        stage to the LIVE topology: ShardedDataSet strided shards and
        ``SampleToBatch(global_batch_size=...)`` local batches.  A
        global batch that does not divide the re-formed world is a
        recovery failure HERE (uniform exit 43), not a raw unwind at
        the first post-recovery iteration."""
        from bigdl_tpu.resilience import elastic

        def check_batch(t):
            gbs = getattr(t, "global_batch_size", None)
            if gbs and gbs % jax.process_count():
                raise elastic.ReformAbort(
                    f"global batch {gbs} cannot be divided over the "
                    f"re-formed world of {jax.process_count()} "
                    "process(es)")
            for sub in getattr(t, "transformers", None) or ():
                check_batch(sub)

        for root in (self.dataset, self.validation_dataset):
            ds = root
            while ds is not None:
                if hasattr(ds, "reshard"):
                    ds.reshard()
                t = getattr(ds, "transformer", None)
                if t is not None:
                    check_batch(t)
                ds = getattr(ds, "base", None)

    def _elastic_fail(self, abort):
        """Recovery was impossible: honor the historical fail-fast
        contract — same crash bundle and exit code as the watchdog's
        default policy, so operators see ONE failure shape."""
        from bigdl_tpu.resilience import elastic
        from bigdl_tpu.resilience.watchdog import EXIT_CODE
        logger.error("elastic: recover-in-place impossible (%s) — "
                     "falling back to the fail-fast exit %d",
                     abort, EXIT_CODE)
        try:
            obs_events.emit("recover", kind="abort", reason=str(abort))
            from bigdl_tpu.obs import diagnostics
            import threading
            t = threading.Thread(
                target=lambda: diagnostics.dump_crash_bundle(
                    "elastic-abort", extra={"reason": str(abort)}),
                daemon=True, name="bigdl-elastic-postmortem")
            t.start()
            t.join(timeout=3.0)
        except Exception:
            logger.exception("elastic abort crash bundle failed")
        if elastic.runtime().orig_index == 0:
            # this process hosts the coordination service: linger so the
            # other survivors' exit-43 lands before the socket close
            # SIGABRTs them mid-unwind (the watchdog's grace, one knob)
            dog = elastic.runtime().watchdog
            time.sleep(dog.coordinator_grace if dog is not None else 2.0)
        os._exit(EXIT_CODE)

    def optimize(self):
        from bigdl_tpu.resilience import elastic
        self._elastic = self._elastic_session()
        self._elastic_resume_count = None
        if self._elastic is None:
            return self._optimize_run()
        try:
            while True:
                try:
                    return self._optimize_run()
                except Exception as err:
                    if isinstance(err, elastic.PeerLossRecovery):
                        trip = err
                    else:
                        # a dead peer surfaces as an immediate collective
                        # error (gloo TCP reset) well before the heartbeat
                        # timeout — park for the watchdog's verdict; only
                        # a confirmed peer death converts into recovery
                        logger.warning(
                            "elastic: training raised %s: %s — awaiting "
                            "the watchdog's verdict before treating it as "
                            "peer loss", type(err).__name__, err)
                        trip = elastic.await_trip()
                        if trip is None:
                            raise
                    # the unwound traceback's frames reference buffers
                    # whose defining computation involves the dead peer;
                    # FREEING such a buffer blocks forever in the PJRT
                    # destructor (awaiting the definition event) — park
                    # the whole traceback with the rest of the doomed
                    # runtime instead of letting it die here
                    elastic.runtime().leaked.append(err)
                try:
                    self._elastic_recover(trip)
                except Exception as abort:
                    # ANY recovery failure — quorum/timeout aborts or an
                    # unexpected error after the new world formed — takes
                    # the uniform fail-fast exit; a raw unwind here would
                    # strand the other survivors in the re-formed
                    # collectives with an arbitrary exit code
                    self._elastic_fail(abort)
        finally:
            self._elastic = None

    def _optimize_run(self):
        state = self.state
        state.get_or_update("epoch", 1)
        state.get_or_update("neval", 1)
        # see LocalOptimizer.optimize: a resumed state blob may carry the
        # previous run's preemption mark
        state["preempted"] = False

        step_fn = self._build_step()  # pipeline mode builds its plan here
        # ledger key for the windowed train_mfu gauge (pipeline-mode
        # steps carry no fn_key; the gauge just stays silent there)
        self._step_fn_key = getattr(step_fn, "fn_key", None)
        params = jax.tree_util.tree_map(jnp.copy, self.model.params())
        net_state = jax.tree_util.tree_map(jnp.copy, self.model.state())
        if self._pipe_plan is not None:
            pipe_s = NamedSharding(self.mesh, P("pipe"))
            params = jax.device_put(self._pipe_plan.pack_params(params),
                                    pipe_s)
            net_state = jax.device_put(self._pipe_plan.pack_state(net_state),
                                       pipe_s)
        opt_state = self._initial_opt_state(params)
        self._resume_opt_state = None   # consumed; never reuse a stale tree
        monitor = self._start_obs_run()

        count = 0
        if self._elastic is not None and self._elastic_resume_count:
            # post-recovery re-entry: continue the interrupted epoch's
            # record count from the anchor (docs/resilience.md: the epoch
            # TAIL re-reads from the re-sharded stream)
            count = int(self._elastic_resume_count)
        self._elastic_resume_count = None
        epoch_size = self.dataset.size()
        n_disp = self.iters_per_dispatch
        straggler = self._straggler
        # straggler drop re-times and accepts/rejects every iteration on
        # the host, so it keeps the per-step sync; _make_train_pipeline
        # already returns None for it
        pipeline = self._make_train_pipeline(n_disp, epoch_size)
        self._train_pipeline = pipeline
        data_iter = None if pipeline is not None \
            else self.dataset.data(train=True)
        self._window = _HostSyncWindow(
            1 if straggler is not None else self._sync_cadence())
        wall_start = time.perf_counter()

        if self._elastic is not None:
            from bigdl_tpu.resilience import elastic as elastic_mod
            # generation-0 anchor: a peer death before the first step's
            # snapshot must still find a complete resume point
            self._elastic_offer(params, net_state, opt_state, state, count)

        try:
            while not self.end_when(state):
                if self._elastic is not None:
                    elastic_mod.check()   # raises PeerLossRecovery on trip
                neval0 = int(state["neval"])
                epoch0 = int(state["epoch"])
                self._window.arm()
                fetch_start = time.perf_counter()
                dev = qdepth = None
                with self.spans.span("data-load"), \
                        self.metrics.timer("data fetch time"):
                    if pipeline is not None:
                        # the span measures the CONSUMER's wait only; the
                        # producer's transform wall rides data-load/fetch
                        item, waited = pipeline.get()
                        self._drain_pipeline_obs(pipeline, item, waited,
                                                 neval0)
                        qdepth = item.queue_depth
                        if item.device is not None:
                            dev = item.device
                    elif n_disp <= 1:
                        batch = next(data_iter)
                        xh = self._chaos_prestep(batch.data, neval0)
                        yh = batch.labels
                    else:
                        xh, yh = self._next_chunk(data_iter, n_disp)
                        xh = self._chaos_prestep(xh, neval0)
                if dev is None:
                    if pipeline is not None:
                        # chaos host mode: poison at CONSUME time, so
                        # every site stays keyed by the consuming step
                        xh = self._chaos_prestep(item.x, neval0)
                        yh = item.y
                    with self.spans.span("h2d"):
                        dev = self._device_put_batch(xh, yh,
                                                     stacked=n_disp > 1)
                x, y = dev
                global_b = (x.shape[0] * x.shape[1] if n_disp > 1
                            else x.shape[0])
                fetch_wall = time.perf_counter() - fetch_start

                drop_mask = None
                if straggler is not None:
                    drop_mask = straggler.mask()
                    if not straggler.accepts(drop_mask):
                        # iteration rejected: batch consumed, no update, no
                        # neval advance (ref DistriOptimizer.scala:224 guard)
                        straggler.reject(drop_mask)
                        continue

                # distributed: summary() adds the per-process breakdown,
                # the reference's "computing time for each node" accumulator
                it_start = time.perf_counter()
                with self.spans.span("dispatch"), \
                        self.metrics.timer("computing time average",
                                           distributed=True):
                    lr = self._current_lr()
                    key = RNG.next_key()
                    step_args = (params, net_state, opt_state, x, y,
                                 jnp.float32(lr), key, self._lr_scales_arg)
                    if straggler is not None:
                        (params, net_state, opt_state, loss, finite,
                         taps) = step_fn(*step_args, jnp.asarray(drop_mask))
                        # the device→host transfer blocks, so the timer
                        # (and the straggler's task clock) sees the real
                        # dispatch wall — the one mode that syncs per
                        # step.  The HOST array rides the window so the
                        # cadence-1 flush does not transfer a second time.
                        loss = np.asarray(loss)
                    elif self._elastic is not None:
                        # on backends that execute collectives on the
                        # dispatching thread (multi-process CPU), a step
                        # whose peer died would wedge the loop right here
                        # — run it abandonably
                        (params, net_state, opt_state, loss, finite,
                         taps) = self._guarded(lambda: step_fn(*step_args))
                    else:
                        (params, net_state, opt_state, loss, finite,
                         taps) = step_fn(*step_args)
                train_time = time.perf_counter() - it_start

                n_dropped = 0
                if straggler is not None:
                    with self.spans.span("aggregate"):
                        # the cross-process task-time merge (allgather)
                        straggler.record(self._straggler_task_times(
                            fetch_wall, time.perf_counter() - it_start),
                            drop_mask)
                    n_dropped = int(len(drop_mask) - drop_mask.sum())
                    if n_dropped:
                        # ref logger.debug("Dropped modules: " + ...) :248
                        logger.debug("Dropped modules: %d", n_dropped)
                        # only the finished tasks' records count toward the
                        # epoch (ref recordsNum += finishedThreads.size *
                        # stackSize, accumulateCount += recordsNum :236)
                        global_b = int(global_b * float(drop_mask.sum())
                                       / len(drop_mask))
                count += global_b
                state["neval"] = neval0 + n_disp
                state["evalCounter"] = state.get("evalCounter", 0) + n_disp
                extra = {}
                if n_dropped:
                    extra["straggler_dropped"] = n_dropped
                if qdepth is not None:
                    extra["queue_depth"] = int(qdepth)
                self._window.push(_PendingStep(
                    neval0, epoch0, count, loss, finite, taps, lr,
                    global_b, fetch_wall, train_time, extra))

                rolled = count >= epoch_size
                count, data_iter = self._advance_epochs(
                    state, count, epoch_size, n_disp, data_iter, pipeline)
                if self._elastic is not None and \
                        neval0 % self._elastic["cadence"] == 0:
                    # consistent post-step snapshot (post-rollover: the
                    # epoch's shuffle draw is already in the RNG payload)
                    self._elastic_offer(params, net_state, opt_state,
                                        state, count)
                if self._window.due() or rolled:
                    self._flush_window(state, monitor,
                                       "epoch" if rolled else "cadence")
                ne_val = self._fired_within(self.validation_trigger, state,
                                            n_disp)
                ne_ck = self._fired_within(self.checkpoint_trigger, state,
                                           n_disp)
                preempt = self._preemption_pending()
                if preempt or ne_val is not None or ne_ck is not None:
                    self._flush_window(state, monitor,
                                       "preempt" if preempt else "trigger")
                if ne_val is not None:
                    self._guarded(lambda: self._maybe_validate(
                        params, net_state, state, force=True))
                if ne_ck is not None:
                    self._guarded(lambda: self._maybe_checkpoint(
                        params, net_state, opt_state, state, force=True,
                        neval_label=ne_ck))
                if preempt:
                    self._checkpoint_and_stop(params, net_state, opt_state,
                                              state)
                    break
            self._flush_window(state, monitor, "run-end")
        finally:
            try:
                # see LocalOptimizer.optimize: crash-adjacent steps must
                # reach the event stream before the pipeline tears down
                self._flush_window(state, monitor, "exception")
            except Exception as e:
                logger.warning("pending-step flush during unwind "
                               "failed: %s", e)
            if pipeline is not None:
                pipeline.close()
            self._train_pipeline = None
            if self._ckpt_writer is not None:
                if self._elastic is None:
                    self._flush_ckpt_writer("run end")
                elif elastic_mod.tripped() is None:
                    # a possibly-doomed unwind: bound the wait — if this
                    # turns into a recovery, _elastic_recover drops the
                    # writer (its thread may be wedged on dead arrays)
                    self._flush_ckpt_writer("elastic unwind", timeout=5.0)

        # gather (replicated -> host) and write back, ref getModel :475-499
        if self._pipe_plan is not None:
            params = self._pipe_plan.unpack_params(params)
            net_state = self._pipe_plan.unpack_state(net_state)
        self.model.load_params(jax.device_get(params))
        self.model.load_state(jax.device_get(net_state))
        # snapshot per-node metrics while every process is still here, so
        # post-training summary(per_node=True) from one process is safe
        # (also what makes the per-host span table below deadlock-free:
        # process 0 renders from the cache, no late collective)
        self.metrics.collect_per_node()
        self._end_obs_run(state, wall_start)
        if jax.process_index() == 0:
            logger.info("per-host phase breakdown (mean s/iter):\n%s",
                        self.spans.per_host_report())
        logger.info("Training finished in %.1fs", time.perf_counter() - wall_start)
        return self.model
