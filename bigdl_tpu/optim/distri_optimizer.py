"""DistriOptimizer — synchronous data-parallel training over a device mesh
(ref optim/DistriOptimizer.scala, call stack SURVEY.md §3.1).

Mapping from the reference, piece by piece:

- Spark partition per node + model replica     -> mesh axis ``data``; the
  (initThreadModels :344-410)                     model is written once, XLA
                                                  replicates per device
- AllReduceParameter reduce-scatter/all-gather -> XLA all-reduce over ICI,
  (putGradients/getWeights)                       emitted by jit from the
                                                  sharded-batch mean loss
- FP16 wire compression                        -> bf16 compute policy
  (FP16CompressedTensor)                          (on-chip cast, no wire)
- per-partition weight update                  -> optional ZeRO-1 optimizer
  (optimMethod.optimize on MY slice :232)         state sharding
- straggler dropping (invokeAndWait2 timeout)  -> N/A: XLA collectives are
                                                  bulk-synchronous on a TPU
                                                  slice; knobs accepted as
                                                  documented no-ops
- Metrics phase breakdown :114-118             -> step metrics below

Multi-host: each process feeds its local batch shard;
``jax.make_array_from_process_local_data`` assembles the global array
(the Spark-RDD locality role, ZippedPartitionsWithLocalityRDD).
"""
from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Context
from bigdl_tpu.optim.local_optimizer import LocalOptimizer, validate
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.parallel.mesh import data_parallel_mesh
from bigdl_tpu.utils.random import RNG
from bigdl_tpu.utils.table import T

logger = logging.getLogger("bigdl_tpu.optim")


class DistriOptimizer(LocalOptimizer):
    def __init__(self, model, dataset, criterion, mesh=None,
                 drop_percentage: float = 0.0, tensor_parallel: bool = False,
                 zero1: bool = False):
        """``tensor_parallel=True`` with a mesh containing a ``model`` axis
        shards eligible weights (and their optimizer state) over that axis
        via ``parallel.sharding.shard_params_rule`` — hybrid DP x TP with
        the same user API as pure DP.

        ``zero1=True`` shards optimizer state over the ``data`` axis
        (ZeRO-1) — the direct analogue of the reference's owner-partition
        update (each AllReduceParameter partition updates only its weight
        slice, DistriOptimizer.scala:232); XLA moves the state shards as
        needed and HBM per chip drops by ~|opt_state|*(1-1/N)."""
        super().__init__(model, dataset, criterion)
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.tensor_parallel = tensor_parallel
        self.zero1 = zero1
        if drop_percentage:
            logger.warning(
                "straggler drop (dropPercentage=%s) is a no-op on TPU: XLA "
                "collectives are bulk-synchronous (ref DistriOptimizer straggler "
                "machinery, DistriOptimizer.scala:154-172)", drop_percentage)

    def set_drop_module_property(self, *args, **kwargs):
        """Accepted for API parity; see class docstring (no-op)."""
        return self

    def _shardings(self, params, net_state, opt_state):
        mesh = self.mesh
        rep = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data"))
        reps = lambda tree: jax.tree_util.tree_map(lambda _: rep, tree)
        if self.tensor_parallel and "model" in mesh.axis_names:
            from bigdl_tpu.parallel.sharding import (shard_params_rule,
                                                     zero1_tp_rule)
            rule = shard_params_rule(mesh, "model")
            orule = zero1_tp_rule(mesh, "data", "model") if self.zero1 else rule
            return (jax.tree_util.tree_map(rule, params), reps(net_state),
                    jax.tree_util.tree_map(orule, opt_state), data)
        if self.zero1:
            from bigdl_tpu.parallel.sharding import zero1_rule
            zrule = zero1_rule(mesh, "data")
            return (reps(params), reps(net_state),
                    jax.tree_util.tree_map(zrule, opt_state), data)
        return reps(params), reps(net_state), reps(opt_state), data

    def _build_step(self):
        model, criterion, method = self.model, self.criterion, self.optim_method
        static_hyper = self._hyper(None)
        del static_hyper["lr"]
        mesh = self.mesh

        def step(params, net_state, opt_state, x, y, lr, key):
            hyper = dict(static_hyper, lr=lr)

            def loss_fn(p):
                out, ns = model.apply(p, x, net_state, Context(training=True, key=key))
                # mean over the GLOBAL batch: with x sharded over "data" and
                # params replicated, jax.grad makes XLA emit the cross-ICI
                # all-reduce — this line IS AllReduceParameter
                return criterion.apply_loss(out, y), ns

            (loss, new_net_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            new_params, new_opt_state = method.update(grads, opt_state, params, hyper)
            return new_params, new_net_state, new_opt_state, loss

        params = self.model.params()
        net_state = self.model.state()
        opt_state = self.optim_method.init_state(params)
        ps, ns, os_, data_s = self._shardings(params, net_state, opt_state)
        rep = NamedSharding(mesh, P())
        # carried state is donated (buffers recycled in place); optimize()
        # passes copies so the module's own arrays survive
        return jax.jit(
            step,
            in_shardings=(ps, ns, os_, data_s, data_s, rep, rep),
            out_shardings=(ps, ns, os_, rep),
            donate_argnums=(0, 1, 2),
        )

    def _device_put_batch(self, x, y):
        """Assemble the global sharded batch from this process's local shard."""
        mesh = self.mesh
        sharding = NamedSharding(mesh, P("data"))
        if jax.process_count() == 1:
            return (jax.device_put(jnp.asarray(x), sharding),
                    jax.device_put(jnp.asarray(y), sharding))
        return (jax.make_array_from_process_local_data(sharding, np.asarray(x)),
                jax.make_array_from_process_local_data(sharding, np.asarray(y)))

    def optimize(self):
        state = self.state
        state.get_or_update("epoch", 1)
        state.get_or_update("neval", 1)

        params = jax.tree_util.tree_map(jnp.copy, self.model.params())
        net_state = jax.tree_util.tree_map(jnp.copy, self.model.state())
        opt_state = self.optim_method.init_state(params)
        step_fn = self._build_step()

        count = 0
        epoch_size = self.dataset.size()
        data_iter = self.dataset.data(train=True)
        n_dev = self.mesh.size
        wall_start = time.perf_counter()

        while not self.end_when(state):
            with self.metrics.timer("data fetch time"):
                batch = next(data_iter)
                x, y = self._device_put_batch(batch.data, batch.labels)
                global_b = x.shape[0]

            with self.metrics.timer("computing time average"):
                lr = self._current_lr()
                key = RNG.next_key()
                params, net_state, opt_state, loss = step_fn(
                    params, net_state, opt_state, x, y, jnp.float32(lr), key)
                loss = float(loss)

            step_time = self.metrics.mean("computing time average")
            count += global_b
            state["neval"] = state["neval"] + 1
            state["loss"] = loss
            state["evalCounter"] = state.get("evalCounter", 0) + 1
            logger.info(
                "Epoch %d %d/%d loss %.6f lr %.5g throughput %.1f records/s "
                "on %d devices", state["epoch"], count, epoch_size, loss, lr,
                global_b / max(step_time, 1e-9), n_dev)

            if count >= epoch_size:
                state["epoch"] = state["epoch"] + 1
                count = 0
                self.dataset.shuffle()
                data_iter = self.dataset.data(train=True)

            self._maybe_validate(params, net_state, state)
            self._maybe_checkpoint(params, net_state, opt_state, state)

        # gather (replicated -> host) and write back, ref getModel :475-499
        self.model.load_params(jax.device_get(params))
        self.model.load_state(jax.device_get(net_state))
        logger.info("Training finished in %.1fs", time.perf_counter() - wall_start)
        return self.model
