"""Triggers — state-table predicates (ref optim/Trigger.scala:22-71).

A trigger is a predicate over the driver state Table (keys: epoch, neval,
maxIteration...).  Factory functions mirror the reference's companion.
"""
from __future__ import annotations

from bigdl_tpu.utils.table import Table


class Trigger:
    def __init__(self, fn, name="trigger"):
        self._fn = fn
        self._name = name

    def __call__(self, state: Table) -> bool:
        return bool(self._fn(state))

    def __repr__(self):
        return f"Trigger({self._name})"


def every_epoch():
    """Fires when a new epoch begins (ref Trigger.everyEpoch)."""
    holder = {"last": -1}

    def fn(state):
        e = state.get("epoch", 1)
        if e != holder["last"]:
            holder["last"] = e
            return True
        return False

    return Trigger(fn, "everyEpoch")


def several_iteration(interval: int):
    """Fires every ``interval`` iterations (ref Trigger.severalIteration)."""
    return Trigger(lambda s: s.get("neval", 0) % interval == 0 and s.get("neval", 0) > 0,
                   f"severalIteration({interval})")


def max_epoch(n: int):
    """End condition: epoch > n (ref Trigger.maxEpoch)."""
    return Trigger(lambda s: s.get("epoch", 1) > n, f"maxEpoch({n})")


def max_iteration(n: int):
    """End condition: neval > n (ref Trigger.maxIteration)."""
    return Trigger(lambda s: s.get("neval", 0) > n, f"maxIteration({n})")


def min_loss(loss: float):
    return Trigger(lambda s: s.get("loss", float("inf")) < loss, f"minLoss({loss})")


def and_trigger(*triggers):
    return Trigger(lambda s: all(t(s) for t in triggers), "and")


def or_trigger(*triggers):
    return Trigger(lambda s: any(t(s) for t in triggers), "or")


# PascalCase aliases matching the reference's Python API
# (dl/src/main/python/optim/optimizer.py: MaxEpoch, MaxIteration, EveryEpoch,
#  SeveralIteration)
MaxEpoch = max_epoch
MaxIteration = max_iteration
EveryEpoch = every_epoch
SeveralIteration = several_iteration
