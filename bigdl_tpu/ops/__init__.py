from bigdl_tpu.ops import pallas_kernels
