"""Pallas TPU kernels (see /opt/skills/guides/pallas_guide.md).

The device-side hot loops of the reference's native layer (mkl.c vector
math / axpy / scal) compile through XLA; Pallas covers the cases where
hand-fusion still wins:

- ``fused_sgd``: momentum-SGD parameter update as ONE pass over HBM
  (read p, g, v -> write p', v').  The unfused update streams the tensors
  multiple times; for the flat multi-MB parameter vector of a large model
  this is pure HBM bandwidth, exactly the regime a fused elementwise
  kernel owns.  The reference's analogue is the fp16-compressed parallel
  update loop (FP16CompressedTensor.parallel add/scal).

On non-TPU backends the kernels run through the Pallas interpreter
(``interpret=True``) so tests exercise the same code path on the CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_BLOCK = 64 * 1024  # elements per grid step (256 KiB f32 — fits VMEM easily)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _make_sgd_kernel(nesterov: bool):
    def kernel(p_ref, g_ref, v_ref, h_ref, p_out, v_out):
        """g~ = g + wd*p; with momentum: v' = mom*v + (1-damp)*g~ and
        p' = p - lr*(g~ + mom*v' if nesterov else v'); with mom == 0 the
        unfused path's semantics hold exactly — velocity untouched, step
        = g~ (dampening ignored).  One VMEM pass.
        h_ref holds [lr, momentum, weight_decay, dampening] in SMEM."""
        lr, mom, wd, damp = h_ref[0], h_ref[1], h_ref[2], h_ref[3]
        has_mom = (mom != 0.0).astype(p_ref.dtype)
        g = g_ref[:] + wd * p_ref[:]
        v_new = mom * v_ref[:] + (1.0 - has_mom * damp) * g
        # mom==0: keep stored velocity, step with plain g
        v_out[:] = has_mom * v_new + (1.0 - has_mom) * v_ref[:]
        d = g + mom * v_new if nesterov else v_new
        p_out[:] = p_ref[:] - lr * (has_mom * d + (1.0 - has_mom) * g)
    return kernel


_SGD_KERNELS = {False: _make_sgd_kernel(False), True: _make_sgd_kernel(True)}


@functools.partial(jax.jit, static_argnames=("interpret", "nesterov"))
def _fused_sgd_flat(p, g, v, hyper4, interpret=False, nesterov=False):
    n = p.shape[0]
    # pad to a whole number of blocks (grid must be static)
    padded = ((n + _BLOCK - 1) // _BLOCK) * _BLOCK
    if padded != n:
        pad = padded - n
        p = jnp.concatenate([p, jnp.zeros(pad, p.dtype)])
        g = jnp.concatenate([g, jnp.zeros(pad, g.dtype)])
        v = jnp.concatenate([v, jnp.zeros(pad, v.dtype)])
    grid = padded // _BLOCK
    p2, v2 = pl.pallas_call(
        _SGD_KERNELS[nesterov],
        out_shape=(jax.ShapeDtypeStruct((padded,), p.dtype),
                   jax.ShapeDtypeStruct((padded,), v.dtype)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(p, g, v, hyper4)
    return p2[:n], v2[:n]


def fused_sgd(params, grads, velocity, lr, momentum=0.0, weight_decay=0.0,
              dampening=0.0, nesterov=False):
    """Fused momentum-SGD update over pytrees.

    Flattens each leaf to 1D and runs the single-pass Pallas kernel;
    returns (new_params, new_velocity).  Uses the interpreter off-TPU.
    """
    interpret = not _on_tpu()
    hyper4 = jnp.asarray([lr, momentum, weight_decay, dampening], jnp.float32)

    def leaf(p, g, v):
        shape = p.shape
        p2, v2 = _fused_sgd_flat(p.reshape(-1), g.reshape(-1), v.reshape(-1),
                                 hyper4, interpret=interpret,
                                 nesterov=bool(nesterov))
        return p2.reshape(shape), v2.reshape(shape)

    flat = jax.tree_util.tree_map(leaf, params, grads, velocity)
    new_p = jax.tree_util.tree_map(lambda pv: pv[0], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_v = jax.tree_util.tree_map(lambda pv: pv[1], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_p, new_v


# --------------------------------------------------------------- LSTM scan

def _lstm_scan_kernel(zx_ref, wht_ref, h0_ref, c0_ref, out_ref, h_scr, c_scr):
    """One grid step = one timestep; h/c live in VMEM scratch across steps.

    zx_ref: (1, B, 4H) precomputed input projection for step t (already
    includes the bias); wht_ref: (H, 4H) recurrent weight, transposed so
    the in-kernel dot needs no transpose; out_ref: (1, B, H).
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h = h_scr[:]
    c = c_scr[:]
    z = zx_ref[0] + pl.dot(h.astype(wht_ref.dtype), wht_ref[:],
                           ).astype(jnp.float32)
    hdim = h.shape[-1]
    i = jax.nn.sigmoid(z[:, :hdim])
    f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
    g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(z[:, 3 * hdim:])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    h_scr[:] = h_new
    c_scr[:] = c_new
    out_ref[0] = h_new


@functools.partial(jax.jit, static_argnames=("interpret",))
def lstm_scan(zx, wht, h0, c0, interpret=False):
    """Whole-recurrence Pallas kernel: zx (T, B, 4H) f32 (input projection
    + bias, precomputed on the MXU outside), wht (H, 4H), h0/c0 (B, H) f32.
    Returns hs (T, B, H).  Forward only — see PERF_NOTES for the measured
    verdict vs lax.scan before wiring this anywhere hot.
    """
    t, b, h4 = zx.shape
    h = h4 // 4
    return pl.pallas_call(
        _lstm_scan_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h, h4), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, b, h), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32),
                        pltpu.VMEM((b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht, h0, c0)


# ------------------------------------------------------------- max pooling
#
# XLA's reduce_window forward and especially its select-and-scatter VJP
# run far below HBM bandwidth on v5e (PROFILE_inception.md round 3: pool
# fwd+bwd = 7.9 ms of a 40 ms Inception step at ZERO useful FLOPs, while
# an isolated streaming op moves the same bytes ~5x faster).  These
# kernels compute the same maxpool (and its first-max-wins gradient, the
# select-and-scatter tie rule) as a handful of VMEM slice/max/add passes.
#
# Layout: NCHW collapsed to (N*C, H, W) rows; grid over row-blocks, each
# block (BC, H, W) resident in VMEM with W on lanes and H on sublanes.
# STRIDE-1 windows only: every window read/write is then a unit-stride
# VMEM slice (Mosaic forbids strided slices and the reshape that a
# phase-decomposition of strided pools would need); strided pools stay
# on the XLA path, whose select-and-scatter cost is acceptable there
# because strided windows barely overlap.


def _mp_out_size(size, k, s, pl_, ph_):
    return (size + pl_ + ph_ - k) // s + 1


def _maxpool_fwd_kernel(x_ref, y_ref, *, kh, kw, pads):
    (plh, phh), (plw, phw) = pads
    # compute in f32: this Mosaic target lacks bf16 vector compares
    x = x_ref[:].astype(jnp.float32)
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    xp = jnp.pad(x, ((0, 0), (plh, phh), (plw, phw)), constant_values=neg)
    bc = x.shape[0]
    oh = x.shape[1] + plh + phh - kh + 1
    ow = x.shape[2] + plw + phw - kw + 1
    y = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, i, j), (bc, i + oh, j + ow))
            y = s if y is None else jnp.maximum(y, s)
    y_ref[:] = y.astype(y_ref.dtype)


def _maxpool_bwd_kernel(x_ref, g_ref, dx_ref, *, kh, kw, pads):
    """First-max-wins gradient (select-and-scatter scan order: row-major
    over window offsets)."""
    (plh, phh), (plw, phw) = pads
    # compute in f32: this Mosaic target lacks bf16 vector compares
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    xp = jnp.pad(x, ((0, 0), (plh, phh), (plw, phw)), constant_values=neg)
    bc, hp, wp = xp.shape
    oh, ow = g.shape[1], g.shape[2]
    y = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, i, j), (bc, i + oh, j + ow))
            y = s if y is None else jnp.maximum(y, s)
    accp = jnp.zeros((bc, hp, wp), jnp.float32)
    claimed = jnp.zeros(g.shape, jnp.bool_)
    for i in range(kh):
        for j in range(kw):
            # re-slice instead of caching all kh*kw windows: keeps the
            # kernel's live VMEM set to ~6 frames
            s = lax.slice(xp, (0, i, j), (bc, i + oh, j + ow))
            m = (s == y) & ~claimed
            claimed = claimed | m
            contrib = g * m.astype(jnp.float32)
            accp = accp + lax.pad(contrib, jnp.asarray(0, jnp.float32),
                                  ((0, 0, 0), (i, hp - oh - i, 0),
                                   (j, wp - ow - j, 0)))
    dx_ref[:] = lax.slice(accp, (0, plh, plw),
                          (bc, plh + x.shape[1], plw + x.shape[2])
                          ).astype(dx_ref.dtype)


def _pick_bc(nc, h, w, arrays=8):
    """Largest row-block that divides nc and keeps ~arrays f32 copies of
    the (BC, H, W) frame under a 6 MB budget — deliberately well under
    the ~16 MB scoped-VMEM limit to leave room for Mosaic's own
    temporaries (frames are upcast to f32 inside the kernels)."""
    budget = 6 * 1024 * 1024
    lanes = -(-(w + 4) // 128) * 128  # Mosaic pads the lane dim to 128
    per_row = (h + 4) * lanes * 4 * arrays
    bc = max(1, min(nc, budget // max(per_row, 1)))
    while nc % bc:
        bc -= 1
    return bc


@functools.partial(jax.jit,
                   static_argnames=("window", "strides", "pads", "interpret"))
def _maxpool_fwd_call(x, window, strides, pads, interpret=False):
    n, c, h, w = x.shape
    kh, kw = window
    assert strides == (1, 1), "pallas maxpool2d is stride-1 only"
    oh = _mp_out_size(h, kh, 1, *pads[0])
    ow = _mp_out_size(w, kw, 1, *pads[1])
    nc = n * c
    bc = _pick_bc(nc, h, w)
    xr = x.reshape(nc, h, w)
    y = pl.pallas_call(
        functools.partial(_maxpool_fwd_kernel, kh=kh, kw=kw, pads=pads),
        grid=(nc // bc,),
        in_specs=[pl.BlockSpec((bc, h, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bc, oh, ow), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nc, oh, ow), x.dtype),
        interpret=interpret,
    )(xr)
    return y.reshape(n, c, oh, ow)


@functools.partial(jax.jit,
                   static_argnames=("window", "strides", "pads", "interpret"))
def _maxpool_bwd_call(x, g, window, strides, pads, interpret=False):
    n, c, h, w = x.shape
    kh, kw = window
    assert strides == (1, 1), "pallas maxpool2d is stride-1 only"
    nc = n * c
    oh, ow = g.shape[2], g.shape[3]
    bc = _pick_bc(nc, h, w, arrays=8)
    dx = pl.pallas_call(
        functools.partial(_maxpool_bwd_kernel, kh=kh, kw=kw, pads=pads),
        grid=(nc // bc,),
        in_specs=[pl.BlockSpec((bc, h, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((bc, oh, ow), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bc, h, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nc, h, w), x.dtype),
        interpret=interpret,
    )(x.reshape(nc, h, w), g.reshape(nc, oh, ow))
    return dx.reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def maxpool2d(x, window, strides, pads, interpret=False):
    """NCHW maxpool with Pallas forward AND first-max backward.

    ``pads`` = ((lo_h, hi_h), (lo_w, hi_w)) explicit amounts (Torch
    ceil-mode handled by the caller, nn/pooling.py).  Gradient tie rule
    matches XLA select-and-scatter (first max in row-major window order).
    """
    return _maxpool_fwd_call(x, window, strides, pads, interpret)


def _maxpool_vjp_fwd(x, window, strides, pads, interpret=False):
    return _maxpool_fwd_call(x, window, strides, pads, interpret), x


def _maxpool_vjp_bwd(window, strides, pads, interpret, x, g):
    return (_maxpool_bwd_call(x, g, window, strides, pads, interpret),)


maxpool2d.defvjp(_maxpool_vjp_fwd, _maxpool_vjp_bwd)


# ---------------------------------------------------------------- LRN
#
# Cross-channel LRN (y = x / (k + alpha/n * sum_win x^2)^beta) costs
# ~5.6 ms of the Inception-v1 step through XLA (channel-window
# reduce_window + the backward's mul/div fusions, PROFILE_inception.md
# round 3).  Unlike the maxpool case, LRN maps PERFECTLY onto Mosaic's
# (sublane, lane) model: collapse HW onto lanes and put C on sublanes —
# the size-5 channel window becomes five unit-stride sublane slices, no
# lane padding waste, no strided slicing.  Forward and the closed-form
# backward
#   dx = dy z^-b - (2 a b / n) x * sum_win(dy x z^(-b-1))
# are each ONE pass over the block (backward recomputes z from x).


def _lrn_zpow(sq_sum, size, alpha, beta, k):
    z = k + (alpha / size) * sq_sum
    return z, _lrn_pow(z, beta)


def _lrn_win_sum(v, size, adjoint=False):
    """Sum over the size-window centred on each channel (sublane dim 0 of
    a (C, T) block), zero padding.  ``adjoint=True`` sums over the
    TRANSPOSED window (pad (hi, lo) instead of (lo, hi)) — required in
    the backward for even sizes, where the window is asymmetric."""
    lo = (size - 1) // 2
    hi = size - 1 - lo
    if adjoint:
        lo, hi = hi, lo
    c = v.shape[0]
    vp = jnp.pad(v, ((lo, hi), (0, 0)))
    acc = None
    for s in range(size):
        sl = lax.slice(vp, (s, 0), (s + c, v.shape[1]))
        acc = sl if acc is None else acc + sl
    return acc


def _lrn_pow(z, beta):
    """z^beta from an already-computed z (no window sum)."""
    if beta == 0.75:
        zb = jnp.sqrt(jnp.sqrt(z))
        return zb * zb * zb
    return z ** beta


def _lrn_fwd_kernel(x_ref, y_ref, *, size, alpha, beta, k):
    """Primal-only forward: no residual writes (validation/inference)."""
    x = x_ref[0].astype(jnp.float32)        # (C, T)
    _, zpow = _lrn_zpow(_lrn_win_sum(x * x, size), size, alpha, beta, k)
    y_ref[0] = (x / zpow).astype(y_ref.dtype)


def _lrn_fwd_res_kernel(x_ref, y_ref, z_ref, *, size, alpha, beta, k):
    """Forward under AD: the square-window running sum z stays in VMEM
    between computing y and being stored as the VJP residual — the
    backward never recomputes the window sum of x^2 (round 6; the
    round-3 kernel recomputed z from x in the backward)."""
    x = x_ref[0].astype(jnp.float32)        # (C, T)
    z, zpow = _lrn_zpow(_lrn_win_sum(x * x, size), size, alpha, beta, k)
    y_ref[0] = (x / zpow).astype(y_ref.dtype)
    z_ref[0] = z


def _lrn_bwd_kernel(x_ref, z_ref, g_ref, dx_ref, *, size, alpha, beta, k):
    """Analytic VJP from the STORED z: one adjoint window sum over
    u = g x z^(-beta-1); the only window pass in the whole backward."""
    x = x_ref[0].astype(jnp.float32)
    z = z_ref[0]
    g = g_ref[0].astype(jnp.float32)
    zpow = _lrn_pow(z, beta)
    u = g * x / (zpow * z)                  # dy x z^(-b-1)
    dx = (g / zpow - (2.0 * alpha * beta / size) * x
          * _lrn_win_sum(u, size, adjoint=True))
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _lrn_call(kernel, args, out_shapes, size, alpha, beta, k,
              interpret=False):
    """``out_shapes``: list of dtypes for (1, c, t)-blocked outputs; the
    first is the primary (y or dx), any extra ride along (z residual)."""
    x = args[0]
    n, c, h, w = x.shape
    hw = h * w
    t = min(3200, -(-hw // 128) * 128)  # multiple of 128 (lane alignment)
    # ragged final block is safe: the channel window never crosses lanes,
    # so out-of-bounds lanes compute garbage that the store drops
    flat = [a.reshape(n, c, hw) for a in args]
    spec = pl.BlockSpec((1, c, t), lambda i, j: (i, 0, j),
                        memory_space=pltpu.VMEM)
    multi = len(out_shapes) > 1
    out = pl.pallas_call(
        functools.partial(kernel, size=size, alpha=alpha, beta=beta, k=k),
        grid=(n, -(-hw // t)),
        in_specs=[spec] * len(flat),
        out_specs=[spec] * len(out_shapes) if multi else spec,
        out_shape=([jax.ShapeDtypeStruct((n, c, hw), d) for d in out_shapes]
                   if multi else jax.ShapeDtypeStruct((n, c, hw),
                                                      out_shapes[0])),
        interpret=interpret,
    )(*flat)
    if multi:
        return [o.reshape(n, c, h, w) for o in out]
    return out.reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_channel(x, size, alpha, beta, k, interpret=False):
    """Fused cross-channel LRN with a hand-written one-pass backward.
    NCHW, any H*W — ragged lane blocks are safe because the channel
    window never crosses lanes (out-of-bounds lanes are dropped on
    store).  Under AD the forward additionally stores z (the k +
    alpha/n * window-sum-of-squares denominator base) so the backward
    is a single pass with ONE adjoint window sum; a no-grad forward
    skips the z writes entirely."""
    return _lrn_call(_lrn_fwd_kernel, (x,), [x.dtype], size, alpha, beta,
                     k, interpret)


def _lrn_vjp_fwd(x, size, alpha, beta, k, interpret=False):
    y, z = _lrn_call(_lrn_fwd_res_kernel, (x,), [x.dtype, jnp.float32],
                     size, alpha, beta, k, interpret)
    return y, (x, z)


def _lrn_vjp_bwd(size, alpha, beta, k, interpret, res, g):
    x, z = res
    return (_lrn_call(_lrn_bwd_kernel, (x, z, g), [x.dtype], size, alpha,
                      beta, k, interpret),)


lrn_channel.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


# ---------------------------------------------------- bidirectional LSTM
#
# The Bi-LSTM flagship's recurrence as TWO whole-sequence Pallas kernels
# (forward + hand-derived backward), direction-batched like
# Recurrent._apply_fused_lstm's scan body.  h/c (and in the backward,
# dh/dc and the dWh accumulator) stay resident in VMEM scratch across
# all T grid steps — the "gates + carry in VMEM" formulation.
#
# This is the first measured Mosaic WIN on this chip (round 5, v5e,
# device clock, B128 T500 H128): forward 1.071 -> 0.527 ms vs lax.scan
# (bit-exact), fwd+bwd 5.0 -> 2.15 ms vs the scan's autodiff (grads
# equal to ~1e-4 rel, f32 accumulation order).  Every previous Pallas
# candidate here lost to the XLA emitter (PERF_NOTES rounds 2-5:
# flash attention, maxpool, LRN stencil, fused SGD, single-direction
# lstm_scan) — the recurrence wins because the emitter's while-loop
# carries per-step overhead the sequential grid amortizes, not because
# Mosaic beats XLA on the math.


def _bilstm_fwd_body(zx_ref, wht_ref, h_ref, c_ref, h_scr, c_scr):
    """One grid step = ``block_t`` timesteps, BOTH directions; zx already
    holds the hoisted input projection + bias.  The h/c carry stays in
    VMEM scratch across the whole block (and across blocks); the
    recurrent gemms stay serial — the sequential dependency is real —
    but the per-grid-step overhead amortizes over the block and the
    zx/h streams move in block_t-sized DMAs.  ``c_ref is None`` =
    primal-only call: the cell-state stack is a VJP residual, so a
    no-grad forward skips its HBM writes entirely."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    hdim = h_scr.shape[-1]
    for tt in range(zx_ref.shape[0]):    # static block_t timesteps
        for d in range(h_scr.shape[0]):  # static direction count (1 or 2)
            z = zx_ref[tt, d].astype(jnp.float32) + jnp.dot(
                h_scr[d].astype(wht_ref.dtype), wht_ref[d],
                preferred_element_type=jnp.float32)
            i = jax.nn.sigmoid(z[:, :hdim])
            f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
            g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
            o = jax.nn.sigmoid(z[:, 3 * hdim:])
            c_new = f * c_scr[d] + i * g
            h_new = o * jnp.tanh(c_new)
            h_scr[d] = h_new
            c_scr[d] = c_new
            h_ref[tt, d] = h_new
            if c_ref is not None:
                c_ref[tt, d] = c_new


def _bilstm_fwd_kernel(zx_ref, wht_ref, h_ref, c_ref, h_scr, c_scr):
    _bilstm_fwd_body(zx_ref, wht_ref, h_ref, c_ref, h_scr, c_scr)


def _bilstm_fwd_kernel_primal(zx_ref, wht_ref, h_ref, h_scr, c_scr):
    _bilstm_fwd_body(zx_ref, wht_ref, h_ref, None, h_scr, c_scr)


def _bilstm_bwd_kernel(zx_ref, hprev_ref, c_ref, cprev_ref, g_ref,
                       wht_ref, dzx_ref, dwh_ref, dh_scr, dc_scr, dwh_scr):
    """Reverse-time block: recompute the gates from zx_t + h_{t-1} @ Wh,
    fold the carried (dh, dc) and each step's output cotangent into
    dzx_t, accumulate dWh.  hprev/cprev arrive PRE-SHIFTED (index t
    holds step t-1's value, zeros at t=0).  The dWh accumulation is the
    one gemm the serial chain does NOT constrain: it batches over the
    whole block as ONE (H, block_t*B) x (block_t*B, 4H) contraction."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    hdim = dh_scr.shape[-1]
    kt = zx_ref.shape[0]
    for d in range(dh_scr.shape[0]):
        dzs, hprevs = [], []
        for tt in reversed(range(kt)):   # reverse time WITHIN the block
            hprev = hprev_ref[tt, d]
            z = zx_ref[tt, d].astype(jnp.float32) + jnp.dot(
                hprev.astype(wht_ref.dtype), wht_ref[d],
                preferred_element_type=jnp.float32)
            i = jax.nn.sigmoid(z[:, :hdim])
            f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
            g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
            o = jax.nn.sigmoid(z[:, 3 * hdim:])
            tc = jnp.tanh(c_ref[tt, d])
            dh_total = g_ref[tt, d] + dh_scr[d]
            dc_total = dc_scr[d] + dh_total * o * (1.0 - tc * tc)
            dz = jnp.concatenate([
                dc_total * g * i * (1.0 - i),
                dc_total * cprev_ref[tt, d] * f * (1.0 - f),
                dc_total * i * (1.0 - g * g),
                dh_total * tc * o * (1.0 - o),
            ], axis=-1)
            dzx_ref[tt, d] = dz
            dh_scr[d] = jnp.dot(dz.astype(wht_ref.dtype), wht_ref[d].T,
                                preferred_element_type=jnp.float32)
            dc_scr[d] = dc_total * f
            dzs.append(dz)
            hprevs.append(hprev)
        if kt == 1:
            dwh_scr[d] += jnp.dot(hprevs[0].T, dzs[0],
                                  preferred_element_type=jnp.float32)
        else:
            dwh_scr[d] += jnp.dot(
                jnp.concatenate(hprevs, axis=0).T,
                jnp.concatenate(dzs, axis=0),
                preferred_element_type=jnp.float32)
    dwh_ref[...] = dwh_scr[...]


def _shift_prev(xs):
    """xs[t] -> xs[t-1] along axis 0, zeros at t=0 (initial h/c)."""
    return jnp.concatenate([jnp.zeros_like(xs[:1]), xs[:-1]], axis=0)


def _pad_time(xs, block_t):
    """Zero-pad the time axis to a multiple of ``block_t``.

    Trailing zero steps are harmless in BOTH directions: the forward's
    padded steps run after every real step (their garbage h/c never
    feeds a real output), and the reverse-time backward starts at them
    with zero cotangents, so every dz/dWh contribution they produce is
    exactly zero and the carries reaching the real steps are the same
    zeros an unpadded kernel initializes with."""
    t = xs.shape[0]
    tp = -(-t // block_t) * block_t
    if tp == t:
        return xs
    return jnp.concatenate(
        [xs, jnp.zeros((tp - t,) + xs.shape[1:], xs.dtype)], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret", "with_c",
                                             "block_t"))
def _bilstm_fwd_call(zx, wht, interpret=False, with_c=True, block_t=1):
    t, nd, b, h4 = zx.shape
    h = h4 // 4
    kt = block_t
    out_spec = pl.BlockSpec((kt, nd, b, h), lambda i: (i, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32)
    return pl.pallas_call(
        _bilstm_fwd_kernel if with_c else _bilstm_fwd_kernel_primal,
        grid=(t // kt,),
        in_specs=[
            pl.BlockSpec((kt, nd, b, h4), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h4), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[out_spec, out_spec] if with_c else out_spec,
        out_shape=[out_shape, out_shape] if with_c else out_shape,
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht)


@functools.partial(jax.jit, static_argnames=("interpret", "block_t"))
def _bilstm_bwd_call(zx, wht, hs, cs, gout, interpret=False, block_t=1):
    t, nd, b, h4 = zx.shape
    h = h4 // 4
    kt = block_t
    nblk = t // kt
    rev = lambda i: (nblk - 1 - i, 0, 0, 0)
    return pl.pallas_call(
        _bilstm_bwd_kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((kt, nd, b, h4), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h4), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((kt, nd, b, h4), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h4), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((t, nd, b, h4), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h4), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, h, h4), jnp.float32)],
        interpret=interpret,
    )(zx, _shift_prev(hs), cs, _shift_prev(cs), gout, wht)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def bilstm_recurrence(zx, wht, interpret=False, block_t=1):
    """Direction-batched LSTM recurrence: zx (T, D, B, 4H) hoisted input
    projection (+bias) with D directions (1 = plain Recurrent, 2 =
    BiRecurrent), wht (D, H, 4H) recurrent weights; returns the h stack
    (T, D, B, H) f32.  Same math as the lax.scan body in
    Recurrent._apply_fused_lstm (forward bit-exact; gradients equal up
    to f32 accumulation order).  ``block_t`` > 1 processes that many
    timesteps per grid step (round-6 multi-timestep blocking; the time
    axis is zero-padded to a multiple — see _pad_time for why that is
    exact)."""
    # primal-only: skip the c-stack output — it is a VJP residual, and
    # a no-grad forward (validation/inference) should not pay its HBM
    # writes (~65 MB at the flagship shapes)
    t = zx.shape[0]
    hs = _bilstm_fwd_call(_pad_time(zx, block_t), wht,
                          interpret=interpret, with_c=False,
                          block_t=block_t)
    return hs[:t]


def _bilstm_vjp_fwd(zx, wht, interpret=False, block_t=1):
    t = zx.shape[0]
    zxp = _pad_time(zx, block_t)
    hs, cs = _bilstm_fwd_call(zxp, wht, interpret=interpret,
                              block_t=block_t)
    return hs[:t], (zxp, wht, hs, cs)


def _bilstm_vjp_bwd(interpret, block_t, res, gout):
    zxp, wht, hs, cs = res
    t = gout.shape[0]
    dzx, dwht = _bilstm_bwd_call(zxp, wht, hs, cs,
                                 _pad_time(gout.astype(jnp.float32),
                                           block_t),
                                 interpret=interpret, block_t=block_t)
    return dzx[:t].astype(zxp.dtype), dwht.astype(wht.dtype)


bilstm_recurrence.defvjp(_bilstm_vjp_fwd, _bilstm_vjp_bwd)


# ------------------------------------------------------------------- GRU
#
# Same sequential-grid/VMEM-carry structure as the LSTM pair, for the
# GRU cell (two recurrent gemms per step: the r/z gates and the
# r-gated candidate — GRUCell._step's math exactly, f32 like the cell).


def _gru_gates(zrz_t, zn_t, h, wrz_ref, wh_ref):
    hdim = h.shape[-1]
    rz = jax.nn.sigmoid(zrz_t + jnp.dot(
        h, wrz_ref, preferred_element_type=jnp.float32))
    r, z = rz[:, :hdim], rz[:, hdim:]
    n = jnp.tanh(zn_t + jnp.dot(
        r * h, wh_ref, preferred_element_type=jnp.float32))
    return r, z, n


def _gru_fwd_kernel(zrz_ref, zn_ref, wrz_ref, wh_ref, h_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)

    for tt in range(zrz_ref.shape[0]):   # static block_t timesteps
        for d in range(h_scr.shape[0]):
            h = h_scr[d]
            r, z, n = _gru_gates(zrz_ref[tt, d].astype(jnp.float32),
                                 zn_ref[tt, d].astype(jnp.float32),
                                 h, wrz_ref[d], wh_ref[d])
            h_new = (1.0 - z) * n + z * h
            h_scr[d] = h_new
            h_ref[tt, d] = h_new


def _gru_bwd_kernel(zrz_ref, zn_ref, hprev_ref, g_ref, wrz_ref, wh_ref,
                    dzrz_ref, dzn_ref, dwrz_ref, dwh_ref,
                    dh_scr, dwrz_scr, dwh_scr):
    """Reverse-time step: recompute r/z/n from the hoisted projections
    and h_{t-1} (pre-shifted), fold the carried dh and this step's
    output cotangent into dzrz_t/dzn_t, accumulate both weight grads."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dwrz_scr[...] = jnp.zeros_like(dwrz_scr)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    kt = zrz_ref.shape[0]
    for d in range(dh_scr.shape[0]):
        dzrzs, dns, hprevs, rhs = [], [], [], []
        for tt in reversed(range(kt)):   # reverse time WITHIN the block
            hprev = hprev_ref[tt, d]
            r, z, n = _gru_gates(zrz_ref[tt, d].astype(jnp.float32),
                                 zn_ref[tt, d].astype(jnp.float32),
                                 hprev, wrz_ref[d], wh_ref[d])
            dh_total = g_ref[tt, d] + dh_scr[d]
            dz = dh_total * (hprev - n)
            dn_pre = dh_total * (1.0 - z) * (1.0 - n * n)
            drh = jnp.dot(dn_pre, wh_ref[d].T,
                          preferred_element_type=jnp.float32)
            dr_pre = drh * hprev * r * (1.0 - r)
            dz_pre = dz * z * (1.0 - z)
            dzrz = jnp.concatenate([dr_pre, dz_pre], axis=-1)
            dzrz_ref[tt, d] = dzrz
            dzn_ref[tt, d] = dn_pre
            dh_scr[d] = (dh_total * z + drh * r
                         + jnp.dot(dzrz, wrz_ref[d].T,
                                   preferred_element_type=jnp.float32))
            dzrzs.append(dzrz)
            dns.append(dn_pre)
            hprevs.append(hprev)
            rhs.append(r * hprev)
        # both weight-grad gemms batch over the block (the serial chain
        # only constrains the dh carry above)
        cat = (lambda vs: vs[0] if kt == 1
               else jnp.concatenate(vs, axis=0))
        dwrz_scr[d] += jnp.dot(cat(hprevs).T, cat(dzrzs),
                               preferred_element_type=jnp.float32)
        dwh_scr[d] += jnp.dot(cat(rhs).T, cat(dns),
                              preferred_element_type=jnp.float32)
    dwrz_ref[...] = dwrz_scr[...]
    dwh_ref[...] = dwh_scr[...]


@functools.partial(jax.jit, static_argnames=("interpret", "block_t"))
def _gru_fwd_call(zrz, zn, wrz, wh, interpret=False, block_t=1):
    t, nd, b, h2 = zrz.shape
    h = h2 // 2
    kt = block_t
    return pl.pallas_call(
        _gru_fwd_kernel,
        grid=(t // kt,),
        in_specs=[
            pl.BlockSpec((kt, nd, b, h2), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h2), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((kt, nd, b, h), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32)],
        interpret=interpret,
    )(zrz, zn, wrz, wh)


@functools.partial(jax.jit, static_argnames=("interpret", "block_t"))
def _gru_bwd_call(zrz, zn, wrz, wh, hs, gout, interpret=False, block_t=1):
    t, nd, b, h2 = zrz.shape
    h = h2 // 2
    kt = block_t
    nblk = t // kt
    rev = lambda i: (nblk - 1 - i, 0, 0, 0)
    wspec2 = pl.BlockSpec((nd, h, h2), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    wspec1 = pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _gru_bwd_kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((kt, nd, b, h2), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            wspec2,
            wspec1,
        ],
        out_specs=[
            pl.BlockSpec((kt, nd, b, h2), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            wspec2,
            wspec1,
        ],
        out_shape=[jax.ShapeDtypeStruct((t, nd, b, h2), jnp.float32),
                   jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h2), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, h, h2), jnp.float32),
                        pltpu.VMEM((nd, h, h), jnp.float32)],
        interpret=interpret,
    )(zrz, zn, _shift_prev(hs), gout, wrz, wh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def gru_recurrence(zrz, zn, wrz, wh, interpret=False, block_t=1):
    """GRU recurrence with VMEM-resident carry: zrz (T, D, B, 2H) and zn
    (T, D, B, H) hoisted input projections (+bias), wrz (D, H, 2H) and
    wh (D, H, H) recurrent weights, D directions in {1, 2}; returns the
    h stack (T, D, B, H) f32.  Same math as GRUCell._step under
    Recurrent's scan; backward recomputes the gates (residual = the h
    stack the forward writes anyway).  ``block_t`` > 1 = multi-timestep
    blocking (time axis zero-padded to a multiple, exact — _pad_time)."""
    t = zrz.shape[0]
    hs = _gru_fwd_call(_pad_time(zrz, block_t), _pad_time(zn, block_t),
                       wrz, wh, interpret=interpret, block_t=block_t)
    return hs[:t]


def _gru_vjp_fwd(zrz, zn, wrz, wh, interpret=False, block_t=1):
    t = zrz.shape[0]
    zrzp = _pad_time(zrz, block_t)
    znp = _pad_time(zn, block_t)
    hs = _gru_fwd_call(zrzp, znp, wrz, wh, interpret=interpret,
                       block_t=block_t)
    return hs[:t], (zrzp, znp, wrz, wh, hs)


def _gru_vjp_bwd(interpret, block_t, res, gout):
    zrzp, znp, wrz, wh, hs = res
    t = gout.shape[0]
    dzrz, dzn, dwrz, dwh = _gru_bwd_call(
        zrzp, znp, wrz, wh, hs,
        _pad_time(gout.astype(jnp.float32), block_t),
        interpret=interpret, block_t=block_t)
    return (dzrz[:t].astype(zrzp.dtype), dzn[:t].astype(znp.dtype),
            dwrz.astype(wrz.dtype), dwh.astype(wh.dtype))


gru_recurrence.defvjp(_gru_vjp_fwd, _gru_vjp_bwd)


# ------------------------------------------------------------ vanilla RNN
#
# h' = tanh(zx_t + h @ Wh) — the reference's own RnnCell (RNN.scala:28)
# through the same sequential-grid/VMEM-carry structure.  The backward
# needs no gate recompute at all: dz = dh_total * (1 - h_t^2) comes
# straight from the stored h stack.


def _rnn_fwd_kernel(zx_ref, wht_ref, h_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)

    for tt in range(zx_ref.shape[0]):    # static block_t timesteps
        for d in range(h_scr.shape[0]):
            z = zx_ref[tt, d].astype(jnp.float32) + jnp.dot(
                h_scr[d].astype(wht_ref.dtype), wht_ref[d],
                preferred_element_type=jnp.float32)
            h_new = jnp.tanh(z)
            h_scr[d] = h_new
            h_ref[tt, d] = h_new


def _rnn_bwd_kernel(h_ref, hprev_ref, g_ref, wht_ref, dzx_ref, dwh_ref,
                    dh_scr, dwh_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    kt = h_ref.shape[0]
    for d in range(dh_scr.shape[0]):
        dzs, hprevs = [], []
        for tt in reversed(range(kt)):   # reverse time WITHIN the block
            h_t = h_ref[tt, d]
            dz = (g_ref[tt, d] + dh_scr[d]) * (1.0 - h_t * h_t)
            dzx_ref[tt, d] = dz
            dh_scr[d] = jnp.dot(dz.astype(wht_ref.dtype), wht_ref[d].T,
                                preferred_element_type=jnp.float32)
            dzs.append(dz)
            hprevs.append(hprev_ref[tt, d])
        cat = (lambda vs: vs[0] if kt == 1
               else jnp.concatenate(vs, axis=0))
        # dWh batches over the block: ONE (H, kt*B) x (kt*B, H) gemm
        dwh_scr[d] += jnp.dot(cat(hprevs).T, cat(dzs),
                              preferred_element_type=jnp.float32)
    dwh_ref[...] = dwh_scr[...]


@functools.partial(jax.jit, static_argnames=("interpret", "block_t"))
def _rnn_fwd_call(zx, wht, interpret=False, block_t=1):
    t, nd, b, h = zx.shape
    kt = block_t
    return pl.pallas_call(
        _rnn_fwd_kernel,
        grid=(t // kt,),
        in_specs=[
            pl.BlockSpec((kt, nd, b, h), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((kt, nd, b, h), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht)


@functools.partial(jax.jit, static_argnames=("interpret", "block_t"))
def _rnn_bwd_call(wht, hs, gout, interpret=False, block_t=1):
    t, nd, b, h = hs.shape
    kt = block_t
    nblk = t // kt
    rev = lambda i: (nblk - 1 - i, 0, 0, 0)
    return pl.pallas_call(
        _rnn_bwd_kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((kt, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, h, h), jnp.float32)],
        interpret=interpret,
    )(hs, _shift_prev(hs), gout, wht)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rnn_recurrence(zx, wht, interpret=False, block_t=1):
    """Vanilla tanh-RNN recurrence with VMEM-resident carry: zx
    (T, D, B, H) hoisted input projection (+both biases), wht (D, H, H)
    recurrent weights, D directions in {1, 2}; returns the h stack
    (T, D, B, H) f32.  Same math as RnnCell._step with the default Tanh
    under Recurrent's scan.  ``block_t`` > 1 = multi-timestep blocking
    (time axis zero-padded to a multiple, exact — _pad_time)."""
    t = zx.shape[0]
    hs = _rnn_fwd_call(_pad_time(zx, block_t), wht, interpret=interpret,
                       block_t=block_t)
    return hs[:t]


def _rnn_vjp_fwd(zx, wht, interpret=False, block_t=1):
    t = zx.shape[0]
    hs = _rnn_fwd_call(_pad_time(zx, block_t), wht, interpret=interpret,
                       block_t=block_t)
    return hs[:t], (wht, hs)


def _rnn_vjp_bwd(interpret, block_t, res, gout):
    wht, hs = res
    t = gout.shape[0]
    dzx, dwht = _rnn_bwd_call(wht, hs,
                              _pad_time(gout.astype(jnp.float32), block_t),
                              interpret=interpret, block_t=block_t)
    return dzx[:t].astype(jnp.float32), dwht.astype(wht.dtype)


rnn_recurrence.defvjp(_rnn_vjp_fwd, _rnn_vjp_bwd)


# ------------------------------------------- Mosaic window maxpool (r6)
#
# Round-6 re-litigation of the round-3 pool rejections (ISSUE 2
# tentpole a) with the round-5 kernel skills.  What is different from
# the retired stride-1 ``maxpool2d`` above:
#
#   * layout: channels ride the 128-lane dim (NHWC inside the kernel, W
#     on sublanes) — Inception pools carry C=64..832, so the lanes are
#     full where the round-3 NCHW kernel padded W=7..28 up to 128
#     (its measured 4.6-18x bandwidth waste);
#   * strides: the H stride lives in the grid's block index maps and
#     the W stride in a phase-folded lane layout ((W/s, s*C) — phase r
#     = lane block r*C..(r+1)*C), so every in-kernel window tap is a
#     unit-stride sublane/lane slice — no strided slices and no
#     in-kernel reshape, the two Mosaic blockers round 3 hit;
#   * the forward stores the window ARGMAX (int32 tap index) and the
#     backward is a scatter-free gather over it: one read of (g,
#     argmax) per tap position instead of select_and_scatter's
#     compare-and-route over x.  Tie rule: FIRST max in row-major
#     window order — bit-identical to XLA select_and_scatter;
#   * VMEM-resident: each grid step owns BH output rows; the input rows
#     it shares with the next block arrive via a second (halo)
#     BlockSpec on the same operand, so worst-case read amplification
#     is 2x (vs kh/s_h x for a naive row-per-step grid).
#
# Adoption is gated on a device-clock A/B (nn/pooling.py _PALLAS_POOL,
# default OFF): every previous pool formulation lost to the XLA
# emitter on v5e (PERF_NOTES rounds 2-5), and this one must buy its
# place the same way.


def _mosaic_pool_geom(h, w, window, strides, pads):
    """Static geometry: output sizes, output-row block, padded frames."""
    kh, kw = window
    sh, sw = strides
    (plh, phh), (plw, phw) = pads
    oh = (h + plh + phh - kh) // sh + 1
    ow = (w + plw + phw - kw) // sw + 1
    bh = max(-(-kh // sh), 8)        # output rows per grid step
    nblk = -(-oh // bh)
    hp = (nblk + 1) * sh * bh        # main blocks + one halo block
    wq = ow + (kw - 1) // sw         # phase-folded sublane extent
    return oh, ow, bh, nblk, hp, wq


def _mosaic_mp_fwd_body(xm_ref, xh_ref, y_ref, a_ref, *, kh, kw, sh, sw,
                        c):
    bh = y_ref.shape[1]
    ow = y_ref.shape[2]
    xall = jnp.concatenate([xm_ref[0], xh_ref[0]],
                           axis=0).astype(jnp.float32)
    for lr in range(bh):             # static output rows in this block
        best, arg = None, None
        for i in range(kh):
            row = xall[sh * lr + i]  # (wq, sw*c) — static row index
            for j in range(kw):
                # phase fold: column s_w*ow + j = (sublane ow + j//s_w,
                # lane block j%s_w) — both unit-stride slices
                tap = lax.slice(row, (j // sw, (j % sw) * c),
                                (j // sw + ow, (j % sw) * c + c))
                if best is None:
                    best = tap
                    arg = jnp.zeros(tap.shape, jnp.int32)
                else:
                    m = tap > best   # strict >: FIRST max wins ties
                    best = jnp.where(m, tap, best)
                    arg = jnp.where(m, i * kw + j, arg)
        y_ref[0, lr] = best.astype(y_ref.dtype)
        if a_ref is not None:
            a_ref[0, lr] = arg


def _mosaic_mp_fwd_kernel(xm_ref, xh_ref, y_ref, a_ref, **kw_):
    _mosaic_mp_fwd_body(xm_ref, xh_ref, y_ref, a_ref, **kw_)


def _mosaic_mp_fwd_kernel_primal(xm_ref, xh_ref, y_ref, **kw_):
    _mosaic_mp_fwd_body(xm_ref, xh_ref, y_ref, None, **kw_)


def _mosaic_mp_bwd_kernel(gp_ref, ap_ref, gm_ref, am_ref, dx_ref, *,
                          kh, kw, sh, sw, c, bh, nblk):
    """Scatter-free gather: dx row-block <- sum over the stored argmax
    of the two g/a row-blocks whose windows can reach it (previous +
    main — the blocking guarantees no window spans further)."""
    blk = pl.program_id(1)
    bi = dx_ref.shape[1]             # s_h * bh input rows per step
    ow = gm_ref.shape[2]
    wq = dx_ref.shape[2]
    acc = jnp.zeros((bi, wq, sw * c), jnp.float32)
    # the prev spec clamps blk-1 to 0 and the main spec clamps blk to
    # nblk-1: a clamped (duplicate) block must contribute nothing
    valid = ((blk > 0).astype(jnp.float32),
             (blk < nblk).astype(jnp.float32))
    for b, (g_ref, a_ref) in enumerate(((gp_ref, ap_ref),
                                        (gm_ref, am_ref))):
        for lr in range(bh):
            g_row, a_row = None, None
            for i in range(kh):
                # input row (static): s_h*oh + i relative to this block
                hloc = sh * lr + i + sh * bh * (b - 1)
                if not 0 <= hloc < bi:
                    continue
                if g_row is None:    # load lazily: edge rows skip taps
                    g_row = g_ref[0, lr].astype(jnp.float32) * valid[b]
                    a_row = a_ref[0, lr]
                for j in range(kw):
                    contrib = g_row * (a_row == (i * kw + j)
                                       ).astype(jnp.float32)
                    acc = acc.at[hloc, j // sw:j // sw + ow,
                                 (j % sw) * c:(j % sw) * c + c
                                 ].add(contrib)
    dx_ref[0] = acc.astype(dx_ref.dtype)


def _mosaic_mp_pack(x, window, strides, pads, fill):
    """NCHW -> the kernel's phase-folded NHWC frame (N, Hp, Wq, s_w*C),
    padded with ``fill`` (-inf for x, 0 for g — zero-padded cotangents
    make every out-of-range contribution vanish)."""
    n, c, h, w = x.shape
    (plh, _), (plw, _) = pads
    oh, ow, bh_, nblk, hp, wq = _mosaic_pool_geom(
        h, w, window, strides, pads)
    sw = strides[1]
    tw = wq * sw
    xt = jnp.transpose(x, (0, 2, 3, 1))
    xt = jnp.pad(xt, ((0, 0), (plh, max(0, hp - h - plh)),
                      (plw, max(0, tw - w - plw)), (0, 0)),
                 constant_values=fill)[:, :hp, :tw]
    return xt.reshape(n, hp, wq, sw * c)


@functools.partial(jax.jit, static_argnames=("window", "strides", "pads",
                                             "interpret", "with_argmax"))
def _mosaic_mp_fwd_call(x, window, strides, pads, interpret=False,
                        with_argmax=True):
    n, c, h, w = x.shape
    kh, kw = window
    sh, sw = strides
    oh, ow, bh, nblk, hp, wq = _mosaic_pool_geom(h, w, window, strides,
                                                 pads)
    xr = _mosaic_mp_pack(x, window, strides, pads, -jnp.inf)
    xspec = pl.BlockSpec((1, sh * bh, wq, sw * c),
                         lambda nn_, b: (nn_, b, 0, 0),
                         memory_space=pltpu.VMEM)
    halo = pl.BlockSpec((1, sh * bh, wq, sw * c),
                        lambda nn_, b: (nn_, b + 1, 0, 0),
                        memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((1, bh, ow, c), lambda nn_, b: (nn_, b, 0, 0),
                         memory_space=pltpu.VMEM)
    oshape = jax.ShapeDtypeStruct((n, nblk * bh, ow, c), x.dtype)
    ashape = jax.ShapeDtypeStruct((n, nblk * bh, ow, c), jnp.int32)
    body = functools.partial(
        _mosaic_mp_fwd_kernel if with_argmax
        else _mosaic_mp_fwd_kernel_primal,
        kh=kh, kw=kw, sh=sh, sw=sw, c=c)
    out = pl.pallas_call(
        body,
        grid=(n, nblk),
        in_specs=[xspec, halo],
        out_specs=[ospec, ospec] if with_argmax else ospec,
        out_shape=[oshape, ashape] if with_argmax else oshape,
        interpret=interpret,
    )(xr, xr)
    if with_argmax:
        yp, a = out
    else:
        yp, a = out, None
    y = jnp.transpose(yp[:, :oh], (0, 3, 1, 2))  # (N, C, OH, OW)
    return (y, a) if with_argmax else y


@functools.partial(jax.jit, static_argnames=("window", "strides", "pads",
                                             "xshape", "interpret"))
def _mosaic_mp_bwd_call(a, g, window, strides, pads, xshape,
                        interpret=False):
    n, c, h, w = xshape
    kh, kw = window
    sh, sw = strides
    (plh, _), (plw, _) = pads
    oh, ow, bh, nblk, hp, wq = _mosaic_pool_geom(h, w, window, strides,
                                                 pads)
    # cotangent into the padded output-row frame (zeros beyond OH)
    gt = jnp.transpose(g, (0, 2, 3, 1))
    gt = jnp.pad(gt, ((0, 0), (0, nblk * bh - oh), (0, 0), (0, 0)))
    prev = lambda nn_, b: (nn_, jnp.maximum(b - 1, 0), 0, 0)
    main = lambda nn_, b: (nn_, jnp.minimum(b, nblk - 1), 0, 0)
    gspec_p = pl.BlockSpec((1, bh, ow, c), prev, memory_space=pltpu.VMEM)
    gspec_m = pl.BlockSpec((1, bh, ow, c), main, memory_space=pltpu.VMEM)
    dspec = pl.BlockSpec((1, sh * bh, wq, sw * c),
                         lambda nn_, b: (nn_, b, 0, 0),
                         memory_space=pltpu.VMEM)
    dxp = pl.pallas_call(
        functools.partial(_mosaic_mp_bwd_kernel, kh=kh, kw=kw, sh=sh,
                          sw=sw, c=c, bh=bh, nblk=nblk),
        grid=(n, nblk + 1),
        in_specs=[gspec_p, gspec_p, gspec_m, gspec_m],
        out_specs=dspec,
        out_shape=jax.ShapeDtypeStruct((n, hp, wq, sw * c), g.dtype),
        interpret=interpret,
    )(gt, a, gt, a)
    # unfold phases, drop padding, back to NCHW
    dxw = dxp.reshape(n, hp, wq * sw, c)
    dxw = jnp.pad(dxw, ((0, 0), (0, 0),
                        (0, max(0, plw + w - wq * sw)), (0, 0)))
    dx = dxw[:, plh:plh + h, plw:plw + w]
    return jnp.transpose(dx, (0, 3, 1, 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _mosaic_maxpool(x, window, strides, pads, xshape, interpret):
    return _mosaic_mp_fwd_call(x, window, strides, pads, interpret,
                               with_argmax=False)


def _mosaic_mp_vjp_fwd(x, window, strides, pads, xshape, interpret):
    y, a = _mosaic_mp_fwd_call(x, window, strides, pads, interpret,
                               with_argmax=True)
    return y, a                      # argmax is the ONLY residual


def _mosaic_mp_vjp_bwd(window, strides, pads, xshape, interpret, a, g):
    return (_mosaic_mp_bwd_call(a, g, window, strides, pads, xshape,
                                interpret),)


_mosaic_maxpool.defvjp(_mosaic_mp_vjp_fwd, _mosaic_mp_vjp_bwd)


def mosaic_maxpool2d(x, window, strides, pads, interpret=False):
    """NCHW maxpool through the round-6 Mosaic kernel pair: argmax-
    storing forward + scatter-free gather backward (replacing
    select_and_scatter).  ``window``/``strides`` any sizes (overlapping
    or not), ``pads`` = ((lo_h, hi_h), (lo_w, hi_w)) explicit.  Gradient
    tie rule: first max in row-major window order == XLA
    select_and_scatter.  A no-grad forward skips the argmax writes."""
    return _mosaic_maxpool(x, tuple(window), tuple(strides),
                           (tuple(pads[0]), tuple(pads[1])),
                           tuple(x.shape), interpret)


# ---------------------------------------------------------------------------
# Paged attention: in-kernel page-table walk + online softmax (round 7).
#
# The decode hot path (`models/transformer.py _lm_forward_window`)
# materializes a per-row gathered K/V view `kpool[li][ptab]` in HBM —
# and under int8 KV runs a separate `kvq.dequantize_view` pass — before
# plain-XLA attention.  This kernel is the vLLM PagedAttention design
# (Kwon et al., SOSP 2023) fused with FlashAttention streaming (Dao et
# al., 2022): the grid's innermost dimension IS the page walk, the
# slot→page table rides scalar prefetch so each page's BlockSpec index
# map resolves `phys = ptab[b, p]` before the DMA is issued (Mosaic
# double-buffers the HBM→VMEM page stream for free), and the softmax is
# the online running-max/denominator form so no (B, n_view) score or
# dequantized K/V tensor ever exists in HBM.  The int8 variant folds
# `kvq.dequantize_view` (q.astype(f32) * scale[..., None], scales
# indexed by the SAME phys coordinates as quant/kv.py) into the QK and
# PV loops.  A multi-query S = k+1 window is the same kernel — that is
# the speculative verify pass (`_PALLAS_SPEC_VERIFY`).
#
# Adoption gate (PR-2 discipline): default OFF via
# `models/transformer.py _PALLAS_PAGED_ATTN / _PALLAS_SPEC_VERIFY`; no
# chip verdict yet → the staged A/B lives in tools/ab_device_clock.py
# and `tools/bench_serve.py --decode-sweep --attn-kernel`.  Equivalence
# vs the gathered-view reference is pinned in interpreter mode by
# tests/test_paged_attention.py.
# ---------------------------------------------------------------------------


def _paged_attn_kernel(ptab_ref, *refs, page_size, scale, quantized):
    """One (batch row b, head h, page p) grid step.

    Page p's K/V block (and scale rows when quantized) land in VMEM via
    the scalar-prefetch index map; scratch carries the flash-attention
    running state (m: row max, l: denominator, acc: unnormalized PV)
    across the sequential page walk.  Page 0 always holds position 0 and
    `pos >= 0`, so m is finite from the first page and the
    `exp(-inf - finite) = 0` identities keep the recurrence exact for
    fully-masked later pages (reserved-but-unwritten tail pages).
    """
    if quantized:
        (pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_ref, l_ref, acc_ref) = refs
    else:
        pos_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, -jnp.inf, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    q = q_ref[0, :, 0, :].astype(jnp.float32)          # (S, hd)
    k = k_ref[0, :, 0, :].astype(jnp.float32)          # (page_size, hd)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    if quantized:
        # kvq.dequantize_view fused in-loop: int8 * per-(page-row, head)
        # scale, indexed by the same phys page the K/V DMA used.
        k = k * ks_ref[0, :, 0][:, None]
        v = v * vs_ref[0, :, 0][:, None]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    t = p * page_size + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    pos = pos_ref[0, :]                                # (S,)
    s = jnp.where(t <= pos[:, None], s, -jnp.inf)
    m_prev = m_ref[...]                                # (S, 1)
    m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    w = jnp.exp(s - m_new)                             # (S, page_size)
    l_ref[...] = l_ref[...] * alpha + w.sum(axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + lax.dot_general(
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = m_new

    @pl.when(p == pl.num_programs(2) - 1)
    def _finish():
        o_ref[0, :, 0, :] = acc_ref[...] / l_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_attention_call(q, kpool, vpool, ptab, pos, kscale, vscale,
                          interpret):
    bsz, ws, n_heads, hd = q.shape
    n_ptab_pages = ptab.shape[1]
    page_size = kpool.shape[1]
    quantized = kscale is not None
    scale = 1.0 / (hd ** 0.5)
    kvspec = pl.BlockSpec((1, page_size, 1, hd),
                          lambda b, h, p, pt: (pt[b, p], 0, h, 0))
    sspec = pl.BlockSpec((1, page_size, 1),
                         lambda b, h, p, pt: (pt[b, p], 0, h))
    in_specs = [
        pl.BlockSpec((1, ws), lambda b, h, p, pt: (b, 0)),          # pos
        pl.BlockSpec((1, ws, 1, hd), lambda b, h, p, pt: (b, 0, h, 0)),
        kvspec, kvspec,
    ]
    operands = [pos.astype(jnp.int32), q, kpool, vpool]
    if quantized:
        in_specs += [sspec, sspec]
        operands += [kscale, vscale]
    return pl.pallas_call(
        functools.partial(_paged_attn_kernel, page_size=page_size,
                          scale=scale, quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bsz, n_heads, n_ptab_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, ws, 1, hd),
                                   lambda b, h, p, pt: (b, 0, h, 0)),
            scratch_shapes=[pltpu.VMEM((ws, 1), jnp.float32),
                            pltpu.VMEM((ws, 1), jnp.float32),
                            pltpu.VMEM((ws, hd), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((bsz, ws, n_heads, hd),
                                       jnp.float32),
        interpret=interpret,
    )(ptab.astype(jnp.int32), *operands)


def paged_attention(q, kpool, vpool, ptab, pos, kscale=None, vscale=None,
                    interpret=None):
    """Causal paged attention over a page-pooled KV cache, one layer.

    ``q`` (B, S, H, hd) f32 queries at absolute positions ``pos``
    (B, S) int32; ``kpool``/``vpool`` (n_pages, page_size, H, hd) the
    layer's physical page pool (f32/f16 slabs or int8 with
    ``kscale``/``vscale`` (n_pages, page_size, H) per-row/per-head
    scales from quant/kv.py); ``ptab`` (B, P) int32 the slot→page
    table.  Logical position t of row b lives at
    ``pool[ptab[b, t // page_size], t % page_size]``; keys with
    ``t <= pos`` attend (the gathered-view reference's causal mask).
    Rows whose window entry is dead must be masked by the CALLER (the
    decode step gates on ``valid`` downstream) — the kernel computes
    every (b, s) row.  Returns (B, S, H, hd) f32.
    """
    if interpret is None:
        interpret = not _on_tpu()
    return _paged_attention_call(q, kpool, vpool, ptab, pos, kscale,
                                 vscale, interpret)


def paged_spec_verify(q, kpool, vpool, ptab, pos, kscale=None, vscale=None,
                      interpret=None):
    """Speculative (k+1)-window verify pass: ``paged_attention`` with a
    multi-query window S = k+1 (draft tokens verified in one shot).  The
    window positions ``pos[:, j]`` are consecutive per row, so the page
    walk streams each page ONCE for all k+1 queries instead of rerunning
    gathered-view attention per window — the `_PALLAS_SPEC_VERIFY` hot
    path.  Same contract as ``paged_attention``.
    """
    if interpret is None:
        interpret = not _on_tpu()
    return _paged_attention_call(q, kpool, vpool, ptab, pos, kscale,
                                 vscale, interpret)
