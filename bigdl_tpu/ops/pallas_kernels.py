"""Pallas TPU kernels (see /opt/skills/guides/pallas_guide.md).

The device-side hot loops of the reference's native layer (mkl.c vector
math / axpy / scal) compile through XLA; Pallas covers the cases where
hand-fusion still wins:

- ``fused_sgd``: momentum-SGD parameter update as ONE pass over HBM
  (read p, g, v -> write p', v').  The unfused update streams the tensors
  multiple times; for the flat multi-MB parameter vector of a large model
  this is pure HBM bandwidth, exactly the regime a fused elementwise
  kernel owns.  The reference's analogue is the fp16-compressed parallel
  update loop (FP16CompressedTensor.parallel add/scal).

On non-TPU backends the kernels run through the Pallas interpreter
(``interpret=True``) so tests exercise the same code path on the CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_BLOCK = 64 * 1024  # elements per grid step (256 KiB f32 — fits VMEM easily)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _make_sgd_kernel(nesterov: bool):
    def kernel(p_ref, g_ref, v_ref, h_ref, p_out, v_out):
        """g~ = g + wd*p; with momentum: v' = mom*v + (1-damp)*g~ and
        p' = p - lr*(g~ + mom*v' if nesterov else v'); with mom == 0 the
        unfused path's semantics hold exactly — velocity untouched, step
        = g~ (dampening ignored).  One VMEM pass.
        h_ref holds [lr, momentum, weight_decay, dampening] in SMEM."""
        lr, mom, wd, damp = h_ref[0], h_ref[1], h_ref[2], h_ref[3]
        has_mom = (mom != 0.0).astype(p_ref.dtype)
        g = g_ref[:] + wd * p_ref[:]
        v_new = mom * v_ref[:] + (1.0 - has_mom * damp) * g
        # mom==0: keep stored velocity, step with plain g
        v_out[:] = has_mom * v_new + (1.0 - has_mom) * v_ref[:]
        d = g + mom * v_new if nesterov else v_new
        p_out[:] = p_ref[:] - lr * (has_mom * d + (1.0 - has_mom) * g)
    return kernel


_SGD_KERNELS = {False: _make_sgd_kernel(False), True: _make_sgd_kernel(True)}


@functools.partial(jax.jit, static_argnames=("interpret", "nesterov"))
def _fused_sgd_flat(p, g, v, hyper4, interpret=False, nesterov=False):
    n = p.shape[0]
    # pad to a whole number of blocks (grid must be static)
    padded = ((n + _BLOCK - 1) // _BLOCK) * _BLOCK
    if padded != n:
        pad = padded - n
        p = jnp.concatenate([p, jnp.zeros(pad, p.dtype)])
        g = jnp.concatenate([g, jnp.zeros(pad, g.dtype)])
        v = jnp.concatenate([v, jnp.zeros(pad, v.dtype)])
    grid = padded // _BLOCK
    p2, v2 = pl.pallas_call(
        _SGD_KERNELS[nesterov],
        out_shape=(jax.ShapeDtypeStruct((padded,), p.dtype),
                   jax.ShapeDtypeStruct((padded,), v.dtype)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(p, g, v, hyper4)
    return p2[:n], v2[:n]


def fused_sgd(params, grads, velocity, lr, momentum=0.0, weight_decay=0.0,
              dampening=0.0, nesterov=False):
    """Fused momentum-SGD update over pytrees.

    Flattens each leaf to 1D and runs the single-pass Pallas kernel;
    returns (new_params, new_velocity).  Uses the interpreter off-TPU.
    """
    interpret = not _on_tpu()
    hyper4 = jnp.asarray([lr, momentum, weight_decay, dampening], jnp.float32)

    def leaf(p, g, v):
        shape = p.shape
        p2, v2 = _fused_sgd_flat(p.reshape(-1), g.reshape(-1), v.reshape(-1),
                                 hyper4, interpret=interpret,
                                 nesterov=bool(nesterov))
        return p2.reshape(shape), v2.reshape(shape)

    flat = jax.tree_util.tree_map(leaf, params, grads, velocity)
    new_p = jax.tree_util.tree_map(lambda pv: pv[0], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_v = jax.tree_util.tree_map(lambda pv: pv[1], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_p, new_v


# --------------------------------------------------------------- LSTM scan

def _lstm_scan_kernel(zx_ref, wht_ref, h0_ref, c0_ref, out_ref, h_scr, c_scr):
    """One grid step = one timestep; h/c live in VMEM scratch across steps.

    zx_ref: (1, B, 4H) precomputed input projection for step t (already
    includes the bias); wht_ref: (H, 4H) recurrent weight, transposed so
    the in-kernel dot needs no transpose; out_ref: (1, B, H).
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h = h_scr[:]
    c = c_scr[:]
    z = zx_ref[0] + pl.dot(h.astype(wht_ref.dtype), wht_ref[:],
                           ).astype(jnp.float32)
    hdim = h.shape[-1]
    i = jax.nn.sigmoid(z[:, :hdim])
    f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
    g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(z[:, 3 * hdim:])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    h_scr[:] = h_new
    c_scr[:] = c_new
    out_ref[0] = h_new


@functools.partial(jax.jit, static_argnames=("interpret",))
def lstm_scan(zx, wht, h0, c0, interpret=False):
    """Whole-recurrence Pallas kernel: zx (T, B, 4H) f32 (input projection
    + bias, precomputed on the MXU outside), wht (H, 4H), h0/c0 (B, H) f32.
    Returns hs (T, B, H).  Forward only — see PERF_NOTES for the measured
    verdict vs lax.scan before wiring this anywhere hot.
    """
    t, b, h4 = zx.shape
    h = h4 // 4
    return pl.pallas_call(
        _lstm_scan_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h, h4), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, b, h), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32),
                        pltpu.VMEM((b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht, h0, c0)
