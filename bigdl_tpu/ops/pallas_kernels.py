"""Pallas TPU kernels (see /opt/skills/guides/pallas_guide.md).

The device-side hot loops of the reference's native layer (mkl.c vector
math / axpy / scal) compile through XLA; Pallas covers the cases where
hand-fusion still wins:

- ``fused_sgd``: momentum-SGD parameter update as ONE pass over HBM
  (read p, g, v -> write p', v').  The unfused update streams the tensors
  multiple times; for the flat multi-MB parameter vector of a large model
  this is pure HBM bandwidth, exactly the regime a fused elementwise
  kernel owns.  The reference's analogue is the fp16-compressed parallel
  update loop (FP16CompressedTensor.parallel add/scal).

On non-TPU backends the kernels run through the Pallas interpreter
(``interpret=True``) so tests exercise the same code path on the CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_BLOCK = 64 * 1024  # elements per grid step (256 KiB f32 — fits VMEM easily)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _make_sgd_kernel(nesterov: bool):
    def kernel(p_ref, g_ref, v_ref, h_ref, p_out, v_out):
        """g~ = g + wd*p; with momentum: v' = mom*v + (1-damp)*g~ and
        p' = p - lr*(g~ + mom*v' if nesterov else v'); with mom == 0 the
        unfused path's semantics hold exactly — velocity untouched, step
        = g~ (dampening ignored).  One VMEM pass.
        h_ref holds [lr, momentum, weight_decay, dampening] in SMEM."""
        lr, mom, wd, damp = h_ref[0], h_ref[1], h_ref[2], h_ref[3]
        has_mom = (mom != 0.0).astype(p_ref.dtype)
        g = g_ref[:] + wd * p_ref[:]
        v_new = mom * v_ref[:] + (1.0 - has_mom * damp) * g
        # mom==0: keep stored velocity, step with plain g
        v_out[:] = has_mom * v_new + (1.0 - has_mom) * v_ref[:]
        d = g + mom * v_new if nesterov else v_new
        p_out[:] = p_ref[:] - lr * (has_mom * d + (1.0 - has_mom) * g)
    return kernel


_SGD_KERNELS = {False: _make_sgd_kernel(False), True: _make_sgd_kernel(True)}


@functools.partial(jax.jit, static_argnames=("interpret", "nesterov"))
def _fused_sgd_flat(p, g, v, hyper4, interpret=False, nesterov=False):
    n = p.shape[0]
    # pad to a whole number of blocks (grid must be static)
    padded = ((n + _BLOCK - 1) // _BLOCK) * _BLOCK
    if padded != n:
        pad = padded - n
        p = jnp.concatenate([p, jnp.zeros(pad, p.dtype)])
        g = jnp.concatenate([g, jnp.zeros(pad, g.dtype)])
        v = jnp.concatenate([v, jnp.zeros(pad, v.dtype)])
    grid = padded // _BLOCK
    p2, v2 = pl.pallas_call(
        _SGD_KERNELS[nesterov],
        out_shape=(jax.ShapeDtypeStruct((padded,), p.dtype),
                   jax.ShapeDtypeStruct((padded,), v.dtype)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(p, g, v, hyper4)
    return p2[:n], v2[:n]


def fused_sgd(params, grads, velocity, lr, momentum=0.0, weight_decay=0.0,
              dampening=0.0, nesterov=False):
    """Fused momentum-SGD update over pytrees.

    Flattens each leaf to 1D and runs the single-pass Pallas kernel;
    returns (new_params, new_velocity).  Uses the interpreter off-TPU.
    """
    interpret = not _on_tpu()
    hyper4 = jnp.asarray([lr, momentum, weight_decay, dampening], jnp.float32)

    def leaf(p, g, v):
        shape = p.shape
        p2, v2 = _fused_sgd_flat(p.reshape(-1), g.reshape(-1), v.reshape(-1),
                                 hyper4, interpret=interpret,
                                 nesterov=bool(nesterov))
        return p2.reshape(shape), v2.reshape(shape)

    flat = jax.tree_util.tree_map(leaf, params, grads, velocity)
    new_p = jax.tree_util.tree_map(lambda pv: pv[0], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_v = jax.tree_util.tree_map(lambda pv: pv[1], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_p, new_v


# --------------------------------------------------------------- LSTM scan

def _lstm_scan_kernel(zx_ref, wht_ref, h0_ref, c0_ref, out_ref, h_scr, c_scr):
    """One grid step = one timestep; h/c live in VMEM scratch across steps.

    zx_ref: (1, B, 4H) precomputed input projection for step t (already
    includes the bias); wht_ref: (H, 4H) recurrent weight, transposed so
    the in-kernel dot needs no transpose; out_ref: (1, B, H).
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h = h_scr[:]
    c = c_scr[:]
    z = zx_ref[0] + pl.dot(h.astype(wht_ref.dtype), wht_ref[:],
                           ).astype(jnp.float32)
    hdim = h.shape[-1]
    i = jax.nn.sigmoid(z[:, :hdim])
    f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
    g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(z[:, 3 * hdim:])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    h_scr[:] = h_new
    c_scr[:] = c_new
    out_ref[0] = h_new


@functools.partial(jax.jit, static_argnames=("interpret",))
def lstm_scan(zx, wht, h0, c0, interpret=False):
    """Whole-recurrence Pallas kernel: zx (T, B, 4H) f32 (input projection
    + bias, precomputed on the MXU outside), wht (H, 4H), h0/c0 (B, H) f32.
    Returns hs (T, B, H).  Forward only — see PERF_NOTES for the measured
    verdict vs lax.scan before wiring this anywhere hot.
    """
    t, b, h4 = zx.shape
    h = h4 // 4
    return pl.pallas_call(
        _lstm_scan_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h, h4), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, b, h), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32),
                        pltpu.VMEM((b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht, h0, c0)


# ------------------------------------------------------------- max pooling
#
# XLA's reduce_window forward and especially its select-and-scatter VJP
# run far below HBM bandwidth on v5e (PROFILE_inception.md round 3: pool
# fwd+bwd = 7.9 ms of a 40 ms Inception step at ZERO useful FLOPs, while
# an isolated streaming op moves the same bytes ~5x faster).  These
# kernels compute the same maxpool (and its first-max-wins gradient, the
# select-and-scatter tie rule) as a handful of VMEM slice/max/add passes.
#
# Layout: NCHW collapsed to (N*C, H, W) rows; grid over row-blocks, each
# block (BC, H, W) resident in VMEM with W on lanes and H on sublanes.
# STRIDE-1 windows only: every window read/write is then a unit-stride
# VMEM slice (Mosaic forbids strided slices and the reshape that a
# phase-decomposition of strided pools would need); strided pools stay
# on the XLA path, whose select-and-scatter cost is acceptable there
# because strided windows barely overlap.


def _mp_out_size(size, k, s, pl_, ph_):
    return (size + pl_ + ph_ - k) // s + 1


def _maxpool_fwd_kernel(x_ref, y_ref, *, kh, kw, pads):
    (plh, phh), (plw, phw) = pads
    # compute in f32: this Mosaic target lacks bf16 vector compares
    x = x_ref[:].astype(jnp.float32)
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    xp = jnp.pad(x, ((0, 0), (plh, phh), (plw, phw)), constant_values=neg)
    bc = x.shape[0]
    oh = x.shape[1] + plh + phh - kh + 1
    ow = x.shape[2] + plw + phw - kw + 1
    y = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, i, j), (bc, i + oh, j + ow))
            y = s if y is None else jnp.maximum(y, s)
    y_ref[:] = y.astype(y_ref.dtype)


def _maxpool_bwd_kernel(x_ref, g_ref, dx_ref, *, kh, kw, pads):
    """First-max-wins gradient (select-and-scatter scan order: row-major
    over window offsets)."""
    (plh, phh), (plw, phw) = pads
    # compute in f32: this Mosaic target lacks bf16 vector compares
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    xp = jnp.pad(x, ((0, 0), (plh, phh), (plw, phw)), constant_values=neg)
    bc, hp, wp = xp.shape
    oh, ow = g.shape[1], g.shape[2]
    y = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, i, j), (bc, i + oh, j + ow))
            y = s if y is None else jnp.maximum(y, s)
    accp = jnp.zeros((bc, hp, wp), jnp.float32)
    claimed = jnp.zeros(g.shape, jnp.bool_)
    for i in range(kh):
        for j in range(kw):
            # re-slice instead of caching all kh*kw windows: keeps the
            # kernel's live VMEM set to ~6 frames
            s = lax.slice(xp, (0, i, j), (bc, i + oh, j + ow))
            m = (s == y) & ~claimed
            claimed = claimed | m
            contrib = g * m.astype(jnp.float32)
            accp = accp + lax.pad(contrib, jnp.asarray(0, jnp.float32),
                                  ((0, 0, 0), (i, hp - oh - i, 0),
                                   (j, wp - ow - j, 0)))
    dx_ref[:] = lax.slice(accp, (0, plh, plw),
                          (bc, plh + x.shape[1], plw + x.shape[2])
                          ).astype(dx_ref.dtype)


def _pick_bc(nc, h, w, arrays=8):
    """Largest row-block that divides nc and keeps ~arrays f32 copies of
    the (BC, H, W) frame under a 6 MB budget — deliberately well under
    the ~16 MB scoped-VMEM limit to leave room for Mosaic's own
    temporaries (frames are upcast to f32 inside the kernels)."""
    budget = 6 * 1024 * 1024
    lanes = -(-(w + 4) // 128) * 128  # Mosaic pads the lane dim to 128
    per_row = (h + 4) * lanes * 4 * arrays
    bc = max(1, min(nc, budget // max(per_row, 1)))
    while nc % bc:
        bc -= 1
    return bc


@functools.partial(jax.jit,
                   static_argnames=("window", "strides", "pads", "interpret"))
def _maxpool_fwd_call(x, window, strides, pads, interpret=False):
    n, c, h, w = x.shape
    kh, kw = window
    assert strides == (1, 1), "pallas maxpool2d is stride-1 only"
    oh = _mp_out_size(h, kh, 1, *pads[0])
    ow = _mp_out_size(w, kw, 1, *pads[1])
    nc = n * c
    bc = _pick_bc(nc, h, w)
    xr = x.reshape(nc, h, w)
    y = pl.pallas_call(
        functools.partial(_maxpool_fwd_kernel, kh=kh, kw=kw, pads=pads),
        grid=(nc // bc,),
        in_specs=[pl.BlockSpec((bc, h, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bc, oh, ow), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nc, oh, ow), x.dtype),
        interpret=interpret,
    )(xr)
    return y.reshape(n, c, oh, ow)


@functools.partial(jax.jit,
                   static_argnames=("window", "strides", "pads", "interpret"))
def _maxpool_bwd_call(x, g, window, strides, pads, interpret=False):
    n, c, h, w = x.shape
    kh, kw = window
    assert strides == (1, 1), "pallas maxpool2d is stride-1 only"
    nc = n * c
    oh, ow = g.shape[2], g.shape[3]
    bc = _pick_bc(nc, h, w, arrays=8)
    dx = pl.pallas_call(
        functools.partial(_maxpool_bwd_kernel, kh=kh, kw=kw, pads=pads),
        grid=(nc // bc,),
        in_specs=[pl.BlockSpec((bc, h, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((bc, oh, ow), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bc, h, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nc, h, w), x.dtype),
        interpret=interpret,
    )(x.reshape(nc, h, w), g.reshape(nc, oh, ow))
    return dx.reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def maxpool2d(x, window, strides, pads, interpret=False):
    """NCHW maxpool with Pallas forward AND first-max backward.

    ``pads`` = ((lo_h, hi_h), (lo_w, hi_w)) explicit amounts (Torch
    ceil-mode handled by the caller, nn/pooling.py).  Gradient tie rule
    matches XLA select-and-scatter (first max in row-major window order).
    """
    return _maxpool_fwd_call(x, window, strides, pads, interpret)


def _maxpool_vjp_fwd(x, window, strides, pads, interpret=False):
    return _maxpool_fwd_call(x, window, strides, pads, interpret), x


def _maxpool_vjp_bwd(window, strides, pads, interpret, x, g):
    return (_maxpool_bwd_call(x, g, window, strides, pads, interpret),)


maxpool2d.defvjp(_maxpool_vjp_fwd, _maxpool_vjp_bwd)


# ---------------------------------------------------------------- LRN
#
# Cross-channel LRN (y = x / (k + alpha/n * sum_win x^2)^beta) costs
# ~5.6 ms of the Inception-v1 step through XLA (channel-window
# reduce_window + the backward's mul/div fusions, PROFILE_inception.md
# round 3).  Unlike the maxpool case, LRN maps PERFECTLY onto Mosaic's
# (sublane, lane) model: collapse HW onto lanes and put C on sublanes —
# the size-5 channel window becomes five unit-stride sublane slices, no
# lane padding waste, no strided slicing.  Forward and the closed-form
# backward
#   dx = dy z^-b - (2 a b / n) x * sum_win(dy x z^(-b-1))
# are each ONE pass over the block (backward recomputes z from x).


def _lrn_zpow(sq_sum, size, alpha, beta, k):
    z = k + (alpha / size) * sq_sum
    if beta == 0.75:
        zb = jnp.sqrt(jnp.sqrt(z))
        return z, zb * zb * zb            # z^0.75 without exp/log
    return z, z ** beta


def _lrn_win_sum(v, size, adjoint=False):
    """Sum over the size-window centred on each channel (sublane dim 0 of
    a (C, T) block), zero padding.  ``adjoint=True`` sums over the
    TRANSPOSED window (pad (hi, lo) instead of (lo, hi)) — required in
    the backward for even sizes, where the window is asymmetric."""
    lo = (size - 1) // 2
    hi = size - 1 - lo
    if adjoint:
        lo, hi = hi, lo
    c = v.shape[0]
    vp = jnp.pad(v, ((lo, hi), (0, 0)))
    acc = None
    for s in range(size):
        sl = lax.slice(vp, (s, 0), (s + c, v.shape[1]))
        acc = sl if acc is None else acc + sl
    return acc


def _lrn_fwd_kernel(x_ref, y_ref, *, size, alpha, beta, k):
    x = x_ref[0].astype(jnp.float32)        # (C, T)
    _, zpow = _lrn_zpow(_lrn_win_sum(x * x, size), size, alpha, beta, k)
    y_ref[0] = (x / zpow).astype(y_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, dx_ref, *, size, alpha, beta, k):
    x = x_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    z, zpow = _lrn_zpow(_lrn_win_sum(x * x, size), size, alpha, beta, k)
    u = g * x / (zpow * z)                  # dy x z^(-b-1)
    dx = (g / zpow - (2.0 * alpha * beta / size) * x
          * _lrn_win_sum(u, size, adjoint=True))
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _lrn_call(kernel, args, out_dtype, size, alpha, beta, k,
              interpret=False):
    x = args[0]
    n, c, h, w = x.shape
    hw = h * w
    t = min(3200, -(-hw // 128) * 128)  # multiple of 128 (lane alignment)
    # ragged final block is safe: the channel window never crosses lanes,
    # so out-of-bounds lanes compute garbage that the store drops
    flat = [a.reshape(n, c, hw) for a in args]
    y = pl.pallas_call(
        functools.partial(kernel, size=size, alpha=alpha, beta=beta, k=k),
        grid=(n, -(-hw // t)),
        in_specs=[pl.BlockSpec((1, c, t), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM)] * len(flat),
        out_specs=pl.BlockSpec((1, c, t), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, c, hw), out_dtype),
        interpret=interpret,
    )(*flat)
    return y.reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_channel(x, size, alpha, beta, k, interpret=False):
    """Fused cross-channel LRN with a hand-written one-pass backward.
    NCHW, any H*W — ragged lane blocks are safe because the channel
    window never crosses lanes (out-of-bounds lanes are dropped on
    store)."""
    return _lrn_call(_lrn_fwd_kernel, (x,), x.dtype, size, alpha, beta, k,
                     interpret)


def _lrn_vjp_fwd(x, size, alpha, beta, k, interpret=False):
    return lrn_channel(x, size, alpha, beta, k, interpret), x


def _lrn_vjp_bwd(size, alpha, beta, k, interpret, x, g):
    return (_lrn_call(_lrn_bwd_kernel, (x, g), x.dtype, size, alpha, beta,
                      k, interpret),)


lrn_channel.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


# ---------------------------------------------------- bidirectional LSTM
#
# The Bi-LSTM flagship's recurrence as TWO whole-sequence Pallas kernels
# (forward + hand-derived backward), direction-batched like
# Recurrent._apply_fused_lstm's scan body.  h/c (and in the backward,
# dh/dc and the dWh accumulator) stay resident in VMEM scratch across
# all T grid steps — the "gates + carry in VMEM" formulation.
#
# This is the first measured Mosaic WIN on this chip (round 5, v5e,
# device clock, B128 T500 H128): forward 1.071 -> 0.527 ms vs lax.scan
# (bit-exact), fwd+bwd 5.0 -> 2.15 ms vs the scan's autodiff (grads
# equal to ~1e-4 rel, f32 accumulation order).  Every previous Pallas
# candidate here lost to the XLA emitter (PERF_NOTES rounds 2-5:
# flash attention, maxpool, LRN stencil, fused SGD, single-direction
# lstm_scan) — the recurrence wins because the emitter's while-loop
# carries per-step overhead the sequential grid amortizes, not because
# Mosaic beats XLA on the math.


def _bilstm_fwd_body(zx_ref, wht_ref, h_ref, c_ref, h_scr, c_scr):
    """One grid step = one timestep, BOTH directions; zx already holds
    the hoisted input projection + bias.  ``c_ref is None`` = primal-only
    call: the cell-state stack is a VJP residual, so a no-grad forward
    skips its HBM writes entirely."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    hdim = h_scr.shape[-1]
    for d in range(h_scr.shape[0]):  # static direction count (1 or 2)
        z = zx_ref[0, d].astype(jnp.float32) + jnp.dot(
            h_scr[d].astype(wht_ref.dtype), wht_ref[d],
            preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(z[:, :hdim])
        f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
        g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
        o = jax.nn.sigmoid(z[:, 3 * hdim:])
        c_new = f * c_scr[d] + i * g
        h_new = o * jnp.tanh(c_new)
        h_scr[d] = h_new
        c_scr[d] = c_new
        h_ref[0, d] = h_new
        if c_ref is not None:
            c_ref[0, d] = c_new


def _bilstm_fwd_kernel(zx_ref, wht_ref, h_ref, c_ref, h_scr, c_scr):
    _bilstm_fwd_body(zx_ref, wht_ref, h_ref, c_ref, h_scr, c_scr)


def _bilstm_fwd_kernel_primal(zx_ref, wht_ref, h_ref, h_scr, c_scr):
    _bilstm_fwd_body(zx_ref, wht_ref, h_ref, None, h_scr, c_scr)


def _bilstm_bwd_kernel(zx_ref, hprev_ref, c_ref, cprev_ref, g_ref,
                       wht_ref, dzx_ref, dwh_ref, dh_scr, dc_scr, dwh_scr):
    """Reverse-time step: recompute the gates from zx_t + h_{t-1} @ Wh,
    fold the carried (dh, dc) and this step's output cotangent into
    dzx_t, accumulate dWh.  hprev/cprev arrive PRE-SHIFTED (index t
    holds step t-1's value, zeros at t=0)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    hdim = dh_scr.shape[-1]
    for d in range(dh_scr.shape[0]):
        hprev = hprev_ref[0, d]
        z = zx_ref[0, d].astype(jnp.float32) + jnp.dot(
            hprev.astype(wht_ref.dtype), wht_ref[d],
            preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(z[:, :hdim])
        f = jax.nn.sigmoid(z[:, hdim:2 * hdim])
        g = jnp.tanh(z[:, 2 * hdim:3 * hdim])
        o = jax.nn.sigmoid(z[:, 3 * hdim:])
        tc = jnp.tanh(c_ref[0, d])
        dh_total = g_ref[0, d] + dh_scr[d]
        dc_total = dc_scr[d] + dh_total * o * (1.0 - tc * tc)
        dz = jnp.concatenate([
            dc_total * g * i * (1.0 - i),
            dc_total * cprev_ref[0, d] * f * (1.0 - f),
            dc_total * i * (1.0 - g * g),
            dh_total * tc * o * (1.0 - o),
        ], axis=-1)
        dzx_ref[0, d] = dz
        dh_scr[d] = jnp.dot(dz.astype(wht_ref.dtype), wht_ref[d].T,
                            preferred_element_type=jnp.float32)
        dc_scr[d] = dc_total * f
        dwh_scr[d] += jnp.dot(hprev.T, dz,
                              preferred_element_type=jnp.float32)
    dwh_ref[...] = dwh_scr[...]


def _shift_prev(xs):
    """xs[t] -> xs[t-1] along axis 0, zeros at t=0 (initial h/c)."""
    return jnp.concatenate([jnp.zeros_like(xs[:1]), xs[:-1]], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret", "with_c"))
def _bilstm_fwd_call(zx, wht, interpret=False, with_c=True):
    t, nd, b, h4 = zx.shape
    h = h4 // 4
    out_spec = pl.BlockSpec((1, nd, b, h), lambda i: (i, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32)
    return pl.pallas_call(
        _bilstm_fwd_kernel if with_c else _bilstm_fwd_kernel_primal,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, nd, b, h4), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h4), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[out_spec, out_spec] if with_c else out_spec,
        out_shape=[out_shape, out_shape] if with_c else out_shape,
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bilstm_bwd_call(zx, wht, hs, cs, gout, interpret=False):
    t, nd, b, h4 = zx.shape
    h = h4 // 4
    rev = lambda i: (t - 1 - i, 0, 0, 0)
    return pl.pallas_call(
        _bilstm_bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, nd, b, h4), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h4), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, nd, b, h4), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h4), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((t, nd, b, h4), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h4), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, h, h4), jnp.float32)],
        interpret=interpret,
    )(zx, _shift_prev(hs), cs, _shift_prev(cs), gout, wht)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def bilstm_recurrence(zx, wht, interpret=False):
    """Direction-batched LSTM recurrence: zx (T, D, B, 4H) hoisted input
    projection (+bias) with D directions (1 = plain Recurrent, 2 =
    BiRecurrent), wht (D, H, 4H) recurrent weights; returns the h stack
    (T, D, B, H) f32.  Same math as the lax.scan body in
    Recurrent._apply_fused_lstm (forward bit-exact; gradients equal up
    to f32 accumulation order)."""
    # primal-only: skip the c-stack output — it is a VJP residual, and
    # a no-grad forward (validation/inference) should not pay its HBM
    # writes (~65 MB at the flagship shapes)
    return _bilstm_fwd_call(zx, wht, interpret=interpret, with_c=False)


def _bilstm_vjp_fwd(zx, wht, interpret=False):
    hs, cs = _bilstm_fwd_call(zx, wht, interpret=interpret)
    return hs, (zx, wht, hs, cs)


def _bilstm_vjp_bwd(interpret, res, gout):
    zx, wht, hs, cs = res
    dzx, dwht = _bilstm_bwd_call(zx, wht, hs, cs,
                                 gout.astype(jnp.float32),
                                 interpret=interpret)
    return dzx.astype(zx.dtype), dwht.astype(wht.dtype)


bilstm_recurrence.defvjp(_bilstm_vjp_fwd, _bilstm_vjp_bwd)


# ------------------------------------------------------------------- GRU
#
# Same sequential-grid/VMEM-carry structure as the LSTM pair, for the
# GRU cell (two recurrent gemms per step: the r/z gates and the
# r-gated candidate — GRUCell._step's math exactly, f32 like the cell).


def _gru_gates(zrz_t, zn_t, h, wrz_ref, wh_ref):
    hdim = h.shape[-1]
    rz = jax.nn.sigmoid(zrz_t + jnp.dot(
        h, wrz_ref, preferred_element_type=jnp.float32))
    r, z = rz[:, :hdim], rz[:, hdim:]
    n = jnp.tanh(zn_t + jnp.dot(
        r * h, wh_ref, preferred_element_type=jnp.float32))
    return r, z, n


def _gru_fwd_kernel(zrz_ref, zn_ref, wrz_ref, wh_ref, h_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)

    for d in range(h_scr.shape[0]):
        h = h_scr[d]
        r, z, n = _gru_gates(zrz_ref[0, d].astype(jnp.float32),
                             zn_ref[0, d].astype(jnp.float32),
                             h, wrz_ref[d], wh_ref[d])
        h_new = (1.0 - z) * n + z * h
        h_scr[d] = h_new
        h_ref[0, d] = h_new


def _gru_bwd_kernel(zrz_ref, zn_ref, hprev_ref, g_ref, wrz_ref, wh_ref,
                    dzrz_ref, dzn_ref, dwrz_ref, dwh_ref,
                    dh_scr, dwrz_scr, dwh_scr):
    """Reverse-time step: recompute r/z/n from the hoisted projections
    and h_{t-1} (pre-shifted), fold the carried dh and this step's
    output cotangent into dzrz_t/dzn_t, accumulate both weight grads."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dwrz_scr[...] = jnp.zeros_like(dwrz_scr)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    for d in range(dh_scr.shape[0]):
        hprev = hprev_ref[0, d]
        r, z, n = _gru_gates(zrz_ref[0, d].astype(jnp.float32),
                             zn_ref[0, d].astype(jnp.float32),
                             hprev, wrz_ref[d], wh_ref[d])
        dh_total = g_ref[0, d] + dh_scr[d]
        dz = dh_total * (hprev - n)
        dn_pre = dh_total * (1.0 - z) * (1.0 - n * n)
        drh = jnp.dot(dn_pre, wh_ref[d].T,
                      preferred_element_type=jnp.float32)
        dr_pre = drh * hprev * r * (1.0 - r)
        dz_pre = dz * z * (1.0 - z)
        dzrz = jnp.concatenate([dr_pre, dz_pre], axis=-1)
        dzrz_ref[0, d] = dzrz
        dzn_ref[0, d] = dn_pre
        dh_scr[d] = (dh_total * z + drh * r
                     + jnp.dot(dzrz, wrz_ref[d].T,
                               preferred_element_type=jnp.float32))
        dwrz_scr[d] += jnp.dot(hprev.T, dzrz,
                               preferred_element_type=jnp.float32)
        dwh_scr[d] += jnp.dot((r * hprev).T, dn_pre,
                              preferred_element_type=jnp.float32)
    dwrz_ref[...] = dwrz_scr[...]
    dwh_ref[...] = dwh_scr[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gru_fwd_call(zrz, zn, wrz, wh, interpret=False):
    t, nd, b, h2 = zrz.shape
    h = h2 // 2
    return pl.pallas_call(
        _gru_fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, nd, b, h2), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h2), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, nd, b, h), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32)],
        interpret=interpret,
    )(zrz, zn, wrz, wh)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gru_bwd_call(zrz, zn, wrz, wh, hs, gout, interpret=False):
    t, nd, b, h2 = zrz.shape
    h = h2 // 2
    rev = lambda i: (t - 1 - i, 0, 0, 0)
    wspec2 = pl.BlockSpec((nd, h, h2), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    wspec1 = pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _gru_bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, nd, b, h2), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            wspec2,
            wspec1,
        ],
        out_specs=[
            pl.BlockSpec((1, nd, b, h2), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            wspec2,
            wspec1,
        ],
        out_shape=[jax.ShapeDtypeStruct((t, nd, b, h2), jnp.float32),
                   jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h2), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, h, h2), jnp.float32),
                        pltpu.VMEM((nd, h, h), jnp.float32)],
        interpret=interpret,
    )(zrz, zn, _shift_prev(hs), gout, wrz, wh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def gru_recurrence(zrz, zn, wrz, wh, interpret=False):
    """GRU recurrence with VMEM-resident carry: zrz (T, D, B, 2H) and zn
    (T, D, B, H) hoisted input projections (+bias), wrz (D, H, 2H) and
    wh (D, H, H) recurrent weights, D directions in {1, 2}; returns the
    h stack (T, D, B, H) f32.  Same math as GRUCell._step under
    Recurrent's scan; backward recomputes the gates (residual = the h
    stack the forward writes anyway)."""
    return _gru_fwd_call(zrz, zn, wrz, wh, interpret=interpret)


def _gru_vjp_fwd(zrz, zn, wrz, wh, interpret=False):
    hs = _gru_fwd_call(zrz, zn, wrz, wh, interpret=interpret)
    return hs, (zrz, zn, wrz, wh, hs)


def _gru_vjp_bwd(interpret, res, gout):
    zrz, zn, wrz, wh, hs = res
    dzrz, dzn, dwrz, dwh = _gru_bwd_call(
        zrz, zn, wrz, wh, hs, gout.astype(jnp.float32),
        interpret=interpret)
    return (dzrz.astype(zrz.dtype), dzn.astype(zn.dtype),
            dwrz.astype(wrz.dtype), dwh.astype(wh.dtype))


gru_recurrence.defvjp(_gru_vjp_fwd, _gru_vjp_bwd)


# ------------------------------------------------------------ vanilla RNN
#
# h' = tanh(zx_t + h @ Wh) — the reference's own RnnCell (RNN.scala:28)
# through the same sequential-grid/VMEM-carry structure.  The backward
# needs no gate recompute at all: dz = dh_total * (1 - h_t^2) comes
# straight from the stored h stack.


def _rnn_fwd_kernel(zx_ref, wht_ref, h_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)

    for d in range(h_scr.shape[0]):
        z = zx_ref[0, d].astype(jnp.float32) + jnp.dot(
            h_scr[d].astype(wht_ref.dtype), wht_ref[d],
            preferred_element_type=jnp.float32)
        h_new = jnp.tanh(z)
        h_scr[d] = h_new
        h_ref[0, d] = h_new


def _rnn_bwd_kernel(h_ref, hprev_ref, g_ref, wht_ref, dzx_ref, dwh_ref,
                    dh_scr, dwh_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)

    for d in range(dh_scr.shape[0]):
        h_t = h_ref[0, d]
        dz = (g_ref[0, d] + dh_scr[d]) * (1.0 - h_t * h_t)
        dzx_ref[0, d] = dz
        dh_scr[d] = jnp.dot(dz.astype(wht_ref.dtype), wht_ref[d].T,
                            preferred_element_type=jnp.float32)
        dwh_scr[d] += jnp.dot(hprev_ref[0, d].T, dz,
                              preferred_element_type=jnp.float32)
    dwh_ref[...] = dwh_scr[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _rnn_fwd_call(zx, wht, interpret=False):
    t, nd, b, h = zx.shape
    return pl.pallas_call(
        _rnn_fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, nd, b, h), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, nd, b, h), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32)],
        interpret=interpret,
    )(zx, wht)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _rnn_bwd_call(wht, hs, gout, interpret=False):
    t, nd, b, h = hs.shape
    rev = lambda i: (t - 1 - i, 0, 0, 0)
    return pl.pallas_call(
        _rnn_bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, nd, b, h), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((nd, h, h), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((t, nd, b, h), jnp.float32),
                   jax.ShapeDtypeStruct((nd, h, h), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((nd, b, h), jnp.float32),
                        pltpu.VMEM((nd, h, h), jnp.float32)],
        interpret=interpret,
    )(hs, _shift_prev(hs), gout, wht)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rnn_recurrence(zx, wht, interpret=False):
    """Vanilla tanh-RNN recurrence with VMEM-resident carry: zx
    (T, D, B, H) hoisted input projection (+both biases), wht (D, H, H)
    recurrent weights, D directions in {1, 2}; returns the h stack
    (T, D, B, H) f32.  Same math as RnnCell._step with the default Tanh
    under Recurrent's scan."""
    return _rnn_fwd_call(zx, wht, interpret=interpret)


def _rnn_vjp_fwd(zx, wht, interpret=False):
    hs = _rnn_fwd_call(zx, wht, interpret=interpret)
    return hs, (wht, hs)


def _rnn_vjp_bwd(interpret, res, gout):
    wht, hs = res
    dzx, dwht = _rnn_bwd_call(wht, hs, gout.astype(jnp.float32),
                              interpret=interpret)
    return dzx.astype(jnp.float32), dwht.astype(wht.dtype)


rnn_recurrence.defvjp(_rnn_vjp_fwd, _rnn_vjp_bwd)
