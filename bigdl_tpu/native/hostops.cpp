// hostops — native host-side data-path kernels.
//
// Role in the framework: the reference keeps a native C layer for its
// hot loops (native/mkl/src/main/c/jni/mkl.c — vector math + BLAS behind
// JNI, with a pure-JVM fallback when the .so is missing).  On TPU the
// *device* hot loops belong to XLA; what remains hot on the HOST is the
// input pipeline (decode/normalize/augment feeding HBM).  This library is
// that layer: C++ + OpenMP kernels exported with a plain C ABI, loaded via
// ctypes (bigdl_tpu/native/__init__.py), with numpy fallbacks when the
// library has not been built — the same graceful-degradation seam as
// MKL.isMKLLoaded (MKL.java:46-63).
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC hostops.cpp -o libhostops.so

#include <cstdint>
#include <cstring>
#include <cmath>

extern "C" {

// normalize: out[i] = (in[i] - mean[i % c]) / std[i % c]
// (the BGRImgNormalizer hot loop; c = channel count for HWC layout)
void hostops_normalize(const float* in, float* out, int64_t n,
                       const float* mean, const float* stddev, int64_t c) {
#pragma omp parallel for
    for (int64_t i = 0; i < n; ++i) {
        const int64_t ch = i % c;
        out[i] = (in[i] - mean[ch]) / stddev[ch];
    }
}

// u8 -> f32 with scale + shift (image decode postprocessing)
void hostops_u8_to_f32(const uint8_t* in, float* out, int64_t n,
                       float scale, float shift) {
#pragma omp parallel for
    for (int64_t i = 0; i < n; ++i) {
        out[i] = in[i] * scale + shift;
    }
}

// HWC crop: src (h, w, c) -> dst (ch, cw, c) starting at (y0, x0)
void hostops_crop(const float* src, float* dst, int64_t h, int64_t w,
                  int64_t c, int64_t y0, int64_t x0, int64_t ch, int64_t cw) {
#pragma omp parallel for
    for (int64_t y = 0; y < ch; ++y) {
        std::memcpy(dst + y * cw * c, src + ((y0 + y) * w + x0) * c,
                    sizeof(float) * cw * c);
    }
}

// horizontal flip, HWC in place-safe (src != dst)
void hostops_hflip(const float* src, float* dst, int64_t h, int64_t w,
                   int64_t c) {
#pragma omp parallel for
    for (int64_t y = 0; y < h; ++y) {
        for (int64_t x = 0; x < w; ++x) {
            std::memcpy(dst + (y * w + x) * c,
                        src + (y * w + (w - 1 - x)) * c, sizeof(float) * c);
        }
    }
}

// HWC -> CHW transpose for a batch member (the ImgToBatch hot loop)
void hostops_hwc_to_chw(const float* src, float* dst, int64_t h, int64_t w,
                        int64_t c) {
#pragma omp parallel for
    for (int64_t k = 0; k < c; ++k) {
        for (int64_t y = 0; y < h; ++y) {
            for (int64_t x = 0; x < w; ++x) {
                dst[(k * h + y) * w + x] = src[(y * w + x) * c + k];
            }
        }
    }
}

// batched idx-ubyte (MNIST) decode: n images of rows*cols u8 -> f32
void hostops_idx_decode(const uint8_t* in, float* out, int64_t n,
                        int64_t px) {
#pragma omp parallel for
    for (int64_t i = 0; i < n * px; ++i) {
        out[i] = static_cast<float>(in[i]);
    }
}

// CIFAR binary record batch: n records of (1 label + 3072 CHW u8)
// -> labels f32 (1-based), images f32 HWC
void hostops_cifar_decode(const uint8_t* in, float* labels, float* images,
                          int64_t n) {
    const int64_t rec = 3073, hw = 1024;
#pragma omp parallel for
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* r = in + i * rec;
        labels[i] = static_cast<float>(r[0]) + 1.0f;
        float* img = images + i * 3072;
        // CHW planes -> HWC
        for (int64_t y = 0; y < 32; ++y) {
            for (int64_t x = 0; x < 32; ++x) {
                const int64_t p = y * 32 + x;
                img[p * 3 + 0] = static_cast<float>(r[1 + p]);
                img[p * 3 + 1] = static_cast<float>(r[1 + hw + p]);
                img[p * 3 + 2] = static_cast<float>(r[1 + 2 * hw + p]);
            }
        }
    }
}

int hostops_version() { return 1; }

}  // extern "C"
