"""Native host-ops loader (the MKL.java role: build/extract + load + probe,
ref native/jni/.../MKL.java:25-63 ``isMKLLoaded``).

``lib()`` returns the ctypes library, building it with g++ on first use if
needed; every wrapper falls back to numpy when unavailable — the
reference's managed-fallback seam (DenseTensorMath MKL gates)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "hostops.cpp")
_SO = os.path.join(_DIR, "libhostops.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True)


def lib():
    """The loaded library, or None (numpy fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            candidate = ctypes.CDLL(_SO)
            if candidate.hostops_version() != 1:
                return None
            _lib = candidate
        except Exception:
            _lib = None
    return _lib


def is_loaded() -> bool:
    return lib() is not None


def _fp(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u8p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def normalize(img: np.ndarray, mean, std) -> np.ndarray:
    """(x - mean) / std per channel over an HWC (or HW) image."""
    img = np.ascontiguousarray(img, np.float32)
    c = img.shape[-1] if img.ndim == 3 else 1
    mean = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32), (c,)))
    std = np.ascontiguousarray(np.broadcast_to(np.asarray(std, np.float32), (c,)))
    l = lib()
    if l is None:
        return (img - mean.reshape((1,) * (img.ndim - 1) + (c,))
                if img.ndim == 3 else img - mean[0]) / (
            std.reshape((1,) * (img.ndim - 1) + (c,)) if img.ndim == 3 else std[0])
    out = np.empty_like(img)
    l.hostops_normalize(_fp(img), _fp(out), ctypes.c_int64(img.size),
                        _fp(mean), _fp(std), ctypes.c_int64(c))
    return out


def hwc_to_chw(img: np.ndarray) -> np.ndarray:
    img = np.ascontiguousarray(img, np.float32)
    l = lib()
    if l is None or img.ndim != 3:
        return np.transpose(img, (2, 0, 1)).copy() if img.ndim == 3 else img
    h, w, c = img.shape
    out = np.empty((c, h, w), np.float32)
    l.hostops_hwc_to_chw(_fp(img), _fp(out), ctypes.c_int64(h),
                         ctypes.c_int64(w), ctypes.c_int64(c))
    return out


def hflip(img: np.ndarray) -> np.ndarray:
    img = np.ascontiguousarray(img, np.float32)
    l = lib()
    if l is None or img.ndim != 3:
        return img[:, ::-1].copy()
    h, w, c = img.shape
    out = np.empty_like(img)
    l.hostops_hflip(_fp(img), _fp(out), ctypes.c_int64(h), ctypes.c_int64(w),
                    ctypes.c_int64(c))
    return out


def crop(img: np.ndarray, y0: int, x0: int, ch: int, cw: int) -> np.ndarray:
    img = np.ascontiguousarray(img, np.float32)
    l = lib()
    if l is None or img.ndim != 3:
        return img[y0:y0 + ch, x0:x0 + cw].copy()
    h, w, c = img.shape
    out = np.empty((ch, cw, c), np.float32)
    l.hostops_crop(_fp(img), _fp(out), ctypes.c_int64(h), ctypes.c_int64(w),
                   ctypes.c_int64(c), ctypes.c_int64(y0), ctypes.c_int64(x0),
                   ctypes.c_int64(ch), ctypes.c_int64(cw))
    return out


def cifar_decode(raw: np.ndarray):
    """n CIFAR records -> (labels 1-based f32 (n,), images HWC f32 (n,32,32,3))."""
    raw = np.ascontiguousarray(raw, np.uint8).reshape(-1)
    n = raw.size // 3073
    l = lib()
    if l is None:
        rec = raw.reshape(n, 3073)
        labels = rec[:, 0].astype(np.float32) + 1.0
        imgs = rec[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32)
        return labels, imgs
    labels = np.empty(n, np.float32)
    images = np.empty((n, 32, 32, 3), np.float32)
    l.hostops_cifar_decode(_u8p(raw), _fp(labels), _fp(images), ctypes.c_int64(n))
    return labels, images


def u8_to_f32(raw: np.ndarray, scale: float = 1.0, shift: float = 0.0) -> np.ndarray:
    raw = np.ascontiguousarray(raw, np.uint8)
    l = lib()
    if l is None:
        return raw.astype(np.float32) * scale + shift
    out = np.empty(raw.shape, np.float32)
    l.hostops_u8_to_f32(_u8p(raw), _fp(out), ctypes.c_int64(raw.size),
                        ctypes.c_float(scale), ctypes.c_float(shift))
    return out
