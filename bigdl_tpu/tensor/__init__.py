"""Tensor substrate: dtype policy + Torch-semantics helpers over jnp.

The reference's 6.5k-LoC tensor package (tensor/Tensor.scala, DenseTensor,
DenseTensorMath, DenseTensorBLAS, TensorNumeric) dissolves into jnp arrays +
XLA.  What remains (per SURVEY.md §7 item 1) is:

- a dtype policy (the ``TensorNumeric[T]`` role: reference supports
  Float/Double, Tensor.scala:605; TPU-native default is float32 with a
  bfloat16 compute policy for the MXU);
- the handful of Torch-shape helpers the module API needs
  (narrow/select/view semantics).
"""
from __future__ import annotations

import jax.numpy as jnp

_DEFAULT_DTYPE = jnp.float32


def default_dtype():
    return _DEFAULT_DTYPE


def set_default_dtype(dtype):
    global _DEFAULT_DTYPE
    _DEFAULT_DTYPE = jnp.dtype(dtype)
    return _DEFAULT_DTYPE


class DTypePolicy:
    """Mixed-precision policy: params in ``param_dtype``, matmuls/convs in
    ``compute_dtype`` (bf16 feeds the MXU at full rate), accumulation/output
    in ``output_dtype``.  The reference's FP16 *wire* compression
    (parameters/FP16CompressedTensor.scala) becomes this compute policy —
    on TPU the cast happens on-chip, not on the network."""

    def __init__(self, param_dtype=jnp.float32, compute_dtype=jnp.float32,
                 output_dtype=jnp.float32):
        self.param_dtype = jnp.dtype(param_dtype)
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.output_dtype = jnp.dtype(output_dtype)

    def cast_compute(self, x):
        return jnp.asarray(x, self.compute_dtype)

    def cast_output(self, x):
        return jnp.asarray(x, self.output_dtype)


FP32 = DTypePolicy()
BF16_COMPUTE = DTypePolicy(compute_dtype=jnp.bfloat16)
# Full bf16 activation flow: conv/matmul OUTPUTS stay bf16, so every
# downstream buffer (pool windows, ReLU, BN apply, concat, LAYOUT copies)
# moves half the HBM bytes.  Params, gradients, BN statistics and the
# loss stay f32 (BN accumulates in f32 explicitly; LogSoftMax upcasts).
BF16_ACT = DTypePolicy(compute_dtype=jnp.bfloat16, output_dtype=jnp.bfloat16)

_POLICY = FP32


def policy() -> DTypePolicy:
    return _POLICY


def set_policy(p: DTypePolicy):
    global _POLICY
    _POLICY = p
    return p


# -- Torch-shape helpers (ref Tensor.scala narrow/select) -----------------

def narrow(x, dim: int, index: int, size: int):
    """Slice ``size`` elements along ``dim`` starting at 1-based ``index``."""
    start = index - 1
    sl = [slice(None)] * x.ndim
    sl[dim - 1] = slice(start, start + size)
    return x[tuple(sl)]


def select(x, dim: int, index: int):
    """Select 1-based ``index`` along 1-based ``dim``, dropping the dim."""
    sl = [slice(None)] * x.ndim
    sl[dim - 1] = index - 1
    return x[tuple(sl)]
