"""Resilience layer: deterministic fault injection + the defenses it
exercises (docs/resilience.md).

- ``faults``: ``FaultInjector`` — reproducible chaos keyed by
  ``(step, process_index, site)``, configured via ``BIGDL_FAULTS``.
- ``watchdog``: heartbeat/timeout peer-death detector for multi-host
  runs (fail fast out of a dead collective, or hand the trip to the
  elastic layer under ``on_peer_death="recover"``).
- ``elastic``: recover-in-place on peer loss — survivors re-form the
  mesh at the reduced world size and continue from an in-memory anchor
  (``BIGDL_ELASTIC=1``).
- ``checkpoint``: asynchronous sharded checkpointing (one CRC-sidecar
  file per shard, written off the training thread;
  ``BIGDL_CKPT_ASYNC``/``BIGDL_CKPT_KEEP``).

The defenses themselves live where the work happens: checksummed atomic
checkpoints in ``utils/fs.py``/``utils/file.py``, the non-finite-grad
skip in ``optim/local_optimizer.py``, the preemption barrier in
``utils/engine.py`` + the optimizer loops, resume scanning in
``optim/optimizer.py``.
"""
from bigdl_tpu.resilience.faults import (  # noqa: F401
    ENV_VAR, SITES, FaultInjector, FaultSpec, clear, configure, get,
    parse_faults,
)
from bigdl_tpu.resilience.watchdog import Watchdog, EXIT_CODE  # noqa: F401
from bigdl_tpu.resilience import checkpoint  # noqa: F401
from bigdl_tpu.resilience import elastic  # noqa: F401
from bigdl_tpu.resilience.elastic import (  # noqa: F401
    PeerLossRecovery, ReformAbort,
)
