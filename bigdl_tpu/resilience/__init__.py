"""Resilience layer: deterministic fault injection + the defenses it
exercises (docs/resilience.md).

- ``faults``: ``FaultInjector`` — reproducible chaos keyed by
  ``(step, process_index, site)``, configured via ``BIGDL_FAULTS``.
- ``watchdog``: heartbeat/timeout peer-death detector for multi-host
  runs (fail fast out of a dead collective).

The defenses themselves live where the work happens: checksummed atomic
checkpoints in ``utils/fs.py``/``utils/file.py``, the non-finite-grad
skip in ``optim/local_optimizer.py``, the preemption barrier in
``utils/engine.py`` + the optimizer loops, resume scanning in
``optim/optimizer.py``.
"""
from bigdl_tpu.resilience.faults import (  # noqa: F401
    ENV_VAR, SITES, FaultInjector, FaultSpec, clear, configure, get,
    parse_faults,
)
from bigdl_tpu.resilience.watchdog import Watchdog, EXIT_CODE  # noqa: F401
