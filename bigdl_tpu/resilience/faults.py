"""Deterministic fault injection for chaos testing.

The reference survives worker loss by leaning on Spark's task retry and
lineage; this port has to *prove* its own defenses (checksummed
checkpoints, non-finite-grad skip, preemption barrier, watchdog) work —
forever, in CI.  That needs faults that fire the same way on every run
and on every process, so a chaos failure reproduces from its command
line alone.

A fault decision is a pure function of ``(step, process_index, site)``
plus the spec parsed from ``BIGDL_FAULTS`` (or passed to ``configure``):
scheduled clauses (``at=`` / ``every=``) compare the step counter
directly; probabilistic clauses (``p=``) hash the key tuple with a
seeded blake2 — no RNG state, no ordering sensitivity between sites.

``BIGDL_FAULTS`` syntax — semicolon-separated clauses::

    site[@key=value[,key=value...]]

    BIGDL_FAULTS="nan_grad@every=5"
    BIGDL_FAULTS="ckpt_bitflip@at=2;ckpt_write_fail@at=0"
    BIGDL_FAULTS="proc_kill@at=4,proc=3;slow_worker@every=3,delay=0.05"
    BIGDL_FAULTS="record_truncate@p=0.01,seed=7"

Keys: ``at`` (fire at these steps, ``|``-separated), ``every`` (fire
when ``step % every == 0``, step > 0), ``p`` (probability per query,
hashed deterministically), ``proc`` (only on this process index),
``delay`` (seconds, ``slow_worker``), ``seed`` (decorrelates ``p``
clauses), ``len_s`` (partition length in seconds,
``serve_partition``).  Sites and where they are threaded:

====================  ====================================================
``record_corrupt``    dataset/seqfile.py — flip a byte of a record payload
``record_truncate``   dataset/seqfile.py — short-read a record (exercises
                      the read-length validation)
``nan_grad``          optim train loop — poison the step's batch with NaN
``inf_grad``          optim train loop — poison the step's batch with Inf
``slow_worker``       optim train loop — sleep ``delay`` s before the step
``ckpt_write_fail``   utils/fs.py — first write attempt raises OSError
                      (exercises the bounded-retry path)
``ckpt_partial``      utils/fs.py — write truncated bytes straight to the
                      target, no atomic rename (a crash mid-write)
``ckpt_bitflip``      utils/fs.py — flip one bit of the stored bytes
                      (below the CRC sidecar, so verification must catch)
``proc_kill``         optim train loop — os._exit(1) (induced host death)
``serve_h2d``         serve/engine.py — the serving engine's H2D transfer
                      raises OSError (the batch's futures fail; the
                      engine must keep serving subsequent batches)
``serve_kill``        serve/cluster.py replica worker — os._exit(1) at
                      the Nth submitted request (the router must requeue
                      the dead replica's outstanding work on survivors)
``serve_partition``   tools/replica_agent.py — drop the TCP session and
                      black-hole new connections for ``len_s`` seconds
                      at the Nth submitted request (a network partition,
                      NOT a death: a blip under the client's liveness
                      budget must re-attach with zero requeues)
====================  ====================================================
"""
from __future__ import annotations

import hashlib
import logging
import os
import struct

logger = logging.getLogger("bigdl_tpu.resilience")

SITES = (
    "record_corrupt", "record_truncate",
    "nan_grad", "inf_grad", "slow_worker",
    "ckpt_write_fail", "ckpt_partial", "ckpt_bitflip",
    "proc_kill", "serve_h2d", "serve_kill", "serve_partition",
)

ENV_VAR = "BIGDL_FAULTS"


class FaultSpec:
    """One parsed clause of a fault plan."""

    __slots__ = ("site", "at", "every", "p", "proc", "delay", "seed",
                 "len_s")

    def __init__(self, site, at=None, every=None, p=None, proc=None,
                 delay=0.05, seed=0, len_s=0.5):
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; known sites: {SITES}")
        if at is None and every is None and p is None:
            raise ValueError(
                f"fault clause {site!r} needs a schedule: at=, every= or p=")
        self.site = site
        self.at = frozenset(int(v) for v in at) if at is not None else None
        self.every = int(every) if every is not None else None
        self.p = float(p) if p is not None else None
        self.proc = int(proc) if proc is not None else None
        self.delay = float(delay)
        self.seed = int(seed)
        self.len_s = float(len_s)

    def fires(self, step: int, process_index: int) -> bool:
        if self.proc is not None and process_index != self.proc:
            return False
        if self.at is not None and step in self.at:
            return True
        if self.every is not None and step > 0 and step % self.every == 0:
            return True
        if self.p is not None:
            return _hash_unit(step, process_index, self.site,
                              self.seed) < self.p
        return False

    def __repr__(self):
        sched = (f"at={sorted(self.at)}" if self.at is not None else
                 f"every={self.every}" if self.every is not None else
                 f"p={self.p}")
        proc = "" if self.proc is None else f",proc={self.proc}"
        return f"FaultSpec({self.site}@{sched}{proc})"


def _hash_unit(step: int, process_index: int, site: str, seed: int) -> float:
    """Deterministic uniform [0,1) from the fault key — blake2 of the
    packed tuple (Python's ``hash`` is salted per process, useless
    here)."""
    h = hashlib.blake2s(
        struct.pack(">qqq", step, process_index, seed) + site.encode(),
        digest_size=8).digest()
    return struct.unpack(">Q", h)[0] / 2.0 ** 64


def parse_faults(spec: str):
    """``BIGDL_FAULTS`` string -> list of FaultSpec (see module doc)."""
    out = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        site, _, argstr = clause.partition("@")
        kwargs = {}
        if argstr:
            for kv in argstr.split(","):
                k, sep, v = kv.partition("=")
                if not sep:
                    raise ValueError(
                        f"bad fault arg {kv!r} in clause {clause!r} "
                        "(want key=value)")
                k = k.strip()
                if k == "at":
                    kwargs["at"] = [int(x) for x in v.split("|")]
                elif k in ("every", "p", "proc", "delay", "seed",
                           "len_s"):
                    kwargs[k] = v
                else:
                    raise ValueError(
                        f"unknown fault arg {k!r} in clause {clause!r}")
        out.append(FaultSpec(site.strip(), **kwargs))
    return out


class FaultInjector:
    """A parsed fault plan plus per-site query counters.

    ``fires(site, step)`` is the single decision point every injection
    site calls.  ``step`` defaults to a per-site query counter (data
    sites count records; checkpoint sites count writes); the train loop
    passes its iteration number explicitly so faults line up with
    ``neval``.  Process identity is resolved lazily from jax (overridable
    for tests / pre-jax-init paths via ``process_index``)."""

    def __init__(self, specs, process_index: int | None = None):
        if isinstance(specs, str):
            specs = parse_faults(specs)
        self.specs = list(specs)
        self._proc = process_index
        self._counters = {}
        self._by_site = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, []).append(s)

    def process_index(self) -> int:
        if self._proc is None:
            try:
                import jax
                self._proc = jax.process_index()
            except Exception:
                self._proc = 0
        return self._proc

    def armed(self, site: str) -> bool:
        """True if any clause targets ``site`` (cheap hot-path guard)."""
        return site in self._by_site

    def fires(self, site: str, step: int | None = None):
        """The matching FaultSpec if ``site`` should fault now, else
        None.  With ``step=None`` the site's own query counter is used
        (and advanced)."""
        specs = self._by_site.get(site)
        if not specs:
            return None
        if step is None:
            step = self._counters.get(site, 0)
            self._counters[site] = step + 1
        proc = self.process_index()
        for s in specs:
            if s.fires(step, proc):
                logger.warning("FaultInjector: %s fires at step %d "
                               "(process %d)", s, step, proc)
                # chaos runs are exactly the runs whose postmortems
                # matter: record the injection in the event stream so
                # the report can line faults up with skips/aborts
                from bigdl_tpu.obs import events as obs_events
                obs_events.emit("fault", site=s.site, step=int(step),
                                spec=repr(s))
                return s
        return None

    def __bool__(self):
        return bool(self.specs)

    def __repr__(self):
        return f"FaultInjector({self.specs})"


# -- process-wide plan (env-configured; tests use configure) ---------------

_INJECTOR: FaultInjector | None = None
_LOADED = False


def get() -> FaultInjector | None:
    """The process fault plan, or None when chaos is off.  Reads
    ``BIGDL_FAULTS`` once; ``configure``/``clear`` override.  Call sites
    keep the disabled path to one None-check."""
    global _INJECTOR, _LOADED
    if not _LOADED:
        _LOADED = True
        spec = os.environ.get(ENV_VAR, "").strip()
        if spec:
            _INJECTOR = FaultInjector(spec)
            logger.warning("chaos mode: %s=%r", ENV_VAR, spec)
    return _INJECTOR


def configure(spec, process_index: int | None = None) -> FaultInjector:
    """Install a fault plan programmatically (tests, drills)."""
    global _INJECTOR, _LOADED
    _INJECTOR = (spec if isinstance(spec, FaultInjector) or spec is None
                 else FaultInjector(spec, process_index=process_index))
    _LOADED = True
    return _INJECTOR


def clear():
    """Disable chaos mode (and forget the env plan until re-read)."""
    global _INJECTOR, _LOADED
    _INJECTOR = None
    _LOADED = True


# -- payload corruptors shared by the injection sites ----------------------

def flip_bit(data: bytes, spec: FaultSpec, step: int = 0) -> bytes:
    """Flip one deterministic bit of ``data`` (storage corruption)."""
    if not data:
        return data
    u = _hash_unit(step, 0, spec.site + ".pos", spec.seed)
    pos = int(u * len(data))
    return data[:pos] + bytes([data[pos] ^ (1 << (step % 8))]) + data[pos + 1:]


def truncate(data: bytes, frac: float = 0.5) -> bytes:
    """Drop the tail of ``data`` (a partial write / short read)."""
    return data[:int(len(data) * frac)]
