"""Asynchronous sharded checkpointing (docs/resilience.md "Async
checkpoints").

The synchronous checkpoint path blocks the training loop for a full
device→host gather plus a pickle+write of every byte — at production
cadence that tax is why operators stretch checkpoint intervals, which
in turn is why restarts are expensive.  This module moves the whole
thing off the critical path, the prefetch double-buffer pattern in
reverse:

- the training loop makes cheap ON-DEVICE copies of the carried state
  (new buffers, so the next step's donation can never invalidate them)
  and enqueues them here with the host-side payload;
- one background writer thread materializes device→host
  (``File.save``'s numpy conversion), writes ``model.N``/``state.N``
  with their CRC sidecars exactly like the sync path, and emits the
  ``checkpoint`` obs event when the snapshot is durable.

ZeRO-1 optimizer state that is sharded ACROSS processes (a multi-host
data axis) cannot be gathered by one writer — ``np.asarray`` on a
non-addressable array is an error, and shipping every slice to process
0 would serialize the fleet through one host's NIC.  Instead each
process writes its own slices as one shard file + CRC sidecar
(``state.N.shard<r>of<n>``); ``state.N`` keeps the tree structure with
:class:`ShardRef` placeholders and records the shard count, and
``optim.load_latest_checkpoint`` reassembles the full logical tree at
load time.  Because the reassembled tree is the FULL state (slices
concatenated back along their original axis), a checkpoint taken at
dp=4 restores at dp=3 or dp=1 — the restoring optimizer re-partitions
over its own mesh (world-size-agnostic restore).

Retention: ``BIGDL_CKPT_KEEP=N`` prunes to the newest N snapshots after
each successful write — but never the newest CRC-valid one, so a
corrupt latest snapshot cannot leave the directory resume-empty
(``optim.optimizer.prune_checkpoints``).

Knobs: ``BIGDL_CKPT_ASYNC=1`` (default off: the sync path is the
historical behavior), ``BIGDL_CKPT_KEEP`` (default 0 = unlimited).
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time

import numpy as np

logger = logging.getLogger("bigdl_tpu.resilience")

ENV_ASYNC = "BIGDL_CKPT_ASYNC"
ENV_KEEP = "BIGDL_CKPT_KEEP"


def async_enabled() -> bool:
    return os.environ.get(ENV_ASYNC, "0").strip() == "1"


def keep_count() -> int:
    """Keep-last-N retention (0 = unlimited)."""
    try:
        return max(0, int(os.environ.get(ENV_KEEP, "0")))
    except ValueError:
        return 0


class ShardRef:
    """Placeholder leaf in a checkpoint's ``opt_state`` tree: the real
    array lives split across the snapshot's shard files, keyed by this
    path.  Deliberately tiny and version-tolerant (plain attrs)."""

    def __init__(self, path: str, shape, dtype: str):
        self.path = path
        self.shape = tuple(int(s) for s in shape)
        self.dtype = str(dtype)

    def __repr__(self):
        return f"ShardRef({self.path}, {self.shape}, {self.dtype})"


def shard_file(ckpt_path: str, neval: int, rank: int, n: int) -> str:
    from bigdl_tpu.utils import fs
    return fs.join(str(ckpt_path), f"state.{neval}.shard{rank}of{n}")


def _leaf_key(key_path) -> str:
    import jax
    return jax.tree_util.keystr(key_path)


def is_cross_process_sharded(leaf) -> bool:
    """True when ``np.asarray(leaf)`` would fail on this process: a jax
    array whose shards span processes without full replication."""
    if not hasattr(leaf, "sharding"):
        return False
    try:
        if leaf.is_fully_addressable or leaf.is_fully_replicated:
            return False
        return True
    except AttributeError:  # plain numpy / scalars
        return False


def split_sharded_state(opt_state):
    """Split a live optimizer-state tree into (tree with ShardRef
    placeholders, this process's slices).

    ``slices`` maps leaf path -> list of ``(spec, device_block)``
    covering this process's addressable shards of that leaf, where
    ``spec`` is a per-dim ``((start, stop), ...)`` tuple — NOT assumed
    dim-0: ``zero1_tp_rule`` shards TP'd leaves over dim 1
    (``P(model, data)``), and the spec must round-trip any layout.
    Blocks stay ON DEVICE here; the writer thread materializes them."""
    import jax

    slices = {}

    def visit(key_path, leaf):
        if not is_cross_process_sharded(leaf):
            return leaf
        key = _leaf_key(key_path)
        # one entry per distinct index range (replicated-within-process
        # shards would duplicate data)
        seen = {}
        for s in leaf.addressable_shards:
            spec = tuple(
                (0 if sl.start is None else int(sl.start),
                 int(dim) if sl.stop is None else int(sl.stop))
                for sl, dim in zip(s.index, leaf.shape))
            seen.setdefault(spec, s.data)
        slices[key] = sorted(seen.items())
        return ShardRef(key, leaf.shape, leaf.dtype)

    marked = jax.tree_util.tree_map_with_path(visit, opt_state)
    return marked, slices


def assemble_sharded_state(blob_opt_state, shard_blobs):
    """Inverse of :func:`split_sharded_state` at load time: replace each
    :class:`ShardRef` with every shard file's blocks written back into
    their index ranges.  Raises ValueError when any element is missing
    — an incomplete shard set must fail the snapshot, not silently
    zero-fill optimizer state."""
    import jax

    merged = {}
    for sb in shard_blobs:
        for key, blocks in sb["slices"].items():
            merged.setdefault(key, []).extend(
                (tuple(tuple(int(v) for v in d) for d in spec),
                 np.asarray(b)) for spec, b in blocks)

    def visit(leaf):
        if not isinstance(leaf, ShardRef):
            return leaf
        blocks = merged.get(leaf.path)
        if not blocks:
            raise ValueError(f"checkpoint shard data missing for "
                             f"{leaf.path}")
        full = np.empty(leaf.shape, leaf.dtype)
        covered = np.zeros(leaf.shape, dtype=bool)
        for spec, b in sorted({s: b for s, b in blocks}.items()):
            idx = tuple(slice(a, z) for a, z in spec)
            if full[idx].shape != b.shape:
                raise ValueError(
                    f"checkpoint shard block for {leaf.path} at {spec} "
                    f"has shape {b.shape}, expected {full[idx].shape}")
            full[idx] = b
            covered[idx] = True
        if not covered.all():
            raise ValueError(
                f"checkpoint shards for {leaf.path} cover only "
                f"{int(covered.sum())}/{covered.size} elements "
                "(incomplete shard set)")
        return full

    return jax.tree_util.tree_map(
        visit, blob_opt_state,
        is_leaf=lambda l: isinstance(l, ShardRef))


class AsyncCheckpointWriter:
    """One background writer; jobs are whole snapshots and execute in
    submission order (a snapshot must never interleave with the next).

    ``submit`` enqueues ``(files, meta)`` where ``files`` is an ordered
    list of ``(path, blob)`` pairs saved via ``File.save`` (CRC sidecar
    per file — every shard gets its own) and ``meta`` drives the
    post-write bookkeeping (obs event, retention pruning).  Blobs may
    contain device arrays; the D2H happens on this thread.  A write
    failure is logged and the job dropped — the training loop must
    never die for a checkpoint (the resume scan skips the partial
    snapshot by CRC)."""

    def __init__(self, name: str = "bigdl-ckpt-writer"):
        self._q = queue.Queue()
        # outstanding-job counter under one lock (an Event toggled from
        # two threads has a submit-vs-drain race that could let flush()
        # return before the final snapshot is durable — the preemption
        # epilogue's one job)
        self._cond = threading.Condition()
        self._outstanding = 0
        self._stop = False
        self.written = 0
        self.failed = 0
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name=name)
        self._thread.start()

    def submit(self, files, meta=None):
        with self._cond:
            self._outstanding += 1
        self._q.put((list(files), dict(meta or {})))

    def _drain(self):
        from bigdl_tpu.utils import file as File
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop:
                    return
                continue
            files, meta = item
            t0 = time.perf_counter()
            try:
                for path, blob in files:
                    File.save(blob, path)
                self.written += 1
                if meta.get("event_path") is not None:
                    from bigdl_tpu.obs import events as obs_events
                    obs_events.emit(
                        "checkpoint", step=int(meta.get("step", 0)),
                        path=meta["event_path"], mode="async",
                        shards=int(meta.get("shards", 0)),
                        write_s=round(time.perf_counter() - t0, 4))
                keep = meta.get("keep")
                if keep:
                    from bigdl_tpu.optim.optimizer import prune_checkpoints
                    prune_checkpoints(meta["ckpt_dir"], keep,
                                      just_written=meta.get("step"))
            except Exception as e:
                self.failed += 1
                logger.warning("async checkpoint write failed (%s); the "
                               "resume scan will skip the partial "
                               "snapshot: %s",
                               files[0][0] if files else "?", e)
            finally:
                with self._cond:
                    self._outstanding -= 1
                    self._cond.notify_all()

    def flush(self, timeout: float = 120.0) -> bool:
        """Block until every submitted snapshot is durable (preemption
        epilogue, run end).  Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._outstanding > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=left)
        return True

    def close(self, timeout: float = 120.0):
        ok = self.flush(timeout=timeout)
        self._stop = True
        return ok
