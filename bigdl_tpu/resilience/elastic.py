"""Elastic training: recover-in-place on peer loss (docs/resilience.md
"Elastic training").

The watchdog's historical answer to a dead peer is exit 43: survivors
fail fast out of the dead collective and an operator restarts the whole
fleet from the last checkpoint.  On a multi-tenant preemptible pod that
turns every eviction into a full job restart.  This module is the other
answer: the survivors *re-form* — quiesce in-flight dispatch, abandon
the poisoned runtime, re-initialize jax distributed among themselves at
the reduced world size, reshard parameters and ZeRO-1 optimizer state
from an in-memory host anchor, and continue from the last consistent
step.  Checkpoint restore becomes the fallback, not the first response.

Protocol (one *generation* per recovery, files under the shared
watchdog/heartbeat directory)::

    trip      watchdog monitor thread sees a silent peer; with the
              ``recover`` policy it records the trip here instead of
              exiting.  The training loop notices at its next host-side
              boundary (dispatch is async, so the loop thread is never
              wedged inside the dead collective itself; blocking host
              syncs run on an abandonable helper thread).
    join      every survivor writes  rf.<gen>.join.<orig>
    plan      the surviving ORIGINAL process 0 waits for the join set to
              settle, checks the quorum floor, picks a fresh coordinator
              port and publishes  rf.<gen>.plan  (survivor list = new
              rank order).
    reform    all survivors abandon the old runtime (see below), then
              bring up jax distributed at the new world size.
    reshard   the optimizer re-partitions the anchor state over the new
              ``data`` axis and rebuilds its executables.
    resume    training continues from the anchor step.

Why the old runtime is LEAKED, not shut down — three hard facts of this
jaxlib (0.4.36, measured by the probes that shaped this module):

- a gloo collective whose peer died HANGS forever (no TCP-reset error),
  so any in-flight train step is unjoinable and the PJRT client that
  owns its thread can never be destructed;
- ``jax.distributed.shutdown`` runs a coordination-service shutdown
  barrier that the dead peer can never join — the client aborts the
  whole process (``client.h:80``);
- destroying the coordination *service* while any old client's
  error-polling RPC is still connected aborts every such process, and a
  custom ``missed_heartbeat_callback`` crashes in pybind before it can
  be called.

So recovery drops every Python reference (jit caches, backends, the
distributed client) and parks the old coordination service on the
original process 0 for the rest of the process lifetime.  Heartbeats at
elastic bring-up are stretched to *never* fire (the file watchdog is
the failure detector), which keeps the leaked stack inert.  The cost is
one idle port + a few idle threads per recovery; the benefit is that
peer loss costs a bounded pause instead of the job.

What still exits (the fail-fast contract survives where recovery is
impossible — the table in docs/resilience.md):

- the ORIGINAL process 0 dies: it hosts the coordination service; the
  survivors' error-polling RPC aborts them within milliseconds on this
  jaxlib, before any protocol could run;
- survivors below the quorum floor (``BIGDL_ELASTIC_QUORUM``, default
  2);
- the reform protocol times out (join/plan/connect), or this process
  is itself declared dead in the published plan;
- non-pure-DP meshes (pipeline/tensor/expert/sequence parallel shard
  *parameters* across processes — a dead peer takes its only copy).

Knobs: ``BIGDL_ELASTIC=1`` arms recovery (with ``Watchdog(
on_peer_death="recover")``), ``BIGDL_ELASTIC_QUORUM`` the minimum
survivor count, ``BIGDL_ELASTIC_HOST`` the host part of the re-formed
coordinator address (default the original coordinator's host, else
localhost).
"""
from __future__ import annotations

import json
import logging
import os
import queue
import socket
import threading
import time

import numpy as np

logger = logging.getLogger("bigdl_tpu.resilience")

ENV_ELASTIC = "BIGDL_ELASTIC"
ENV_QUORUM = "BIGDL_ELASTIC_QUORUM"
ENV_HOST = "BIGDL_ELASTIC_HOST"

#: heartbeat windows for the elastic bring-up: long enough that the
#: coordination service never declares a task dead on its own (the file
#: watchdog is the failure detector) and the leaked post-recovery stack
#: stays silent for the rest of the process lifetime.
_CLIENT_HEARTBEAT_S = 86400
_SERVICE_HEARTBEAT_S = 10
_SERVICE_MAX_MISSING = 1000000


def enabled() -> bool:
    return os.environ.get(ENV_ELASTIC, "0").strip() == "1"


def quorum() -> int:
    try:
        return max(1, int(os.environ.get(ENV_QUORUM, "2")))
    except ValueError:
        return 2


class PeerLossRecovery(Exception):
    """Control-flow signal: a peer died and the recover policy is armed —
    unwind to the training loop's recovery point.  Carries the watchdog's
    stale view.  Constructing one marks the trip CONSUMED (a recovery
    owner exists), which is what stands down the watchdog's
    unconsumed-trip fallback to exit 43."""

    def __init__(self, stale):
        super().__init__(f"peer loss: stale={sorted(stale)}")
        self.stale = frozenset(stale)
        _RT.recovering = True


class ReformAbort(RuntimeError):
    """Recovery is impossible (quorum, timeout, not in plan, dead
    coordinator): fall back to the fail-fast exit."""


# -- module state -----------------------------------------------------------

class _Runtime:
    """This process's elastic bring-up bookkeeping across generations."""

    def __init__(self):
        self.armed = False
        self.generation = 0
        self.orig_index = None     # process index at generation 0 (stable id)
        self.n_orig = None
        self.rank = None           # current rank
        self.world = None          # current world size
        self.survivors = None      # current membership as orig indices
        self.reform_dir = None     # shared dir for join/plan files
        self.coordinator_host = "localhost"
        self.leaked = []           # old services/clients parked forever
        self.watchdog = None
        self.recovered = False
        self.recovering = False    # a PeerLossRecovery owner exists
        self._trip = None          # frozenset of stale orig indices
        self._trip_mono = None     # monotonic clock at the FIRST trip
        self._lock = threading.Lock()


_RT = _Runtime()


def runtime() -> _Runtime:
    return _RT


def reset():
    """Forget all elastic state (tests)."""
    global _RT, _SYNC_WORKER
    _RT = _Runtime()
    _SYNC_WORKER = None


def note_trip(stale):
    """Record a watchdog trip under the recover policy.  Called from the
    watchdog monitor thread; the training loop polls :func:`tripped`."""
    with _RT._lock:
        if _RT._trip is None:
            _RT._trip = frozenset(int(s) for s in stale)
            _RT._trip_mono = time.monotonic()
        else:
            _RT._trip = _RT._trip | frozenset(int(s) for s in stale)
    from bigdl_tpu.obs import events as obs_events
    obs_events.emit("recover", kind="trip", stale=sorted(_RT._trip),
                    generation=_RT.generation)
    logger.error("elastic: peer(s) %s dead — recovery pending (the loop "
                 "re-forms at its next host boundary)", sorted(_RT._trip))


def tripped():
    """The pending stale set (frozenset of orig indices), or None."""
    return _RT._trip


def trip_age() -> float | None:
    """Seconds since the first pending trip was recorded, or None — the
    recovery-pause clock the ``resume`` obs event reports."""
    t = _RT._trip_mono
    return None if t is None else time.monotonic() - t


def clear_trip():
    with _RT._lock:
        _RT._trip = None
        _RT._trip_mono = None
        _RT.recovering = False


def check():
    """Raise :class:`PeerLossRecovery` if a trip is pending — the one
    probe the training loop calls at host-side boundaries."""
    t = _RT._trip
    if t is not None:
        raise PeerLossRecovery(t)


def await_trip(timeout: float | None = None):
    """Wait for the watchdog to confirm a peer death; returns the
    :class:`PeerLossRecovery` to raise, or None if no trip lands within
    ``timeout``.

    The error-conversion net under the training loop: a dead peer can
    surface as an immediate collective error (gloo TCP reset) long
    before the heartbeat timeout expires — the loop catches the error,
    parks here for the watchdog's verdict, and recovers if the verdict
    is peer death (any other error re-raises untouched).  Default
    timeout: the watchdog's timeout plus margin."""
    if timeout is None:
        dog = _RT.watchdog
        timeout = (dog.timeout + 3.0 * dog.interval + 2.0
                   if dog is not None else 10.0)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        t = _RT._trip
        if t is not None:
            return PeerLossRecovery(t)
        time.sleep(0.05)
    return None


# -- distributed bring-up ---------------------------------------------------

def initialize(coordinator_address: str, num_processes: int,
               process_id: int, reform_dir: str | None = None):
    """Elastic replacement for ``jax.distributed.initialize``.

    Builds the coordination service (process 0) and client directly so
    it can pass the options plain ``initialize`` hides: heartbeat
    windows stretched to never fire, and ``shutdown_on_destruction=
    False`` so dropping the client never runs the (un-joinable)
    shutdown barrier.  Idempotent per generation; must be used INSTEAD
    of ``jax.distributed.initialize`` for a run that wants recovery —
    the stock bring-up's heartbeat/error-polling defaults abort
    survivors ~100s after a peer dies, before or during any recovery.
    """
    from jax._src import distributed as jdist
    from jax._src.lib import xla_extension as xe

    gs = jdist.global_state
    if process_id == 0:
        bind = "[::]:" + coordinator_address.rsplit(":", 1)[1]
        gs.service = xe.get_distributed_runtime_service(
            bind, num_processes,
            heartbeat_interval=_SERVICE_HEARTBEAT_S,
            max_missing_heartbeats=_SERVICE_MAX_MISSING)
    client = xe.get_distributed_runtime_client(
        coordinator_address, process_id, init_timeout=120,
        heartbeat_interval=_CLIENT_HEARTBEAT_S, max_missing_heartbeats=10,
        shutdown_on_destruction=False, use_compression=True)
    client.connect()
    gs.client = client
    gs.coordinator_address = coordinator_address
    gs.process_id = process_id
    gs.num_processes = num_processes

    rt = _RT
    rt.armed = True
    if rt.orig_index is None:
        rt.orig_index = int(process_id)
        rt.n_orig = int(num_processes)
        rt.survivors = list(range(num_processes))
    rt.rank = int(process_id)
    rt.world = int(num_processes)
    rt.coordinator_host = coordinator_address.rsplit(":", 1)[0]
    if reform_dir is not None:
        rt.reform_dir = reform_dir
        os.makedirs(reform_dir, exist_ok=True)
    return client


def _free_port(host: str) -> int:
    s = socket.socket()
    s.bind((host if host not in ("", "[::]") else "localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- reform protocol (files under the shared heartbeat dir) ----------------

def _join_path(d, gen, orig):
    return os.path.join(d, f"rf.{gen}.join.{orig}")


def _plan_path(d, gen):
    return os.path.join(d, f"rf.{gen}.plan")


def _write_atomic(path, data: bytes):
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def publish_plan(reform_dir: str, gen: int, stale, orig_index: int,
                 n_orig: int, live_probe=None, settle: float = 1.0,
                 timeout: float = 60.0, host: str | None = None,
                 min_survivors: int | None = None) -> dict:
    """Coordinator side of the reform handshake (original process 0).

    Waits for the join-file set to settle (no new joiner for ``settle``
    seconds), intersects it with the heartbeat view (``live_probe() ->
    stale list``), enforces the quorum floor and publishes the plan:
    ``{"gen", "survivors": [orig...], "addr": "host:port"}``.  Survivor
    order IS the new rank order.  Testable without jax: pure files +
    callbacks."""
    min_survivors = quorum() if min_survivors is None else min_survivors
    host = host or os.environ.get(ENV_HOST, "").strip() or "localhost"
    deadline = time.time() + timeout
    stale = set(int(s) for s in stale)
    joined = set()
    last_change = time.time()
    while True:
        now = time.time()
        cur = {o for o in range(n_orig)
               if o not in stale and os.path.exists(
                   _join_path(reform_dir, gen, o))}
        if cur != joined:
            joined = cur
            last_change = now
        expected = set(range(n_orig)) - stale
        if joined and (joined == expected or now - last_change >= settle):
            break
        if now > deadline:
            raise _abort_plan(
                reform_dir, gen,
                f"reform gen {gen}: join set never settled "
                f"(joined={sorted(joined)}, stale={sorted(stale)})")
        time.sleep(0.05)
    if live_probe is not None:
        joined -= set(int(s) for s in live_probe())
    if orig_index not in joined:
        raise _abort_plan(
            reform_dir, gen,
            f"reform gen {gen}: coordinator {orig_index} not in its own "
            "join set")
    survivors = sorted(joined)
    if len(survivors) < min_survivors:
        raise _abort_plan(
            reform_dir, gen,
            f"reform gen {gen}: {len(survivors)} survivor(s) "
            f"{survivors} below the quorum floor {min_survivors}")
    plan = {"gen": gen, "survivors": survivors,
            "addr": "%s:%d" % (host, _free_port(host))}
    _write_atomic(_plan_path(reform_dir, gen),
                  json.dumps(plan).encode())
    return plan


def _abort_plan(reform_dir: str, gen: int, reason: str) -> ReformAbort:
    """Publish the coordinator's abort verdict as the plan, so the other
    survivors abort PROMPTLY instead of burning their await timeout (and
    being SIGABRTed mid-wait when the first aborter's exit closes the
    old coordination-service socket).  Returns the exception to raise."""
    try:
        _write_atomic(_plan_path(reform_dir, gen),
                      json.dumps({"gen": gen, "abort": reason}).encode())
    except OSError:  # pragma: no cover - the abort still stands
        pass
    return ReformAbort(reason)


def await_plan(reform_dir: str, gen: int, timeout: float = 90.0) -> dict:
    """Non-coordinator side: poll for the published plan.  A plan
    carrying an ``abort`` verdict raises :class:`ReformAbort`."""
    deadline = time.time() + timeout
    path = _plan_path(reform_dir, gen)
    while time.time() < deadline:
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    plan = json.loads(f.read())
            except (OSError, ValueError):
                plan = None  # racing the atomic rename; retry
            if plan is not None:
                if "abort" in plan:
                    raise ReformAbort(plan["abort"])
                return plan
        time.sleep(0.05)
    raise ReformAbort(f"reform gen {gen}: no plan within {timeout:.0f}s "
                      "(coordinator dead or partitioned)")


def _abandon_runtime():
    """Drop every Python reference into the old jax runtime and leak
    what cannot die (module docstring: why).  After this, jax.devices()
    lazily builds a fresh CPU/TPU client against the NEW distributed
    state on next touch."""
    import gc

    import jax
    from jax._src import distributed as jdist
    from jax.extend import backend as jax_backend

    gs = jdist.global_state
    jax.clear_caches()
    jax_backend.clear_backends()
    # the executable registry holds old-backend executables; drop them so
    # the rebuilt step re-registers against the new mesh cleanly
    try:
        from bigdl_tpu.serve import xcache
        xcache.reset()
    except Exception:  # pragma: no cover - serve layer absent
        pass
    if gs.client is not None:
        _RT.leaked.append(gs.client)   # undestructible: hung collective
        gs.client = None
    if gs.service is not None:
        # destroying the service aborts every leaked client's polling
        # RPC (probe-verified) — park it for the process lifetime
        _RT.leaked.append(gs.service)
        gs.service = None
    gs.coordinator_address = None
    gc.collect()


def reform(stale, settle: float = 1.0, timeout: float = 90.0) -> dict:
    """Run the full membership handshake + runtime swap for this
    process.  Returns the plan.  Raises :class:`ReformAbort` when
    recovery is impossible (callers fall back to exit 43)."""
    rt = _RT
    if not rt.armed or rt.reform_dir is None:
        raise ReformAbort("elastic runtime not armed (bring the job up "
                          "with resilience.elastic.initialize)")
    stale = set(int(s) for s in stale)
    if 0 in stale and rt.orig_index != 0:
        # the coordination service died with original process 0; on this
        # jaxlib the leaked clients abort within ms of the socket close —
        # don't pretend a handshake could win that race
        raise ReformAbort("original process 0 (coordination service) is "
                          "dead: recover-in-place is impossible")
    gen = rt.generation + 1
    _write_atomic(_join_path(rt.reform_dir, gen, rt.orig_index), b"1")
    dog = rt.watchdog
    if rt.orig_index == 0:
        plan = publish_plan(
            rt.reform_dir, gen, stale, rt.orig_index, rt.n_orig,
            live_probe=(dog.stale_peers if dog is not None else None),
            settle=settle, timeout=timeout,
            host=os.environ.get(ENV_HOST, "").strip()
            or rt.coordinator_host)
    else:
        plan = await_plan(rt.reform_dir, gen, timeout=timeout)
    survivors = [int(s) for s in plan["survivors"]]
    if rt.orig_index not in survivors:
        raise ReformAbort(f"reform gen {gen}: this process "
                          f"({rt.orig_index}) is not in the published "
                          f"plan {survivors}")
    if len(survivors) < quorum():
        raise ReformAbort(f"reform gen {gen}: plan {survivors} below "
                          f"quorum {quorum()}")

    world_before = rt.world
    _abandon_runtime()
    new_rank = survivors.index(rt.orig_index)
    initialize(plan["addr"], len(survivors), new_rank,
               reform_dir=rt.reform_dir)
    rt.generation = gen
    rt.survivors = survivors
    rt.recovered = True
    if dog is not None:
        dog.rebind(peers=survivors)
    clear_trip()
    from bigdl_tpu.obs import events as obs_events
    obs_events.emit("recover", kind="reform", generation=gen,
                    world_before=int(world_before),
                    world_after=len(survivors),
                    survivors=survivors, addr=plan["addr"])
    logger.warning("elastic: re-formed at generation %d — world %d -> %d "
                   "(survivors %s, coordinator %s, this process rank %d)",
                   gen, world_before, len(survivors), survivors,
                   plan["addr"], new_rank)
    return plan


# -- host anchor ------------------------------------------------------------

class Anchor:
    """One consistent host-side training snapshot: full numpy trees plus
    the loop bookkeeping needed to continue from exactly this step."""

    __slots__ = ("params", "net_state", "opt_state", "state", "neval",
                 "epoch", "count", "rng", "seq")

    def __init__(self, params, net_state, opt_state, state, neval, epoch,
                 count, rng, seq):
        self.params = params
        self.net_state = net_state
        self.opt_state = opt_state
        self.state = state
        self.neval = neval
        self.epoch = epoch
        self.count = count
        self.rng = rng
        self.seq = seq


class AnchorKeeper:
    """Background snapshot-to-host of the training state (the prefetch
    double-buffer pattern in reverse: the loop enqueues freshly-gathered
    device trees; one transfer thread materializes them to numpy).

    The loop hands in REPLICATED, NON-DONATED device trees (the gather
    jit produces new arrays), so the next step's donation can never
    invalidate an in-flight transfer.  If a peer dies mid-gather the
    transfer thread blocks forever on the doomed arrays — it is a
    daemon, the keeper just keeps serving the last COMPLETE anchor."""

    def __init__(self):
        self._q = queue.Queue(maxsize=1)
        self._lock = threading.Lock()
        self._anchor = None
        self._seq = 0
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="bigdl-elastic-anchor")
        self._thread.start()

    def offer(self, device_trees, payload: dict):
        """Enqueue a gathered snapshot; drops the previous pending one
        (latest wins — an anchor is only useful if it is the newest
        complete state)."""
        self._seq += 1
        item = (self._seq, device_trees, payload)
        try:
            self._q.put_nowait(item)
        except queue.Full:
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            try:
                self._q.put_nowait(item)
            except queue.Full:  # pragma: no cover - single producer
                pass

    def _drain(self):
        import jax
        while True:
            seq, trees, payload = self._q.get()
            try:
                host = jax.tree_util.tree_map(np.asarray, trees)
            except Exception as e:
                # doomed gather (peer died mid-window): keep the previous
                # complete anchor; this thread survives for the next one
                logger.warning("elastic anchor transfer failed: %s", e)
                continue
            params, net_state, opt_state = host
            anchor = Anchor(params, net_state, opt_state,
                            payload["state"], payload["neval"],
                            payload["epoch"], payload["count"],
                            payload["rng"], seq)
            with self._lock:
                if self._anchor is None or seq > self._anchor.seq:
                    self._anchor = anchor

    def capture_sync(self, host_trees, payload: dict):
        """Synchronous anchor install from already-host trees (the
        generation-0 snapshot before the loop starts)."""
        self._seq += 1
        params, net_state, opt_state = host_trees
        with self._lock:
            self._anchor = Anchor(params, net_state, opt_state,
                                  payload["state"], payload["neval"],
                                  payload["epoch"], payload["count"],
                                  payload["rng"], self._seq)

    def latest(self, grace: float = 2.0) -> Anchor:
        """The newest complete anchor, giving an in-flight transfer a
        short grace to land (it usually has: D2H is fast next to a
        watchdog timeout)."""
        target = self._seq
        deadline = time.time() + grace
        while time.time() < deadline:
            with self._lock:
                a = self._anchor
            if a is not None and a.seq >= target:
                return a
            time.sleep(0.05)
        with self._lock:
            if self._anchor is None:
                raise ReformAbort("no complete anchor (peer died before "
                                  "the first snapshot landed)")
            return self._anchor


class _GuardedWorker:
    """One long-lived helper thread serving :func:`guarded_sync` calls
    in order — the guarded path sits on the per-step hot path when
    elastic is armed, and a thread spawn per call would be thousands of
    short-lived threads per run.  A worker abandoned mid-call (its fn
    wedged in a dead collective) is replaced, never reused."""

    def __init__(self):
        self._req = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="bigdl-elastic-sync")
        self._thread.start()

    def _run(self):
        while True:
            fn, box, done = self._req.get()
            try:
                box.append(("ok", fn()))
            except BaseException as e:  # surfaced on the caller
                box.append(("err", e))
            finally:
                done.set()

    def submit(self, fn):
        box = []
        done = threading.Event()
        self._req.put((fn, box, done))
        return box, done


_SYNC_WORKER = None


def guarded_sync(fn, poll: float = 0.2):
    """Run a potentially-blocking device→host sync on an abandonable
    helper thread, polling the trip flag.  A doomed sync (collective
    with a dead peer hangs forever on this backend) would otherwise wedge
    the training loop past any recovery; here the loop abandons the
    helper (daemon; its buffers die with the old runtime) and raises
    :class:`PeerLossRecovery`."""
    global _SYNC_WORKER
    if _RT._trip is not None:
        raise PeerLossRecovery(_RT._trip)
    if _SYNC_WORKER is None:
        _SYNC_WORKER = _GuardedWorker()
    box, done = _SYNC_WORKER.submit(fn)
    while not done.wait(timeout=poll):
        if _RT._trip is not None:
            # the worker is wedged inside fn (or about to be abandoned
            # with work queued) — poison it; the next call gets a fresh
            # one and this thread parks with the doomed runtime
            _SYNC_WORKER = None
            raise PeerLossRecovery(_RT._trip)
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


# -- ordered job exit -------------------------------------------------------

def finalize(exit_code: int = 0, timeout: float = 60.0):
    """Ordered end-of-job exit for a RECOVERED fleet; a no-op (returns)
    when no recovery ever happened.

    The original process 0 hosts the leaked pre-recovery coordination
    service; its exit closes that socket and aborts any other survivor
    still running (the leaked clients' error-polling RPC).  So the
    non-coordinators exit first (``os._exit`` — the leaked runtime's
    threads make a clean interpreter teardown unreliable), each leaving
    an exit marker; the coordinator waits for the markers, then exits.
    """
    rt = _RT
    if not rt.recovered:
        return
    import sys
    sys.stdout.flush()
    sys.stderr.flush()
    d = rt.reform_dir
    me = rt.orig_index
    if me == 0:
        deadline = time.time() + timeout
        others = [o for o in (rt.survivors or []) if o != 0]
        while time.time() < deadline:
            if all(os.path.exists(os.path.join(d, f"exit.{o}"))
                   for o in others):
                break
            time.sleep(0.05)
        os._exit(exit_code)
    else:
        _write_atomic(os.path.join(d, f"exit.{me}"), b"1")
        os._exit(exit_code)
