"""Heartbeat/timeout watchdog for multi-host training.

A killed process leaves its peers blocked inside the next collective —
XLA cannot time out a dead all-reduce, so without outside help a 4-host
job with one dead host hangs until the cluster scheduler reaps it (the
reference fails fast instead: spark.task.maxFailures=1 kills the job and
the operator restarts from the checkpoint).

This watchdog is that fail-fast signal: every process runs a heartbeat
thread touching ``<dir>/hb.<process_index>`` each ``interval`` seconds
and a monitor thread checking every peer's file mtime.  A peer silent
for ``timeout`` seconds means the job is dead — the monitor fires
``on_stale`` (default: log loudly and ``os._exit(EXIT_CODE)``), so the
survivors exit promptly and the restart-from-checkpoint path
(``optim.optimizer.load_latest_checkpoint``) takes over.

The heartbeat directory must be shared across the hosts being watched
(NFS/GCS-fuse in production; a tmp dir in the 4-process CPU drill,
tests/test_resilience.py).
"""
from __future__ import annotations

import logging
import os
import threading
import time

logger = logging.getLogger("bigdl_tpu.resilience")

#: survivors exit with this code when a peer goes silent — distinct from
#: crash (1) and clean exit (0) so drills can assert the watchdog fired
EXIT_CODE = 43


class Watchdog:
    def __init__(self, directory: str, process_index: int, n_processes: int,
                 interval: float = 0.5, timeout: float = 10.0,
                 on_stale=None):
        if timeout <= interval:
            raise ValueError(
                f"timeout ({timeout}) must exceed the heartbeat interval "
                f"({interval}) or every process looks stale")
        self.dir = directory
        self.process_index = int(process_index)
        self.n_processes = int(n_processes)
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.on_stale = on_stale or self._default_on_stale
        self._stop = threading.Event()
        self._threads = []
        # peers get a grace period from watchdog start until their first
        # beat: process bring-up (jax.distributed handshake, first
        # compile) must not read as death
        self._started_at = None
        os.makedirs(directory, exist_ok=True)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._started_at = time.time()
        self._beat()  # own file exists before any peer can probe it
        for fn in (self._heartbeat_loop, self._monitor_loop):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"bigdl-watchdog-{fn.__name__}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2 * self.interval)
        self._threads = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- heartbeat side ----------------------------------------------------
    def _path(self, index: int) -> str:
        return os.path.join(self.dir, f"hb.{index}")

    def _beat(self):
        path = self._path(self.process_index)
        with open(path, "a"):
            os.utime(path, None)

    def _heartbeat_loop(self):
        while not self._stop.wait(self.interval):
            try:
                self._beat()
            except OSError as e:  # transient FS hiccup: keep beating
                logger.warning("watchdog heartbeat write failed: %s", e)

    # -- monitor side ------------------------------------------------------
    def stale_peers(self, now: float | None = None):
        """Process indices whose heartbeat is older than ``timeout``
        (missing files count only after the bring-up grace period)."""
        now = time.time() if now is None else now
        # probing before start(): the grace clock hasn't begun — nothing
        # can be stale yet
        started = self._started_at if self._started_at is not None else now
        stale = []
        for i in range(self.n_processes):
            if i == self.process_index:
                continue
            try:
                age = now - os.path.getmtime(self._path(i))
            except OSError:
                # no beat yet: stale only once the grace period passed
                age = now - started
            if age > self.timeout:
                stale.append(i)
        return stale

    def _monitor_loop(self):
        while not self._stop.wait(self.interval):
            stale = self.stale_peers()
            if stale:
                self._stop.set()
                self.on_stale(stale)
                return

    def _default_on_stale(self, stale):
        logger.error(
            "watchdog: process(es) %s silent > %.1fs — peer death; "
            "exiting with code %d so the job fails fast (restart resumes "
            "from the last valid checkpoint)", stale, self.timeout,
            EXIT_CODE)
        # postmortem on the way down (docs/observability.md): the event
        # names the dead peers, the bundle captures where THIS process
        # was blocked (threads.txt: usually inside the dead collective).
        # Strictly best-effort AND time-bounded: the whole point of this
        # exit is to beat the hang, so the dump runs on a side thread
        # with a hard 3s budget — a wedged device-stats query must not
        # turn fail-fast back into a hang.
        def _postmortem():
            try:
                from bigdl_tpu.obs import diagnostics, events
                events.emit("watchdog", stale=list(stale),
                            timeout=self.timeout,
                            process_index=self.process_index)
                diagnostics.dump_crash_bundle(
                    "watchdog-peer-death",
                    extra={"stale": list(stale), "timeout": self.timeout,
                           "process_index": self.process_index})
            except Exception:
                logger.exception("watchdog crash bundle failed")

        t = threading.Thread(target=_postmortem, daemon=True,
                             name="bigdl-watchdog-postmortem")
        t.start()
        t.join(timeout=3.0)
        # os._exit, not sys.exit: the main thread is likely blocked inside
        # a dead collective and would never unwind a SystemExit
        os._exit(EXIT_CODE)
