"""Heartbeat/timeout watchdog for multi-host training.

A killed process leaves its peers blocked inside the next collective —
XLA cannot time out a dead all-reduce, so without outside help a 4-host
job with one dead host hangs until the cluster scheduler reaps it (the
reference fails fast instead: spark.task.maxFailures=1 kills the job and
the operator restarts from the checkpoint).

This watchdog is that fail-fast signal: every process runs a heartbeat
thread touching ``<dir>/hb.<process_index>`` each ``interval`` seconds
and a monitor thread checking every peer's file mtime.  A peer silent
for ``timeout`` seconds means the job is dead — the monitor fires
``on_stale`` (default: log loudly and ``os._exit(EXIT_CODE)``), so the
survivors exit promptly and the restart-from-checkpoint path
(``optim.optimizer.load_latest_checkpoint``) takes over.

The heartbeat directory must be shared across the hosts being watched
(NFS/GCS-fuse in production; a tmp dir in the 4-process CPU drill,
tests/test_resilience.py).
"""
from __future__ import annotations

import logging
import os
import threading
import time

logger = logging.getLogger("bigdl_tpu.resilience")

#: survivors exit with this code when a peer goes silent — distinct from
#: crash (1) and clean exit (0) so drills can assert the watchdog fired
EXIT_CODE = 43


class Watchdog:
    def __init__(self, directory: str, process_index: int, n_processes: int,
                 interval: float = 0.5, timeout: float = 10.0,
                 on_stale=None, on_peer_death: str = "exit"):
        """``on_peer_death`` picks the policy when a peer goes silent:

        - ``"exit"`` (default, the historical contract): log loudly and
          ``os._exit(EXIT_CODE)`` — survivors fail fast out of the dead
          collective and a restart resumes from the last checkpoint.
        - ``"recover"``: record the trip with the elastic layer
          (``resilience/elastic.py``) and KEEP RUNNING — the training
          loop re-forms the fleet at the reduced world size at its next
          host-side boundary.  The heartbeat thread keeps beating so the
          other survivors' monitors don't read *this* process as dead
          mid-recovery.

        An explicit ``on_stale`` callable overrides either policy (the
        historical escape hatch, unchanged)."""
        if timeout <= interval:
            raise ValueError(
                f"timeout ({timeout}) must exceed the heartbeat interval "
                f"({interval}) or every process looks stale")
        if on_peer_death not in ("exit", "recover"):
            raise ValueError(
                f"on_peer_death must be 'exit' or 'recover', got "
                f"{on_peer_death!r}")
        self.dir = directory
        self.process_index = int(process_index)
        self.n_processes = int(n_processes)
        self.interval = float(interval)
        self.timeout = float(timeout)
        #: extra seconds process 0 lingers before its fail-fast exit so
        #: the other survivors' exit-43 lands before the coordination-
        #: service socket closes (see _default_on_stale)
        self.coordinator_grace = 2.0
        #: how long the recover policy waits for a recovery owner to
        #: consume the trip before downgrading to the fail-fast exit
        #: (see _recover_on_stale)
        self.trip_fallback = max(30.0, 4 * self.timeout)
        self.on_peer_death = on_peer_death
        # recover keeps the monitor/heartbeat threads alive through the
        # re-form (an explicit flag: bound-method identity is useless)
        self._policy_recover = False
        if on_stale is not None:
            self.on_stale = on_stale
        elif on_peer_death == "recover":
            self.on_stale = self._recover_on_stale
            self._policy_recover = True
        else:
            self.on_stale = self._default_on_stale
        self._stop = threading.Event()
        self._threads = []
        #: orig indices this monitor watches (None = all < n_processes);
        #: rebind() narrows it to the survivors after a recovery
        self._peers = None
        # peers get a grace period from watchdog start until their first
        # beat: process bring-up (jax.distributed handshake, first
        # compile) must not read as death
        self._started_at = None
        os.makedirs(directory, exist_ok=True)
        if on_peer_death == "recover":
            # the heartbeat dir doubles as the reform-protocol dir: every
            # process can already reach it, and join/plan files sit next
            # to the heartbeats they are decided from
            from bigdl_tpu.resilience import elastic
            rt = elastic.runtime()
            rt.watchdog = self
            if rt.reform_dir is None:
                rt.reform_dir = directory

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._started_at = time.time()
        self._beat()  # own file exists before any peer can probe it
        for fn in (self._heartbeat_loop, self._monitor_loop):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"bigdl-watchdog-{fn.__name__}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2 * self.interval)
        self._threads = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- heartbeat side ----------------------------------------------------
    def _path(self, index: int) -> str:
        return os.path.join(self.dir, f"hb.{index}")

    def _beat(self):
        path = self._path(self.process_index)
        with open(path, "a"):
            os.utime(path, None)

    def _heartbeat_loop(self):
        while not self._stop.wait(self.interval):
            try:
                self._beat()
            except OSError as e:  # transient FS hiccup: keep beating
                logger.warning("watchdog heartbeat write failed: %s", e)

    def rebind(self, peers=None, n_processes: int | None = None):
        """Re-key the monitor after an elastic re-form: watch only the
        surviving ORIGINAL indices (heartbeat files keep their original
        names — a process's identity never changes, only the membership).
        Restarts the threads with a fresh bring-up grace."""
        was_running = bool(self._threads)
        self.stop()
        if n_processes is not None:
            self.n_processes = int(n_processes)
        self._peers = None if peers is None else [int(p) for p in peers]
        self._stop = threading.Event()
        if was_running:
            self.start()
        return self

    # -- monitor side ------------------------------------------------------
    def stale_peers(self, now: float | None = None):
        """Process indices whose heartbeat is older than ``timeout``
        (missing files count only after the bring-up grace period)."""
        now = time.time() if now is None else now
        # probing before start(): the grace clock hasn't begun — nothing
        # can be stale yet
        started = self._started_at if self._started_at is not None else now
        stale = []
        peers = (self._peers if self._peers is not None
                 else range(self.n_processes))
        for i in peers:
            if i == self.process_index:
                continue
            try:
                age = now - os.path.getmtime(self._path(i))
            except OSError:
                # no beat yet: stale only once the grace period passed
                age = now - started
            if age > self.timeout:
                stale.append(i)
        return stale

    def _monitor_loop(self):
        while not self._stop.wait(self.interval):
            stale = self.stale_peers()
            if stale:
                if not self._policy_recover:
                    # exit/custom policy: one shot, stop both threads
                    # (the default exits the process anyway)
                    self._stop.set()
                self.on_stale(stale)
                return

    def _recover_on_stale(self, stale):
        """The ``recover`` policy: hand the trip to the elastic layer and
        keep beating — this process is alive and about to re-form; going
        heartbeat-silent here would cascade false deaths through the
        other survivors' monitors.  The monitor thread then watches for
        CONSUMPTION: if no recovery owner claims the trip within a
        bounded window (no elastic session armed — wrong bring-up,
        non-pure-DP mesh — or the loop is wedged beyond the guarded
        probes), the policy downgrades to the fail-fast exit rather
        than converting peer death into an unbounded fleet hang."""
        logger.error(
            "watchdog: process(es) %s silent > %.1fs — peer death; "
            "recover policy armed, deferring to elastic re-form instead "
            "of exiting %d", stale, self.timeout, EXIT_CODE)
        from bigdl_tpu.resilience import elastic
        from bigdl_tpu.obs import events
        events.emit("watchdog", stale=list(stale), timeout=self.timeout,
                    process_index=self.process_index, policy="recover")
        elastic.note_trip(stale)
        deadline = time.time() + self.trip_fallback
        while time.time() < deadline:
            if self._stop.is_set():
                return
            rt = elastic.runtime()
            if rt.recovering or elastic.tripped() is None:
                return   # a recovery owner has the process's fate now
            time.sleep(self.interval)
        logger.error(
            "watchdog: recover policy armed but NO recovery owner "
            "consumed the trip within %.0fs (elastic session not armed, "
            "or the loop is wedged) — falling back to the fail-fast "
            "exit %d", self.trip_fallback, EXIT_CODE)
        self._default_on_stale(stale)

    def arbitrate(self, error, timeout: float | None = None):
        """Hand a training-loop error to the watchdog's verdict (exit
        policy).  A dead peer can surface as an IMMEDIATE collective
        error (TCP reset) long before the heartbeat timeout; if the
        erroring process unwound on its own it would die with an
        arbitrary exit code — or worse, be SIGABRTed by the runtime's
        error-poll when the first survivor's exit closes the
        coordination service.  Parking here lets the monitor thread
        deliver the uniform contract: confirmed peer death exits
        ``EXIT_CODE`` (this call never returns), anything else re-raises
        ``error`` after the verdict window."""
        deadline = time.time() + (timeout if timeout is not None
                                  else self.timeout + 3 * self.interval + 2)
        logger.warning(
            "watchdog: training raised %s: %s — holding for the peer-"
            "death verdict before unwinding", type(error).__name__, error)
        while time.time() < deadline:
            if self.stale_peers():
                # confirmed: the monitor thread exits the process (crash
                # bundle included) — give it room, then exit directly as
                # the fallback
                time.sleep(self.coordinator_grace + 2 * self.interval
                           + 3.5)
                os._exit(EXIT_CODE)
            time.sleep(self.interval)
        raise error

    def _default_on_stale(self, stale):
        logger.error(
            "watchdog: process(es) %s silent > %.1fs — peer death; "
            "exiting with code %d so the job fails fast (restart resumes "
            "from the last valid checkpoint)", stale, self.timeout,
            EXIT_CODE)
        # postmortem on the way down (docs/observability.md): the event
        # names the dead peers, the bundle captures where THIS process
        # was blocked (threads.txt: usually inside the dead collective).
        # Strictly best-effort AND time-bounded: the whole point of this
        # exit is to beat the hang, so the dump runs on a side thread
        # with a hard 3s budget — a wedged device-stats query must not
        # turn fail-fast back into a hang.
        def _postmortem():
            try:
                from bigdl_tpu.obs import diagnostics, events
                events.emit("watchdog", stale=list(stale),
                            timeout=self.timeout,
                            process_index=self.process_index)
                diagnostics.dump_crash_bundle(
                    "watchdog-peer-death",
                    extra={"stale": list(stale), "timeout": self.timeout,
                           "process_index": self.process_index})
            except Exception:
                logger.exception("watchdog crash bundle failed")

        t = threading.Thread(target=_postmortem, daemon=True,
                             name="bigdl-watchdog-postmortem")
        t.start()
        t.join(timeout=3.0)
        if self.process_index == 0 and self.n_processes > 1:
            # process 0 usually hosts the coordination service; its exit
            # closes that socket and the runtime's error-poll SIGABRTs
            # any survivor still unwinding — before it could deliver the
            # contract's EXIT_CODE.  A short grace lets the peers' own
            # fail-fast exits land first (still bounded: fail fast means
            # seconds, not hangs).
            time.sleep(self.coordinator_grace)
        # os._exit, not sys.exit: the main thread is likely blocked inside
        # a dead collective and would never unwind a SystemExit
        os._exit(EXIT_CODE)
