"""Shape manipulation layers (SURVEY.md §2.3 "Shape ops"): Reshape,
InferReshape, View, Transpose, Replicate, Squeeze, Unsqueeze, Padding,
SpatialZeroPadding, Contiguous, Copy, Identity, Echo.

All 1-based dims, matching the reference. ``Contiguous``/``Copy`` are
identities under XLA (arrays are immutable and layout is the compiler's),
kept for API parity.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule, Module


class Reshape(TensorModule):
    """(ref Reshape.scala) — reshapes non-batch dims; ``batch_mode`` forces
    treating dim 0 as batch (None = auto-detect like the reference)."""

    def __init__(self, size, batch_mode: bool = None):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def _forward(self, P, x, S, ctx):
        n_el = int(np.prod(self.size))
        batched = self.batch_mode
        if batched is None:
            # heuristic (ref Reshape.scala batch disambiguation): batched when
            # per-sample elements match; a singleton leading dim with more
            # input dims than target dims counts as a batch of one
            batched = (x.size == x.shape[0] * n_el and
                       (x.size != n_el or
                        (x.shape[0] == 1 and x.ndim > len(self.size))))
        if batched:
            return x.reshape((x.shape[0],) + self.size), None
        return x.reshape(self.size), None

    def __repr__(self):
        return f"Reshape({'x'.join(map(str, self.size))})"


class InferReshape(TensorModule):
    """Reshape with -1 (inferred) and 0 (copy input dim) entries
    (ref InferReshape.scala)."""

    def __init__(self, size, batch_mode: bool = False):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def _forward(self, P, x, S, ctx):
        base = 1 if self.batch_mode else 0
        out = []
        if self.batch_mode:
            out.append(x.shape[0])
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(x.shape[base + i])
            else:
                out.append(s)  # -1 handled by jnp.reshape
        return x.reshape(tuple(out)), None


class View(TensorModule):
    """(ref View.scala) — reshape keeping total elements; supports
    ``num_input_dims`` for batch disambiguation."""

    def __init__(self, *sizes):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(int(s) for s in sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n):
        self.num_input_dims = n
        return self

    def _forward(self, P, x, S, ctx):
        n_el = int(np.prod(self.sizes))
        if x.size == n_el and not (x.shape[0] == 1 and x.ndim > len(self.sizes)):
            return x.reshape(self.sizes), None
        return x.reshape((x.shape[0],) + self.sizes), None


class Transpose(TensorModule):
    """Swap listed (1-based) dim pairs in order (ref Transpose.scala)."""

    def __init__(self, permutations):
        super().__init__()
        self.permutations = [(int(a), int(b)) for a, b in permutations]

    def _forward(self, P, x, S, ctx):
        for a, b in self.permutations:
            x = jnp.swapaxes(x, a - 1, b - 1)
        return x, None


class Replicate(TensorModule):
    """Insert a new dim of size nFeatures at 1-based ``dim``
    (ref Replicate.scala)."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = np.inf):
        super().__init__()
        self.n_features = n_features
        self.dim = dim

    def _forward(self, P, x, S, ctx):
        y = jnp.expand_dims(x, self.dim - 1)
        reps = [1] * y.ndim
        reps[self.dim - 1] = self.n_features
        return jnp.tile(y, reps), None


class Squeeze(TensorModule):
    def __init__(self, dim: int = None, num_input_dims: int = None):
        super().__init__()
        self.dim = dim

    def _forward(self, P, x, S, ctx):
        if self.dim is None:
            return jnp.squeeze(x), None
        return (jnp.squeeze(x, axis=self.dim - 1) if x.shape[self.dim - 1] == 1
                else x), None


class Unsqueeze(TensorModule):
    def __init__(self, pos: int, num_input_dims: int = None):
        super().__init__()
        self.pos = pos

    def _forward(self, P, x, S, ctx):
        return jnp.expand_dims(x, self.pos - 1), None


class Padding(TensorModule):
    """Pad ``pad`` entries (negative = front) along 1-based ``dim`` with
    ``value``; ``n_index`` offsets the insert position (ref Padding.scala)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim = dim
        self.pad = pad
        self.n_input_dim = n_input_dim
        self.value = value
        self.n_index = n_index

    def _forward(self, P, x, S, ctx):
        dim = self.dim - 1
        if x.ndim > self.n_input_dim:
            dim += 1  # batched input
        widths = [(0, 0)] * x.ndim
        if self.pad < 0:
            widths[dim] = (-self.pad, 0)
        else:
            widths[dim] = (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value), None


class SpatialZeroPadding(TensorModule):
    """(ref SpatialZeroPadding.scala) pad H/W dims of (N,C,H,W) or (C,H,W);
    negative pads crop."""

    def __init__(self, pad_left: int, pad_right: int = None,
                 pad_top: int = None, pad_bottom: int = None):
        super().__init__()
        self.pl = pad_left
        self.pr = pad_left if pad_right is None else pad_right
        self.pt = pad_left if pad_top is None else pad_top
        self.pb = pad_left if pad_bottom is None else pad_bottom

    def _forward(self, P, x, S, ctx):
        was3d = x.ndim == 3
        if was3d:
            x = x[None]

        def do(v, lo, hi, axis):
            if lo > 0 or hi > 0:
                widths = [(0, 0)] * v.ndim
                widths[axis] = (max(lo, 0), max(hi, 0))
                v = jnp.pad(v, widths)
            sl = [slice(None)] * v.ndim
            sl[axis] = slice(-min(lo, 0), v.shape[axis] + min(hi, 0))
            return v[tuple(sl)]

        x = do(x, self.pt, self.pb, 2)
        x = do(x, self.pl, self.pr, 3)
        return (x[0] if was3d else x), None


class Contiguous(TensorModule):
    """No-op under XLA (ref Contiguous.scala forces a compact copy on JVM)."""

    def _forward(self, P, x, S, ctx):
        return x, None


class Copy(TensorModule):
    """(ref Copy.scala)"""

    def _forward(self, P, x, S, ctx):
        return jnp.asarray(x), None


class Identity(Module):
    """(ref Identity.scala) — passes through any Activity."""

    def _forward(self, P, x, S, ctx):
        return x, None


class Echo(TensorModule):
    """Debug layer: print shape during eager forward (ref Echo.scala)."""

    def _forward(self, P, x, S, ctx):
        print(f"{self.get_name()}: shape {tuple(x.shape)}")
        return x, None
