"""Table/structure ops (SURVEY.md §2.3 "Table/structure ops (14)"):
CAddTable, CSubTable, CMulTable, CDivTable, CMaxTable, CMinTable, JoinTable,
SelectTable, NarrowTable, FlattenTable, MixtureTable, CriterionTable,
DotProduct, PairwiseDistance, CosineDistance.
"""
from __future__ import annotations

from functools import reduce

import jax.numpy as jnp

from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import Table


class CAddTable(Module):
    def __init__(self, inplace: bool = False):
        super().__init__()

    def _forward(self, P, x, S, ctx):
        return reduce(jnp.add, list(x)), None


class CSubTable(Module):
    def _forward(self, P, x, S, ctx):
        return x[1] - x[2], None


class CMulTable(Module):
    def _forward(self, P, x, S, ctx):
        return reduce(jnp.multiply, list(x)), None


class CDivTable(Module):
    def _forward(self, P, x, S, ctx):
        return x[1] / x[2], None


class CMaxTable(Module):
    def _forward(self, P, x, S, ctx):
        return reduce(jnp.maximum, list(x)), None


class CMinTable(Module):
    def _forward(self, P, x, S, ctx):
        return reduce(jnp.minimum, list(x)), None


class JoinTable(Module):
    """Concatenate table elements along 1-based ``dimension``; ``n_input_dims``
    disambiguates batched input (ref JoinTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def _forward(self, P, x, S, ctx):
        elems = list(x)
        dim = self.dimension - 1
        if self.n_input_dims > 0 and elems[0].ndim > self.n_input_dims:
            dim += 1
        return jnp.concatenate(elems, axis=dim), None


class SelectTable(Module):
    """Select i-th element of the input Table; negative indexes from the end
    (ref SelectTable.scala)."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def _forward(self, P, x, S, ctx):
        idx = self.index if self.index > 0 else x.length() + self.index + 1
        return x[idx], None


class NarrowTable(Module):
    """Slice ``length`` elements of the table starting at ``offset``
    (ref NarrowTable.scala)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset = offset
        self.length = length

    def _forward(self, P, x, S, ctx):
        n = self.length
        if n < 0:
            n = x.length() - self.offset + 2 + n
        out = Table()
        for i in range(n):
            out[i + 1] = x[self.offset + i]
        return out, None


class FlattenTable(Module):
    """Flatten nested Tables into a flat Table (ref FlattenTable.scala)."""

    def _forward(self, P, x, S, ctx):
        out = Table()

        def rec(t):
            for v in t:
                if isinstance(v, Table):
                    rec(v)
                else:
                    out.insert(v)

        rec(x)
        return out, None


class MixtureTable(Module):
    """Mixture-of-experts blend: input Table(gates (N,E), experts)
    where experts is a Table of E tensors (N, ...) or a tensor (N, E, ...)
    (ref MixtureTable.scala:221 — single-device gating, not distributed EP)."""

    def __init__(self, dim: int = None):
        super().__init__()
        self.dim = dim

    def _forward(self, P, x, S, ctx):
        gates, experts = x[1], x[2]
        if isinstance(experts, Table):
            stacked = jnp.stack(list(experts), axis=1)  # (N, E, ...)
        else:
            stacked = experts
        g = gates.reshape(gates.shape + (1,) * (stacked.ndim - gates.ndim))
        return (stacked * g).sum(axis=1), None


class DotProduct(Module):
    """Row-wise dot product of Table(a, b) (ref DotProduct.scala)."""

    def _forward(self, P, x, S, ctx):
        a, b = x[1], x[2]
        if a.ndim == 1:
            return jnp.dot(a, b), None
        return (a * b).sum(axis=-1), None


class PairwiseDistance(Module):
    """Row-wise Lp distance of Table(a, b) (ref PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def _forward(self, P, x, S, ctx):
        a, b = x[1], x[2]
        d = jnp.abs(a - b)
        axis = -1 if a.ndim > 1 else 0
        return (d ** self.norm).sum(axis=axis) ** (1.0 / self.norm), None


class CosineDistance(Module):
    """Row-wise cosine similarity of Table(a, b) (ref CosineDistance.scala)."""

    def _forward(self, P, x, S, ctx):
        a, b = x[1], x[2]
        axis = -1 if a.ndim > 1 else 0
        num = (a * b).sum(axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, 1e-12), None


class CriterionTable(Module):
    """Wrap a criterion as a module over Table(input, target)
    (ref CriterionTable.scala)."""

    def __init__(self, criterion):
        super().__init__()
        self.criterion = criterion

    def _forward(self, P, x, S, ctx):
        return self.criterion.apply_loss(x[1], x[2]), None
