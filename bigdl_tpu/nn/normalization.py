"""Normalization layers (ref BatchNormalization.scala:31 [673 LoC],
SpatialBatchNormalization, SpatialCrossMapLRN.scala [221 LoC],
SpatialSubtractiveNormalization / SpatialDivisiveNormalization /
SpatialContrastiveNormalization).

BatchNorm running stats are the one true *state* in the module system: the
pure ``_forward`` returns updated buffers, which the eager path writes back
and the jitted trainer threads through the step function — the reference's
in-place ``runningMean/runningVar`` mutation made functional.
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import TensorModule
from bigdl_tpu.nn import init as init_
from bigdl_tpu.tensor import policy

_COMPUTE_DTYPE_NORM = True  # norm APPLY chains in the policy compute dtype


def _apply_in_compute_dtype(x):
    """The big (N, …) normalize apply is pure bandwidth: run it in the
    policy compute dtype when a reduced-precision policy is active
    (statistics always stay f32 — the callers compute them before this).
    Shared by BatchNormalization and LayerNorm; measured −1.6 ms/step on
    ResNet-50 (PERF_NOTES round 4)."""
    p = policy()
    if (_COMPUTE_DTYPE_NORM and p.compute_dtype != jnp.float32
            and p.compute_dtype != x.dtype and x.dtype == jnp.float32):
        return x.astype(p.compute_dtype)
    return x


class BatchNormalization(TensorModule):
    """Batch norm over (N, D) input (ref BatchNormalization.scala:31).

    Constructor mirrors the reference: (nOutput, eps, momentum, affine).
    Training: batch stats + EMA update of running stats; eval: running stats.
    """

    n_dim = 2

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.reset()

    def reset(self):
        if self.affine:
            self._add_param("weight", init_.uniform((self.n_output,), 0.0, 1.0))
            self._add_param("bias", np.zeros((self.n_output,), np.float32))
        self._add_buffer("running_mean", np.zeros((self.n_output,), np.float32))
        self._add_buffer("running_var", np.ones((self.n_output,), np.float32))
        return self

    def _stat_axes(self, x):
        return tuple(i for i in range(x.ndim) if i != 1) if x.ndim > 2 else (0,)

    def _forward(self, P, x, S, ctx):
        was_unbatched = x.ndim == self.n_dim - 1
        if was_unbatched:
            x = x[None]
        axes = self._stat_axes(x)
        bshape = [1] * x.ndim
        bshape[1 if x.ndim > 2 else -1] = self.n_output
        new_S = None
        if ctx.training:
            # statistics accumulate in f32: under the BF16_ACT policy x is
            # bfloat16 and a bf16 mean over N*H*W elements loses the tail
            x32 = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
            mean = x32.mean(axis=axes)
            var = x32.var(axis=axes)
            n = x.size / self.n_output
            unbiased = var * (n / max(n - 1, 1.0))
            new_S = {
                "running_mean": (1 - self.momentum) * S["running_mean"] + self.momentum * mean,
                "running_var": (1 - self.momentum) * S["running_var"] + self.momentum * unbiased,
            }
        else:
            mean, var = S["running_mean"], S["running_var"]
        inv = lax.rsqrt(var + self.eps)
        scale, shift = inv, -mean * inv
        if self.affine:
            scale = scale * P["weight"]
            shift = shift * P["weight"] + P["bias"]
        xa = _apply_in_compute_dtype(x)
        y = (xa * scale.astype(xa.dtype).reshape(bshape)
             + shift.astype(xa.dtype).reshape(bshape))
        return ((y[0] if was_unbatched else y).astype(x.dtype)), new_S

    def __repr__(self):
        return f"{type(self).__name__}({self.n_output})"


class SpatialBatchNormalization(BatchNormalization):
    """Batch norm over (N, C, H, W) (ref SpatialBatchNormalization.scala)."""

    n_dim = 4


class LayerNorm(TensorModule):
    """Layer normalization over the trailing feature dim: (…, D) -> (…, D).

    Absent in the reference (its normalizers are batch/spatial/LRN);
    added for the attention/transformer family (``nn/attention.py``) —
    LayerNorm is per-token, so it needs NO cross-device statistics under
    data/sequence sharding, which is exactly why transformer stacks use
    it.  Statistics in f32; the (…, D) apply follows the compute-dtype
    policy like BatchNorm's."""

    def __init__(self, d_model: int, eps: float = 1e-5,
                 affine: bool = True):
        super().__init__()
        self.d_model = d_model
        self.eps = eps
        self.affine = affine
        self.reset()

    def reset(self):
        if self.affine:
            self._add_param("weight", np.ones((self.d_model,), np.float32))
            self._add_param("bias", np.zeros((self.d_model,), np.float32))
        return self

    def _forward(self, P, x, S, ctx):
        x32 = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
        mean = x32.mean(axis=-1, keepdims=True)
        var = x32.var(axis=-1, keepdims=True)
        inv = lax.rsqrt(var + self.eps)
        scale, shift = inv, -mean * inv
        if self.affine:
            scale = scale * P["weight"]
            shift = shift * P["weight"] + P["bias"]
        xa = _apply_in_compute_dtype(x)
        y = xa * scale.astype(xa.dtype) + shift.astype(xa.dtype)
        return y.astype(x.dtype), None

    def __repr__(self):
        return f"LayerNorm({self.d_model})"


class SpatialCrossMapLRN(TensorModule):
    """Local response normalization across channels
    (ref SpatialCrossMapLRN.scala:221):
    y = x / (k + alpha/size * sum_{window} x^2) ** beta.

    Implemented as a window reduction over the channel dim — a single fused
    XLA op instead of the reference's per-thread sliding accumulation.
    """

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    _STENCIL = False  # module-level A/B switches, see tools/ab_step.py:
    _SQRT_POW = True  # in-model grid measured rw-LRN+sqrt fastest (PERF_NOTES)
    # Fused Pallas LRN (ops/pallas_kernels.lrn_channel).  The round-3
    # form measured SLOWER than this XLA path on the v5e (538 vs
    # 808-852 us fwd+bwd on the Inception C64 56x56 shape,
    # device-clock).  Round 6 rebuilt the kernel pair — the forward now
    # stores z (the window-sum denominator base) as the VJP residual so
    # the backward is ONE pass with a single adjoint window sum, where
    # the round-3 backward recomputed z from x — and the verdict must
    # be re-measured (tools/ab_device_clock.py pallas_lrn variant).
    # DEFAULT OFF until that device A/B wins; "interpret" forces the
    # Pallas interpreter on any backend (tests).
    _PALLAS = False
    _ANALYTIC_VJP = True   # see _lrn below
    _COMPUTE_DTYPE = True  # run the LRN chain in the policy compute dtype

    def _forward(self, P, x, S, ctx):
        if self._PALLAS and x.ndim == 4:
            from bigdl_tpu.ops.pallas_kernels import lrn_channel, _on_tpu
            return lrn_channel(x, self.size, self.alpha, self.beta, self.k,
                               not _on_tpu()), None
        lo = (self.size - 1) // 2
        hi = self.size - 1 - lo
        if self._ANALYTIC_VJP and not self._STENCIL:
            p = policy()
            cast = (self._COMPUTE_DTYPE
                    and p.compute_dtype != jnp.float32
                    and p.compute_dtype != x.dtype
                    and x.dtype == jnp.float32)
            if cast:
                # LRN is pure bandwidth (window sums + eltwise): the
                # compute-dtype cast halves its bytes like every matmul/
                # conv operand under the policy.  Denominator error is
                # bounded: z = k + (alpha/n) sum x^2 with k=1 dominates,
                # and bf16 keeps ~3 significant digits of the small
                # correction term.  Measured loss drift and device win:
                # PERF_NOTES round 4.
                y = _lrn(x.astype(p.compute_dtype), self.size, self.alpha,
                         self.beta, self.k, self._SQRT_POW)
                return y.astype(x.dtype), None
            return _lrn(x, self.size, self.alpha, self.beta, self.k,
                        self._SQRT_POW), None
        if self._STENCIL:
            # Cross-channel window sum as ``size`` shifted slice-adds — a
            # pure elementwise stencil XLA fuses into one pass regardless
            # of layout.  Measured alternatives (tools/ab_pool_lrn.py,
            # PERF_NOTES.md): lax.reduce_window over the channel dim is
            # slower at C=192, and a banded [C,C] matmul gets pattern-
            # matched into a 1x1 NHWC conv whose backward runs at
            # single-digit % of peak in-model.
            c = x.shape[1]
            sqp = jnp.pad(x * x, ((0, 0), (lo, hi), (0, 0), (0, 0)))
            sq_sum = sum(lax.slice_in_dim(sqp, t, t + c, axis=1)
                         for t in range(self.size))
            z = self.k + (self.alpha / self.size) * sq_sum
        else:
            z = self.k + (self.alpha / self.size) * _lrn_window_sum(
                x * x, self.size, lo, hi)
        denom = _lrn_denom(z, self.beta, self.size, self._SQRT_POW)
        return x / denom, None


def _lrn_window_sum(v, size, lo, hi):
    return lax.reduce_window(
        v, 0.0, lax.add,
        window_dimensions=(1, size, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (lo, hi), (0, 0), (0, 0)))


def _lrn_denom(z, beta, size, sqrt_pow):
    if beta == 0.75 and sqrt_pow:
        # z^(3/4) = (z^(1/4))^3 via two sqrts: no exp/log transcendentals
        return jnp.sqrt(jnp.sqrt(z)) ** 3
    return z ** beta


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _lrn(x, size, alpha, beta, k, sqrt_pow):
    """LRN with the ANALYTIC backward instead of the jvp-transpose one.

    y_c = x_c z_c^{-beta} with z = k + (alpha/n) sum_win x^2 gives

        dx_d = g_d / denom_d - (2 alpha beta / n) x_d
               * sum_{c : d in win(c)} g_c y_c / z_c

    — ONE reversed-window reduce_window over g*y/z, where the
    jvp-transpose backward emits TWO window reductions plus a wider
    mul/add fusion chain (measured 1.44 ms of reduce_window + 2.1 ms of
    fusions per Inception step, PROFILE round 3/4).  Device-clock A/B in
    PERF_NOTES round 4.  Residuals: x and z only; denom/y are two-sqrt
    recomputes."""
    lo = (size - 1) // 2
    hi = size - 1 - lo
    z = k + (alpha / size) * _lrn_window_sum(x * x, size, lo, hi)
    return x / _lrn_denom(z, beta, size, sqrt_pow)


def _lrn_fwd(x, size, alpha, beta, k, sqrt_pow):
    lo = (size - 1) // 2
    hi = size - 1 - lo
    z = k + (alpha / size) * _lrn_window_sum(x * x, size, lo, hi)
    return x / _lrn_denom(z, beta, size, sqrt_pow), (x, z)


def _lrn_bwd(size, alpha, beta, k, sqrt_pow, res, g):
    x, z = res
    lo = (size - 1) // 2
    hi = size - 1 - lo
    denom = _lrn_denom(z, beta, size, sqrt_pow)
    # g*y/z^  — y recomputed as x/denom; z^{-beta-1} = 1/(z*denom)
    t = _lrn_window_sum(g * x / (z * denom), size, hi, lo)  # flipped window
    dx = g / denom - (2.0 * alpha * beta / size) * x * t
    return (dx,)


_lrn.defvjp(_lrn_fwd, _lrn_bwd)


def _gaussian_kernel(kernel_size: int) -> np.ndarray:
    """Normalized 2D gaussian, like image.gaussian in Torch."""
    sigma = 0.25 * kernel_size  # torch default sigma=0.25 relative to size
    xs = np.arange(kernel_size, dtype=np.float64)
    c = (kernel_size - 1) / 2.0
    g = np.exp(-((xs - c) ** 2) / (2 * sigma ** 2))
    k2 = np.outer(g, g)
    return (k2 / k2.sum()).astype(np.float32)


class SpatialSubtractiveNormalization(TensorModule):
    """Subtract a kernel-weighted local mean
    (ref SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        self.n_input_plane = n_input_plane
        if kernel is None:
            kernel = _gaussian_kernel(9)
        kernel = np.asarray(kernel, np.float32)
        if kernel.ndim == 1:
            kernel = np.outer(kernel, kernel)
        self.kernel = kernel / (kernel.sum() * n_input_plane)
        self.kh, self.kw = self.kernel.shape

    def _conv_sum(self, x):
        """Zero-padded cross-channel correlation with the normalized kernel:
        the reference's ``meanestimator`` conv stage
        (SpatialZeroPadding + SpatialConvolution(C,1) + Replicate,
        SpatialSubtractiveNormalization.scala:69-78) — one map shared by
        all channels, returned broadcastable as (N,1,H,W)."""
        n, c, h, w = x.shape
        k = jnp.asarray(self.kernel)[None, None].repeat(c, axis=1)  # (1,C,kh,kw)
        ph, pw = (self.kh - 1) // 2, (self.kw - 1) // 2
        pad = [(ph, self.kh - 1 - ph), (pw, self.kw - 1 - pw)]
        return lax.conv_general_dilated(
            x, k, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def _coef(self, x):
        """Border-mass map: the conv applied to ones
        (the reference's ``coef``, SpatialSubtractiveNormalization.scala:112-121)."""
        ones = jnp.ones((1,) + x.shape[1:], x.dtype)
        return self._conv_sum(ones)

    def _local_mean(self, x):
        return self._conv_sum(x) / self._coef(x)

    def _forward(self, P, x, S, ctx):
        was3d = x.ndim == 3
        if was3d:
            x = x[None]
        y = x - self._local_mean(x)
        return (y[0] if was3d else y), None


class SpatialDivisiveNormalization(TensorModule):
    """Divide by the coef-adjusted local std-dev estimate, floored by
    Threshold(threshold, thresval)
    (ref SpatialDivisiveNormalization.scala:114-136:
    ``localstds = sqrt(conv(x^2))``, ``adjustedstds = localstds / coef``,
    ``out = x / Threshold(adjustedstds)``; the division by the border
    mass happens AFTER the sqrt, and there is no mean-std clause)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.threshold = threshold
        self.thresval = thresval

    def _forward(self, P, x, S, ctx):
        was3d = x.ndim == 3
        if was3d:
            x = x[None]
        local_std = jnp.sqrt(jnp.maximum(self.sub._conv_sum(x * x), 0.0))
        adjusted = local_std / self.sub._coef(x)
        denom = jnp.where(adjusted > self.threshold, adjusted, self.thresval)
        y = x / denom
        return (y[0] if was3d else y), None


class SpatialContrastiveNormalization(TensorModule):
    """Subtractive then divisive normalization
    (ref SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def _forward(self, P, x, S, ctx):
        y, _ = self.sub._forward(P, x, S, ctx)
        return self.div._forward(P, y, S, ctx)
