"""Weight initialization methods (ref nn/InitializationMethod.scala:23).

The reference offers Default (per-layer Torch-style fan scaling), Xavier and
BilinearFiller; each layer's ``reset()`` draws from the global RNG so model
construction is reproducible under ``set_seed``.
"""
from __future__ import annotations

import numpy as np

from bigdl_tpu.utils.random import RNG


class InitializationMethod:
    DEFAULT = "default"
    XAVIER = "xavier"
    BILINEAR_FILLER = "bilinearfiller"
    MSRA = "msra"  # He init, used by the reference's ResNet (models/resnet/ResNet.scala:102)


Default = InitializationMethod.DEFAULT
Xavier = InitializationMethod.XAVIER
BilinearFiller = InitializationMethod.BILINEAR_FILLER
MSRA = InitializationMethod.MSRA


def uniform(shape, a, b):
    return RNG.uniform(a, b, size=shape).astype(np.float32)


def normal(shape, mean, stdv):
    return RNG.normal(mean, stdv, size=shape).astype(np.float32)


def default_linear(shape, fan_in):
    """Torch nn.Linear default: U(-1/sqrt(fanIn), 1/sqrt(fanIn))."""
    stdv = 1.0 / np.sqrt(fan_in)
    return uniform(shape, -stdv, stdv)


def xavier(shape, fan_in, fan_out):
    stdv = np.sqrt(6.0 / (fan_in + fan_out))
    return uniform(shape, -stdv, stdv)


def msra(shape, fan_out_spatial):
    """He/MSRA: N(0, sqrt(2/n)) (ref ResNet.modelInit ResNet.scala:102-132)."""
    return normal(shape, 0.0, np.sqrt(2.0 / fan_out_spatial))


def bilinear_filler(shape):
    """Bilinear upsampling kernel for deconvolution
    (ref InitializationMethod BilinearFiller, used by SpatialFullConvolution)."""
    assert len(shape) == 4, "bilinear filler expects (out, in, kh, kw)"
    kh, kw = shape[2], shape[3]
    f_h, f_w = np.ceil(kh / 2.0), np.ceil(kw / 2.0)
    c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
    ys = np.arange(kh)[:, None]
    xs = np.arange(kw)[None, :]
    k = (1 - np.abs(ys / f_h - c_h)) * (1 - np.abs(xs / f_w - c_w))
    return np.broadcast_to(k, shape).astype(np.float32).copy()
