"""Linear-algebra layers (SURVEY.md §2.3 "Linear-algebra layers"):
Linear, Bilinear, CMul, CAdd, Mul, Add, MulConstant, AddConstant, MM, MV,
Cosine, Euclidean, LookupTable.

Matmuls go through one dot chokepoint (``_dot``) with the bf16 compute
policy — the TPU-native equivalent of the reference's single-gemm design
(DenseTensorBLAS.gemm, DenseTensorBLAS.scala:70 → MKL vsgemm mkl.c:408),
where every layer funnels into one tuned kernel.  Here the kernel is the
MXU via XLA dot_general.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule, Module
from bigdl_tpu.nn import init as init_
from bigdl_tpu.tensor import policy
from bigdl_tpu.utils.table import Table


def _dot(a, b):
    """Single matmul chokepoint: cast per dtype policy (bf16 feeds the MXU;
    accumulation is f32 inside the MXU), output cast back."""
    p = policy()
    return jnp.matmul(p.cast_compute(a), p.cast_compute(b)).astype(p.output_dtype)


class Linear(TensorModule):
    """y = x W^T + b (ref Linear.scala:~40, gemm path :103-136)."""

    #: quantized-serving declaration (bigdl_tpu/quant/weights.py):
    #: param name -> (output-channel axis, input-channel axis) of the
    #: leaf.  weight is (out, in).
    quant_spec = {"weight": (0, 1)}

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 init_method: str = init_.Default):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.init_method = init_method
        self.reset()

    def reset(self):
        if self.init_method == init_.Xavier:
            w = init_.xavier((self.output_size, self.input_size),
                             self.input_size, self.output_size)
            b = np.zeros((self.output_size,), np.float32)
        else:
            w = init_.default_linear((self.output_size, self.input_size),
                                     self.input_size)
            b = init_.default_linear((self.output_size,), self.input_size)
        self._add_param("weight", w)
        if self.with_bias:
            self._add_param("bias", b)
        return self

    def _forward(self, P, x, S, ctx):
        y = _dot(x, P["weight"].T)
        if self.with_bias:
            y = y + P["bias"]
        return y, None

    def __repr__(self):
        return f"Linear({self.input_size} -> {self.output_size})"


class Bilinear(TensorModule):
    """y_k = x1^T W_k x2 + b_k over a Table(x1, x2) (ref Bilinear.scala)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True):
        super().__init__()
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.input_size1)
        self._add_param("weight", init_.uniform(
            (self.output_size, self.input_size1, self.input_size2), -stdv, stdv))
        if self.bias_res:
            self._add_param("bias", init_.uniform((self.output_size,), -stdv, stdv))
        return self

    def _forward(self, P, x, S, ctx):
        x1, x2 = x[1], x[2]
        # (n,i1) x (o,i1,i2) x (n,i2) -> (n,o)
        y = jnp.einsum("ni,oij,nj->no", x1, P["weight"], x2)
        if self.bias_res:
            y = y + P["bias"]
        return y, None


class CMul(TensorModule):
    """Learnable per-element scale, broadcast over batch (ref CMul.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)
        self.reset()

    def reset(self):
        n = int(np.prod(self.size))
        stdv = 1.0 / np.sqrt(n)
        self._add_param("weight", init_.uniform(self.size, -stdv, stdv))
        return self

    def _forward(self, P, x, S, ctx):
        return x * P["weight"], None


class CAdd(TensorModule):
    """Learnable per-element bias (ref CAdd.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)
        self.reset()

    def reset(self):
        n = int(np.prod(self.size))
        stdv = 1.0 / np.sqrt(n)
        self._add_param("bias", init_.uniform(self.size, -stdv, stdv))
        return self

    def _forward(self, P, x, S, ctx):
        return x + P["bias"], None


class Mul(TensorModule):
    """Single learnable scalar gain (ref Mul.scala)."""

    def __init__(self):
        super().__init__()
        self.reset()

    def reset(self):
        self._add_param("weight", init_.uniform((1,), -1.0, 1.0))
        return self

    def _forward(self, P, x, S, ctx):
        return x * P["weight"][0], None


class Add(TensorModule):
    """Learnable bias vector of ``input_size`` (ref Add.scala)."""

    def __init__(self, input_size: int, scalar: bool = False):
        super().__init__()
        self.input_size = 1 if scalar else input_size
        self.scalar = scalar
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.input_size)
        self._add_param("bias", init_.uniform((self.input_size,), -stdv, stdv))
        return self

    def _forward(self, P, x, S, ctx):
        b = P["bias"]
        return (x + b[0], None) if self.scalar else (x + b, None)


class MulConstant(TensorModule):
    def __init__(self, scalar: float, inplace: bool = False):
        super().__init__()
        self.scalar = scalar

    def _forward(self, P, x, S, ctx):
        return x * self.scalar, None


class AddConstant(TensorModule):
    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant_scalar = constant_scalar

    def _forward(self, P, x, S, ctx):
        return x + self.constant_scalar, None


class MM(Module):
    """Batch/plain matmul of Table(a, b) (ref MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a = trans_a
        self.trans_b = trans_b

    def _forward(self, P, x, S, ctx):
        a, b = x[1], x[2]
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return _dot(a, b), None


class MV(Module):
    """Matrix-vector product of Table(mat, vec), batched (ref MV.scala)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def _forward(self, P, x, S, ctx):
        m, v = x[1], x[2]
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), None


class Cosine(TensorModule):
    """Cosine similarity to each of ``output_size`` learned prototypes
    (ref Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.input_size)
        self._add_param("weight", init_.uniform(
            (self.output_size, self.input_size), -stdv, stdv))
        return self

    def _forward(self, P, x, S, ctx):
        w = P["weight"]
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        wn = w / (jnp.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
        return _dot(xn, wn.T), None


class Euclidean(TensorModule):
    """Euclidean distance to each learned prototype (ref Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int, fast_backward: bool = True):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.input_size)
        self._add_param("weight", init_.uniform(
            (self.input_size, self.output_size), -stdv, stdv))
        return self

    def _forward(self, P, x, S, ctx):
        w = P["weight"]  # (in, out)
        diff = x[..., :, None] - w[None, :, :]
        return jnp.linalg.norm(diff, axis=-2), None


class LookupTable(TensorModule):
    """Embedding lookup with optional max-norm renorm
    (ref LookupTable.scala:273).  Indices are 1-based, like Torch."""

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0,
                 max_norm: float = None, norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False):
        super().__init__()
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.reset()

    def reset(self):
        self._add_param("weight", init_.normal((self.n_index, self.n_output), 0, 1))
        return self

    def _forward(self, P, x, S, ctx):
        w = P["weight"]
        if self.max_norm is not None:
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1, keepdims=True)
            scale = jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
            w = w * scale
        idx = jnp.asarray(x, jnp.int32) - 1  # 1-based -> 0-based
        return jnp.take(w, idx, axis=0), None
