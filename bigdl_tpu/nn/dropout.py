"""Regularization layers (ref Dropout.scala:31, L1Penalty.scala).

Dropout's Bernoulli mask comes from the ctx PRNG key stream — the pure-
functional equivalent of the reference's thread-local Mersenne draws
(Dropout.scala threads over Engine.model; XLA fuses the masked multiply).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule


class Dropout(TensorModule):
    """Zero with prob ``init_p``; scale kept units by 1/(1-p) in training
    (inverted dropout, matching the reference's scale-at-train default)."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale

    def set_p(self, p):
        self.p = p
        return self

    def _forward(self, P, x, S, ctx):
        if not ctx.training or self.p <= 0.0:
            return x, None
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.next_key(), keep, x.shape)
        # barrier = "store the mask, don't recompute it": without it XLA
        # rematerializes the whole threefry mask generation inside the
        # BACKWARD's eltwise fusions (measured: 6 extra ~0.7 ms kLoop
        # fusions on the transformer flagship; device-busy 44.1 -> 37.5
        # ms/step with the barrier, PERF_NOTES round 4).  The stored pred
        # mask is bit-packed and tiny next to the activations; semantics
        # are identical (the barrier is an identity)
        mask = jax.lax.optimization_barrier(mask)
        y = jnp.where(mask, x, 0.0)
        if self.scale:
            y = y / keep
        return y, None

    def __repr__(self):
        return f"Dropout({self.p})"


class L1Penalty(TensorModule):
    """Identity forward; adds l1 subgradient in backward
    (ref L1Penalty.scala).  Implemented with a custom VJP so trainers using
    ``jax.grad`` see the same effect."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = l1weight
        self.size_average = size_average

    def _forward(self, P, x, S, ctx):
        w = self.l1weight
        avg = self.size_average

        @jax.custom_vjp
        def pen(v):
            return v

        def fwd(v):
            return v, v

        def bwd(v, g):
            m = w / v.size if avg else w
            return (g + m * jnp.sign(v),)

        pen.defvjp(fwd, bwd)
        return pen(x), None
