"""Reduction / indexing layers (SURVEY.md §2.3): Mean, Sum, Max, Min, Index,
Select, Narrow, MaskedSelect.  All dims 1-based per the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule, Module
from bigdl_tpu.tensor import narrow as _narrow, select as _select


class Mean(TensorModule):
    """(ref Mean.scala) mean over 1-based ``dimension``; ``n_input_dims``
    shifts for batched input; ``squeeze`` drops the reduced dim."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def _axis(self, x):
        d = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += 1
        return d

    def _forward(self, P, x, S, ctx):
        return x.mean(axis=self._axis(x), keepdims=not self.squeeze), None


class Sum(TensorModule):
    """(ref Sum.scala) with optional ``size_average`` divide by dim size."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.size_average = size_average
        self.squeeze = squeeze

    def _forward(self, P, x, S, ctx):
        d = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += 1
        y = x.sum(axis=d, keepdims=not self.squeeze)
        if self.size_average:
            y = y / x.shape[d]
        return y, None


class Max(TensorModule):
    """Max over ``dim``, returning values (ref Max.scala returns max;
    indices available via ``Index``)."""

    def __init__(self, dim: int = 1, num_input_dims: int = None):
        super().__init__()
        self.dim = dim

    def _forward(self, P, x, S, ctx):
        return x.max(axis=self.dim - 1), None


class Min(TensorModule):
    def __init__(self, dim: int = 1, num_input_dims: int = None):
        super().__init__()
        self.dim = dim

    def _forward(self, P, x, S, ctx):
        return x.min(axis=self.dim - 1), None


class Index(Module):
    """Gather rows: Table(src, indices 1-based) -> src indexed along ``dim``
    (ref Index.scala)."""

    def __init__(self, dimension: int = 1):
        super().__init__()
        self.dimension = dimension

    def _forward(self, P, x, S, ctx):
        src, idx = x[1], x[2]
        idx = jnp.asarray(idx, jnp.int32) - 1
        return jnp.take(src, idx, axis=self.dimension - 1), None


class Select(TensorModule):
    """Select 1-based ``index`` along 1-based ``dim`` (ref Select.scala);
    negative index counts from the end."""

    def __init__(self, dimension: int, index: int):
        super().__init__()
        self.dimension = dimension
        self.index = index

    def _forward(self, P, x, S, ctx):
        idx = self.index if self.index > 0 else x.shape[self.dimension - 1] + self.index + 1
        return _select(x, self.dimension, idx), None


class Narrow(TensorModule):
    """Slice ``length`` entries from 1-based ``offset`` along ``dimension``
    (ref Narrow.scala); negative length counts from the end."""

    def __init__(self, dimension: int, offset: int, length: int = 1):
        super().__init__()
        self.dimension = dimension
        self.offset = offset
        self.length = length

    def _forward(self, P, x, S, ctx):
        n = self.length
        if n < 0:
            n = x.shape[self.dimension - 1] - self.offset + 2 + n
        return _narrow(x, self.dimension, self.offset, n), None


class MaskedSelect(Module):
    """Table(src, byte mask) -> 1D tensor of selected elements
    (ref MaskedSelect.scala).

    XLA constraint: the output size is data-dependent, which cannot live
    under jit with static shapes.  Eager use returns the compact vector;
    under jit, wrap with a fixed-size pad or avoid (documented divergence).
    """

    def _forward(self, P, x, S, ctx):
        src, mask = x[1], x[2]
        import numpy as np
        return jnp.asarray(np.asarray(src)[np.asarray(mask) != 0]), None
