"""Core module system: Torch ergonomics over a pure functional JAX core.

Reference contract (nn/abstractnn/AbstractModule.scala:41):
  forward(input)                -> output            (timed)
  backward(input, gradOutput)   -> gradInput + accumulates param grads (timed)
  parameters()                  -> (weights, gradWeights)
  getParameters()               -> flattened (weight, grad) vectors
  zeroGradParameters / training / evaluate / clearState / cloneModule

TPU-first redesign: the reference implements ~20k LoC of hand-written
``updateGradInput``/``accGradParameters`` pairs; here every layer defines a
single pure function and gradients come from ``jax.vjp``.  Each module
exposes:

  _forward(P, x, S, ctx) -> (y, new_S | None)     # leaf layers override
  apply(params, x, state, ctx) -> (y, new_state)  # containers override

where ``params``/``state`` are pytrees mirroring the module tree
(``{'~': own_dict, child_name: child_tree, ...}``), ``ctx`` carries the
training flag and a PRNG key stream.  Trainers jit ``apply`` directly; the
eager ``forward``/``backward`` wrappers reproduce the reference's mutable
ergonomics on top of it.

Activity (= Tensor | Table, abstractnn/Activity.scala:26): inputs/outputs may
be jnp arrays, Tables, or any pytree — everything here is pytree-polymorphic.
"""
from __future__ import annotations

import contextlib
import copy
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.utils.random import RNG
from bigdl_tpu.tensor import default_dtype


class Context:
    """Per-call context threaded through ``apply``: train/eval mode + RNG.

    The key stream is split deterministically at trace time, so the same
    ``apply`` traced under jit produces the same key-derivation graph.

    ``seq_mesh``/``seq_axis``: set by a sequence-parallel trainer
    (``DistriOptimizer(sequence_parallel=True)``); attention layers read
    them to route through the exact ring-attention collective instead of
    the single-device softmax (``nn/attention.py``).
    """

    __slots__ = ("training", "key", "seq_mesh", "seq_axis")

    def __init__(self, training: bool = False, key=None, seq_mesh=None,
                 seq_axis: str = "seq"):
        self.training = training
        self.key = key
        self.seq_mesh = seq_mesh
        self.seq_axis = seq_axis

    def next_key(self):
        if self.key is None:
            # Eager-mode convenience; inside jit always pass a key in.
            self.key = RNG.next_key()
        self.key, sub = jax.random.split(self.key)
        return sub


@contextlib.contextmanager
def stripped_caches(module):
    """Temporarily remove ``_cached_*`` attrs (jitted fn wrappers) from the
    module tree: they must never be deep-copied or pickled.  Shared by
    ``Module.clone_module`` and checkpoint pickling
    (utils/file._pickle_architecture)."""
    stash = []

    def pop(mod):
        cached = {k: mod.__dict__.pop(k) for k in list(mod.__dict__)
                  if k.startswith("_cached_")}
        stash.append((mod, cached))
        for child in mod._modules.values():
            pop(child)

    pop(module)
    try:
        yield
    finally:
        for mod, cached in stash:
            mod.__dict__.update(cached)


def _tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


class Module:
    """Base class for all layers (ref AbstractModule.scala:41)."""

    def __init__(self):
        self._params: "OrderedDict[str, jnp.ndarray]" = OrderedDict()
        self._buffers: "OrderedDict[str, jnp.ndarray]" = OrderedDict()
        self._grads: "OrderedDict[str, jnp.ndarray]" = OrderedDict()
        self._modules: "OrderedDict[str, Module]" = OrderedDict()
        self.training_mode = True
        self.output = None
        self.grad_input = None
        # per-module wall-clock profiling (ref AbstractModule.scala:125-136)
        self.forward_time = 0.0
        self.backward_time = 0.0
        self._last_key = None
        self.name = None

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def _add_param(self, name, value):
        value = jnp.asarray(value, dtype=default_dtype())
        self._params[name] = value
        self._grads[name] = jnp.zeros_like(value)
        return value

    def _add_buffer(self, name, value):
        value = jnp.asarray(value)
        self._buffers[name] = value
        return value

    def __getstate__(self):
        # the validator/serve eval-fn cache (optim._eval_fn) holds a
        # jitted closure: process-local by nature and unpicklable.
        # Dropping it here keeps a model that has been validated or
        # served in-process shippable to a subprocess replica
        # (serve/cluster.ProcessReplica pickles the model at spawn).
        state = dict(self.__dict__)
        state.pop("_cached_eval_fn", None)
        return state

    def set_name(self, name):
        self.name = name
        return self

    def get_name(self):
        return self.name if self.name is not None else type(self).__name__

    # ------------------------------------------------------------------
    # pytree extraction / loading
    # ------------------------------------------------------------------
    def params(self):
        tree = {"~": dict(self._params)}
        for name, m in self._modules.items():
            tree[name] = m.params()
        return tree

    def state(self):
        tree = {"~": dict(self._buffers)}
        for name, m in self._modules.items():
            tree[name] = m.state()
        return tree

    def grads(self):
        tree = {"~": dict(self._grads)}
        for name, m in self._modules.items():
            tree[name] = m.grads()
        return tree

    def load_params(self, tree):
        for k, v in tree.get("~", {}).items():
            self._params[k] = jnp.asarray(v)
        for name, m in self._modules.items():
            if name in tree:
                m.load_params(tree[name])
        return self

    def load_state(self, tree):
        for k, v in tree.get("~", {}).items():
            self._buffers[k] = jnp.asarray(v)
        for name, m in self._modules.items():
            if name in tree:
                m.load_state(tree[name])
        return self

    def load_grads(self, tree):
        for k, v in tree.get("~", {}).items():
            self._grads[k] = jnp.asarray(v)
        for name, m in self._modules.items():
            if name in tree:
                m.load_grads(tree[name])
        return self

    # ------------------------------------------------------------------
    # pure functional path (what trainers jit)
    # ------------------------------------------------------------------
    def _forward(self, P, x, S, ctx):
        """Leaf computation. Override in subclasses.

        P: own param dict; S: own buffer dict; returns (y, new_S or None).
        """
        raise NotImplementedError(type(self).__name__)

    def apply(self, params, x, state, ctx):
        y, new_own = self._forward(params.get("~", {}), x, state.get("~", {}), ctx)
        if new_own is None:
            return y, state
        ns = dict(state)
        ns["~"] = new_own
        return y, ns

    # ------------------------------------------------------------------
    # eager Torch-style path (ref forward/backward AbstractModule.scala:145-170)
    # ------------------------------------------------------------------
    def forward(self, x):
        t0 = time.perf_counter()
        self._last_key = RNG.next_key() if self.training_mode else jax.random.PRNGKey(0)
        ctx = Context(training=self.training_mode, key=self._last_key)
        y, new_state = self.apply(self.params(), x, self.state(), ctx)
        self.load_state(new_state)
        self.output = y
        self.forward_time += time.perf_counter() - t0
        return y

    def __call__(self, x):
        return self.forward(x)

    def update_output(self, x):
        return self.forward(x)

    def backward(self, x, grad_output):
        """Returns gradInput and accumulates parameter gradients
        (= updateGradInput + accGradParameters of the reference)."""
        t0 = time.perf_counter()
        ctx_key = self._last_key if self._last_key is not None else jax.random.PRNGKey(0)
        state = self.state()

        def f(p, inp):
            ctx = Context(training=self.training_mode, key=ctx_key)
            y, _ = self.apply(p, inp, state, ctx)
            return y

        _, vjp = jax.vjp(f, self.params(), x)
        gp, gx = vjp(grad_output)
        self.load_grads(_tree_add(self.grads(), gp))
        self.grad_input = gx
        self.backward_time += time.perf_counter() - t0
        return gx

    def update_grad_input(self, x, grad_output):
        """Input gradient only (no param-grad accumulation)."""
        ctx_key = self._last_key if self._last_key is not None else jax.random.PRNGKey(0)
        state = self.state()

        def f(inp):
            ctx = Context(training=self.training_mode, key=ctx_key)
            return self.apply(self.params(), inp, state, ctx)[0]

        _, vjp = jax.vjp(f, x)
        (gx,) = vjp(grad_output)
        self.grad_input = gx
        return gx

    def acc_grad_parameters(self, x, grad_output):
        ctx_key = self._last_key if self._last_key is not None else jax.random.PRNGKey(0)
        state = self.state()

        def f(p):
            ctx = Context(training=self.training_mode, key=ctx_key)
            return self.apply(p, x, state, ctx)[0]

        _, vjp = jax.vjp(f, self.params())
        (gp,) = vjp(grad_output)
        self.load_grads(_tree_add(self.grads(), gp))

    # ------------------------------------------------------------------
    # parameter access (ref parameters()/getParameters(), AbstractModule.scala:217-228)
    # ------------------------------------------------------------------
    def parameters(self):
        """(list of weight arrays, list of grad arrays), depth-first."""
        ws = list(self._params.values())
        gs = list(self._grads.values())
        for m in self._modules.values():
            w2, g2 = m.parameters()
            ws += w2
            gs += g2
        return ws, gs

    def get_parameters(self):
        """Flattened (weight, grad) vectors (ref Module.flatten Module.scala:42).

        Divergence from the reference: the returned vectors are snapshots,
        not live views — JAX arrays are immutable, so storage aliasing is
        impossible (and unnecessary: trainers operate on pytrees).
        """
        ws, gs = self.parameters()
        if not ws:
            return jnp.zeros((0,)), jnp.zeros((0,))
        return (jnp.concatenate([w.reshape(-1) for w in ws]),
                jnp.concatenate([g.reshape(-1) for g in gs]))

    def zero_grad_parameters(self):
        self._grads = OrderedDict((k, jnp.zeros_like(v)) for k, v in self._grads.items())
        for m in self._modules.values():
            m.zero_grad_parameters()
        return self

    def n_parameters(self):
        ws, _ = self.parameters()
        return sum(int(np.prod(w.shape)) for w in ws)

    # ------------------------------------------------------------------
    # mode / lifecycle (ref AbstractModule.scala:248-287)
    # ------------------------------------------------------------------
    def training(self):
        self.training_mode = True
        for m in self._modules.values():
            m.training()
        return self

    def evaluate(self):
        self.training_mode = False
        for m in self._modules.values():
            m.evaluate()
        return self

    def is_training(self):
        return self.training_mode

    def clear_state(self):
        self.output = None
        self.grad_input = None
        for m in self._modules.values():
            m.clear_state()
        return self

    def clone_module(self):
        # strip cached jitted fns BEFORE the copy: avoids deep-copying jax
        # function wrappers (and depending on them supporting deepcopy)
        with stripped_caches(self):
            return copy.deepcopy(self)

    def copy_status(self, src: "Module"):
        """Copy running-status buffers (e.g. BN stats) from ``src``
        (ref AbstractModule.copyStatus:65)."""
        self.load_state(src.state())
        return self

    def reset(self):
        """Re-initialize parameters. Layers with params override."""
        for m in self._modules.values():
            m.reset()
        return self

    def reset_times(self):
        self.forward_time = 0.0
        self.backward_time = 0.0
        for m in self._modules.values():
            m.reset_times()

    def get_times(self):
        """[(module, forward_s, backward_s)] recursively
        (ref Container.getTimes Container.scala:71)."""
        out = [(self, self.forward_time, self.backward_time)]
        for m in self._modules.values():
            out += m.get_times()
        return out

    # -- persistence (ref AbstractModule.save:306; utils/File.scala) ------
    def save(self, path, overwrite=True):
        from bigdl_tpu.utils import file as File
        File.save_module(self, path, overwrite=overwrite)
        return self

    def predict(self, x):
        was_training = self.training_mode
        self.evaluate()
        out = self.forward(x)
        if was_training:
            self.training()
        return out

    def __repr__(self):
        return f"{type(self).__name__}()"


class TensorModule(Module):
    """Marker base for modules mapping Tensor -> Tensor (ref TensorModule)."""


class Container(Module):
    """Base for modules holding submodules (ref Container.scala:30)."""

    def __init__(self, *modules):
        super().__init__()
        for m in modules:
            self.add(m)

    def add(self, module: Module):
        self._modules[str(len(self._modules))] = module
        return self

    @property
    def modules(self):
        return list(self._modules.values())

    def __len__(self):
        return len(self._modules)

    def get(self, index: int):
        """1-based indexing, like Torch ``container:get(i)``."""
        return self.modules[index - 1]

    def __repr__(self):
        inner = "\n".join(
            "  " + repr(m).replace("\n", "\n  ") for m in self.modules)
        return f"{type(self).__name__} {{\n{inner}\n}}"


class Criterion:
    """Loss base (ref abstractnn/AbstractCriterion.scala).

    ``apply_loss(input, target) -> scalar`` is the pure function; eager
    ``forward``/``backward`` mirror the reference contract.
    """

    def __init__(self, size_average: bool = True):
        self.size_average = size_average
        self.output = None
        self.grad_input = None

    def apply_loss(self, input, target):
        raise NotImplementedError(type(self).__name__)

    def forward(self, input, target):
        self.output = self.apply_loss(input, target)
        return self.output

    def __call__(self, input, target):
        return self.forward(input, target)

    def backward(self, input, target):
        self.grad_input = jax.grad(lambda i: self.apply_loss(i, target))(input)
        return self.grad_input

    def clone_criterion(self):
        return copy.deepcopy(self)

    def __repr__(self):
        return f"{type(self).__name__}()"
