"""Pooling layers (ref SpatialMaxPooling.scala:279, SpatialAveragePooling.scala:458,
RoiPooling.scala:363).

The reference hand-writes strided window loops (NNPrimitive.scala maxpool
:357-499); here ``lax.reduce_window`` compiles to fused TPU window
reductions.  Ceil-mode output sizing matches Torch semantics: the last
window may start in the padded region but must begin before the end of the
real input + left padding.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import TensorModule, Module
from bigdl_tpu.tensor import policy


_COMPUTE_DTYPE_POOL = True  # run max pools in the policy compute dtype
_RESHAPE_POOL = True  # exact non-overlapping max pools via reshape+max
_SEPARABLE_POOL = False  # kxk max pool as (1,k)+(k,1) passes (A/B, r5)
_NHWC_POOL = False  # windowed pools transposed to NHWC (A/B, r5)
# Round-6 Mosaic kernel pair (ops/pallas_kernels.mosaic_maxpool2d):
# argmax-storing forward + scatter-free gather backward replacing
# select_and_scatter, C-on-lanes layout, strides via index maps + phase
# folding.  DEFAULT OFF pending a device-clock A/B win (the adoption
# rule every pool formulation has had to meet — PERF_NOTES round 6);
# "interpret" forces the Pallas interpreter on any backend (tests).
_PALLAS_POOL = False


def _max_pool2d(x, window, strides, padding):
    """Max pooling over NCHW via lax.reduce_window.

    The backward is XLA's default select-and-scatter VJP.  Measured
    alternatives on v5e (tools/ab_pool_lrn.py, PERF_NOTES.md): a custom
    gather-stencil VJP with tie-splitting was 1.1-4x SLOWER on every
    Inception pool shape in both f32 and bf16 — select-and-scatter on TPU
    already runs near HBM bandwidth, so it is kept.

    Under a reduced-precision compute policy the pool runs in the
    COMPUTE dtype (max of bf16 values = bf16 of the f32 max, so only
    rounding-level tie routing can differ): the window ops are pure
    bandwidth, and halving the bytes measured 1.85x faster isolated
    (f32 0.349 -> bf16 0.189 ms on the 128x192x56x56 fwd+bwd) and
    -2.6 ms/step on Inception (PERF_NOTES round 4) — the same
    dtype decision the policy already makes for every matmul/conv
    operand.
    """
    kh, kw = window
    dh, dw = strides
    p = policy()
    # gate on a reduced-precision policy being ACTIVE, not on any dtype
    # mismatch: f64 inputs under the default FP32 policy must not be
    # silently downcast, and bf16 inputs must not be upcast
    cast = (_COMPUTE_DTYPE_POOL
            and p.compute_dtype != jnp.float32
            and p.compute_dtype != x.dtype
            and x.dtype == jnp.float32)
    xin = x.astype(p.compute_dtype) if cast else x
    n, c, h, w = xin.shape
    if _PALLAS_POOL:
        from bigdl_tpu.ops.pallas_kernels import mosaic_maxpool2d, _on_tpu
        interp = _PALLAS_POOL == "interpret"
        if interp or _on_tpu():
            y = mosaic_maxpool2d(xin, window, strides, padding, interp)
            return y.astype(x.dtype) if cast else y
    if (_RESHAPE_POOL and (kh, kw) == (dh, dw)
            and padding == ((0, 0), (0, 0))
            and h % kh == 0 and w % kw == 0):
        # Exact non-overlapping pool: windows tile the input, so the
        # reduce is a plain reshape+max — no window machinery forward,
        # and the backward is an eq-select instead of select_and_scatter.
        # Tie semantics: jnp.max's VJP SPLITS the cotangent EVENLY among
        # tied maxima (measured: an all-equal 2x2 window grads 0.25
        # each), where select_and_scatter routes the full value to one
        # element — an equally valid subgradient with the same
        # per-window mass; documented in porting guide #6.
        y = xin.reshape(n, c, h // kh, kh, w // kw, kw).max(axis=(3, 5))
    elif _SEPARABLE_POOL and kh > 1 and kw > 1:
        # separable rectangle: max over (kh,kw) == max over rows of the
        # max over columns; two 1-D windows whose select-and-scatter
        # backwards each route over k elements instead of k*k
        y = lax.reduce_window(
            xin, np.array(-np.inf, xin.dtype), lax.max,
            window_dimensions=(1, 1, 1, kw),
            window_strides=(1, 1, 1, dw),
            padding=((0, 0), (0, 0), (0, 0), padding[1]))
        y = lax.reduce_window(
            y, np.array(-np.inf, xin.dtype), lax.max,
            window_dimensions=(1, 1, kh, 1),
            window_strides=(1, 1, dh, 1),
            padding=((0, 0), (0, 0), padding[0], (0, 0)))
    elif _NHWC_POOL:
        # channels on the 128-wide lane dim instead of the (often
        # half-empty) W dim: the select-and-scatter backward is the
        # zero-FLOP bandwidth sink these layouts decide
        y = lax.reduce_window(
            xin.transpose(0, 2, 3, 1), np.array(-np.inf, xin.dtype),
            lax.max,
            window_dimensions=(1, kh, kw, 1),
            window_strides=(1, dh, dw, 1),
            padding=((0, 0),) + padding + ((0, 0),))
        y = y.transpose(0, 3, 1, 2)
    else:
        y = lax.reduce_window(
            xin, np.array(-np.inf, xin.dtype), lax.max,
            window_dimensions=(1, 1, kh, kw),
            window_strides=(1, 1, dh, dw),
            padding=((0, 0), (0, 0)) + padding)
    return y.astype(x.dtype) if cast else y


def _pool_out_size(in_size, k, stride, pad, ceil_mode):
    if ceil_mode:
        out = int(np.ceil(float(in_size - k + 2 * pad) / stride)) + 1
    else:
        out = int(np.floor(float(in_size - k + 2 * pad) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= in_size + pad:
        out -= 1  # last window must start inside input+left-pad (Torch rule)
    return out


def _pad_amounts(in_size, k, stride, pad, out):
    """(lo, hi) padding so reduce_window emits exactly ``out`` windows."""
    needed = (out - 1) * stride + k
    hi = max(needed - in_size - pad, 0)
    return pad, hi


class SpatialMaxPooling(TensorModule):
    def __init__(self, kw: int, kh: int, dw: int = None, dh: int = None,
                 pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _forward(self, P, x, S, ctx):
        was3d = x.ndim == 3
        if was3d:
            x = x[None]
        n, c, h, w = x.shape
        oh = _pool_out_size(h, self.kh, self.dh, self.pad_h, self.ceil_mode)
        ow = _pool_out_size(w, self.kw, self.dw, self.pad_w, self.ceil_mode)
        ph = _pad_amounts(h, self.kh, self.dh, self.pad_h, oh)
        pw = _pad_amounts(w, self.kw, self.dw, self.pad_w, ow)
        y = _max_pool2d(x, (self.kh, self.kw), (self.dh, self.dw), (ph, pw))
        return (y[0] if was3d else y), None

    def __repr__(self):
        return f"SpatialMaxPooling({self.kw}x{self.kh}, {self.dw},{self.dh})"


class SpatialAveragePooling(TensorModule):
    def __init__(self, kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0, ceil_mode: bool = False,
                 count_include_pad: bool = True, divide: bool = True):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide

    def ceil(self):
        self.ceil_mode = True
        return self

    def _forward(self, P, x, S, ctx):
        was3d = x.ndim == 3
        if was3d:
            x = x[None]
        n, c, h, w = x.shape
        oh = _pool_out_size(h, self.kh, self.dh, self.pad_h, self.ceil_mode)
        ow = _pool_out_size(w, self.kw, self.dw, self.pad_w, self.ceil_mode)
        ph = _pad_amounts(h, self.kh, self.dh, self.pad_h, oh)
        pw = _pad_amounts(w, self.kw, self.dw, self.pad_w, ow)

        def wsum(v):
            return lax.reduce_window(
                v, 0.0, lax.add,
                window_dimensions=(1, 1, self.kh, self.kw),
                window_strides=(1, 1, self.dh, self.dw),
                padding=((0, 0), (0, 0), ph, pw))

        y = wsum(x)
        if self.divide:
            if self.count_include_pad:
                y = y / float(self.kh * self.kw)
            else:
                ones = jnp.ones((1, 1, h, w), x.dtype)
                y = y / wsum(ones)
        return (y[0] if was3d else y), None

    def __repr__(self):
        return f"SpatialAveragePooling({self.kw}x{self.kh}, {self.dw},{self.dh})"


class RoiPooling(Module):
    """Region-of-interest max pooling (ref RoiPooling.scala:363).

    Input: Table(features (N,C,H,W), rois (R,5) rows [batchIdx(0-based,
    like the reference: RoiPooling.scala ``roiBatchInd >= 0 &&
    dataSize(0) > roiBatchInd``), x1, y1, x2, y2] in input-image coords
    scaled by ``spatial_scale``).  Output: (R, C, pooled_h, pooled_w).
    Coordinate rounding is the reference's ``Math.round`` = floor(x+0.5)
    (round-half-up, not banker's rounding).

    TPU-first note: the reference loops over variable-sized bins; here each
    ROI bin is computed by masked max over the full feature map, keeping
    shapes static for XLA (R is the only batch-like dim).
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float):
        super().__init__()
        self.pooled_w = pooled_w
        self.pooled_h = pooled_h
        self.spatial_scale = spatial_scale

    def _forward(self, P, x, S, ctx):
        data, rois = x[1], x[2]
        n, c, h, w = data.shape
        r = rois.shape[0]
        batch_idx = jnp.asarray(rois[:, 0], jnp.int32)
        x1 = jnp.floor(rois[:, 1] * self.spatial_scale + 0.5)
        y1 = jnp.floor(rois[:, 2] * self.spatial_scale + 0.5)
        x2 = jnp.floor(rois[:, 3] * self.spatial_scale + 0.5)
        y2 = jnp.floor(rois[:, 4] * self.spatial_scale + 0.5)
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = roi_w / self.pooled_w
        bin_h = roi_h / self.pooled_h

        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        ph = jnp.arange(self.pooled_h, dtype=jnp.float32)
        pw = jnp.arange(self.pooled_w, dtype=jnp.float32)

        # bin bounds: (R, PH) and (R, PW)
        h_start = jnp.clip(jnp.floor(ph[None] * bin_h[:, None] + y1[:, None]), 0, h)
        h_end = jnp.clip(jnp.ceil((ph[None] + 1) * bin_h[:, None] + y1[:, None]), 0, h)
        w_start = jnp.clip(jnp.floor(pw[None] * bin_w[:, None] + x1[:, None]), 0, w)
        w_end = jnp.clip(jnp.ceil((pw[None] + 1) * bin_w[:, None] + x1[:, None]), 0, w)

        hmask = (ys[None, None] >= h_start[..., None]) & (ys[None, None] < h_end[..., None])  # (R,PH,H)
        wmask = (xs[None, None] >= w_start[..., None]) & (xs[None, None] < w_end[..., None])  # (R,PW,W)
        feats = data[batch_idx]  # (R,C,H,W)
        masked = (feats[:, None, None] +
                  jnp.where(hmask[:, :, None, None, :, None] & wmask[:, None, :, None, None, :],
                            0.0, -jnp.inf))  # (R,PH,PW,C,H,W)
        out = masked.max(axis=(-1, -2))  # (R,PH,PW,C)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
        return jnp.transpose(out, (0, 3, 1, 2)), None
