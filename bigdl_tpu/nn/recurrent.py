"""Recurrence (ref Recurrent.scala:27, RNN.scala:28, TimeDistributed.scala).

The reference's ``Recurrent`` container runs a serial Scala time loop with
truncated BPTT (bpttTruncate, Recurrent.scala:66-110).  TPU-native design:
the time loop is ``lax.scan`` — one compiled region, weights resident in
HBM, per-step matmuls batched onto the MXU.  Truncated BPTT maps to chunked
scans with ``stop_gradient`` on the carry at chunk boundaries.

The reference ships only the vanilla ``RnnCell``; BASELINE.json config 4
("Bi-LSTM text classifier ... recurrence via scan") additionally requires
LSTM and bidirectional wrappers, provided here as ``LSTMCell``, ``GRUCell``
and ``BiRecurrent``.

Layout: batch-first (N, T, D) input; hidden state (N, H).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module, TensorModule, Container, Context
from bigdl_tpu.nn import init as init_
from bigdl_tpu.nn.activations import Tanh
from bigdl_tpu.tensor import policy
from bigdl_tpu.utils.table import Table


class Cell(Module):
    """Recurrent cell protocol: ``_step(P, x_t, h, ctx) -> (out_t, h_new)``
    where ``h`` is an array or a tuple of arrays (LSTM)."""

    hidden_size: int

    def init_hidden(self, batch):
        return jnp.zeros((batch, self.hidden_size))

    def _step(self, P, x, h, ctx):
        raise NotImplementedError

    def _forward(self, P, x, S, ctx):
        # standalone use: input Table(x, h) -> h' (ref RnnCell contract)
        out, h = self._step(P, x[1], x[2], ctx)
        return out, None


class RnnCell(Cell):
    """Vanilla RNN: h' = act(W_i x + b_i + W_h h + b_h) (ref RNN.scala:28)."""

    def __init__(self, input_size: int, hidden_size: int, activation=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation if activation is not None else Tanh()
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.hidden_size)
        self._add_param("i2h", init_.uniform((self.hidden_size, self.input_size), -stdv, stdv))
        self._add_param("h2h", init_.uniform((self.hidden_size, self.hidden_size), -stdv, stdv))
        self._add_param("bias_i", init_.uniform((self.hidden_size,), -stdv, stdv))
        self._add_param("bias_h", init_.uniform((self.hidden_size,), -stdv, stdv))
        return self

    def _step(self, P, x, h, ctx):
        p = policy()
        pre = (jnp.matmul(p.cast_compute(x), p.cast_compute(P["i2h"].T),
                          preferred_element_type=jnp.float32) + P["bias_i"] +
               jnp.matmul(p.cast_compute(h), p.cast_compute(P["h2h"].T),
                          preferred_element_type=jnp.float32) + P["bias_h"])
        h_new = self.activation._fn(pre.astype(p.output_dtype), ctx)
        return h_new, h_new


class LSTMCell(Cell):
    """Standard LSTM cell; hidden is (h, c).  One fused (4H, D+H) gemm per
    step keeps the MXU busy."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.hidden_size)
        h, d = self.hidden_size, self.input_size
        self._add_param("w", init_.uniform((4 * h, d + h), -stdv, stdv))
        self._add_param("bias", init_.uniform((4 * h,), -stdv, stdv))
        return self

    def init_hidden(self, batch):
        z = jnp.zeros((batch, self.hidden_size))
        return (z, z)

    def _step(self, P, x, hc, ctx):
        h, c = hc
        p = policy()
        z = jnp.matmul(p.cast_compute(jnp.concatenate([x, h], axis=-1)),
                       p.cast_compute(P["w"].T),
                       preferred_element_type=jnp.float32) + P["bias"]
        z = z.astype(p.output_dtype)
        return self._gates(z, c)

    @staticmethod
    def _gates(z, c):
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    # NOTE (measured, PERF_NOTES round 2): splitting the cell gemm into a
    # precomputed (N*T, D) input projection + an (N, H) recurrent gemm in
    # the scan body ran 40% SLOWER than this single concat-gemm per step on
    # v5e (21.3 vs 15.3 ms fwd at B128 T500 D200 H128) — the per-step cost
    # is launch/latency-dominated, so shrinking the matmul buys nothing and
    # the projected activations add 260 MB of HBM traffic.  A full Pallas
    # scan kernel (ops/pallas_kernels.lstm_scan) measured within 1% of
    # lax.scan.  Both alternatives retired; lax.scan over this cell stands.


class GRUCell(Cell):
    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.hidden_size)
        h, d = self.hidden_size, self.input_size
        self._add_param("w_rz", init_.uniform((2 * h, d + h), -stdv, stdv))
        self._add_param("b_rz", init_.uniform((2 * h,), -stdv, stdv))
        self._add_param("w_h", init_.uniform((h, d + h), -stdv, stdv))
        self._add_param("b_h", init_.uniform((h,), -stdv, stdv))
        return self

    def _step(self, P, x, h, ctx):
        xh = jnp.concatenate([x, h], axis=-1)
        rz = jax.nn.sigmoid(jnp.matmul(xh, P["w_rz"].T) + P["b_rz"])
        r, z = jnp.split(rz, 2, axis=-1)
        xrh = jnp.concatenate([x, r * h], axis=-1)
        n = jnp.tanh(jnp.matmul(xrh, P["w_h"].T) + P["b_h"])
        h_new = (1 - z) * n + z * h
        return h_new, h_new


class Recurrent(Container):
    """Time-loop container (ref Recurrent.scala:27).

    ``Recurrent().add(cell)``; forward over (N, T, D) returns (N, T, H).
    ``bptt_truncate > 0`` stops gradients at chunk boundaries (the scan
    equivalent of the reference's truncated backward loop).
    ``reverse=True`` scans right-to-left (for BiRecurrent).
    """

    def __init__(self, bptt_truncate: int = 0, reverse: bool = False):
        super().__init__()
        self.bptt_truncate = int(bptt_truncate)
        self.reverse = reverse

    @property
    def cell(self) -> Cell:
        return self.modules[0]

    def apply(self, params, x, state, ctx):
        cell = self.cell
        cp = params["0"]["~"]  # cells keep all params in their own dict
        cs = state["0"]
        n, t = x.shape[0], x.shape[1]
        h0 = cell.init_hidden(n)
        xs = jnp.swapaxes(x, 0, 1)  # (T, N, D) scan-major
        if self.reverse:
            xs = jnp.flip(xs, axis=0)
        key = ctx.next_key() if ctx.training else jax.random.PRNGKey(0)

        def step(carry, x_t):
            h, k = carry
            k, sub = jax.random.split(k)
            sctx = Context(training=ctx.training, key=sub)
            out, h_new = cell._step(cp, x_t, h, sctx)
            return (h_new, k), out

        k = self.bptt_truncate
        if k <= 0 or k >= t:
            (_, _), outs = lax.scan(step, (h0, key), xs)
        else:
            # chunked scan; stop_gradient on the carry between chunks
            outs_list = []
            carry = (h0, key)
            for start in range(0, t, k):
                chunk = xs[start:start + k]
                carry, o = lax.scan(step, carry, chunk)
                h_c, k_c = carry
                carry = (jax.tree_util.tree_map(lax.stop_gradient, h_c), k_c)
                outs_list.append(o)
            outs = jnp.concatenate(outs_list, axis=0)
        if self.reverse:
            outs = jnp.flip(outs, axis=0)
        return jnp.swapaxes(outs, 0, 1), state


class BiRecurrent(Container):
    """Bidirectional wrapper: runs a forward and a backward Recurrent over
    the same input and merges (concat on feature dim, or add).  Not in the
    reference (capability extension for BASELINE config 4 Bi-LSTM)."""

    def __init__(self, cell_fwd: Cell, cell_bwd: Cell, merge: str = "concat",
                 bptt_truncate: int = 0):
        super().__init__()
        self.merge = merge
        self.add(Recurrent(bptt_truncate).add(cell_fwd))
        self.add(Recurrent(bptt_truncate, reverse=True).add(cell_bwd))

    def apply(self, params, x, state, ctx):
        yf, sf = self.modules[0].apply(params["0"], x, state["0"], ctx)
        yb, sb = self.modules[1].apply(params["1"], x, state["1"], ctx)
        y = jnp.concatenate([yf, yb], axis=-1) if self.merge == "concat" else yf + yb
        return y, {"~": state.get("~", {}), "0": sf, "1": sb}


class TimeDistributed(Container):
    """Apply a module independently at every timestep of (N, T, ...)
    (ref TimeDistributed.scala): fold T into the batch so the inner module
    sees one big (N*T, ...) batch — a single large MXU-friendly call instead
    of T small ones."""

    def __init__(self, module: Module):
        super().__init__(module)

    def apply(self, params, x, state, ctx):
        n, t = x.shape[0], x.shape[1]
        flat = x.reshape((n * t,) + x.shape[2:])
        y, ns = self.modules[0].apply(params["0"], flat, state["0"], ctx)
        y = y.reshape((n, t) + y.shape[1:])
        new_state = dict(state)
        new_state["0"] = ns
        return y, new_state
