"""Recurrence (ref Recurrent.scala:27, RNN.scala:28, TimeDistributed.scala).

The reference's ``Recurrent`` container runs a serial Scala time loop with
truncated BPTT (bpttTruncate, Recurrent.scala:66-110).  TPU-native design:
the time loop is ``lax.scan`` — one compiled region, weights resident in
HBM, per-step matmuls batched onto the MXU.  Truncated BPTT maps to chunked
scans with ``stop_gradient`` on the carry at chunk boundaries.

The reference ships only the vanilla ``RnnCell``; BASELINE.json config 4
("Bi-LSTM text classifier ... recurrence via scan") additionally requires
LSTM and bidirectional wrappers, provided here as ``LSTMCell``, ``GRUCell``
and ``BiRecurrent``.

Layout: batch-first (N, T, D) input; hidden state (N, H).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module, TensorModule, Container, Context
from bigdl_tpu.nn import init as init_
from bigdl_tpu.nn.activations import Tanh
from bigdl_tpu.tensor import policy
from bigdl_tpu.utils.table import Table

# LSTM/GRU recurrence through the Pallas kernel pairs on TPU (2.3-4x
# the scan's autodiff, ops/pallas_kernels — PERF_NOTES round 5).
# False = lax.scan everywhere; "interpret" forces the kernels through
# the Pallas interpreter on any backend (tests).  The kernels compute
# gates/carries in f32, so they only replace the scan when the policy's
# output dtype is f32 (FP32/BF16_COMPUTE); BF16_ACT keeps the scan,
# whose gates round through bf16.
_PALLAS_BILSTM = True
# Multi-timestep blocking (round 6): timesteps per kernel grid step for
# ALL five recurrence paths (LSTM/Bi-LSTM/GRU/BiGRU/RNN).  >1 amortizes
# per-grid-step overhead, moves the zx/h streams in block-sized DMAs
# and batches the backward's weight-grad gemms over the block (the
# serial dh chain is untouched — it is the real dependency).  Exact
# math (time axis zero-padded; weight-grad f32 summation order
# differs).  DEFAULT 1 (= round-5 behavior) pending a device-clock A/B
# win, per the adoption rule (PERF_NOTES round 6).
_BLOCK_T = 1


def _pallas_gate():
    """(use, interpret) — the ONE activation gate for the fused
    recurrence kernels, shared by every dispatch site."""
    interp = _PALLAS_BILSTM == "interpret"
    use = (bool(_PALLAS_BILSTM)
           and policy().output_dtype == jnp.float32
           and (interp or jax.default_backend() == "tpu"))
    return use, interp


class Cell(Module):
    """Recurrent cell protocol: ``_step(P, x_t, h, ctx) -> (out_t, h_new)``
    where ``h`` is an array or a tuple of arrays (LSTM)."""

    hidden_size: int

    def init_hidden(self, batch):
        return jnp.zeros((batch, self.hidden_size))

    def _step(self, P, x, h, ctx):
        raise NotImplementedError

    def _forward(self, P, x, S, ctx):
        # standalone use: input Table(x, h) -> h' (ref RnnCell contract)
        out, h = self._step(P, x[1], x[2], ctx)
        return out, None


class RnnCell(Cell):
    """Vanilla RNN: h' = act(W_i x + b_i + W_h h + b_h) (ref RNN.scala:28)."""

    def __init__(self, input_size: int, hidden_size: int, activation=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation if activation is not None else Tanh()
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.hidden_size)
        self._add_param("i2h", init_.uniform((self.hidden_size, self.input_size), -stdv, stdv))
        self._add_param("h2h", init_.uniform((self.hidden_size, self.hidden_size), -stdv, stdv))
        self._add_param("bias_i", init_.uniform((self.hidden_size,), -stdv, stdv))
        self._add_param("bias_h", init_.uniform((self.hidden_size,), -stdv, stdv))
        return self

    def _step(self, P, x, h, ctx):
        p = policy()
        pre = (jnp.matmul(p.cast_compute(x), p.cast_compute(P["i2h"].T),
                          preferred_element_type=jnp.float32) + P["bias_i"] +
               jnp.matmul(p.cast_compute(h), p.cast_compute(P["h2h"].T),
                          preferred_element_type=jnp.float32) + P["bias_h"])
        h_new = self.activation._fn(pre.astype(p.output_dtype), ctx)
        return h_new, h_new


class LSTMCell(Cell):
    """Standard LSTM cell; hidden is (h, c).  One fused (4H, D+H) gemm per
    step keeps the MXU busy."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.hidden_size)
        h, d = self.hidden_size, self.input_size
        self._add_param("w", init_.uniform((4 * h, d + h), -stdv, stdv))
        self._add_param("bias", init_.uniform((4 * h,), -stdv, stdv))
        return self

    def init_hidden(self, batch):
        z = jnp.zeros((batch, self.hidden_size))
        return (z, z)

    def _step(self, P, x, hc, ctx):
        h, c = hc
        p = policy()
        z = jnp.matmul(p.cast_compute(jnp.concatenate([x, h], axis=-1)),
                       p.cast_compute(P["w"].T),
                       preferred_element_type=jnp.float32) + P["bias"]
        z = z.astype(p.output_dtype)
        return self._gates(z, c)

    @staticmethod
    def _gates(z, c):
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    # NOTE (round-3 correction): round 2 measured a hoisted input
    # projection "40% slower" with the chained wall-clock harness; the
    # device-clock trace reverses that verdict — the hoisted projection
    # is FASTER and ships in BiRecurrent._apply_fused_lstm (PERF_NOTES
    # round 3 "LSTM").  The single-direction path here keeps the
    # concat-gemm body (simplest form; the win comes from direction
    # batching, which needs the bidirectional wrapper).  A full Pallas
    # scan kernel (ops/pallas_kernels.lstm_scan) measured within 1% of
    # lax.scan and stays retired.


class GRUCell(Cell):
    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset()

    def reset(self):
        stdv = 1.0 / np.sqrt(self.hidden_size)
        h, d = self.hidden_size, self.input_size
        self._add_param("w_rz", init_.uniform((2 * h, d + h), -stdv, stdv))
        self._add_param("b_rz", init_.uniform((2 * h,), -stdv, stdv))
        self._add_param("w_h", init_.uniform((h, d + h), -stdv, stdv))
        self._add_param("b_h", init_.uniform((h,), -stdv, stdv))
        return self

    def _step(self, P, x, h, ctx):
        xh = jnp.concatenate([x, h], axis=-1)
        rz = jax.nn.sigmoid(jnp.matmul(xh, P["w_rz"].T) + P["b_rz"])
        r, z = jnp.split(rz, 2, axis=-1)
        xrh = jnp.concatenate([x, r * h], axis=-1)
        n = jnp.tanh(jnp.matmul(xrh, P["w_h"].T) + P["b_h"])
        h_new = (1 - z) * n + z * h
        return h_new, h_new


class Recurrent(Container):
    """Time-loop container (ref Recurrent.scala:27).

    ``Recurrent().add(cell)``; forward over (N, T, D) returns (N, T, H).
    ``bptt_truncate > 0`` stops gradients at chunk boundaries (the scan
    equivalent of the reference's truncated backward loop).
    ``reverse=True`` scans right-to-left (for BiRecurrent).
    """

    def __init__(self, bptt_truncate: int = 0, reverse: bool = False):
        super().__init__()
        self.bptt_truncate = int(bptt_truncate)
        self.reverse = reverse

    @property
    def cell(self) -> Cell:
        return self.modules[0]

    def _finish_pallas(self, outs):
        """Shared epilogue of the kernel branches: undo the reverse-time
        flip and return to batch-major (N, T, H)."""
        if self.reverse:
            outs = jnp.flip(outs, axis=0)
        return jnp.swapaxes(outs, 0, 1)

    def apply(self, params, x, state, ctx):
        cell = self.cell
        cp = params["0"]["~"]  # cells keep all params in their own dict
        cs = state["0"]
        n, t = x.shape[0], x.shape[1]
        h0 = cell.init_hidden(n)
        xs = jnp.swapaxes(x, 0, 1)  # (T, N, D) scan-major
        if self.reverse:
            xs = jnp.flip(xs, axis=0)
        key = ctx.next_key() if ctx.training else jax.random.PRNGKey(0)

        p = policy()
        gate, interp = _pallas_gate()
        use_pallas = (gate
                      # exact types only: a subclass's overridden _step
                      # would silently be bypassed
                      and (type(cell) in (LSTMCell, GRUCell)
                           or (type(cell) is RnnCell
                               and type(cell.activation) is Tanh))
                      and (self.bptt_truncate <= 0
                           or self.bptt_truncate >= t))
        if use_pallas and type(cell) is RnnCell:
            # vanilla tanh RNN (the reference's own RnnCell) through the
            # same pattern; backward reuses the stored h stack directly
            from bigdl_tpu.ops.pallas_kernels import rnn_recurrence
            zx = (jnp.matmul(p.cast_compute(xs),
                             p.cast_compute(cp["i2h"].T),
                             preferred_element_type=jnp.float32)
                  + cp["bias_i"] + cp["bias_h"])      # (T, N, H)
            wh = p.cast_compute(cp["h2h"].T)          # (H, H)
            outs = rnn_recurrence(zx[:, None], wh[None], interp,
                                  _BLOCK_T)[:, 0]
            return self._finish_pallas(outs), state
        if use_pallas and type(cell) is GRUCell:
            # GRU case of the VMEM-carry kernel pattern
            # (ops/pallas_kernels.gru_recurrence): hoist the two input
            # projections, run the recurrence with a direction dim of 1.
            # GRUCell._step computes in f32 (no policy cast) — so does
            # the kernel.
            from bigdl_tpu.ops.pallas_kernels import gru_recurrence
            d = cell.input_size
            zrz = jnp.matmul(xs, cp["w_rz"][:, :d].T) + cp["b_rz"]
            zn = jnp.matmul(xs, cp["w_h"][:, :d].T) + cp["b_h"]
            outs = gru_recurrence(zrz[:, None], zn[:, None],
                                  cp["w_rz"][:, d:].T[None],
                                  cp["w_h"][:, d:].T[None], interp,
                                  _BLOCK_T)[:, 0]
            return self._finish_pallas(outs), state
        if use_pallas:
            # single-direction case of the same VMEM-carry kernel pair
            # that earned the Bi-LSTM 2.3x (PERF_NOTES round 5): hoist
            # the input projection to one MXU matmul, run the
            # recurrence with a direction dim of 1.  The key drawn
            # above keeps the ctx stream identical to the scan path
            # (LSTMCell._step ignores its per-step keys).
            from bigdl_tpu.ops.pallas_kernels import bilstm_recurrence
            d = cell.input_size
            wx = p.cast_compute(cp["w"][:, :d].T)     # (D, 4H)
            wh = p.cast_compute(cp["w"][:, d:].T)     # (H, 4H)
            zx = (jnp.matmul(p.cast_compute(xs), wx,
                             preferred_element_type=jnp.float32)
                  + cp["bias"])                       # (T, N, 4H)
            outs = bilstm_recurrence(zx[:, None], wh[None], interp,
                                     _BLOCK_T)[:, 0]
            return self._finish_pallas(outs), state

        def step(carry, x_t):
            h, k = carry
            k, sub = jax.random.split(k)
            sctx = Context(training=ctx.training, key=sub)
            out, h_new = cell._step(cp, x_t, h, sctx)
            return (h_new, k), out

        k = self.bptt_truncate
        if k <= 0 or k >= t:
            (_, _), outs = lax.scan(step, (h0, key), xs)
        else:
            # chunked scan; stop_gradient on the carry between chunks
            outs_list = []
            carry = (h0, key)
            for start in range(0, t, k):
                chunk = xs[start:start + k]
                carry, o = lax.scan(step, carry, chunk)
                h_c, k_c = carry
                carry = (jax.tree_util.tree_map(lax.stop_gradient, h_c), k_c)
                outs_list.append(o)
            outs = jnp.concatenate(outs_list, axis=0)
        if self.reverse:
            outs = jnp.flip(outs, axis=0)
        return jnp.swapaxes(outs, 0, 1), state


class BiRecurrent(Container):
    """Bidirectional wrapper: runs a forward and a backward Recurrent over
    the same input and merges (concat on feature dim, or add).  Not in the
    reference (capability extension for BASELINE config 4 Bi-LSTM)."""

    def __init__(self, cell_fwd: Cell, cell_bwd: Cell, merge: str = "concat",
                 bptt_truncate: int = 0):
        super().__init__()
        self.merge = merge
        self.add(Recurrent(bptt_truncate).add(cell_fwd))
        self.add(Recurrent(bptt_truncate, reverse=True).add(cell_bwd))

    def _cells_eligible(self, cell_type):
        """Both children hold exactly ``cell_type`` with matching sizes
        and no truncation — the structural half of fused eligibility."""
        cf = self.modules[0].cell
        cb = self.modules[1].cell
        return (type(cf) is cell_type and type(cb) is cell_type
                and cf.input_size == cb.input_size
                and cf.hidden_size == cb.hidden_size
                and self.modules[0].bptt_truncate <= 0
                and self.modules[1].bptt_truncate <= 0)

    def _fused_lstm_eligible(self):
        return self._cells_eligible(LSTMCell)

    def _fused_gru_eligible(self):
        # no scan form of the fused GRU exists: the kernels must be
        # usable, so the gate joins the structural check
        return self._cells_eligible(GRUCell) and _pallas_gate()[0]

    def apply(self, params, x, state, ctx):
        fused = (self._apply_fused_lstm if self._fused_lstm_eligible()
                 else self._apply_fused_gru if self._fused_gru_eligible()
                 else None)
        if fused is not None:
            if ctx.training:
                # consume exactly the two keys the two-scan path draws
                # (one per Recurrent.apply): a model with stochastic
                # layers AFTER this module must see the same downstream
                # key stream whichever path runs
                ctx.next_key()
                ctx.next_key()
            return fused(params, x, ctx), state
        yf, sf = self.modules[0].apply(params["0"], x, state["0"], ctx)
        yb, sb = self.modules[1].apply(params["1"], x, state["1"], ctx)
        y = jnp.concatenate([yf, yb], axis=-1) if self.merge == "concat" else yf + yb
        return y, {"~": state.get("~", {}), "0": sf, "1": sb}

    def _apply_fused_gru(self, params, x, ctx):
        """Both GRU directions through ONE direction-batched kernel pair
        (ops/pallas_kernels.gru_recurrence, nd=2) with the two input
        projections hoisted to batched MXU matmuls — the GRU analogue of
        _apply_fused_lstm, half the kernel dispatches of two nd=1
        Recurrent applies.  GRUCell math is f32 (no policy cast)."""
        cf = self.modules[0].cell
        from bigdl_tpu.ops.pallas_kernels import gru_recurrence
        d = cf.input_size
        xs = jnp.swapaxes(x, 0, 1)                        # (T, N, D)
        xs2 = jnp.stack([xs, jnp.flip(xs, axis=0)], axis=1)  # (T, 2, N, D)
        wrz2 = jnp.stack([params["0"]["0"]["~"]["w_rz"],
                          params["1"]["0"]["~"]["w_rz"]])  # (2, 2H, D+H)
        wh2 = jnp.stack([params["0"]["0"]["~"]["w_h"],
                         params["1"]["0"]["~"]["w_h"]])    # (2, H, D+H)
        brz2 = jnp.stack([params["0"]["0"]["~"]["b_rz"],
                          params["1"]["0"]["~"]["b_rz"]])
        bh2 = jnp.stack([params["0"]["0"]["~"]["b_h"],
                         params["1"]["0"]["~"]["b_h"]])
        # batched input projections over (dir, time*batch)
        zrz = lax.dot_general(xs2, jnp.swapaxes(wrz2[:, :, :d], 1, 2),
                              (((3,), (1,)), ((1,), (0,))))
        zrz = jnp.swapaxes(zrz, 0, 1) + brz2[:, None]     # (T, 2, N, 2H)
        zn = lax.dot_general(xs2, jnp.swapaxes(wh2[:, :, :d], 1, 2),
                             (((3,), (1,)), ((1,), (0,))))
        zn = jnp.swapaxes(zn, 0, 1) + bh2[:, None]        # (T, 2, N, H)
        outs = gru_recurrence(zrz, zn,
                              jnp.swapaxes(wrz2[:, :, d:], 1, 2),
                              jnp.swapaxes(wh2[:, :, d:], 1, 2),
                              _pallas_gate()[1], _BLOCK_T)
        yf = jnp.swapaxes(outs[:, 0], 0, 1)               # (N, T, H)
        yb = jnp.swapaxes(jnp.flip(outs[:, 1], axis=0), 0, 1)
        return (jnp.concatenate([yf, yb], axis=-1)
                if self.merge == "concat" else yf + yb)

    def _apply_fused_lstm(self, params, x, ctx):
        """Both directions in ONE scan with the input projection hoisted
        out: per timestep only one direction-batched (2, N, H) x
        (2, H, 4H) recurrent gemm; the (T*N, D) x (D, 4H) input
        projection runs as one big MXU matmul outside the loop.

        Measured on the BASELINE Bi-LSTM config (B128 T500 D200 H128,
        v5e, DEVICE-clock trace timing): two-scan 13.75 ms/step ->
        direction-batched concat-gemm 11.70 -> + hoisted projection
        ~10.1 ms (1.36x).  The remaining floor is the serial recurrence
        itself: gemm-only scan body = 1.3 us/step, full cell = 3.5
        us/step fwd; see PERF_NOTES round 3 "LSTM".  Exact same math as
        the two-scan path (equivalence-tested incl. gradients).

        NOTE: round 2 rejected the hoisted projection as "40% slower" —
        that measurement came from the chained-wall-clock harness whose
        serialization noise exceeded the effect; the device-clock trace
        reverses the verdict."""
        cf = self.modules[0].cell
        p = policy()
        n, t = x.shape[0], x.shape[1]
        hdim = cf.hidden_size
        d = cf.input_size
        w2 = jnp.stack([params["0"]["0"]["~"]["w"],
                        params["1"]["0"]["~"]["w"]])      # (2, 4H, D+H)
        b2 = jnp.stack([params["0"]["0"]["~"]["bias"],
                        params["1"]["0"]["~"]["bias"]])
        wx = p.cast_compute(jnp.swapaxes(w2[:, :, :d], 1, 2))  # (2, D, 4H)
        wh = p.cast_compute(jnp.swapaxes(w2[:, :, d:], 1, 2))  # (2, H, 4H)
        xs = jnp.swapaxes(x, 0, 1)                        # (T, N, D)
        xs2 = jnp.stack([xs, jnp.flip(xs, axis=0)], axis=1)  # (T, 2, N, D)
        # input projection for every timestep in one batched matmul
        zx = lax.dot_general(p.cast_compute(xs2), wx,
                             (((3,), (1,)), ((1,), (0,))),
                             preferred_element_type=jnp.float32)
        zx = jnp.swapaxes(zx, 0, 1) + b2[:, None]         # (T, 2, N, 4H)
        # under a reduced-precision policy the two big scan-adjacent
        # buffers ride in the COMPUTE dtype: zx (T,2,N,4H — written once,
        # re-read per step and again in the backward replay) and the
        # stacked per-step outputs (T,2,N,H).  The serial recurrence
        # itself stays f32 (carry h/c and gate math) — only the streamed
        # tensors halve their bytes.  Device-clock A/B: PERF_NOTES r4.
        reduced = p.compute_dtype != jnp.float32
        if reduced:
            zx = zx.astype(p.compute_dtype)
        z0 = jnp.zeros((2, n, hdim))

        def step(carry, zx_t):
            h, c = carry
            z = zx_t.astype(jnp.float32) + lax.dot_general(
                p.cast_compute(h), wh,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            z = z.astype(p.output_dtype)
            h_new, hc = LSTMCell._gates(z, c)
            out = h_new.astype(p.compute_dtype) if reduced else h_new
            return hc, out

        use_pallas, interp = _pallas_gate()
        if use_pallas:
            # whole-recurrence Pallas kernel pair (fwd + hand-derived
            # bwd), carries resident in VMEM across steps: 2.3x faster
            # than the scan's autodiff on the flagship shapes — the one
            # measured Mosaic win on this chip (ops/pallas_kernels.py
            # bilstm_recurrence, PERF_NOTES round 5).  f32-policy only:
            # forward bit-exact vs the scan body; grads differ by f32
            # accumulation order.
            from bigdl_tpu.ops.pallas_kernels import bilstm_recurrence
            outs = bilstm_recurrence(zx, wh, interp,
                                     _BLOCK_T)        # (T, 2, N, H)
            if reduced:
                outs = outs.astype(p.compute_dtype)
        else:
            _, outs = lax.scan(step, (z0, z0), zx)        # (T, 2, N, H)
        yf = jnp.swapaxes(outs[:, 0], 0, 1)               # (N, T, H)
        yb = jnp.swapaxes(jnp.flip(outs[:, 1], axis=0), 0, 1)
        y = (jnp.concatenate([yf, yb], axis=-1)
             if self.merge == "concat" else yf + yb)
        # back to the output dtype so the head's reductions (Mean over T)
        # accumulate in f32 over the rounded values
        return y.astype(p.output_dtype) if reduced else y


class TimeDistributed(Container):
    """Apply a module independently at every timestep of (N, T, ...)
    (ref TimeDistributed.scala): fold T into the batch so the inner module
    sees one big (N*T, ...) batch — a single large MXU-friendly call instead
    of T small ones."""

    def __init__(self, module: Module):
        super().__init__(module)

    def apply(self, params, x, state, ctx):
        n, t = x.shape[0], x.shape[1]
        flat = x.reshape((n * t,) + x.shape[2:])
        y, ns = self.modules[0].apply(params["0"], flat, state["0"], ctx)
        y = y.reshape((n, t) + y.shape[1:])
        new_state = dict(state)
        new_state["0"] = ns
        return y, new_state
