"""Multi-head self-attention as a model-zoo module.

Absent in the reference (its only sequence machinery is the serial
truncated-BPTT Recurrent loop, SURVEY.md §5.7); first-class here because
long-context attention is the workload sequence/context parallelism
exists for.  The layer has TWO execution paths with identical math:

- single-device: full softmax attention (``parallel.ring_attention.
  full_attention``);
- sequence-parallel: when the trainer sets ``ctx.seq_mesh``
  (``DistriOptimizer(sequence_parallel=True)``), attention runs as the
  EXACT blockwise ring collective (``ring_self_attention``) — Q/K/V
  sequence blocks stay on their devices, K/V rotate around the ``seq``
  ring over ICI with an online softmax, and the batch dim rides a
  ``data`` axis when the mesh has one.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule
from bigdl_tpu.nn import init as init_
from bigdl_tpu.tensor import policy


class MultiHeadSelfAttention(TensorModule):
    """(B, T, D) -> (B, T, D) multi-head self-attention.

    Params: in-projections ``wq/wk/wv`` and out-projection ``wo`` (all
    (D, D)) with biases.  ``causal=True`` applies the autoregressive
    mask (identically in both execution paths).
    """

    #: quantized-serving declaration (bigdl_tpu/quant/weights.py): the
    #: projections multiply as x @ W, so the OUTPUT channels are the
    #: columns (axis 1) and inputs the rows (axis 0) — the transpose of
    #: Linear's layout.  Biases stay fp32.
    quant_spec = {"wq": (1, 0), "wk": (1, 0), "wv": (1, 0),
                  "wo": (1, 0)}

    def __init__(self, d_model: int, n_heads: int, causal: bool = False):
        super().__init__()
        if d_model % n_heads:
            raise ValueError(f"d_model ({d_model}) must divide by "
                             f"n_heads ({n_heads})")
        self.d_model = d_model
        self.n_heads = n_heads
        self.causal = causal
        self.reset()

    def reset(self):
        d = self.d_model
        for name in ("wq", "wk", "wv", "wo"):
            self._add_param(name, init_.default_linear((d, d), d))
            self._add_param(name.replace("w", "b"),
                            np.zeros((d,), np.float32))
        return self

    def _forward(self, P, x, S, ctx):
        from bigdl_tpu.parallel.ring_attention import (full_attention,
                                                       ring_self_attention)
        p = policy()
        b, t, d = x.shape
        h = self.n_heads
        hd = d // h

        def proj(w, bias):
            # stay in the policy compute dtype THROUGH the attention core:
            # the (B,H,T,T) score/probability tensors are pure bandwidth
            # (measured 22.8 -> ~14 ms/step on the bs32 T512 d512 L6
            # encoder, PERF_NOTES round 4), and the QK/AV contractions ride
            # the MXU at bf16 rate; softmax stats stay f32 inside
            # full_attention/ring_attention
            y = jnp.matmul(p.cast_compute(x), p.cast_compute(w))
            return (y + jnp.asarray(bias, y.dtype)).reshape(b, t, h, hd)

        q = proj(P["wq"], P["bq"])
        k = proj(P["wk"], P["bk"])
        v = proj(P["wv"], P["bv"])
        if ctx.seq_mesh is not None:
            batch_axis = ("data" if "data" in ctx.seq_mesh.axis_names
                          else None)
            o = ring_self_attention(q, k, v, ctx.seq_mesh, ctx.seq_axis,
                                    causal=self.causal,
                                    batch_axis=batch_axis)
        else:
            o = full_attention(q, k, v, causal=self.causal)
        o = o.reshape(b, t, d)
        y = jnp.matmul(p.cast_compute(o), p.cast_compute(P["wo"]))
        return y.astype(p.output_dtype) + P["bo"], None

    def __repr__(self):
        return (f"MultiHeadSelfAttention({self.d_model}, heads="
                f"{self.n_heads}{', causal' if self.causal else ''})")


class SinusoidalPositionalEncoding(TensorModule):
    """x + PE[:T] with the standard sin/cos table (parameter-free).

    No reference counterpart (its sequence order comes from recurrence);
    needed by the attention-family LM, whose attention is permutation-
    equivariant without it.  The table is built from the STATIC (T, D)
    of the traced input, so jit sees a constant."""

    def __init__(self, d_model: int, base: float = 10000.0):
        super().__init__()
        self.d_model = d_model
        self.base = base

    def table(self, t: int) -> np.ndarray:
        """The (t, d_model) sin/cos table — shared with the KV-cached
        decoder (models/transformer.lm_decode), which must add the exact
        same positions the training forward added."""
        d = self.d_model
        ang = np.arange(t)[:, None] * np.exp(
            np.arange(0, d, 2) * (-np.log(self.base) / d))
        pe = np.zeros((t, d), np.float32)
        pe[:, 0::2] = np.sin(ang)
        pe[:, 1::2] = np.cos(ang[:, :d // 2])
        return pe

    def _forward(self, P, x, S, ctx):
        t, d = x.shape[1], x.shape[2]
        if d != self.d_model:
            raise ValueError(f"input dim {d} != d_model {self.d_model}")
        return x + jnp.asarray(self.table(t), x.dtype), None

    def __repr__(self):
        return f"SinusoidalPositionalEncoding({self.d_model})"
