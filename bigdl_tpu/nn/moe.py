"""Mixture-of-experts FFN as a model-zoo module.

The reference's ``MixtureTable`` (nn/MixtureTable.scala:221) is a
single-device soft mixture over branch outputs; a sparse expert layer
trainable through the Optimizer is absent (SURVEY.md §2.9: EP = NO).
This module is the missing front door: drop ``nn.MoE`` into a
``Sequential`` and train it like any layer — and with
``DistriOptimizer(expert_parallel=True)`` over a mesh with an ``expert``
axis, the expert-stacked parameters shard across chips and XLA GSPMD
partitions the dispatch/expert/combine einsums (all-to-all over ICI),
the same computation the hand-scheduled ``parallel/moe.moe_apply``
expresses with shard_map.

Formulation: GShard/Switch static-capacity top-1 routing
(``parallel.moe.top1_gating``): one-hot dispatch (T, E, C) einsums keep
every shape static for XLA; tokens over an expert's capacity are dropped
(standard switch semantics — pair with a residual connection).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule
from bigdl_tpu.nn import init as init_
from bigdl_tpu.tensor import policy


class MoE(TensorModule):
    """Top-1 switch-routed expert FFN: (…, D) -> (…, D).

    Params: ``router`` (D, E); expert-stacked ``w1`` (E, D, H), ``b1``
    (E, H), ``w2`` (E, H, D), ``b2`` (E, D) — the leading expert dim is
    what ``expert_parallel`` shards.
    """

    def __init__(self, d_model: int, hidden: int, n_experts: int,
                 capacity_factor: float = 1.25):
        super().__init__()
        self.d_model = d_model
        self.hidden = hidden
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.reset()

    def reset(self):
        d, h, e = self.d_model, self.hidden, self.n_experts
        self._add_param("router", init_.default_linear((d, e), d))
        self._add_param("w1", init_.default_linear((e, d, h), d))
        self._add_param("b1", np.zeros((e, h), np.float32))
        self._add_param("w2", init_.default_linear((e, h, d), h))
        self._add_param("b2", np.zeros((e, d), np.float32))
        return self

    def _forward(self, P, x, S, ctx):
        from bigdl_tpu.parallel.moe import expert_capacity, top1_gating
        p = policy()
        d = x.shape[-1]
        xt = x.reshape(-1, d)                        # (T, D) tokens
        n_tok = xt.shape[0]
        e = self.n_experts
        cap = expert_capacity(n_tok, e, self.capacity_factor)

        logits = jnp.matmul(p.cast_compute(xt),
                            p.cast_compute(P["router"])).astype(jnp.float32)
        dispatch, combine = top1_gating(logits, e, cap)  # (T, E, C) each

        cc = p.cast_compute
        xe = jnp.einsum("tec,td->ecd", cc(dispatch), cc(xt))
        hdn = jnp.einsum("ecd,edh->ech", xe, cc(P["w1"]))
        hdn = jax.nn.relu(hdn.astype(jnp.float32) + P["b1"][:, None])
        ye = jnp.einsum("ech,ehd->ecd", cc(hdn), cc(P["w2"]))
        ye = ye.astype(jnp.float32) + P["b2"][:, None]
        y = jnp.einsum("tec,ecd->td", cc(combine), cc(ye))
        return y.astype(p.output_dtype).reshape(x.shape), None

    def __repr__(self):
        return (f"MoE({self.d_model}, hidden={self.hidden}, "
                f"experts={self.n_experts})")
