"""Spatial convolution family (SURVEY.md §2.3 "Convolution/spatial family").

The reference lowers conv to im2col + gemm with hand-threading
(SpatialConvolution.scala:31, NNPrimitive.scala im2col :25-355).  On TPU,
``lax.conv_general_dilated`` compiles directly onto the MXU — im2col,
threading and the shared-buffer trick (SpatialShareConvolution.scala) are
all compiler concerns, so this file is ~10x smaller than its reference
counterpart while covering the same layers.

Layout: NCHW activations / OIHW weights, matching the reference's Torch
semantics.  XLA re-layouts internally for the MXU.
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import TensorModule
from bigdl_tpu.nn import init as init_
from bigdl_tpu.tensor import policy
from bigdl_tpu.utils.random import RNG

_DN = ("NCHW", "OIHW", "NCHW")


_DOT_1X1 = False  # REJECTED default: isolated 1.7-2.1x wins, end-to-end
# loss (Inception 26.30 -> 27.92, ResNet-50 32.31 -> 32.59 ms/step) —
# see the dot-1x1 comment in _conv and PERF_NOTES round 5


def _conv(x, w, stride, padding, *, lhs_dilation=None, rhs_dilation=None, groups=1):
    # Both operands cast to the compute dtype (bf16 feeds the MXU at full
    # rate; accumulation is f32 inside the MXU regardless), output cast back.
    # No preferred_element_type: its VJP would pair an f32 cotangent with
    # bf16 operands, which conv_general_dilated rejects — and a custom-VJP
    # formulation with pet=f32 in all three convs, despite a 1.7x win on an
    # isolated chained-conv microbench, measured 4-12% SLOWER end-to-end on
    # Inception-v1/VGG-16 training steps (PERF_NOTES.md), so it was removed.
    p = policy()
    if (_DOT_1X1 and x.ndim == 4 and w.shape[2:] == (1, 1)
            and tuple(stride) == (1, 1) and groups == 1
            and lhs_dilation in (None, (1, 1))
            and rhs_dilation in (None, (1, 1))
            and (isinstance(padding, str)  # k=1: SAME == VALID == zero pad
                 or all(lo == 0 and hi == 0 for lo, hi in padding))):
        # A stride-1 1x1 conv IS a channel GEMM.  Isolated, this form
        # measured 1.7-2.1x faster than the conv emitter on the worst
        # ResNet 1x1-bwd shapes and never worse on any tested 1x1, bit-
        # exact (tools/ab_conv_form.py).  END-TO-END it LOSES: Inception
        # 26.30 -> 27.92, ResNet-50 32.31 -> 32.59 ms/step device-busy —
        # the emitter's 1x1s fuse with the surrounding BN/ReLU/concat
        # eltwise and the dot+transpose breaks those fusions (the same
        # isolated-win/in-context-loss pattern as round 4's pet=f32
        # experiment).  Kept OFF as measured evidence, PERF_NOTES r5.
        co, ci = w.shape[0], w.shape[1]
        y = lax.dot_general(p.cast_compute(w).reshape(co, ci),
                            p.cast_compute(x),
                            (((1,), (1,)), ((), ())))
        return y.transpose(1, 0, 2, 3).astype(p.output_dtype)
    y = lax.conv_general_dilated(
        p.cast_compute(x), p.cast_compute(w),
        window_strides=stride, padding=padding,
        lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
        dimension_numbers=_DN, feature_group_count=groups)
    return y.astype(p.output_dtype)


def _maybe_batch(x):
    """Accept 3D (C,H,W) like the reference; return (x4d, was_3d)."""
    if x.ndim == 3:
        return x[None], True
    return x, False


_S2D_STEM = True  # isolated win, end-to-end neutral on Inception (PERF_NOTES); helps ResNet/AlexNet stems

_SPLIT_DB = False  # REJECTED default: measured 33.84 -> 37.64 ms/step


@jax.custom_vjp
def _bias_add(y, b):
    """Bias add whose backward computes db in a standalone kernel —
    kept as measured evidence, default OFF.

    Hypothesis (VERDICT r3 lever a): the autodiff db is sum(g, (0,2,3))
    — isolated it streams at 754 GB/s, but XLA folds it into the
    multi-operand backward fusion around the conv which runs at ~270
    GB/s effective, so splitting it out with ``optimization_barrier``
    should win.  Device-clock A/B (round 4): Inception device-busy
    33.84 -> **37.64 ms/step WITH the split** — the barrier forces a
    second full read of every conv cotangent (~2 ms of standalone
    reduces) while the fusions shrink by less; the "270 GB/s fusion"
    was SHARING one read between dx and db all along.  The
    isolated-vs-fused bandwidth comparison was the misleading number.
    See PERF_NOTES round 4."""
    return y + b[None, :, None, None]


def _bias_add_fwd(y, b):
    return _bias_add(y, b), None


def _bias_add_bwd(_, g):
    return g, jnp.sum(lax.optimization_barrier(g), axis=(0, 2, 3))


_bias_add.defvjp(_bias_add_fwd, _bias_add_bwd)


def bias_add(y, b):
    """Conv bias add (NCHW); routed through the split-db custom VJP."""
    if _SPLIT_DB:
        return _bias_add(y, b)
    return y + b[None, :, None, None]


def _s2d_parts(x, w, s, pad):
    """The space-to-depth operands: (xs, ws, out crop) — see
    _space_to_depth_conv."""
    o, c, kh, kw = w.shape
    b, _, h, wd = x.shape
    (plh, phh), (plw, phw) = pad
    khp = -(-kh // s)   # ceil(k/s) taps after the rewrite
    kwp = -(-kw // s)
    # pad the image to the conv's own padding, then up to a multiple of s
    hp = h + plh + phh
    wp = wd + plw + phw
    eh = (-hp) % s
    ew = (-wp) % s
    xp = jnp.pad(x, ((0, 0), (0, 0), (plh, phh + eh), (plw, phw + ew)))
    m, n = (hp + eh) // s, (wp + ew) // s
    xs = xp.reshape(b, c, m, s, n, s).transpose(0, 1, 3, 5, 2, 4)
    xs = xs.reshape(b, c * s * s, m, n)
    # weight phases: w'[o, (c, rh, rw), u, v] = w[o, c, s*u+rh, s*v+rw]
    wpad = jnp.pad(w, ((0, 0), (0, 0), (0, s * khp - kh), (0, s * kwp - kw)))
    ws = wpad.reshape(o, c, khp, s, kwp, s).transpose(0, 1, 3, 5, 2, 4)
    ws = ws.reshape(o, c * s * s, khp, kwp)
    oh = (hp - kh) // s + 1
    ow = (wp - kw) // s + 1
    return xs, ws, oh, ow


def _barrier_grad_supported() -> bool:
    """Older jaxlibs have no differentiation rule for
    ``optimization_barrier``; trace (no dispatch) a grad through one to
    decide whether the s2d backward may pin its operands."""
    try:
        jax.make_jaxpr(jax.grad(
            lambda v: lax.optimization_barrier(v * v)))(1.0)
        return True
    except NotImplementedError:
        return False


# keep the stem wgrad in s2d geometry (A/B: PERF_NOTES r4) where the
# barrier is differentiable; otherwise plain autodiff geometry (slower
# stem wgrad, same numbers)
_S2D_BWD = _barrier_grad_supported()


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _space_to_depth_conv(x, w, s, pad):
    """Strided low-channel conv rewritten as space-to-depth + stride-1 conv.

    A k x k stride-s conv over C channels equals a ceil(k/s)^2 stride-1
    conv over C*s*s space-to-depth channels.  For stem convs (C=3, s=2 or
    4) this multiplies the MXU contraction depth by s^2: the 7x7/s2
    Inception-v1 stem measured 33 TF/s as-is (3 input channels fill 3/128
    MXU rows) and proportionally better after this rewrite.  Exact same
    arithmetic, reassociated.

    out(i,j) = sum_t w[t] xpad[s*i + t]  becomes, with t = s*u + r,
    sum_r sum_u w[s*u + r] X_r[i + u]  where X_r is the r-th phase of the
    space-to-depth transform.

    The custom VJP keeps the BACKWARD convs in the s2d geometry too:
    plain autodiff emits the right s2d-shaped grad convs, but XLA's
    layout/canonicalization pass folds the phase transforms back in and
    rewrites the weight grad to the original low-channel form
    (out[64,3,7,7] over raw 224x224 input: 0.907 ms at 18%% of roofline,
    PROFILE round 4) — ``optimization_barrier`` on the cotangent side
    pins the s2d form the same way the round-2 maxpool lesson pinned
    residuals."""
    xs, ws, oh, ow = _s2d_parts(x, w, s, pad)
    y = _conv(xs, ws, (1, 1), [(0, 0), (0, 0)])
    return y[:, :, :oh, :ow]


def _s2d_conv_fwd(x, w, s, pad):
    return _space_to_depth_conv(x, w, s, pad), (x, w)


def _s2d_conv_bwd(s, pad, res, g):
    x, w = res

    def inner(x_, w_):
        xs, ws, oh, ow = _s2d_parts(x_, w_, s, pad)
        # barrier the s2d operands: without it XLA folds the phase
        # transforms into the grad convs and canonicalizes them back to
        # the slow low-channel geometry
        if _S2D_BWD:
            xs = lax.optimization_barrier(xs)
            ws = lax.optimization_barrier(ws)
        y = _conv(xs, ws, (1, 1), [(0, 0), (0, 0)])
        return y[:, :, :oh, :ow]

    _, vjp = jax.vjp(inner, x, w)
    dx, dw = vjp(g)
    return dx, dw


_space_to_depth_conv.defvjp(_s2d_conv_fwd, _s2d_conv_bwd)


class SpatialConvolution(TensorModule):
    """2D convolution (ref SpatialConvolution.scala:31).

    Args mirror the reference constructor: (nInputPlane, nOutputPlane,
    kernelW, kernelH, strideW, strideH, padW, padH, nGroup, propagateBack,
    initMethod).
    """

    #: quantized-serving declaration (bigdl_tpu/quant/weights.py):
    #: weight is (O, C/group, kh, kw) — per-output-plane scales
    quant_spec = {"weight": (0, 1)}

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1,
                 propagate_back: bool = True, init_method: str = init_.Default,
                 with_bias: bool = True):
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.init_method = init_method
        self.with_bias = with_bias
        self.reset()

    def reset(self):
        shape = (self.n_output_plane, self.n_input_plane // self.n_group,
                 self.kernel_h, self.kernel_w)
        fan_in = (self.n_input_plane // self.n_group) * self.kernel_h * self.kernel_w
        fan_out = (self.n_output_plane // self.n_group) * self.kernel_h * self.kernel_w
        if self.init_method == init_.Xavier:
            w = init_.xavier(shape, fan_in, fan_out)
            b = np.zeros((self.n_output_plane,), np.float32)
        elif self.init_method == init_.MSRA:
            n = self.kernel_w * self.kernel_h * self.n_output_plane
            w = init_.msra(shape, n)
            b = np.zeros((self.n_output_plane,), np.float32)
        else:
            stdv = 1.0 / np.sqrt(self.kernel_w * self.kernel_h * self.n_input_plane)
            w = init_.uniform(shape, -stdv, stdv)
            b = init_.uniform((self.n_output_plane,), -stdv, stdv)
        self._add_param("weight", w)
        if self.with_bias:
            self._add_param("bias", b)
        return self

    def _forward(self, P, x, S, ctx):
        x, was3d = _maybe_batch(x)
        s = self.stride_h
        if (s == self.stride_w and s > 1 and self.n_group == 1
                and self.n_input_plane * s * s <= 64 and _S2D_STEM
                and self.kernel_h > s and self.kernel_w > s):
            # stem convs (few input channels, strided): space-to-depth
            # rewrite fills the MXU contraction dim s^2 times better
            y = _space_to_depth_conv(
                x, P["weight"], s,
                ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)))
        else:
            y = _conv(x, P["weight"], (self.stride_h, self.stride_w),
                      [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
                      groups=self.n_group)
        if self.with_bias:
            y = bias_add(y, P["bias"])
        return (y[0] if was3d else y), None

    def __repr__(self):
        return (f"SpatialConvolution({self.n_input_plane} -> {self.n_output_plane}, "
                f"{self.kernel_w}x{self.kernel_h}, {self.stride_w},{self.stride_h}, "
                f"{self.pad_w},{self.pad_h})")


class SpatialShareConvolution(SpatialConvolution):
    """API-parity alias (ref SpatialShareConvolution.scala shares im2col
    buffers across layers to cut JVM memory; XLA's buffer assignment does
    this automatically, so the layer is computationally identical here)."""


class SpatialDilatedConvolution(TensorModule):
    """Atrous convolution (ref SpatialDilatedConvolution.scala, 561 LoC)."""

    #: weight is (O, C, kh, kw) — see SpatialConvolution.quant_spec
    quant_spec = {"weight": (0, 1)}

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 init_method: str = init_.Default):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        self.init_method = init_method
        self.reset()

    def reset(self):
        shape = (self.n_output_plane, self.n_input_plane, self.kh, self.kw)
        fan_in = self.n_input_plane * self.kh * self.kw
        if self.init_method == init_.Xavier:
            w = init_.xavier(shape, fan_in, self.n_output_plane * self.kh * self.kw)
            b = np.zeros((self.n_output_plane,), np.float32)
        else:
            stdv = 1.0 / np.sqrt(fan_in)
            w = init_.uniform(shape, -stdv, stdv)
            b = init_.uniform((self.n_output_plane,), -stdv, stdv)
        self._add_param("weight", w)
        self._add_param("bias", b)
        return self

    def _forward(self, P, x, S, ctx):
        x, was3d = _maybe_batch(x)
        y = _conv(x, P["weight"], (self.dh, self.dw),
                  [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
                  rhs_dilation=(self.dilation_h, self.dilation_w))
        y = y + P["bias"][None, :, None, None]
        return (y[0] if was3d else y), None


class SpatialFullConvolution(TensorModule):
    """Transposed convolution / deconvolution
    (ref SpatialFullConvolution.scala, 791 LoC).

    out = (in - 1) * stride - 2 * pad + kernel + adj.
    Implemented as input-dilated conv with a spatially-flipped,
    channel-swapped kernel — the XLA-native formulation of conv-transpose.
    Weight stored Torch-style: (nInputPlane, nOutputPlane // nGroup, kH, kW).
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 init_method: str = init_.Default):
        super().__init__()
        assert adj_w < dw and adj_h < dh, "adjW/adjH must be smaller than strides"
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.adj_w, self.adj_h = adj_w, adj_h
        self.n_group = n_group
        self.no_bias = no_bias
        self.init_method = init_method
        self.reset()

    def reset(self):
        shape = (self.n_input_plane, self.n_output_plane // self.n_group,
                 self.kh, self.kw)
        if self.init_method == init_.BilinearFiller:
            w = init_.bilinear_filler(shape)
            b = np.zeros((self.n_output_plane,), np.float32)
        else:
            fan_in = (self.n_input_plane // self.n_group) * self.kh * self.kw
            stdv = 1.0 / np.sqrt(fan_in)
            w = init_.uniform(shape, -stdv, stdv)
            b = init_.uniform((self.n_output_plane,), -stdv, stdv)
        self._add_param("weight", w)
        if not self.no_bias:
            self._add_param("bias", b)
        return self

    def _forward(self, P, x, S, ctx):
        x, was3d = _maybe_batch(x)
        w = P["weight"]  # (I, O/g, kh, kw)
        pad_h0 = self.kh - 1 - self.pad_h
        pad_w0 = self.kw - 1 - self.pad_w
        padding = [(pad_h0, pad_h0 + self.adj_h), (pad_w0, pad_w0 + self.adj_w)]
        g = self.n_group
        ys = []
        cin_g = self.n_input_plane // g
        for gi in range(g):  # static tiny loop; XLA fuses
            wg = w[gi * cin_g:(gi + 1) * cin_g]          # (I/g, O/g, kh, kw)
            wg = jnp.flip(wg, axis=(-1, -2)).swapaxes(0, 1)  # (O/g, I/g, kh, kw)
            xg = x[:, gi * cin_g:(gi + 1) * cin_g]
            ys.append(_conv(xg, wg, (1, 1), padding, lhs_dilation=(self.dh, self.dw)))
        y = jnp.concatenate(ys, axis=1) if g > 1 else ys[0]
        if not self.no_bias:
            y = y + P["bias"][None, :, None, None]
        return (y[0] if was3d else y), None


class SpatialConvolutionMap(TensorModule):
    """Convolution over an explicit input->output connection table
    (ref SpatialConvolutionMap.scala, 361 LoC; Torch conn tables).

    TPU-first formulation: a dense conv with a constant 0/1 connectivity
    mask on the kernel — sparse gather loops would defeat the MXU, and for
    the table sizes involved the masked dense conv is faster.
    ``conn_table`` is an (n, 2) array of 1-based (fromPlane, toPlane) pairs.
    """

    def __init__(self, conn_table, kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        conn = np.asarray(conn_table, np.int64).reshape(-1, 2)
        self.conn_table = conn
        self.n_input_plane = int(conn[:, 0].max())
        self.n_output_plane = int(conn[:, 1].max())
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        mask = np.zeros((self.n_output_plane, self.n_input_plane, 1, 1), np.float32)
        for f, t in conn:
            mask[t - 1, f - 1, 0, 0] = 1.0
        self._mask = mask
        self.reset()

    def reset(self):
        # Torch: per-output fan-in = (#connections into it) * kW * kH
        fan_in = np.maximum(self._mask.sum(axis=(1, 2, 3)), 1.0) * self.kw * self.kh
        stdv = 1.0 / np.sqrt(fan_in)  # (O,)
        w = (RNG.uniform(-1, 1, (self.n_output_plane, self.n_input_plane,
                                 self.kh, self.kw)) * stdv[:, None, None, None])
        b = RNG.uniform(-1, 1, (self.n_output_plane,)) * stdv
        self._add_param("weight", (w * self._mask).astype(np.float32))
        self._add_param("bias", b.astype(np.float32))
        return self

    @staticmethod
    def full(n_in: int, n_out: int):
        """fullConnection table."""
        return np.array([(i + 1, o + 1) for o in range(n_out) for i in range(n_in)])

    @staticmethod
    def one_to_one(n: int):
        return np.array([(i + 1, i + 1) for i in range(n)])

    @staticmethod
    def random(n_in: int, n_out: int, n_to: int):
        pairs = []
        for o in range(n_out):
            ins = RNG.np_rng().choice(n_in, size=n_to, replace=False)
            pairs += [(int(i) + 1, o + 1) for i in ins]
        return np.array(pairs)

    def _forward(self, P, x, S, ctx):
        x, was3d = _maybe_batch(x)
        w = P["weight"] * jnp.asarray(self._mask)
        y = _conv(x, w, (self.dh, self.dw),
                  [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)])
        y = y + P["bias"][None, :, None, None]
        return (y[0] if was3d else y), None
