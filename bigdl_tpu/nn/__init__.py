"""nn — the layer + criterion inventory (ref dl/.../bigdl/nn, SURVEY.md §2.3)."""

from bigdl_tpu.nn.module import (
    Module, TensorModule, Container, Criterion, Context,
)
from bigdl_tpu.nn import init
from bigdl_tpu.nn.init import InitializationMethod, Default, Xavier, BilinearFiller, MSRA
from bigdl_tpu.nn.containers import (
    Sequential, Concat, ConcatTable, ParallelTable, MapTable, Bottle,
)
from bigdl_tpu.nn.activations import (
    ReLU, ReLU6, PReLU, RReLU, LeakyReLU, ELU, Tanh, TanhShrink, Sigmoid,
    LogSigmoid, LogSoftMax, SoftMax, SoftMin, SoftPlus, SoftShrink, SoftSign,
    HardTanh, HardShrink, Threshold, Clamp, Abs, Sqrt, Square, Power, Exp,
    Log, GradientReversal,
)
from bigdl_tpu.nn.linear import (
    Linear, Bilinear, CMul, CAdd, Mul, Add, MulConstant, AddConstant, MM, MV,
    Cosine, Euclidean, LookupTable,
)
from bigdl_tpu.nn.conv import (
    SpatialConvolution, SpatialShareConvolution, SpatialDilatedConvolution,
    SpatialFullConvolution, SpatialConvolutionMap,
)
from bigdl_tpu.nn.pooling import (
    SpatialMaxPooling, SpatialAveragePooling, RoiPooling,
)
from bigdl_tpu.nn.normalization import (
    BatchNormalization, SpatialBatchNormalization, SpatialCrossMapLRN,
    SpatialSubtractiveNormalization, SpatialDivisiveNormalization,
    SpatialContrastiveNormalization, LayerNorm,
)
from bigdl_tpu.nn.shape_ops import (
    Reshape, InferReshape, View, Transpose, Replicate, Squeeze, Unsqueeze,
    Padding, SpatialZeroPadding, Contiguous, Copy, Identity, Echo,
)
from bigdl_tpu.nn.table_ops import (
    CAddTable, CSubTable, CMulTable, CDivTable, CMaxTable, CMinTable,
    JoinTable, SelectTable, NarrowTable, FlattenTable, MixtureTable,
    DotProduct, PairwiseDistance, CosineDistance, CriterionTable,
)
from bigdl_tpu.nn.reductions import (
    Mean, Sum, Max, Min, Index, Select, Narrow, MaskedSelect,
)
from bigdl_tpu.nn.dropout import Dropout, L1Penalty
from bigdl_tpu.nn.nms import Nms, nms_mask, nms_indices
from bigdl_tpu.nn.recurrent import (
    Cell, RnnCell, LSTMCell, GRUCell, Recurrent, BiRecurrent, TimeDistributed,
)
from bigdl_tpu.nn.moe import MoE
from bigdl_tpu.nn.attention import (MultiHeadSelfAttention,
                                    SinusoidalPositionalEncoding)
from bigdl_tpu.nn.criterion import (
    ClassNLLCriterion, CrossEntropyCriterion, MSECriterion, AbsCriterion,
    BCECriterion, DistKLDivCriterion, ClassSimplexCriterion,
    CosineEmbeddingCriterion, HingeEmbeddingCriterion,
    L1HingeEmbeddingCriterion, MarginCriterion, MarginRankingCriterion,
    MultiCriterion, ParallelCriterion, MultiLabelMarginCriterion,
    MultiLabelSoftMarginCriterion, MultiMarginCriterion, SmoothL1Criterion,
    SmoothL1CriterionWithWeights, SoftMarginCriterion, SoftmaxWithCriterion,
    L1Cost, TimeDistributedCriterion,
)
