"""Containers (ref SURVEY.md §2.3: 8 containers).

Sequential (Sequential.scala:26), Concat (Concat.scala — the reference runs
branches on a thread pool, Concat.scala:73; under XLA the branches fuse into
one program and the compiler schedules them), ConcatTable, ParallelTable,
MapTable, Bottle (Bottle.scala).  Recurrent/TimeDistributed live in
``recurrent.py``.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module
from bigdl_tpu.utils.table import Table


def _child_apply(container, i, params, x, state, ctx):
    name = str(i)
    m = container.modules[i]
    y, ns = m.apply(params[name], x, state[name], ctx)
    return y, ns


class Sequential(Container):
    """Chain modules serially (ref Sequential.scala:26)."""

    def apply(self, params, x, state, ctx):
        new_state = dict(state)
        for i in range(len(self.modules)):
            x, ns = _child_apply(self, i, params, x, state, ctx)
            new_state[str(i)] = ns
        return x, new_state


_MERGE_1X1 = True  # kill switch for the merged-pointwise-head execution


class Concat(Container):
    """Apply every branch to the same input, concatenate outputs along
    ``dimension`` (1-based, ref Concat.scala).

    TPU execution detail: when several branches START with a pointwise
    (1x1/s1/p0, grouped=1, biased) convolution of the shared input —
    the Inception block shape — those heads execute as ONE conv whose
    weight is the trace-time concat of the branch weights, and the
    result is sliced back per branch.  Exact same arithmetic and the
    identical parameter tree (the concat/slice pair is differentiable,
    so each branch's grads land on its own weight); what changes is the
    kernel economy: one (B*HW, C) x (C, sum(c_i)) MXU matmul instead of
    three skinny ones, in a step whose measured limiter is inter-kernel
    scheduling of many small kernels (PERF_NOTES round 3: ~6 ms/step of
    gaps; round 4 A/B table for this rewrite)."""

    def __init__(self, dimension: int, *modules):
        super().__init__(*modules)
        self.dimension = dimension

    def _merge_plan(self):
        """Branch indices whose leading module is a mergeable pointwise
        conv (>= 2 needed to merge).  Recomputed per apply — it is a
        microsecond loop that only runs at trace time under jit, and a
        cache would go stale if a branch head were surgically swapped
        between calls."""
        from bigdl_tpu.nn.conv import SpatialConvolution
        plan = []
        if self.dimension == 2:
            for i, br in enumerate(self.modules):
                if not (isinstance(br, Sequential) and br.modules):
                    continue
                c = br.modules[0]
                if (isinstance(c, SpatialConvolution)
                        and c.kernel_w == 1 and c.kernel_h == 1
                        and c.stride_w == 1 and c.stride_h == 1
                        and c.pad_w == 0 and c.pad_h == 0
                        and c.n_group == 1 and c.with_bias):
                    plan.append(i)
        return plan if len(plan) >= 2 else []

    def apply(self, params, x, state, ctx):
        plan = self._merge_plan() if _MERGE_1X1 else []
        if plan and hasattr(x, "ndim") and x.ndim == 4:
            return self._apply_merged(params, x, state, ctx, plan)
        outs = []
        new_state = dict(state)
        for i in range(len(self.modules)):
            y, ns = _child_apply(self, i, params, x, state, ctx)
            outs.append(y)
            new_state[str(i)] = ns
        return jnp.concatenate(outs, axis=self.dimension - 1), new_state

    def _apply_merged(self, params, x, state, ctx, plan):
        from bigdl_tpu.nn.conv import _conv, bias_add
        heads = [params[str(i)]["0"]["~"] for i in plan]
        w = jnp.concatenate([h["weight"] for h in heads], axis=0)
        b = jnp.concatenate([h["bias"] for h in heads], axis=0)
        merged = bias_add(_conv(x, w, (1, 1), [(0, 0), (0, 0)]), b)
        sizes = [h["weight"].shape[0] for h in heads]
        offs = np.cumsum([0] + sizes)
        slices = {i: merged[:, offs[k]:offs[k + 1]]
                  for k, i in enumerate(plan)}

        outs = []
        new_state = dict(state)
        for i in range(len(self.modules)):
            if i in slices:
                br = self.modules[i]
                bparams, bstate = params[str(i)], state[str(i)]
                y = slices[i]
                ns = dict(bstate)
                for j in range(1, len(br.modules)):
                    y, s_j = br.modules[j].apply(bparams[str(j)], y,
                                                 bstate[str(j)], ctx)
                    ns[str(j)] = s_j
            else:
                y, ns = _child_apply(self, i, params, x, state, ctx)
            outs.append(y)
            new_state[str(i)] = ns
        return jnp.concatenate(outs, axis=self.dimension - 1), new_state


class ConcatTable(Container):
    """Apply every branch to the same input; output is a Table of results
    (ref ConcatTable.scala)."""

    def apply(self, params, x, state, ctx):
        out = Table()
        new_state = dict(state)
        for i in range(len(self.modules)):
            y, ns = _child_apply(self, i, params, x, state, ctx)
            out[i + 1] = y
            new_state[str(i)] = ns
        return out, new_state


class ParallelTable(Container):
    """i-th module consumes i-th element of the input Table
    (ref ParallelTable.scala)."""

    def apply(self, params, x, state, ctx):
        out = Table()
        new_state = dict(state)
        for i in range(len(self.modules)):
            y, ns = _child_apply(self, i, params, x[i + 1], state, ctx)
            out[i + 1] = y
            new_state[str(i)] = ns
        return out, new_state


class MapTable(Container):
    """Apply the same module to every element of the input Table
    (ref MapTable.scala).  The single child's parameters are shared across
    all elements — exactly the reference's clone-with-shared-storage."""

    def __init__(self, module: Module = None):
        super().__init__()
        if module is not None:
            self.add(module)

    def apply(self, params, x, state, ctx):
        out = Table()
        new_state = dict(state)
        n = x.length()
        ns = state["0"]
        for i in range(n):
            y, ns = self.modules[0].apply(params["0"], x[i + 1], ns, ctx)
            out[i + 1] = y
        new_state["0"] = ns
        return out, new_state


class Bottle(Container):
    """Flatten leading dims to apply an n-D module to higher-D input
    (ref Bottle.scala): input (d1..dk, rest) -> view (prod(d1..dk), rest)
    -> module -> restore leading dims."""

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: int = None):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim if n_output_dim is not None else n_input_dim

    def apply(self, params, x, state, ctx):
        in_shape = x.shape
        lead = in_shape[: x.ndim - self.n_input_dim + 1]
        rest = in_shape[x.ndim - self.n_input_dim + 1:]
        squashed = x.reshape((-1,) + rest)
        y, ns = _child_apply(self, 0, params, squashed, state, ctx)
        out_rest = y.shape[1:]
        y = y.reshape(lead + out_rest)
        new_state = dict(state)
        new_state["0"] = ns
        return y, new_state
