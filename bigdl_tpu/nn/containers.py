"""Containers (ref SURVEY.md §2.3: 8 containers).

Sequential (Sequential.scala:26), Concat (Concat.scala — the reference runs
branches on a thread pool, Concat.scala:73; under XLA the branches fuse into
one program and the compiler schedules them), ConcatTable, ParallelTable,
MapTable, Bottle (Bottle.scala).  Recurrent/TimeDistributed live in
``recurrent.py``.
"""
from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module
from bigdl_tpu.utils.table import Table


def _child_apply(container, i, params, x, state, ctx):
    name = str(i)
    m = container.modules[i]
    y, ns = m.apply(params[name], x, state[name], ctx)
    return y, ns


class Sequential(Container):
    """Chain modules serially (ref Sequential.scala:26)."""

    def apply(self, params, x, state, ctx):
        new_state = dict(state)
        for i in range(len(self.modules)):
            x, ns = _child_apply(self, i, params, x, state, ctx)
            new_state[str(i)] = ns
        return x, new_state


class Concat(Container):
    """Apply every branch to the same input, concatenate outputs along
    ``dimension`` (1-based, ref Concat.scala)."""

    def __init__(self, dimension: int, *modules):
        super().__init__(*modules)
        self.dimension = dimension

    def apply(self, params, x, state, ctx):
        outs = []
        new_state = dict(state)
        for i in range(len(self.modules)):
            y, ns = _child_apply(self, i, params, x, state, ctx)
            outs.append(y)
            new_state[str(i)] = ns
        return jnp.concatenate(outs, axis=self.dimension - 1), new_state


class ConcatTable(Container):
    """Apply every branch to the same input; output is a Table of results
    (ref ConcatTable.scala)."""

    def apply(self, params, x, state, ctx):
        out = Table()
        new_state = dict(state)
        for i in range(len(self.modules)):
            y, ns = _child_apply(self, i, params, x, state, ctx)
            out[i + 1] = y
            new_state[str(i)] = ns
        return out, new_state


class ParallelTable(Container):
    """i-th module consumes i-th element of the input Table
    (ref ParallelTable.scala)."""

    def apply(self, params, x, state, ctx):
        out = Table()
        new_state = dict(state)
        for i in range(len(self.modules)):
            y, ns = _child_apply(self, i, params, x[i + 1], state, ctx)
            out[i + 1] = y
            new_state[str(i)] = ns
        return out, new_state


class MapTable(Container):
    """Apply the same module to every element of the input Table
    (ref MapTable.scala).  The single child's parameters are shared across
    all elements — exactly the reference's clone-with-shared-storage."""

    def __init__(self, module: Module = None):
        super().__init__()
        if module is not None:
            self.add(module)

    def apply(self, params, x, state, ctx):
        out = Table()
        new_state = dict(state)
        n = x.length()
        ns = state["0"]
        for i in range(n):
            y, ns = self.modules[0].apply(params["0"], x[i + 1], ns, ctx)
            out[i + 1] = y
        new_state["0"] = ns
        return out, new_state


class Bottle(Container):
    """Flatten leading dims to apply an n-D module to higher-D input
    (ref Bottle.scala): input (d1..dk, rest) -> view (prod(d1..dk), rest)
    -> module -> restore leading dims."""

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: int = None):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim if n_output_dim is not None else n_input_dim

    def apply(self, params, x, state, ctx):
        in_shape = x.shape
        lead = in_shape[: x.ndim - self.n_input_dim + 1]
        rest = in_shape[x.ndim - self.n_input_dim + 1:]
        squashed = x.reshape((-1,) + rest)
        y, ns = _child_apply(self, 0, params, squashed, state, ctx)
        out_rest = y.shape[1:]
        y = y.reshape(lead + out_rest)
        new_state = dict(state)
        new_state["0"] = ns
        return y, new_state
