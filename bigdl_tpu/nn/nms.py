"""Non-maximum suppression (ref nn/Nms.scala — helper used by the
detection path next to RoiPooling).

TPU-first formulation: fixed-iteration greedy NMS via ``lax.fori_loop`` on
static shapes (returns a keep mask rather than a compacted index list, so
it runs under jit); ``nms_indices`` gives the host-side compacted indices.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np


def _iou_matrix(boxes):
    """boxes: (N, 4) [x1, y1, x2, y2] -> (N, N) IoU."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1 + 1, 0) * jnp.maximum(y2 - y1 + 1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    iw = jnp.maximum(ix2 - ix1 + 1, 0)
    ih = jnp.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-9)


def nms_mask(boxes, scores, threshold: float):
    """Greedy NMS keep-mask, jit-compatible (static N iterations)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes)

    def body(i, state):
        keep, suppressed = state
        idx = order[i]
        is_live = ~suppressed[idx]
        keep = keep.at[idx].set(is_live)
        # suppress everything overlapping idx (only if idx is live)
        over = iou[idx] > threshold
        suppressed = suppressed | (over & is_live)
        suppressed = suppressed.at[idx].set(suppressed[idx] | is_live)  # self
        return keep, suppressed

    keep0 = jnp.zeros(n, bool)
    sup0 = jnp.zeros(n, bool)
    keep, _ = lax.fori_loop(0, n, body, (keep0, sup0))
    return keep


def nms_indices(boxes, scores, threshold: float):
    """Host-side: kept indices sorted by descending score (Nms.scala API)."""
    keep = np.asarray(nms_mask(jnp.asarray(boxes), jnp.asarray(scores),
                               threshold))
    scores = np.asarray(scores)
    idx = np.where(keep)[0]
    return idx[np.argsort(-scores[idx])]


class Nms:
    """Stateful NMS helper matching the reference class shape (ref
    nn/Nms.scala: construct once, call per proposal set)."""

    def __init__(self, threshold: float = 0.7):
        self.threshold = threshold

    def __call__(self, boxes, scores):
        return nms_indices(boxes, scores, self.threshold)

    def keep_mask(self, boxes, scores):
        """jit-compatible mask form for on-device detection heads."""
        return nms_mask(boxes, scores, self.threshold)
