"""Activation layers — full inventory of the reference (SURVEY.md §2.3,
"Activations (24)"): ReLU, ReLU6, PReLU, RReLU, LeakyReLU, ELU, Tanh,
TanhShrink, Sigmoid, LogSigmoid, LogSoftMax, SoftMax, SoftMin, SoftPlus,
SoftShrink, SoftSign, HardTanh, HardShrink, Threshold, Clamp, Abs, Sqrt,
Square, Power, Exp, Log, GradientReversal.

All are stateless jnp expressions that XLA fuses into neighbouring matmuls —
the reference's hand-threaded versions (e.g. Threshold.scala's Engine.model
pool) are unnecessary on TPU.

Note on in-place (``ip``) flags: the reference offers in-place variants to
save JVM allocations; under XLA, buffer reuse is the compiler's job, so the
flag is accepted for API parity and ignored.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import TensorModule
from bigdl_tpu.nn import init as init_


class _Elementwise(TensorModule):
    """Base for parameter-free elementwise layers."""

    def _fn(self, x, ctx):
        raise NotImplementedError

    def _forward(self, P, x, S, ctx):
        return self._fn(x, ctx), None


class ReLU(_Elementwise):
    def __init__(self, ip: bool = False):
        super().__init__()
        self.inplace = ip

    def _fn(self, x, ctx):
        return jax.nn.relu(x)


class ReLU6(_Elementwise):
    def __init__(self, inplace: bool = False):
        super().__init__()
        self.inplace = inplace

    def _fn(self, x, ctx):
        return jnp.clip(x, 0.0, 6.0)


class Tanh(_Elementwise):
    def _fn(self, x, ctx):
        return jnp.tanh(x)


class TanhShrink(_Elementwise):
    def _fn(self, x, ctx):
        return x - jnp.tanh(x)


class Sigmoid(_Elementwise):
    def _fn(self, x, ctx):
        return jax.nn.sigmoid(x)


class LogSigmoid(_Elementwise):
    def _fn(self, x, ctx):
        return jax.nn.log_sigmoid(x)


class LogSoftMax(_Elementwise):
    """Over the last dim for 1D/2D input, matching Torch LogSoftMax.

    Always computed in f32: under the BF16_ACT policy the incoming logits
    are bfloat16, and log-probabilities need the f32 mantissa (the loss
    path is tiny, so the upcast is free)."""

    def _fn(self, x, ctx):
        if x.dtype in (jnp.bfloat16, jnp.float16):
            x = x.astype(jnp.float32)
        return jax.nn.log_softmax(x, axis=-1)


class SoftMax(_Elementwise):
    def _fn(self, x, ctx):
        return jax.nn.softmax(x, axis=-1)


class SoftMin(_Elementwise):
    def _fn(self, x, ctx):
        return jax.nn.softmax(-x, axis=-1)


class SoftPlus(_Elementwise):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def _fn(self, x, ctx):
        # Torch: 1/beta * log(1 + exp(beta * x)), with linear tail for stability
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    def _fn(self, x, ctx):
        return x / (1.0 + jnp.abs(x))


class SoftShrink(_Elementwise):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def _fn(self, x, ctx):
        return jnp.where(x > self.lam, x - self.lam,
                         jnp.where(x < -self.lam, x + self.lam, 0.0))


class HardShrink(_Elementwise):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def _fn(self, x, ctx):
        return jnp.where(jnp.abs(x) > self.lam, x, 0.0)


class HardTanh(_Elementwise):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 inplace: bool = False):
        super().__init__()
        assert max_value > min_value
        self.min_value = min_value
        self.max_value = max_value

    def _fn(self, x, ctx):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    """(ref Clamp.scala — HardTanh with int bounds)"""

    def __init__(self, min_value: int, max_value: int):
        super().__init__(float(min_value), float(max_value))


class Threshold(_Elementwise):
    """x if x > th else value (ref Threshold.scala:403)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__()
        self.threshold = th
        self.value = v

    def _fn(self, x, ctx):
        return jnp.where(x > self.threshold, x, self.value)


class LeakyReLU(_Elementwise):
    def __init__(self, negval: float = 0.01, inplace: bool = False):
        super().__init__()
        self.negval = negval

    def _fn(self, x, ctx):
        return jnp.where(x >= 0, x, x * self.negval)


class ELU(_Elementwise):
    def __init__(self, alpha: float = 1.0, inplace: bool = False):
        super().__init__()
        self.alpha = alpha

    def _fn(self, x, ctx):
        return jnp.where(x > 0, x, self.alpha * (jnp.exp(x) - 1.0))


class Abs(_Elementwise):
    def _fn(self, x, ctx):
        return jnp.abs(x)


class Sqrt(_Elementwise):
    def _fn(self, x, ctx):
        return jnp.sqrt(x)


class Square(_Elementwise):
    def _fn(self, x, ctx):
        return x * x


class Power(_Elementwise):
    """(shift + scale * x) ** power (ref Power.scala)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power = power
        self.scale = scale
        self.shift = shift

    def _fn(self, x, ctx):
        return jnp.power(self.shift + self.scale * x, self.power)


class Exp(_Elementwise):
    def _fn(self, x, ctx):
        return jnp.exp(x)


class Log(_Elementwise):
    def _fn(self, x, ctx):
        return jnp.log(x)


class PReLU(TensorModule):
    """Learnable leaky slope; nOutputPlane=0 means one shared slope
    (ref PReLU.scala:318)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane
        self.reset()

    def reset(self):
        n = max(1, self.n_output_plane)
        self._add_param("weight", jnp.full((n,), 0.25))
        return self

    def _forward(self, P, x, S, ctx):
        w = P["weight"]
        if self.n_output_plane > 0:
            # per-channel slope; channel dim is 1 for 4D (N,C,H,W), 0 for 3D
            shape = [1] * x.ndim
            ch_dim = 1 if x.ndim >= 2 else 0
            shape[ch_dim] = self.n_output_plane
            w = w.reshape(shape)
        return jnp.where(x >= 0, x, x * w), None


class RReLU(TensorModule):
    """Randomized leaky ReLU (ref RReLU.scala): slope ~ U(lower, upper) in
    training, fixed mean slope in eval."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 inplace: bool = False):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def _forward(self, P, x, S, ctx):
        if ctx.training:
            a = jax.random.uniform(ctx.next_key(), x.shape,
                                   minval=self.lower, maxval=self.upper,
                                   dtype=x.dtype)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, x * a), None


class GradientReversal(TensorModule):
    """Identity forward, -lambda * grad backward (ref GradientReversal.scala)."""

    def __init__(self, lam: float = 1.0):
        super().__init__()
        self.lam = lam

    def _forward(self, P, x, S, ctx):
        lam = self.lam

        @jax.custom_vjp
        def rev(v):
            return v

        def fwd(v):
            return v, None

        def bwd(_, g):
            return (-lam * g,)

        rev.defvjp(fwd, bwd)
        return rev(x), None

    def set_lambda(self, lam):
        self.lam = lam
        return self
