"""Criterions — full inventory (SURVEY.md §2.3 "Criterions (21)").

Conventions match the reference/Torch: class targets are **1-based** index
tensors; ``size_average=True`` divides by batch size.  Every criterion is a
pure scalar function (``apply_loss``) so ``jax.grad`` supplies the backward
the reference hand-writes per criterion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Criterion
from bigdl_tpu.utils.table import Table


def _reduce(per_sample, size_average):
    return per_sample.mean() if size_average else per_sample.sum()


def _onehot_1based(target, n_classes):
    return jax.nn.one_hot(jnp.asarray(target, jnp.int32) - 1, n_classes)


class ClassNLLCriterion(Criterion):
    """NLL over log-probabilities; expects LogSoftMax input + 1-based class
    targets (ref ClassNLLCriterion.scala).  Optional per-class weights."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__(size_average)
        self.weights = None if weights is None else jnp.asarray(weights)

    def apply_loss(self, input, target):
        if input.ndim == 1:
            input = input[None]
        target = jnp.reshape(target, (input.shape[0],))  # accept (B,) or (B,1)
        idx = jnp.asarray(target, jnp.int32) - 1
        picked = jnp.take_along_axis(input, idx[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, idx)
            loss = -(w * picked)
            return loss.sum() / w.sum() if self.size_average else loss.sum()
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (ref CrossEntropyCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__(size_average)
        self.nll = ClassNLLCriterion(weights, size_average)

    def apply_loss(self, input, target):
        return self.nll.apply_loss(jax.nn.log_softmax(input, axis=-1), target)


class MSECriterion(Criterion):
    """(ref MSECriterion.scala) — sizeAverage divides by n elements."""

    def apply_loss(self, input, target):
        d = (input - target) ** 2
        return d.mean() if self.size_average else d.sum()


class AbsCriterion(Criterion):
    def apply_loss(self, input, target):
        d = jnp.abs(input - target)
        return d.mean() if self.size_average else d.sum()


class BCECriterion(Criterion):
    """Binary cross-entropy on probabilities (ref BCECriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__(size_average)
        self.weights = None if weights is None else jnp.asarray(weights)

    def apply_loss(self, input, target):
        eps = 1e-12
        l = -(target * jnp.log(input + eps) + (1 - target) * jnp.log(1 - input + eps))
        if self.weights is not None:
            l = l * self.weights
        return l.mean() if self.size_average else l.sum()


class DistKLDivCriterion(Criterion):
    """KL(target || input) with log-prob input (ref DistKLDivCriterion.scala)."""

    def apply_loss(self, input, target):
        l = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-30)) - input), 0.0)
        n = input.shape[0] if input.ndim > 1 else 1
        return l.sum() / n if self.size_average else l.sum()


class ClassSimplexCriterion(Criterion):
    """MSE against simplex-embedded class targets
    (ref ClassSimplexCriterion.scala): classes map to vertices of a regular
    (nClasses-1)-simplex."""

    def __init__(self, n_classes: int):
        super().__init__(True)
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._build_simplex(n_classes))
        self.mse = MSECriterion()

    @staticmethod
    def _build_simplex(n):
        m = np.zeros((n, n), np.float32)
        np.fill_diagonal(m, 1.0)
        a = np.zeros((n, n), np.float32)
        for k in range(n - 1):
            s = a[k, :k] @ m[k, :k] if k else 0.0
            a[k, k] = np.sqrt(1.0 - (a[k, :k] ** 2).sum())
            for r in range(k + 1, n):
                dot = (a[k, :k] * a[r, :k]).sum()
                a[r, k] = ((-1.0 / (n - 1)) - dot) / a[k, k]
        return a

    def apply_loss(self, input, target):
        idx = jnp.asarray(target, jnp.int32) - 1
        t = jnp.take(self.simplex, idx, axis=0)
        return self.mse.apply_loss(input, t)


class CosineEmbeddingCriterion(Criterion):
    """Table(x1,x2) + y∈{1,-1}: 1-cos for similar, max(0, cos-margin) for
    dissimilar (ref CosineEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def apply_loss(self, input, target):
        x1, x2 = input[1], input[2]
        axis = -1 if x1.ndim > 1 else 0
        cos = (x1 * x2).sum(axis) / jnp.maximum(
            jnp.linalg.norm(x1, axis=axis) * jnp.linalg.norm(x2, axis=axis), 1e-12)
        y = jnp.reshape(target, cos.shape) if hasattr(target, "shape") else target
        l = jnp.where(y > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(l, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    """x + y∈{1,-1}: x if y=1 else max(0, margin - x)
    (ref HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def apply_loss(self, input, target):
        l = jnp.where(target > 0, input, jnp.maximum(0.0, self.margin - input))
        return _reduce(l, self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """Table(x1,x2) + y: L1 distance if y=1 else hinge
    (ref L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        super().__init__(True)
        self.margin = margin

    def apply_loss(self, input, target):
        d = jnp.abs(input[1] - input[2]).sum(-1 if input[1].ndim > 1 else 0)
        l = jnp.where(jnp.reshape(target, d.shape) > 0, d,
                      jnp.maximum(0.0, self.margin - d))
        return _reduce(l, self.size_average)


class MarginCriterion(Criterion):
    """Hinge loss max(0, margin - y*x) (ref MarginCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def apply_loss(self, input, target):
        l = jnp.maximum(0.0, self.margin - input * target)
        return l.mean() if self.size_average else l.sum()


class MarginRankingCriterion(Criterion):
    """Table(x1,x2) + y: max(0, -y*(x1-x2) + margin)
    (ref MarginRankingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def apply_loss(self, input, target):
        y = target[1] if isinstance(target, Table) else target
        l = jnp.maximum(0.0, -y * (input[1] - input[2]) + self.margin)
        return _reduce(l, self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (ref MultiCriterion.scala)."""

    def __init__(self):
        super().__init__(True)
        self.criterions = []
        self.weights = []

    def add(self, criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply_loss(self, input, target):
        return sum(w * c.apply_loss(input, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """i-th criterion on (input[i], target[i]) (ref ParallelCriterion.scala);
    ``repeat_target`` broadcasts one target to all."""

    def __init__(self, repeat_target: bool = False):
        super().__init__(True)
        self.repeat_target = repeat_target
        self.criterions = []
        self.weights = []

    def add(self, criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply_loss(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i + 1]
            total = total + w * c.apply_loss(input[i + 1], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """Multi-label hinge (ref MultiLabelMarginCriterion.scala): targets are
    1-based label lists padded with 0."""

    def __init__(self, size_average: bool = True):
        super().__init__(size_average)

    def apply_loss(self, input, target):
        if input.ndim == 1:
            input, target = input[None], jnp.reshape(target, (1, -1))
        n, d = input.shape
        tgt = jnp.asarray(target, jnp.int32)  # (n, d) 1-based, 0-padded
        valid = tgt > 0                        # (n, d)
        idx = jnp.maximum(tgt - 1, 0)
        tgt_scores = jnp.take_along_axis(input, idx, axis=1)  # (n, d)
        is_target = (_onehot_1based(tgt, d) * valid[..., None]).sum(axis=1) > 0  # (n, d)
        # for each valid target t and each non-target j: max(0, 1 - (x[t]-x[j]))
        margins = jnp.maximum(0.0, 1.0 - (tgt_scores[:, :, None] - input[:, None, :]))
        mask = valid[:, :, None] & ~is_target[:, None, :]
        l = (margins * mask).sum(axis=(1, 2)) / d
        return _reduce(l, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid + BCE per label (ref MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__(size_average)
        self.weights = None if weights is None else jnp.asarray(weights)

    def apply_loss(self, input, target):
        l = (jax.nn.softplus(-input) * target + jax.nn.softplus(input) * (1 - target))
        if self.weights is not None:
            l = l * self.weights
        per = l.mean(axis=-1) if l.ndim > 1 else l.mean()
        return _reduce(per, self.size_average) if l.ndim > 1 else per


class MultiMarginCriterion(Criterion):
    """Multi-class hinge: mean_j max(0, margin - x[y] + x[j])^p
    (ref MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__(size_average)
        self.p = p
        self.margin = margin
        self.weights = None if weights is None else jnp.asarray(weights)

    def apply_loss(self, input, target):
        if input.ndim == 1:
            input = input[None]
        n, d = input.shape
        idx = jnp.asarray(jnp.reshape(target, (n,)), jnp.int32) - 1
        x_y = jnp.take_along_axis(input, idx[:, None], axis=1)  # (n,1)
        m = jnp.maximum(0.0, self.margin - x_y + input) ** self.p
        if self.weights is not None:
            m = m * jnp.take(self.weights, idx)[:, None]
        m = m * (1.0 - jax.nn.one_hot(idx, d))
        l = m.sum(axis=1) / d
        return _reduce(l, self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber with delta=1 (ref SmoothL1Criterion.scala)."""

    def apply_loss(self, input, target):
        d = jnp.abs(input - target)
        l = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return l.mean() if self.size_average else l.sum()


class SmoothL1CriterionWithWeights(Criterion):
    """Fast-RCNN bbox loss with inside/outside weights and sigma
    (ref SmoothL1CriterionWithWeights.scala).  Input/target may be Tables
    (pred, ...) with weights, or plain tensors + weights at construction."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__(False)
        self.sigma2 = sigma * sigma
        self.num = num

    def apply_loss(self, input, target):
        if isinstance(target, Table):
            t, in_w, out_w = target[1], target[2], target[3]
        else:
            t, in_w, out_w = target, 1.0, 1.0
        d = (input - t) * in_w
        ad = jnp.abs(d)
        l = jnp.where(ad < 1.0 / self.sigma2,
                      0.5 * d * d * self.sigma2,
                      ad - 0.5 / self.sigma2) * out_w
        s = l.sum()
        if self.num > 0:
            return s / self.num
        # ref SmoothL1CriterionWithWeights.scala:100: sum / input.size(1)
        # (the batch dimension) when num is unset
        return s / input.shape[0]


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) (ref SoftMarginCriterion.scala)."""

    def apply_loss(self, input, target):
        l = jax.nn.softplus(-input * target)
        return l.mean() if self.size_average else l.sum()


class SoftmaxWithCriterion(Criterion):
    """Caffe-style SoftmaxWithLoss on (N, C, [H, W]) logits with spatial
    targets; supports ignore_label and normalize modes
    (ref SoftmaxWithCriterion.scala)."""

    def __init__(self, ignore_label: int = None, normalize_mode: str = "valid"):
        super().__init__(True)
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def apply_loss(self, input, target):
        logp = jax.nn.log_softmax(input, axis=1)
        idx = jnp.asarray(target, jnp.int32) - 1  # (N, [H, W])
        picked = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        if self.ignore_label is not None:
            mask = jnp.asarray(target, jnp.int32) != self.ignore_label
            picked = picked * mask
            count = mask.sum()
        else:
            count = picked.size
        loss = -picked.sum()
        if self.normalize_mode == "valid":
            return loss / jnp.maximum(count, 1)
        if self.normalize_mode == "batch_size":
            return loss / input.shape[0]
        if self.normalize_mode == "full":
            return loss / picked.size
        return loss


class L1Cost(Criterion):
    """sum |x| ignoring the target (ref L1Cost.scala)."""

    def apply_loss(self, input, target):
        return jnp.abs(input).sum()


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (N, T, ...) input
    (ref TimeDistributedCriterion.scala)."""

    def __init__(self, critrn: Criterion, size_average: bool = False):
        super().__init__(size_average)
        self.critrn = critrn

    def apply_loss(self, input, target):
        # vmap over the time axis: the same per-step sum for ANY inner
        # criterion, O(1) compile in T AND fully parallel — the static
        # Python unroll this replaces made XLA compile 8192 criterion
        # graphs at T=8k (round-5 long-context work), and a lax.scan
        # would serialize an embarrassingly parallel reduction
        t_len = input.shape[1]
        xs = jnp.swapaxes(input, 0, 1)
        per_step_target = hasattr(target, "ndim") and target.ndim > 1
        if per_step_target:
            losses = jax.vmap(self.critrn.apply_loss)(
                xs, jnp.swapaxes(target, 0, 1))
        else:
            losses = jax.vmap(self.critrn.apply_loss,
                              in_axes=(0, None))(xs, target)
        total = jnp.sum(losses)
        return total / t_len if self.size_average else total
