"""Autoencoder on MNIST (ref models/autoencoder/Train.scala).

  python examples/train_autoencoder.py -f ./mnist -b 150
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--folder", default="./mnist")
    p.add_argument("-b", "--batchSize", type=int, default=150)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--learningRate", type=float, default=0.01)
    p.add_argument("--maxEpoch", type=int, default=10)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import mnist, DataSet, Sample
    from bigdl_tpu.dataset.transformer import FuncTransformer, SampleToBatch
    from bigdl_tpu.optim import LocalOptimizer, max_epoch
    from bigdl_tpu.utils.table import T
    from bigdl_tpu.models.autoencoder import Autoencoder

    try:
        data = mnist.load(args.folder, training=True)
    except FileNotFoundError:
        logging.warning("no MNIST in %s — synthetic", args.folder)
        data = mnist.synthetic(2048)

    # target = the (normalized) input itself (ref autoencoder Train:
    # GreyImgToSample with feature as label)
    def to_sample(img):
        flat = (img.data / 255.0).astype(np.float32).reshape(-1)
        return Sample(flat, flat)

    ds = (DataSet.array(data) >> FuncTransformer(to_sample)
          >> SampleToBatch(args.batchSize))

    model = Autoencoder(class_num=32)
    opt = LocalOptimizer(model, ds, nn.MSECriterion())
    opt.set_state(T(learningRate=args.learningRate, momentum=0.9))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.set_iterations_per_dispatch(args.iterationsPerDispatch)
    opt.optimize()


if __name__ == "__main__":
    main()
