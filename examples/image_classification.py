"""Batch image classification with a trained model — the DLClassifier
pipeline (ref example/imageclassification/ImagePredictor.scala:34-54:
DataFrame of images -> DLClassifier.transform -> predictions).

  python examples/image_classification.py --modelPath lenet.model \
      -f ./images [-b 32] [--imageSize 28] [--grey]

With no --folder, classifies synthetic images (always runnable).
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--modelPath", required=True, help="saved .model snapshot")
    p.add_argument("-f", "--folder", default=None,
                   help="image folder (class subdirs optional)")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--imageSize", type=int, default=28)
    p.add_argument("--grey", action="store_true", help="single-channel input")
    p.add_argument("--mean", default=None,
                   help="comma-separated per-channel mean, MUST match what "
                        "the model was trained with (e.g. 123,117,104)")
    p.add_argument("--std", default=None,
                   help="comma-separated per-channel std (e.g. 58.4,57.1,57.4)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import numpy as np
    from bigdl_tpu.optim import DLClassifier
    from bigdl_tpu.utils import file as File

    model = File.load_module(args.modelPath)
    clf = DLClassifier(model, batch_size=args.batchSize)

    s = args.imageSize
    if args.folder:
        import os
        from bigdl_tpu.dataset import (
            ByteRecord, BytesToImg, ImgCropper, ImgToSample)
        names = []
        recs = []
        for root, _, files in os.walk(args.folder):
            for fn in sorted(files):
                if fn.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    path = os.path.join(root, fn)
                    with open(path, "rb") as f:
                        recs.append(ByteRecord(f.read(), 0.0))
                    names.append(path)
        if not recs:
            p.error(f"no .jpg/.jpeg/.png/.bmp images found under {args.folder}")
        pipeline = BytesToImg(scale_to=s) >> ImgCropper(s, s)
        if args.std is not None and args.mean is None:
            p.error("--std requires --mean")
        if args.mean is not None:
            from bigdl_tpu.dataset import ImgNormalizer
            mean = [float(v) for v in args.mean.split(",")]
            std = ([float(v) for v in args.std.split(",")]
                   if args.std is not None else [1.0] * len(mean))
            pipeline = pipeline >> ImgNormalizer(mean, std)
        else:
            logging.warning(
                "no --mean/--std given: feeding raw 0-255 pixels; pass the "
                "normalization the model was trained with for real results")
        pipeline = pipeline >> ImgToSample()
        feats = np.stack([smp.feature for smp in pipeline(iter(recs))])
        if args.grey:
            feats = feats.mean(axis=1, keepdims=True)
    else:
        logging.warning("no --folder given — classifying synthetic images")
        c = 1 if args.grey else 3
        feats = np.random.RandomState(0).rand(8, c, s, s).astype(np.float32)
        names = [f"synthetic-{i}" for i in range(len(feats))]

    preds = clf.predict_class(feats)
    for name, cls in zip(names, preds):
        print(f"{name}\t{cls}")
    return preds


if __name__ == "__main__":
    main()
