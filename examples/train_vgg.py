"""VGG on CIFAR-10 (ref models/vgg/Train.scala), BASELINE config 2.

  python examples/train_vgg.py -f ./cifar10 -b 128 --maxEpoch 90
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--folder", default="./cifar10")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--learningRate", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weightDecay", type=float, default=0.0005)
    p.add_argument("--maxEpoch", type=int, default=90)
    p.add_argument("--maxIteration", type=int, default=None,
                   help="stop after N iterations (smoke/perf runs)")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--pipeline", type=int, default=0,
                   help="train with P pipeline-parallel stages over a "
                        "'pipe' mesh axis (DistriOptimizer(pipeline_stages"
                        "=P)); 0 = off")
    p.add_argument("--pipelineSchedule", default="1f1b",
                   choices=["1f1b", "gpipe"])
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import cifar, DataSet
    from bigdl_tpu.dataset.image import (
        ImgNormalizer, ImgToBatch, ImgRdmCropper, HFlip)
    from bigdl_tpu.models.vgg import VggForCifar10
    from bigdl_tpu.optim import (Optimizer, max_epoch, max_iteration,
                                 every_epoch, Top1Accuracy)
    from bigdl_tpu.utils.table import T

    try:
        train_data = cifar.load(args.folder, training=True)
        test_data = cifar.load(args.folder, training=False)
    except FileNotFoundError:
        logging.warning("no CIFAR bins in %s — using synthetic data", args.folder)
        train_data, test_data = cifar.synthetic(2048), cifar.synthetic(512, seed=1)

    norm = ImgNormalizer(cifar.TRAIN_MEAN, cifar.TRAIN_STD)
    train_ds = (DataSet.array(train_data, distributed=args.distributed)
                >> norm >> ImgRdmCropper(32, 32, padding=4) >> HFlip()
                >> ImgToBatch(args.batchSize))
    test_ds = DataSet.array(test_data) >> norm >> ImgToBatch(args.batchSize)

    model = VggForCifar10(class_num=10)
    if args.pipeline:
        from bigdl_tpu.optim import DistriOptimizer
        optimizer = DistriOptimizer(model, train_ds, nn.ClassNLLCriterion(),
                                    pipeline_stages=args.pipeline,
                                    pipeline_schedule=args.pipelineSchedule)
    else:
        optimizer = Optimizer(model, train_ds, nn.ClassNLLCriterion())
    optimizer.set_state(T(learningRate=args.learningRate,
                          momentum=args.momentum,
                          weightDecay=args.weightDecay))
    if args.maxIteration:
        optimizer.set_end_when(max_iteration(args.maxIteration))
    else:
        optimizer.set_end_when(max_epoch(args.maxEpoch))
    optimizer.set_validation(every_epoch(), test_ds, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
    optimizer.set_iterations_per_dispatch(args.iterationsPerDispatch)
    optimizer.optimize()


if __name__ == "__main__":
    main()
