"""SimpleRNN character/word language model (ref models/rnn/Train.scala +
Utils: Dictionary, WordTokenizer, readSentence).

  python examples/train_rnn.py -f input.txt --hiddenSize 40 --bptt 4
Falls back to a small built-in corpus when the file is missing.
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

FALLBACK_CORPUS = """the quick brown fox jumps over the lazy dog
a stitch in time saves nine
all that glitters is not gold
actions speak louder than words
practice makes perfect every single day
the early bird catches the worm
better late than never they say
birds of a feather flock together
"""


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--dataFolder", default="./rnn_corpus.txt")
    p.add_argument("-b", "--batchSize", type=int, default=4)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--vocabSize", type=int, default=4000)
    p.add_argument("--hiddenSize", type=int, default=40)
    p.add_argument("--bptt", type=int, default=4)
    p.add_argument("--learningRate", type=float, default=0.1)
    p.add_argument("--maxEpoch", type=int, default=5)
    p.add_argument("--seqLength", type=int, default=8)
    p.add_argument("--numOfWords", type=int, default=0,
                   help="after training, autoregressively generate this "
                        "many words from the first corpus sentence (ref "
                        "rnn/Test.scala numOfWords)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import os
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.text import (
        Dictionary, WordTokenizer, SentenceToLabeledSentence,
        LabeledSentenceToSample)
    from bigdl_tpu.dataset.transformer import SampleToBatch
    from bigdl_tpu.models.rnn import SimpleRNN
    from bigdl_tpu.optim import LocalOptimizer, max_epoch
    from bigdl_tpu.utils.table import T

    if os.path.exists(args.dataFolder):
        with open(args.dataFolder) as f:
            lines = f.readlines()
    else:
        logging.warning("no corpus at %s — using built-in sample", args.dataFolder)
        lines = FALLBACK_CORPUS.strip().split("\n")

    tokenized = list(WordTokenizer()(iter(lines)))
    dictionary = Dictionary(tokenized, vocab_size=args.vocabSize)
    vocab = dictionary.vocab_size() + 1  # + OOV bucket

    ds = (DataSet.array(tokenized)
          >> SentenceToLabeledSentence(dictionary)
          >> LabeledSentenceToSample(n_input_dims=vocab,
                                     fixed_length=args.seqLength)
          >> SampleToBatch(args.batchSize))

    model = SimpleRNN(input_size=vocab, hidden_size=args.hiddenSize,
                      output_size=vocab, bptt_truncate=args.bptt)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True)
    opt = LocalOptimizer(model, ds, crit)
    opt.set_state(T(learningRate=args.learningRate))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.set_iterations_per_dispatch(args.iterationsPerDispatch)
    opt.optimize()

    if args.numOfWords > 0:
        # the reference's generation pass (rnn/Test.scala:58-90): seed
        # with a corpus sentence, sample word by word
        from bigdl_tpu.models.rnn import generate
        seed = [dictionary.index(w) for w in tokenized[0]]
        ids = generate(model, dictionary, seed, args.numOfWords)
        logging.info("generated: %s",
                     ",".join(dictionary.word(i) for i in ids))


if __name__ == "__main__":
    main()
