"""Inception-v1 on ImageNet — the distributed flagship
(ref models/inception/Train.scala: SGD + Poly(0.5, maxIter) schedule,
Train.scala:39-51), BASELINE config 3.

  python examples/train_inception.py -f ./imagenet -b 256 --maxIteration 62000
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--folder", default="./imagenet",
                   help="ImageFolder layout (class subdirs) or shard files")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--learningRate", type=float, default=0.0898)
    p.add_argument("--weightDecay", type=float, default=0.0001)
    p.add_argument("--maxIteration", type=int, default=62000)
    p.add_argument("--classNumber", type=int, default=1000)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--synthetic", action="store_true",
                   help="synthetic 224x224 data (DistriOptimizerPerf mode)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.image import (
        LabeledImage, ImgNormalizer, ImgToBatch, ImgRdmCropper, HFlip,
        BytesToImg)
    from bigdl_tpu.models.inception import Inception_v1
    from bigdl_tpu.optim import (
        Optimizer, max_iteration, several_iteration, Top1Accuracy,
        Top5Accuracy)
    from bigdl_tpu.optim.optim_method import Poly
    from bigdl_tpu.utils.table import T

    import os
    if not args.synthetic and not os.path.isdir(args.folder):
        if args.folder != p.get_default("folder"):
            # an explicitly-given path that doesn't exist is a user error,
            # not a cue to burn cycles training on noise
            p.error(f"image folder not found: {args.folder}")
        logging.warning("no image folder at %s — falling back to synthetic "
                        "data (DistriOptimizerPerf mode)", args.folder)
        args.synthetic = True
    if args.synthetic:
        rng = np.random.RandomState(0)
        data = [LabeledImage(rng.uniform(0, 255, (256, 256, 3)),
                             rng.randint(1, args.classNumber + 1))
                for _ in range(args.batchSize * 4)]
        train_ds = (DataSet.array(data, distributed=True)
                    >> ImgRdmCropper(224, 224) >> HFlip()
                    >> ImgNormalizer((123.0, 117.0, 104.0), (1.0, 1.0, 1.0))
                    >> ImgToBatch(args.batchSize))
    else:
        train_ds = (DataSet.image_folder(args.folder, distributed=True)
                    >> BytesToImg(256)
                    >> ImgRdmCropper(224, 224) >> HFlip()
                    >> ImgNormalizer((123.0, 117.0, 104.0), (1.0, 1.0, 1.0))
                    >> ImgToBatch(args.batchSize))

    model = Inception_v1(class_num=args.classNumber)
    optimizer = Optimizer(model, train_ds, nn.ClassNLLCriterion())
    optimizer.set_state(T(
        learningRate=args.learningRate,
        weightDecay=args.weightDecay,
        momentum=0.9,
        dampening=0.0,
        learningRateSchedule=Poly(0.5, args.maxIteration)))
    optimizer.set_end_when(max_iteration(args.maxIteration))
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, several_iteration(620))
    optimizer.set_iterations_per_dispatch(args.iterationsPerDispatch)
    optimizer.optimize()


if __name__ == "__main__":
    main()
