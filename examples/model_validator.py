"""Model import + validation (ref example/loadmodel/ModelValidator.scala:37-146):
load a BigDL-TPU / Torch .t7 / Caffe model and evaluate top-1/top-5.

  python examples/model_validator.py -t caffe --model alexnet \
      --modelPath net.caffemodel -f ./val_images
  python examples/model_validator.py -t torch --model alexnet --modelPath net.t7
  python examples/model_validator.py -t bigdl --modelPath snap.model
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

MODELS = {}

# per-architecture synthetic-eval input (HWC or HW) + class count
INPUT_SHAPES = {
    "alexnet": ((227, 227, 3), 1000),
    "inception": ((224, 224, 3), 1000),
    "vgg16": ((224, 224, 3), 1000),
    "lenet": ((28, 28), 10),
}


def _register():
    from bigdl_tpu.models.alexnet import AlexNet
    from bigdl_tpu.models.inception import Inception_v1
    from bigdl_tpu.models.vgg import Vgg_16
    from bigdl_tpu.models.lenet import LeNet5
    MODELS.update({
        "alexnet": lambda: AlexNet(1000),
        "inception": lambda: Inception_v1(1000),
        "vgg16": lambda: Vgg_16(1000),
        "lenet": lambda: LeNet5(10),
    })


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-t", "--modelType", choices=["bigdl", "torch", "caffe"],
                   required=True)
    p.add_argument("--model", default="alexnet",
                   help="architecture name (for torch/caffe weight import)")
    p.add_argument("--modelPath", required=True)
    p.add_argument("-f", "--folder", default=None,
                   help="validation ImageFolder; synthetic eval if omitted")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    _register()

    import numpy as np
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils import file as File
    from bigdl_tpu.utils import torch_file, caffe_loader
    from bigdl_tpu.optim import validate, Top1Accuracy, Top5Accuracy
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.image import (
        BytesToImg, ImgCropper, ImgNormalizer, ImgToBatch)

    if args.modelType == "bigdl":
        blob = File.load(args.modelPath)
        model = MODELS[args.model]()
        model.load_params(blob["params"])
        model.load_state(blob["state"])
    elif args.modelType == "torch":
        model = MODELS[args.model]()
        torch_file.load_module_weights(model, args.modelPath, strict=False)
    else:
        model = MODELS[args.model]()
        caffe_loader.load(model, args.modelPath, match_all=False)

    if args.folder:
        ds = (DataSet.image_folder(args.folder)
              >> BytesToImg(256) >> ImgCropper(224, 224)
              >> ImgNormalizer((123.0, 117.0, 104.0), (1.0, 1.0, 1.0))
              >> ImgToBatch(args.batchSize))
    else:
        logging.warning("no folder given — evaluating on synthetic data")
        from bigdl_tpu.dataset.image import LabeledImage
        shape, classes = INPUT_SHAPES.get(args.model, ((224, 224, 3), 1000))
        rng = np.random.RandomState(0)
        data = [LabeledImage(rng.uniform(0, 255, shape),
                             rng.randint(1, classes + 1)) for _ in range(64)]
        norm_mean = (123.0, 117.0, 104.0) if len(shape) == 3 else 33.0
        norm_std = (1.0, 1.0, 1.0) if len(shape) == 3 else 78.0
        ds = (DataSet.array(data)
              >> ImgNormalizer(norm_mean, norm_std)
              >> ImgToBatch(args.batchSize))

    results = validate(model, model.params(), model.state(), ds,
                       [Top1Accuracy(), Top5Accuracy()])
    for method, result in results:
        logging.info("%s: %s", method, result)


if __name__ == "__main__":
    main()
