"""LeNet-5 on MNIST — the reference's canonical Train main
(ref models/lenet/Train.scala:41-104), flag-for-flag:

  python examples/train_lenet.py -f /path/to/mnist -b 128 \
      --learningRate 0.05 --maxEpoch 15 [--model snap.model --state snap.state]

Falls back to synthetic data when no MNIST idx files are found (so the
example always runs; the reference instead exits).
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--folder", default="./mnist",
                   help="folder with train/t10k idx files")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--learningRate", type=float, default=0.05)
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--weightDecay", type=float, default=0.0)
    p.add_argument("--maxEpoch", type=int, default=15)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--model", default=None, help="model snapshot to resume")
    p.add_argument("--state", default=None, help="state snapshot to resume")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest VALID snapshot under "
                        "--checkpoint (corrupt/partial ones are skipped; "
                        "docs/resilience.md)")
    p.add_argument("--preemptible", action="store_true",
                   help="SIGTERM checkpoints and exits cleanly instead of "
                        "killing the run (docs/resilience.md)")
    p.add_argument("--distributed", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import mnist, DataSet
    from bigdl_tpu.dataset.image import ImgNormalizer, ImgToBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import (
        Optimizer, max_epoch, every_epoch, Top1Accuracy)
    from bigdl_tpu.utils.table import T
    from bigdl_tpu.utils import file as File

    try:
        train_data = mnist.load(args.folder, training=True)
        test_data = mnist.load(args.folder, training=False)
        norm_train = ImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)
        norm_test = ImgNormalizer(mnist.TEST_MEAN, mnist.TEST_STD)
    except FileNotFoundError:
        logging.warning("no MNIST idx files in %s — using synthetic data", args.folder)
        train_data, test_data = mnist.synthetic(2048), mnist.synthetic(512, seed=1)
        norm_train = norm_test = ImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)

    train_ds = (DataSet.array(train_data, distributed=args.distributed)
                >> norm_train >> ImgToBatch(args.batchSize))
    test_ds = DataSet.array(test_data) >> norm_test >> ImgToBatch(args.batchSize)

    model = LeNet5(class_num=10)
    if args.model:
        File.load_module_into(model, args.model)

    resume_blob = None
    if args.resume:
        if not args.checkpoint:
            p.error("--resume needs --checkpoint (the snapshot folder)")
        from bigdl_tpu.optim import load_latest_checkpoint
        found = load_latest_checkpoint(args.checkpoint, restore_rng=True)
        if found is not None:
            model, resume_blob, neval = found
            logging.info("resuming from snapshot %d under %s", neval,
                         args.checkpoint)
        else:
            logging.warning("no valid snapshot under %s — starting fresh",
                            args.checkpoint)

    optimizer = Optimizer(model, train_ds, nn.ClassNLLCriterion())
    state = T(learningRate=args.learningRate, momentum=args.momentum,
              weightDecay=args.weightDecay)
    if args.state:
        resume_blob = File.load(args.state)
    if resume_blob is not None:
        state.update(resume_blob["state"])
        if resume_blob.get("opt_state") is not None:
            optimizer.set_optim_state(resume_blob["opt_state"])  # momentum
    optimizer.set_state(state)
    if args.preemptible:
        from bigdl_tpu.utils.engine import Engine
        Engine.install_preemption_handler()
    optimizer.set_end_when(max_epoch(args.maxEpoch))
    optimizer.set_validation(every_epoch(), test_ds, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
    optimizer.set_iterations_per_dispatch(args.iterationsPerDispatch)
    optimizer.optimize()


if __name__ == "__main__":
    main()
