"""Text classifier (ref example/textclassification/TextClassifier.scala:119-140):
a temporal conv net over word embeddings (the reference uses GloVe vectors +
SpatialConvolution as 1D conv), 20-newsgroups-style classification — plus the
Bi-LSTM variant of BASELINE.md config 4 (``--model lstm``:
BiRecurrent(LSTMCell, LSTMCell) with recurrence as lax.scan).

  python examples/text_classifier.py -f ./20news --classNum 20
  python examples/text_classifier.py --model lstm
Falls back to a synthetic corpus when no data dir exists.
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--baseDir", default="./20news")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--classNum", type=int, default=5)
    p.add_argument("--seqLength", type=int, default=200)
    p.add_argument("--embedDim", type=int, default=50)
    p.add_argument("--learningRate", type=float, default=0.01)
    p.add_argument("--maxEpoch", type=int, default=3)
    p.add_argument("--model", choices=["conv", "lstm"], default="conv",
                   help="conv = reference temporal conv net; lstm = Bi-LSTM "
                        "(BASELINE config 4)")
    p.add_argument("--hiddenSize", type=int, default=128)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample
    from bigdl_tpu.dataset.transformer import SampleToBatch
    from bigdl_tpu.optim import LocalOptimizer, max_epoch, every_epoch, Top1Accuracy
    from bigdl_tpu.utils.table import T

    import os
    from bigdl_tpu.dataset import news20
    if os.path.isdir(args.baseDir):
        # real 20-newsgroups + GloVe (pre-extracted; ref news20.py)
        texts = news20.get_news20(args.baseDir)
        w2v = news20.get_glove_w2v(args.baseDir, dim=args.embedDim)
        samples = news20.embed_samples(texts, w2v, args.seqLength,
                                       args.embedDim)
        args.classNum = int(max(s.label[0] for s in samples))
        rng = np.random.RandomState(0)
        rng.shuffle(samples)
    else:
        logging.warning("no data at %s — synthetic embedded documents",
                        args.baseDir)
        # class-dependent mean in embedding space
        rng = np.random.RandomState(0)
        class_means = rng.randn(args.classNum, args.embedDim)
        samples = []
        for i in range(512):
            c = i % args.classNum
            doc = (rng.randn(args.seqLength, args.embedDim) * 0.5
                   + class_means[c]).astype(np.float32)
            samples.append(Sample(doc, np.asarray([c + 1.0])))

    split = int(len(samples) * 0.8)
    train_ds = DataSet.array(samples[:split]) >> SampleToBatch(args.batchSize, drop_last=True)
    val_ds = DataSet.array(samples[split:]) >> SampleToBatch(args.batchSize, drop_last=True)

    from bigdl_tpu.models.textclassifier import (TextClassifierConv,
                                                  TextClassifierBiLSTM)
    if args.model == "lstm":
        model = TextClassifierBiLSTM(args.classNum, args.embedDim,
                                     args.hiddenSize)
    else:
        model = TextClassifierConv(args.classNum, args.seqLength,
                                   args.embedDim)
    opt = LocalOptimizer(model, train_ds, nn.ClassNLLCriterion())
    opt.set_state(T(learningRate=args.learningRate, momentum=0.9))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.set_validation(every_epoch(), val_ds, [Top1Accuracy()])
    opt.optimize()


if __name__ == "__main__":
    main()
