"""Text classifier (ref example/textclassification/TextClassifier.scala:119-140):
a temporal conv net over word embeddings (the reference uses GloVe vectors +
SpatialConvolution as 1D conv), 20-newsgroups-style classification.

  python examples/text_classifier.py -f ./20news --classNum 20
Falls back to a synthetic corpus when no data dir exists.
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def build_model(class_num: int, seq_len: int = 200, embed_dim: int = 50):
    """(ref TextClassifier.buildModel :119-140): three conv5-relu-maxpool
    stages on the (1, seq, embed) plane, then a linear head.  The
    reference hardcodes the last pooling to 35 for its 1000-token
    sequences; here the final pool consumes whatever extent remains, so
    any seq_len that survives the first two stages (>= 149) works."""
    import bigdl_tpu.nn as nn
    h1 = seq_len - 4          # conv kh=5
    h2 = (h1 - 5) // 5 + 1    # pool 5/5
    h3 = h2 - 4               # conv kh=5
    h4 = (h3 - 5) // 5 + 1    # pool 5/5
    h5 = h4 - 4               # conv kh=5
    if h5 < 1:
        raise ValueError(f"seqLength {seq_len} too short for 3 conv stages")
    m = nn.Sequential()
    m.add(nn.Reshape([1, seq_len, embed_dim]))
    m.add(nn.SpatialConvolution(1, 128, embed_dim, 5))   # kw=embed, kh=5
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(1, 5, 1, 5))
    m.add(nn.SpatialConvolution(128, 128, 1, 5))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(1, 5, 1, 5))
    m.add(nn.SpatialConvolution(128, 128, 1, 5))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(1, h5, 1, h5))            # ref: 35 @ seq 1000
    m.add(nn.Reshape([128]))
    m.add(nn.Linear(128, 100))
    m.add(nn.ReLU())
    m.add(nn.Linear(100, class_num))
    m.add(nn.LogSoftMax())
    return m


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--baseDir", default="./20news")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--classNum", type=int, default=5)
    p.add_argument("--seqLength", type=int, default=200)
    p.add_argument("--embedDim", type=int, default=50)
    p.add_argument("--learningRate", type=float, default=0.01)
    p.add_argument("--maxEpoch", type=int, default=3)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample
    from bigdl_tpu.dataset.transformer import SampleToBatch
    from bigdl_tpu.optim import LocalOptimizer, max_epoch, every_epoch, Top1Accuracy
    from bigdl_tpu.utils.table import T

    import os
    from bigdl_tpu.dataset import news20
    if os.path.isdir(args.baseDir):
        # real 20-newsgroups + GloVe (pre-extracted; ref news20.py)
        texts = news20.get_news20(args.baseDir)
        w2v = news20.get_glove_w2v(args.baseDir, dim=args.embedDim)
        samples = news20.embed_samples(texts, w2v, args.seqLength,
                                       args.embedDim)
        args.classNum = int(max(s.label[0] for s in samples))
        rng = np.random.RandomState(0)
        rng.shuffle(samples)
    else:
        logging.warning("no data at %s — synthetic embedded documents",
                        args.baseDir)
        # class-dependent mean in embedding space
        rng = np.random.RandomState(0)
        class_means = rng.randn(args.classNum, args.embedDim)
        samples = []
        for i in range(512):
            c = i % args.classNum
            doc = (rng.randn(args.seqLength, args.embedDim) * 0.5
                   + class_means[c]).astype(np.float32)
            samples.append(Sample(doc, np.asarray([c + 1.0])))

    split = int(len(samples) * 0.8)
    train_ds = DataSet.array(samples[:split]) >> SampleToBatch(args.batchSize, drop_last=True)
    val_ds = DataSet.array(samples[split:]) >> SampleToBatch(args.batchSize, drop_last=True)

    model = build_model(args.classNum, args.seqLength, args.embedDim)
    opt = LocalOptimizer(model, train_ds, nn.ClassNLLCriterion())
    opt.set_state(T(learningRate=args.learningRate, momentum=0.9))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.set_validation(every_epoch(), val_ds, [Top1Accuracy()])
    opt.optimize()


if __name__ == "__main__":
    main()
