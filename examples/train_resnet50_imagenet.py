"""ResNet-50 on ImageNet with streaming shard input and optionally loaded
Caffe weights (BASELINE config 5: "ResNet-50/ImageNet with Spark RDD->HBM
streaming + loaded Caffe weights" — here shards stream through the host
pipeline with background prefetch into device batches).

  python -m bigdl_tpu.dataset.imagenet_tools -f ./imagenet/train -o ./shards
  python examples/train_resnet50_imagenet.py -f ./shards \
      [--caffeWeights resnet50.caffemodel] -b 256
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--shardFolder", default="./shards",
                   help="local dir or fsspec URL (gs://bucket/shards, "
                        "s3://..., memory://) of .bdts shards")
    p.add_argument("-b", "--batchSize", type=int, default=256)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--caffeWeights", default=None)
    p.add_argument("--learningRate", type=float, default=0.1)
    p.add_argument("--weightDecay", type=float, default=1e-4)
    p.add_argument("--maxEpoch", type=int, default=90)
    p.add_argument("--classNumber", type=int, default=1000)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--synthetic", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.shardfile import ShardFolder
    from bigdl_tpu.dataset.image import (
        LabeledImage, BytesToImg, ImgRdmCropper, HFlip, ColorJitter,
        Lighting, ImgNormalizer, ImgToBatch)
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.optim import (
        Optimizer, DistriOptimizer, max_epoch, every_epoch)
    from bigdl_tpu.optim.optim_method import EpochStep
    from bigdl_tpu.utils.table import T
    from bigdl_tpu.utils import caffe_loader

    if args.synthetic:
        rng = np.random.RandomState(0)
        data = [LabeledImage(rng.uniform(0, 255, (224, 224, 3)),
                             rng.randint(1, args.classNumber + 1))
                for _ in range(args.batchSize * 2)]
        train_ds = (DataSet.array(data, distributed=True)
                    >> HFlip()
                    >> ImgNormalizer((123.0, 117.0, 104.0), (58.4, 57.1, 57.4))
                    >> ImgToBatch(args.batchSize))
    else:
        # streaming: shards -> decode -> augment -> batch.  No explicit
        # PreFetch stage: the optimizer's built-in pipeline
        # (BIGDL_PREFETCH, dataset/prefetch.py) runs this whole chain on
        # a background producer and double-buffers batches onto device
        train_ds = (ShardFolder(args.shardFolder, distributed=True)
                    >> BytesToImg(256)
                    >> ImgRdmCropper(224, 224) >> HFlip()
                    >> ColorJitter(channel_order="rgb") >> Lighting()
                    >> ImgNormalizer((123.0, 117.0, 104.0), (58.4, 57.1, 57.4))
                    >> ImgToBatch(args.batchSize))

    model = ResNet(depth=50, class_num=args.classNumber)
    if args.caffeWeights:
        _, copied = caffe_loader.load(model, args.caffeWeights, match_all=False)
        logging.info("loaded caffe weights for %d layers", len(copied))

    optimizer = Optimizer(model, train_ds, nn.ClassNLLCriterion())
    optimizer.set_state(T(learningRate=args.learningRate,
                          weightDecay=args.weightDecay,
                          momentum=0.9, dampening=0.0, nesterov=True,
                          learningRateSchedule=EpochStep(30, 0.1)))
    optimizer.set_end_when(max_epoch(args.maxEpoch))
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
    optimizer.set_iterations_per_dispatch(args.iterationsPerDispatch)
    optimizer.optimize()


if __name__ == "__main__":
    main()
