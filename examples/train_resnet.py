"""ResNet on CIFAR-10 (ref models/resnet/Train.scala).

  python examples/train_resnet.py -f ./cifar10 --depth 20 -b 128
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--folder", default="./cifar10")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side loop: n scanned steps per dispatch")
    p.add_argument("--depth", type=int, default=20)
    p.add_argument("--learningRate", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weightDecay", type=float, default=1e-4)
    p.add_argument("--maxEpoch", type=int, default=165)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--distributed", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import cifar, DataSet
    from bigdl_tpu.dataset.image import (
        ImgNormalizer, ImgToBatch, ImgRdmCropper, HFlip)
    from bigdl_tpu.models.resnet import ResNetCifar
    from bigdl_tpu.optim import Optimizer, max_epoch, every_epoch, Top1Accuracy
    from bigdl_tpu.optim.optim_method import EpochSchedule, EpochStep
    from bigdl_tpu.utils.table import T

    try:
        train_data = cifar.load(args.folder, training=True)
        test_data = cifar.load(args.folder, training=False)
    except FileNotFoundError:
        logging.warning("no CIFAR bins in %s — using synthetic data", args.folder)
        train_data, test_data = cifar.synthetic(2048), cifar.synthetic(512, seed=1)

    norm = ImgNormalizer(cifar.TRAIN_MEAN, cifar.TRAIN_STD)
    train_ds = (DataSet.array(train_data, distributed=args.distributed)
                >> norm >> ImgRdmCropper(32, 32, padding=4) >> HFlip()
                >> ImgToBatch(args.batchSize))
    test_ds = DataSet.array(test_data) >> norm >> ImgToBatch(args.batchSize)

    model = ResNetCifar(depth=args.depth, class_num=10, shortcut_type="A")
    optimizer = Optimizer(model, train_ds, nn.ClassNLLCriterion())
    # the fb.resnet-style 81/122 epoch decay the reference uses
    optimizer.set_state(T(learningRate=args.learningRate,
                          momentum=args.momentum,
                          weightDecay=args.weightDecay,
                          dampening=0.0,
                          nesterov=True,
                          learningRateSchedule=EpochStep(81, 0.1)))
    optimizer.set_end_when(max_epoch(args.maxEpoch))
    optimizer.set_validation(every_epoch(), test_ds, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
    optimizer.set_iterations_per_dispatch(args.iterationsPerDispatch)
    optimizer.optimize()


if __name__ == "__main__":
    main()
