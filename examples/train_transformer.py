"""Transformer text classifier on news20 embeddings — the attention-family
training CLI (no reference counterpart; the reference's text example is
the RNN text classifier, examples/textclassifier).

  python examples/train_transformer.py -b 128 --maxEpoch 5
  python examples/train_transformer.py --sequenceParallel 4   # dp x sp mesh
  python examples/train_transformer.py --moeExperts 8 --expertParallel 4
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--folder", default="./news20")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--embedDim", type=int, default=128)
    p.add_argument("--seqLen", type=int, default=128)
    p.add_argument("--dModel", type=int, default=None,
                   help="model width; defaults to --embedDim (a projection "
                        "is prepended when they differ)")
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--moeExperts", type=int, default=0,
                   help="replace FFN blocks with nn.MoE of this many experts")
    p.add_argument("--learningRate", type=float, default=0.01)
    p.add_argument("--maxEpoch", type=int, default=5)
    p.add_argument("--maxIteration", type=int, default=None)
    p.add_argument("--iterationsPerDispatch", type=int, default=1)
    p.add_argument("--sequenceParallel", type=int, default=0,
                   help="shard the sequence dim over a 'seq' mesh axis of "
                        "this size (ring attention); 0 = off")
    p.add_argument("--expertParallel", type=int, default=0,
                   help="shard MoE experts over an 'expert' mesh axis of "
                        "this size; 0 = off")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToBatch
    from bigdl_tpu.models.transformer import TransformerClassifier
    from bigdl_tpu.optim import (DistriOptimizer, Optimizer, Top1Accuracy,
                                 every_epoch, max_epoch, max_iteration)
    from bigdl_tpu.parallel.mesh import make_mesh
    from bigdl_tpu.utils.table import T

    if args.sequenceParallel and args.expertParallel:
        raise SystemExit("pick one of --sequenceParallel/--expertParallel")
    if args.expertParallel:
        if args.moeExperts <= 0:
            raise SystemExit("--expertParallel needs --moeExperts > 0 "
                             "(there are no expert params to shard)")
        if args.moeExperts % args.expertParallel:
            raise SystemExit(
                f"--moeExperts ({args.moeExperts}) must divide by "
                f"--expertParallel ({args.expertParallel})")

    import os
    from bigdl_tpu.dataset import news20
    n_class = 20
    if os.path.isdir(args.folder):
        # real 20-newsgroups + GloVe (pre-extracted; ref news20.py)
        texts = news20.get_news20(args.folder)
        w2v = news20.get_glove_w2v(args.folder, dim=args.embedDim)
        samples = news20.embed_samples(texts, w2v, args.seqLen,
                                       args.embedDim)
        n_class = int(max(s.label[0] for s in samples))
        np.random.RandomState(0).shuffle(samples)
    else:
        logging.warning("no news20 data in %s — synthetic embedded docs",
                        args.folder)
        rs = np.random.RandomState(0)
        class_means = rs.randn(n_class, args.embedDim)
        samples = [Sample((rs.randn(args.seqLen, args.embedDim) * 0.5
                           + class_means[i % n_class]).astype(np.float32),
                          np.asarray([float(i % n_class + 1)], np.float32))
                   for i in range(2048)]

    split = int(len(samples) * 0.8)
    train_ds = (DataSet.array(samples[:split])
                >> SampleToBatch(args.batchSize, drop_last=True))
    test_ds = (DataSet.array(samples[split:])
               >> SampleToBatch(args.batchSize, drop_last=True))

    d_model = args.dModel or args.embedDim
    model = TransformerClassifier(n_class, d_model=d_model,
                                  n_heads=args.heads, n_layers=args.layers,
                                  hidden=args.hidden,
                                  moe_experts=args.moeExperts)
    if d_model != args.embedDim:
        model = nn.Sequential(
            nn.TimeDistributed(nn.Linear(args.embedDim, d_model)), model)
    if args.sequenceParallel:
        optimizer = DistriOptimizer(
            model, train_ds, nn.ClassNLLCriterion(),
            mesh=make_mesh({"data": -1, "seq": args.sequenceParallel}),
            sequence_parallel=True)
    elif args.expertParallel:
        optimizer = DistriOptimizer(
            model, train_ds, nn.ClassNLLCriterion(),
            mesh=make_mesh({"data": -1, "expert": args.expertParallel}),
            expert_parallel=True)
    else:
        optimizer = Optimizer(model, train_ds, nn.ClassNLLCriterion())
    optimizer.set_state(T(learningRate=args.learningRate))
    if args.maxIteration:
        optimizer.set_end_when(max_iteration(args.maxIteration))
    else:
        optimizer.set_end_when(max_epoch(args.maxEpoch))
    optimizer.set_validation(every_epoch(), test_ds, [Top1Accuracy()])
    optimizer.set_iterations_per_dispatch(args.iterationsPerDispatch)
    optimizer.optimize()


if __name__ == "__main__":
    main()
