"""Causal transformer word language model + generation — the attention-
family counterpart of examples/train_rnn.py (ref models/rnn Train.scala +
Test.scala pairing; the reference has no transformer, SURVEY.md §2.9).

  python examples/train_transformer_lm.py -f input.txt --layers 2
  python examples/train_transformer_lm.py --numOfWords 10   # sample after
Falls back to a small built-in corpus when the file is missing.
"""
import argparse
import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from examples.train_rnn import load_corpus


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-f", "--dataFolder", default="./rnn_corpus.txt")
    p.add_argument("-b", "--batchSize", type=int, default=4)
    p.add_argument("--iterationsPerDispatch", type=int, default=1,
                   help="device-side scanned steps per dispatch")
    p.add_argument("--vocabSize", type=int, default=4000)
    p.add_argument("--dModel", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--learningRate", type=float, default=0.1)
    p.add_argument("--maxEpoch", type=int, default=5)
    p.add_argument("--seqLength", type=int, default=8)
    p.add_argument("--numOfWords", type=int, default=0,
                   help="after training, autoregressively generate this "
                        "many words from the first corpus sentence (the "
                        "rnn/Test.scala numOfWords role)")
    p.add_argument("--fastDecode", action="store_true",
                   help="generate via the KV-cached single-scan decoder "
                        "(models.transformer.lm_decode) instead of "
                        "re-forwarding the prefix per word")
    p.add_argument("--beamSize", type=int, default=0,
                   help="> 0: beam-search the continuation instead of "
                        "sampling (models.transformer.lm_beam_search; "
                        "implies the KV-cached scan)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.text import (
        Dictionary, WordTokenizer, SentenceToLabeledSentence,
        LabeledSentenceToSample)
    from bigdl_tpu.dataset.transformer import SampleToBatch
    from bigdl_tpu.models.rnn import generate
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.optim import LocalOptimizer, max_epoch
    from bigdl_tpu.utils.table import T

    lines = load_corpus(args.dataFolder)
    tokenized = list(WordTokenizer()(iter(lines)))
    dictionary = Dictionary(tokenized, vocab_size=args.vocabSize)
    vocab = dictionary.vocab_size() + 1  # + OOV bucket

    ds = (DataSet.array(tokenized)
          >> SentenceToLabeledSentence(dictionary)
          >> LabeledSentenceToSample(n_input_dims=vocab,
                                     fixed_length=args.seqLength)
          >> SampleToBatch(args.batchSize))

    model = TransformerLM(vocab_size=vocab, d_model=args.dModel,
                          n_heads=args.heads, n_layers=args.layers,
                          hidden=args.hidden)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    opt = LocalOptimizer(model, ds, crit)
    opt.set_state(T(learningRate=args.learningRate))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.set_iterations_per_dispatch(args.iterationsPerDispatch)
    opt.optimize()

    if args.numOfWords > 0:
        seed = [dictionary.index(w) for w in tokenized[0]]
        if args.beamSize > 0:
            from bigdl_tpu.models.transformer import lm_beam_search
            ids = lm_beam_search(model, seed, args.numOfWords,
                                 beam_size=args.beamSize)
        elif args.fastDecode:
            # one lax.scan with per-layer KV caches: no O(T^2) prefix
            # re-forward, no host round-trip per token
            import jax
            from bigdl_tpu.models.transformer import lm_decode
            ids = lm_decode(model, seed, args.numOfWords, greedy=False,
                            key=jax.random.PRNGKey(0))
        else:
            # same sampling loop as the RNN family — the LM shares the
            # one-hot (B, T, vocab) -> per-token log-probs contract
            ids = generate(model, dictionary, seed, args.numOfWords)
        logging.info("generated: %s",
                     ",".join(dictionary.word(i) for i in ids))


if __name__ == "__main__":
    main()
