"""Benchmark: the five BASELINE.md configs plus the transformer-encoder
flagship, like the reference's DistriOptimizerPerf CLI
(models/utils/DistriOptimizerPerf.scala:41-138: synthetic data,
multi-model `-m` flag, default batch 128).

Prints ONE JSON line (driver contract): the headline metric is the
Inception-v1 config; ``detail.configs`` carries all six entries
(LeNet-5/MNIST, VGG-16/CIFAR-10, Inception-v1/ImageNet, Bi-LSTM text
classifier, ResNet-50/ImageNet, Transformer encoder), each with step ms,
records/s, MFU and the same-run measured matmul roofline.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the BASELINE.json north-star bar of 0.4 MFU:
vs_baseline = achieved_MFU / 0.4 (>1.0 beats the target).  MFU uses XLA's
own per-step FLOP count from compiled cost analysis and the chip's
datasheet peak for the dtype in use.

Usage: python bench.py [substring]   # e.g. `python bench.py lenet`
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def guess_peak(device) -> float:
    """Datasheet bf16 peak — resolved through the obs ledger's shared
    table (``bigdl_tpu/obs/ledger.py``), the SAME denominator the live
    ``train_mfu`` gauge divides by, so bench MFU and runtime MFU can
    never disagree on the peak.  Lazy import keeps the bench CLI's
    startup jax-free."""
    from bigdl_tpu.obs.ledger import device_peak_flops
    return device_peak_flops(device)


_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOFLINE_SIDECAR = os.path.join(_HERE, ".bench_roofline.json")


def _enable_compile_cache():
    """Persistent XLA compilation cache for bench subprocesses (same
    mechanism as tests/conftest.py).  Measured on the axon-relay v5e: the
    cache DOES serve TPU executables across processes (1.15s cold ->
    0.01s warm for a probe jit), so retries after a relay wedge and
    repeat runs skip their compile, reclaiming 20-60s of each 300s
    config budget."""
    import jax
    try:
        os.makedirs(os.path.join(_HERE, ".xla_cache"), exist_ok=True)
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_HERE, ".xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # cache is an optimization, never a blocker
        print("compile cache unavailable: %r" % e, file=sys.stderr,
              flush=True)


def _save_roofline_sidecar(roof, device):
    try:
        with open(_ROOFLINE_SIDECAR, "w") as f:
            json.dump({"roofline_tflops": roof, "device": device,
                       "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S")},
                      f)
    except Exception as e:
        print("roofline sidecar write failed: %r" % e, file=sys.stderr,
              flush=True)


# last-good in-band measurement, committed so a FRESH workspace (no
# sidecar file yet) can still ship a self-interpreting artifact when the
# probes wedge (the sidecar file itself stays untracked: every run
# rewrites its timestamp).  Only honored for the matching chip.
_ROOFLINE_LAST_GOOD = {"roofline_tflops": 186.9, "device": "TPU v5 lite",
                       "measured_at": "2026-07-31 (committed default)"}


def _load_roofline_sidecar(run_device):
    """Last-good roofline, ONLY if it was measured on ``run_device``
    (or either side is unknown).  The chip-match guard lives here so no
    call site can contextualize a run with another chip's roofline."""
    try:
        with open(_ROOFLINE_SIDECAR) as f:
            cached = json.load(f)
    except Exception:
        cached = dict(_ROOFLINE_LAST_GOOD)
    if (cached.get("device") in (run_device, "unknown")
            or run_device == "unknown"):
        return cached
    print("roofline sidecar is for %r, this run is on %r — not using it"
          % (cached.get("device"), run_device), file=sys.stderr, flush=True)
    return None


def _raw_step(model, criterion):
    """The un-jitted per-step train function shared by make_step (one
    dispatch per step) and make_chunk_step (scanned device-side loop)."""
    import jax
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.optim_method import SGD

    method = SGD()
    hyper = {"lr": 0.01, "momentum": 0.9, "dampening": 0.0,
             "weight_decay": 0.0001, "nesterov": False}

    def train_step(params, net_state, opt_state, x, y, key):
        def loss_fn(p):
            out, ns = model.apply(p, x, net_state,
                                  Context(training=True, key=key))
            return criterion.apply_loss(out, y), ns
        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = method.update(grads, opt_state, params, hyper)
        return new_params, ns, new_opt, loss

    params, net_state = model.params(), model.state()
    opt_state = method.init_state(params)
    return train_step, params, net_state, opt_state


def make_step(model, criterion):
    import jax
    train_step, params, net_state, opt_state = _raw_step(model, criterion)
    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    return step, params, net_state, opt_state


def make_chunk_step(model, criterion, n_steps):
    """A device-side training loop: ONE dispatch runs ``n_steps`` train
    steps via lax.scan, each consuming a DISTINCT minibatch from a
    stacked (n_steps, B, ...) device array — the TPU-native host-loop
    pattern (the optimizer exposes it as set_iterations_per_dispatch).
    Small models are relay/dispatch-latency-bound per call (VGG-CIFAR:
    4.7 ms device work inside a 25.7 ms wall step); amortizing the fixed
    cost over n_steps recovers the device-limited rate."""
    import jax
    from jax import lax

    train_step, params, net_state, opt_state = _raw_step(model, criterion)

    def one(carry, xyk):
        x, y, key = xyk
        p, ns, o, loss = train_step(*carry, x, y, key)
        return (p, ns, o), loss

    def chunk(params, net_state, opt_state, xs, ys, key):
        keys = jax.random.split(key, n_steps)
        (params, net_state, opt_state), losses = lax.scan(
            one, (params, net_state, opt_state), (xs, ys, keys))
        return params, net_state, opt_state, losses[-1]

    step = jax.jit(chunk, donate_argnums=(0, 1, 2))
    return step, params, net_state, opt_state


def bench_config(build, records_per_batch, warmup=3, iters=10, windows=3,
                 flops_override=None, steps_per_dispatch=8):
    """Returns (records/s, step_ms, mfu, flops_per_step, loss, band,
    fetch_ms_per_step).

    Trains with the device-side loop (``steps_per_dispatch`` scanned
    steps per dispatch over DISTINCT stacked minibatches) — what a real
    prefetching training loop on this hardware does; the per-call relay
    latency otherwise dominates the small configs.  ``fetch_ms_per_step``
    is the host-side batch staging + H2D wall amortized per scanned step
    — the work the training loops' prefetch pipeline
    (``dataset/prefetch.py``) overlaps with compute."""
    import jax
    import jax.numpy as jnp

    model, criterion, x, y = build()
    n = steps_per_dispatch
    # distinct batch per scanned step: vary the shared synthetic batch
    # with a cheap per-step perturbation (content does not affect timing;
    # training semantics stay honest — every step sees different data)
    rs = np.random.RandomState(7)
    xh = np.asarray(x)
    xs = jnp.stack([jnp.asarray(xh * (1.0 + 0.01 * rs.randn()), x.dtype)
                    for _ in range(n)])
    ys = jnp.stack([y] * n)
    step, params, net_state, opt_state = make_chunk_step(model, criterion, n)
    from bigdl_tpu.utils.random import RNG
    key = RNG.next_key()  # honors the bench's rbg device-PRNG selection
    if flops_override is not None:
        flops = float(flops_override)
    else:
        flops = float("nan")
        for _ in range(2):   # transient relay errors can fail one attempt
            try:
                # XLA cost analysis counts a lax.scan body ONCE, so the
                # chunk's number is already the per-step count.  The
                # probe resolves through the shared CostLedger — ONE
                # cost code path with the live train_mfu gauge and
                # tools/profile_step.py, which also normalizes the
                # list-form cost_analysis newer jax returns (indexing
                # it with ["flops"] used to silently nan this number)
                from bigdl_tpu.obs import ledger as cost_ledger
                entry = cost_ledger.get().capture_compiled(
                    ("bench_chunk", records_per_batch, n),
                    step.lower(params, net_state, opt_state, xs, ys,
                               key).compile())
            except Exception:
                continue     # transient relay/compile error: one more try
            if entry is not None and np.isfinite(entry.flops):
                flops = entry.flops
                break
            # the ledger swallowed an analysis hiccup (entry missing or
            # flops nan): retry once — entries key per call, so this is
            # a fresh probe, not a cache hit
    for _ in range(warmup):
        params, net_state, opt_state, loss = step(
            params, net_state, opt_state, xs, ys, key)
    float(loss)  # device->host copy = hard sync (block_until_ready may be
    # a no-op under remote-relay PJRT backends; a transfer cannot lie)
    # fetch/train split evidence: steady-state HOST staging cost per step
    # (the work dataset/prefetch.py overlaps) — measured POST-warmup and
    # host-side only, so no first-call tracing and no second bulk relay
    # upload rides the number
    t_fetch = time.perf_counter()
    np.stack([xh * (1.0 + 0.01 * rs.randn()) for _ in range(n)])
    fetch_ms = (time.perf_counter() - t_fetch) * 1e3 / n

    # best-of-N timing windows: the relay-attached chip shows >10% run-to-
    # run variance; a window minimum is the standard de-noising (each
    # window syncs once at the end)
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, net_state, opt_state, loss = step(
                params, net_state, opt_state, xs, ys, key)
        last = float(loss)
        dts.append((time.perf_counter() - t0) / (iters * n))
    dt = min(dts)
    peak = guess_peak(jax.devices()[0])
    mfu = (flops / dt) / peak if np.isfinite(flops) else float("nan")
    # window band: [best, worst] step ms across the timing windows.  The
    # dispatch-latency-bound configs (LeNet) spread up to ~40% run to
    # run; the band in the artifact separates relay noise from real
    # regressions (VERDICT r4 weak 4)
    band = (round(min(dts) * 1e3, 3), round(max(dts) * 1e3, 3))
    return (records_per_batch / dt, dt * 1e3, mfu, flops, last, band,
            fetch_ms)


def measured_roofline():
    """Achievable bf16 matmul TF/s on THIS chip right now (8192^3
    chained) — contextualizes MFU when the runtime can't reach the
    datasheet peak.  Prefers the DEVICE-CLOCK measurement
    (tools/profile_step.measure_matmul_roofline, a jax.profiler kernel
    duration): host wall time through the relay tunnel deflated round
    2/3's numbers to 65-117 TF/s on a chip whose device clock shows
    186.9 (95%% of datasheet) — see PERF_NOTES.  Falls back to the
    wall-clock probe if the profiler is unavailable."""
    import importlib.util
    try:
        spec = importlib.util.spec_from_file_location(
            "bigdl_profile_step", os.path.join(_HERE, "tools",
                                               "profile_step.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.measure_matmul_roofline()
    except Exception as e:
        print("device-clock roofline unavailable (%r); using wall-clock "
              "probe (relay-deflated — see PERF_NOTES)" % e,
              file=sys.stderr, flush=True)
    import jax
    import jax.numpy as jnp
    # probe matrix generated ON DEVICE: a cold-connection 256 MB
    # host->device transfer has been observed to wedge the relay tunnel
    a = (jax.random.normal(jax.random.PRNGKey(1), (8192, 8192),
                           jnp.bfloat16) * 0.01)
    mm = jax.jit(lambda v: (v @ a).astype(jnp.bfloat16) * 0.001)
    z = mm(a)
    float(jnp.sum(z).astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(10):
        z = mm(z)
    float(jnp.sum(z).astype(jnp.float32))
    return 2 * 8192 ** 3 / ((time.perf_counter() - t0) / 10) / 1e12


def configs():
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn

    rs = np.random.RandomState(0)

    def imgs(batch, c, h, w, nclass):
        x = jnp.asarray(rs.randn(batch, c, h, w), jnp.float32)
        y = jnp.asarray(rs.randint(1, nclass + 1, (batch,)))
        return x, y

    def lenet():
        from bigdl_tpu.models.lenet import LeNet5
        # bs256, NOT 512: XLA's TPU conv emitter compile time explodes
        # superlinearly in batch for LeNet's tiny channel counts
        # (measured: 15s @128, 56s @256, >280s @512 — the round-2 bench
        # timeout was exactly this).  256 keeps the chip saturated and
        # compiles inside the per-config budget.
        x, y = imgs(256, 1, 28, 28, 10)
        return LeNet5(class_num=10), nn.ClassNLLCriterion(), x, y

    def vgg16_cifar():
        from bigdl_tpu.models.vgg import VggForCifar10
        x, y = imgs(128, 3, 32, 32, 10)
        return VggForCifar10(class_num=10), nn.ClassNLLCriterion(), x, y

    def inception():
        from bigdl_tpu.models.inception import Inception_v1
        x, y = imgs(128, 3, 224, 224, 1000)
        return Inception_v1(class_num=1000), nn.ClassNLLCriterion(), x, y

    def bilstm():
        from bigdl_tpu.models.textclassifier import TextClassifierBiLSTM
        batch, t, e = 128, 500, 200
        x = jnp.asarray(rs.randn(batch, t, e), jnp.float32)
        y = jnp.asarray(rs.randint(1, 21, (batch,)))
        return (TextClassifierBiLSTM(20, e, hidden_size=128),
                nn.ClassNLLCriterion(), x, y)

    def bilstm_flops():
        # XLA cost analysis counts a scan body ONCE, so recurrent models
        # need the analytic count: per direction per step one
        # (B, D+H) x (D+H, 4H) gemm; x2 directions, xT steps, x3 for
        # fwd + data-grad + weight-grad.
        batch, t, e, h = 128, 500, 200, 128
        return 3 * 2 * 2 * batch * t * (e + h) * 4 * h

    def resnet50():
        from bigdl_tpu.models.resnet import ResNet
        x, y = imgs(64, 3, 224, 224, 1000)
        return ResNet(depth=50, class_num=1000), nn.ClassNLLCriterion(), x, y

    def transformer():
        # the attention-family flagship (beyond the reference's model zoo):
        # GPT-2-medium-class encoder geometry chosen for the MXU — d_model
        # 1024 contractions and d_head 256 (this XLA's batched gemms run
        # 4-7x slower at K<=128, PERF_NOTES round 4).  Measured 0.55
        # datasheet MFU on v5e (matmuls at 92-94% of roofline,
        # PROFILE_transformer.md) — past the >=0.4 north-star bar,
        # evidence the compute path is emitter-bound on convs, not
        # framework-bound
        from bigdl_tpu.models.transformer import TransformerClassifier
        batch, t, d = 16, 512, 1024
        x = jnp.asarray(rs.randn(batch, t, d), jnp.float32)
        y = jnp.asarray(rs.randint(1, 21, (batch,)))
        return (TransformerClassifier(class_num=20, d_model=d, n_heads=4,
                                      n_layers=6, hidden=4096),
                nn.ClassNLLCriterion(), x, y)

    # (name, build, records_per_batch, unit, analytic_flops_or_None,
    #  steps_per_dispatch) — small/latency-bound configs amortize more
    # steps per dispatch (measured: LeNet n=32 2.9x over n=8, VGG +18%);
    # the big configs stay at 8 to bound the stacked-batch HBM footprint
    return [
        ("LeNet-5 bs256 (MNIST, local)", lenet, 256, "images/sec", None, 32),
        ("VGG-16 bs128 (CIFAR-10)", vgg16_cifar, 128, "images/sec", None, 32),
        ("Inception-v1 bs128 (ImageNet sync-SGD)", inception, 128,
         "images/sec", None, 8),
        ("Bi-LSTM bs128 T500 (text classifier)", bilstm, 128 * 500,
         "tokens/sec", bilstm_flops(), 8),
        ("ResNet-50 bs64 (ImageNet streaming cfg)", resnet50, 64,
         "images/sec", None, 8),
        ("Transformer-enc bs16 T512 d1024 (attention family)", transformer,
         16 * 512, "tokens/sec", None, 8),
    ]


def bench_eval(build, records_per_batch, warmup=2, iters=10, windows=3):
    """Forward-only evaluation throughput + top1/top5 on the synthetic
    batch — the reference logs validation records/s
    (LocalOptimizer.scala:231-233); this closes the measurement-apparatus
    contract for the eval path."""
    import jax
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.validation import Top1Accuracy, Top5Accuracy

    model, criterion, x, y = build()
    params, net_state = model.params(), model.state()

    @jax.jit
    def fwd(p, s, xb):
        out, _ = model.apply(p, xb, s,
                             Context(training=False,
                                     key=jax.random.PRNGKey(0)))
        return out
    for _ in range(warmup):
        out = fwd(params, net_state, x)
    np.asarray(out[0, 0])  # device->host copy = hard sync
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fwd(params, net_state, x)
        np.asarray(out[0, 0])
        dts.append((time.perf_counter() - t0) / iters)
    dt = min(dts)
    top1 = Top1Accuracy()(out, y)
    top5 = Top5Accuracy()(out, y)
    return {"records_per_sec": round(records_per_batch / dt, 2),
            "step_time_ms": round(dt * 1e3, 3),
            "top1": round(top1.result()[0], 4),
            "top5": round(top5.result()[0], 4)}


def run_one(only: str):
    """Measure the configs matching ``only`` in THIS process and print one
    JSON line per config (subprocess mode)."""
    import jax

    from bigdl_tpu import tensor as bt
    from bigdl_tpu.utils.random import set_device_prng, set_seed

    _enable_compile_cache()
    set_seed(1)
    bt.set_policy(bt.BF16_COMPUTE)  # matmuls/convs in bf16 on the MXU
    # hardware RngBitGenerator for dropout masks: threefry mask math was
    # 15.7% of the VGG-CIFAR step's device time (round-5 A/B; same win
    # class as the reference's MKL-VSL RNG over Torch's MT)
    set_device_prng("rbg")
    device_kind = jax.devices()[0].device_kind

    if only == "--roofline":
        roof = round(measured_roofline(), 1)
        _save_roofline_sidecar(roof, device_kind)
        print(json.dumps({"roofline_tflops": roof, "device": device_kind}))
        return
    for name, build, recs, unit, aflops, n_disp in configs():
        if only.lower() not in name.lower():
            continue
        rps, ms, mfu, flops, loss, band, fetch_ms = bench_config(
            build, recs, flops_override=aflops, steps_per_dispatch=n_disp)
        from bigdl_tpu.dataset import prefetch as _pf
        entry = {
            "config": name, "unit": unit, "value": round(rps, 2),
            "step_time_ms": round(ms, 3),
            "step_time_ms_band": list(band),
            # fetch/train split: host batch-staging wall per step (the
            # train side is step_time_ms above) — the work the training
            # loops' prefetch pipeline hides (depth = BIGDL_PREFETCH
            # double-buffer)
            "fetch_ms_per_step": round(fetch_ms, 3),
            "prefetch_depth": _pf.depth() if _pf.enabled() else 0,
            "mfu": round(mfu, 4) if np.isfinite(mfu) else None,
            "step_tflops": round(flops / (ms / 1e3) / 1e12, 1)
            if np.isfinite(flops) else None,
            "flops_per_step": flops, "loss": loss,
            "device": device_kind,
        }
        # entry goes out BEFORE any roofline attempt: a roofline wedge
        # must never cost an already-measured config
        print(json.dumps(entry), flush=True)
        # mirror the measurement into the obs event stream
        # (docs/observability.md): with BIGDL_OBS_DIR set, a bench run
        # leaves the same machine-readable trail as training — a no-op
        # in-memory ring otherwise
        try:
            from bigdl_tpu.obs import events as obs_events
            obs_events.emit("phase", name=f"bench/{name}",
                            seconds=ms / 1e3, step=0,
                            records_per_sec=round(rps, 2),
                            mfu=entry["mfu"], device=device_kind)
        except Exception:
            pass
        if "Inception" in name:
            # eval apparatus FIRST (bounded forward loop), roofline probe
            # LAST: the probe is the wedge-prone step under a degraded
            # relay, and a wedge here must only cost the probe — a
            # rehearsal lost the eval entry to exactly that ordering
            try:
                ev = bench_eval(build, recs)
                ev["config"] = name.replace("sync-SGD", "eval forward")
                ev["unit"] = "images/sec"
                # Real-data accuracy evidence (VERDICT r4 item 3): decode
                # the reference's shipped CIFAR PNG class folders, train a
                # small conv net on-chip, evaluate through the Validator —
                # a discriminating nonzero top1 proves the decode->train->
                # accuracy plumbing end to end (the throughput entry above
                # keeps its untrained-synthetic top1 for apparatus parity).
                try:
                    from bigdl_tpu.models.utils.real_data import (
                        train_and_eval_image_folder)
                    cifar = ("/root/reference/dl/src/test/resources/cifar")
                    if os.path.isdir(cifar):
                        ev["real_data"] = dict(
                            train_and_eval_image_folder(cifar),
                            dataset="reference-shipped CIFAR PNG folders")
                except Exception as e:
                    print("real-data eval failed: %r" % e, file=sys.stderr,
                          flush=True)
                print(json.dumps({"eval": ev}), flush=True)
            except Exception as e:
                print("eval bench failed: %r" % e, file=sys.stderr,
                      flush=True)
            # roofline in THIS warm process (a separate cold subprocess
            # wedged the relay twice in rehearsals), as its own line
            try:
                roof = round(measured_roofline(), 1)
                _save_roofline_sidecar(roof, device_kind)
                print(json.dumps({"roofline_tflops": roof,
                                  "device": device_kind}), flush=True)
            except Exception as e:
                # never silent (VERDICT r3: BENCH_r03 shipped roofline
                # null because this except swallowed the reason)
                print("in-band roofline probe failed: %r" % e,
                      file=sys.stderr, flush=True)


_BENCH_DEADLINE = time.monotonic() + float(
    os.environ.get("BIGDL_BENCH_DEADLINE_S", 18 * 60))


def _subprocess_json(arg, timeout_s, retries=1, retry_sleep=10):
    """Run ``python bench.py <arg>`` with a hard timeout; the relay tunnel
    backing this chip occasionally wedges a stream mid-compile (PERF_NOTES
    "Relay operations note"), and a wedged in-process XLA call can never be
    cancelled — a supervised subprocess can.  A global deadline
    (BIGDL_BENCH_DEADLINE_S, default 18 min — deliberately well under any
    plausible driver budget) bounds the whole run so a dead relay yields a
    partial result instead of an unbounded stall."""
    import subprocess
    for attempt in range(retries + 1):
        budget = _BENCH_DEADLINE - time.monotonic()
        if budget <= 30:
            print("bench deadline reached; skipping %r" % arg,
                  file=sys.stderr, flush=True)
            return []
        try:
            out = subprocess.run(
                [sys.executable, "-u", os.path.abspath(__file__), arg],
                capture_output=True, text=True,
                timeout=min(timeout_s, budget))
            lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
            if out.returncode == 0 and lines:
                return [json.loads(l) for l in lines]
            print("bench subprocess %r rc=%d (attempt %d): %s" % (
                arg, out.returncode, attempt + 1, out.stderr[-500:]),
                file=sys.stderr, flush=True)
        except subprocess.TimeoutExpired as e:
            print("bench subprocess %r timed out (attempt %d)"
                  % (arg, attempt + 1), file=sys.stderr, flush=True)
            # salvage whatever the child already printed: a wedge AFTER
            # a config's entry line (e.g. in the in-band roofline probe)
            # must not cost the measured config
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            lines = [l for l in partial.splitlines() if l.startswith("{")]
            if lines:
                return [json.loads(l) for l in lines]
        if attempt < retries:        # no pointless sleep after the last try
            time.sleep(retry_sleep)
    return []


def _summary_line(entries, primary, roof, device, roof_src="measured",
                  eval_entry=None):
    """The driver-contract JSON line for whatever has been measured so
    far.  Printed after EVERY config (the driver takes the LAST line), so
    a mid-run kill still reports the completed configs.

    FENCED (VERDICT r5 weak 1): the driver captures only the last
    ~2000 bytes of stdout, and round 5's summary — which inlined every
    full config entry plus the eval block — outgrew that window, so
    BENCH_r05.json shipped ``parsed: null``.  The summary now carries
    only the headline keys plus a COMPACT per-config digest
    (config/value/mfu) and a trimmed eval; the full per-config detail
    (bands, flops, losses) lives in the per-config lines main() re-emits
    just above.  tests/test_bench_contract.py asserts a fully-populated
    summary stays under 2000 bytes."""
    if primary is None and entries:
        primary = entries[0]
    if primary is None:
        return json.dumps({"metric": "bench failed: relay unavailable",
                           "value": 0, "unit": "images/sec",
                           "vs_baseline": 0})
    if device == "unknown":
        # every config entry records the chip it ran on
        device = next((e.get("device") for e in entries if e.get("device")),
                      "unknown")
    vs_baseline = (primary["mfu"] / 0.4) if primary.get("mfu") else 1.0
    detail = {
        "step_time_ms": primary["step_time_ms"],
        "mfu": primary.get("mfu"),
        "measured_matmul_roofline_tflops": roof,
        "roofline_source": roof_src if roof is not None else "unavailable",
        "device": device,
        # digest only — full entries are their own stdout lines
        "configs": [{"config": e.get("config"), "value": e.get("value"),
                     "mfu": e.get("mfu")} for e in entries],
    }
    if eval_entry is not None:
        ev = {k: eval_entry[k] for k in
              ("records_per_sec", "step_time_ms", "top1", "top5")
              if k in eval_entry}
        rd = eval_entry.get("real_data")
        if isinstance(rd, dict):
            ev["real_data"] = {k: rd[k] for k in
                               ("top1", "top5", "n_records") if k in rd}
        detail["eval"] = ev
    return json.dumps({
        "metric": "images/sec/chip (Inception-v1 bs128 sync-SGD train)",
        "value": primary["value"],
        "unit": "images/sec",
        "vs_baseline": round(vs_baseline, 4),
        "detail": detail,
    })


def main():
    if len(sys.argv) > 1:
        run_one(sys.argv[1])
        return

    entries = []
    primary = None
    eval_entry = None
    roof, device, roof_src = None, "unknown", "measured"
    # headline (Inception) FIRST so a driver kill at any point still
    # leaves the number that matters on stdout
    # headline first; bi-lstm before the fast tail configs (it is the
    # most wedge-prone and must not be the one the deadline reaps)
    for key in ("inception", "resnet", "bi-lstm", "transformer", "lenet",
                "vgg-16"):
        t0 = time.monotonic()
        print("benching: %s" % key, file=sys.stderr, flush=True)
        got = _subprocess_json(key, timeout_s=300)
        print("%s done in %.0fs" % (key, time.monotonic() - t0),
              file=sys.stderr, flush=True)
        for entry in got:
            if "roofline_tflops" in entry:
                roof = entry["roofline_tflops"]
                device = entry.get("device", device)
                continue
            if "eval" in entry:
                eval_entry = entry["eval"]
                print(json.dumps(entry), flush=True)   # full eval detail
                continue
            entries.append(entry)
            # re-emit the FULL per-config entry as its own stdout line:
            # the fenced summary below carries only a digest of it
            print(json.dumps(entry), flush=True)
            if "Inception" in entry["config"]:
                primary = entry
        print(_summary_line(entries, primary, roof, device, roof_src,
                            eval_entry), flush=True)
    if roof is None:
        # fallback 1: the standalone probe (short leash)
        roof_info = _subprocess_json("--roofline", timeout_s=90, retries=0)
        if roof_info:
            roof = roof_info[0]["roofline_tflops"]
            device = roof_info[0]["device"]
    if roof is None:
        # fallback 2: last-good sidecar — the artifact must always be
        # self-interpreting even when this run's probes all failed
        # (VERDICT r3 item 4: BENCH_r03 shipped a null roofline).  Only
        # honored when the cached chip matches the one that ran the
        # configs — a v5e roofline must not contextualize a v6e run.
        run_device = next((e.get("device") for e in entries
                           if e.get("device")), device)
        cached = _load_roofline_sidecar(run_device)
        if cached:
            roof = cached.get("roofline_tflops")
            if device == "unknown":
                device = cached.get("device", device)
            roof_src = "cached %s on %s" % (cached.get("measured_at", "?"),
                                            cached.get("device", "?"))
    print(_summary_line(entries, primary, roof, device, roof_src,
                        eval_entry), flush=True)


if __name__ == "__main__":
    main()
