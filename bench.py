"""Benchmark: Inception-v1 synchronous-SGD training throughput.

The TPU-native counterpart of the reference's DistriOptimizerPerf CLI
(models/utils/DistriOptimizerPerf.scala:41-138: synthetic data, inception_v1,
default batch 128).  Prints ONE JSON line:
  {"metric": ..., "value": images/sec, "unit": ..., "vs_baseline": ...}

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the BASELINE.json north-star bar of 0.4 MFU:
vs_baseline = achieved_MFU / 0.4 (>1.0 beats the target).  MFU uses XLA's
own per-step FLOP count from compiled cost analysis and the chip's peak
for the dtype in use.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


PEAK_FLOPS = {
    # bf16 dense peak per chip
    "TPU v2": 45e12, "TPU v3": 123e12, "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 459e12,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def guess_peak(device) -> float:
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 197e12  # default to v5e


def main(batch_size: int = 128, iterations: int = 10, warmup: int = 3):
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu import tensor as bt
    from bigdl_tpu.models.inception import Inception_v1
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.utils.random import set_seed

    set_seed(1)
    bt.set_policy(bt.BF16_COMPUTE)  # matmuls/convs in bf16 on the MXU

    model = Inception_v1(class_num=1000)
    criterion = nn.ClassNLLCriterion()
    method = SGD()
    params, net_state = model.params(), model.state()
    opt_state = method.init_state(params)
    hyper = {"lr": 0.01, "momentum": 0.9, "dampening": 0.0,
             "weight_decay": 0.0001, "nesterov": False}

    def train_step(params, net_state, opt_state, x, y, key):
        def loss_fn(p):
            out, ns = model.apply(p, x, net_state, Context(training=True, key=key))
            return criterion.apply_loss(out, y), ns

        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = method.update(grads, opt_state, params, hyper)
        return new_params, ns, new_opt, loss

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch_size, 3, 224, 224), jnp.float32)
    y = jnp.asarray(rs.randint(1, 1001, (batch_size,)))
    key = jax.random.PRNGKey(0)

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    try:
        flops_per_step = float(
            step.lower(params, net_state, opt_state, x, y, key)
            .compile().cost_analysis()["flops"])
    except Exception:
        flops_per_step = float("nan")

    for _ in range(warmup):
        params, net_state, opt_state, loss = step(
            params, net_state, opt_state, x, y, key)
    float(loss)  # device->host copy = hard sync (block_until_ready may be a
    # no-op under remote-relay PJRT backends; a transfer cannot lie)

    # best-of-3 timing windows: the relay-attached chip shows >10% run-to-
    # run variance, and a window minimum is the standard de-noising for
    # throughput benchmarks (each window still syncs only once at the end)
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iterations):
            params, net_state, opt_state, loss = step(
                params, net_state, opt_state, x, y, key)
        last_loss = float(loss)  # syncs the whole sequential step chain
        dts.append((time.perf_counter() - t0) / iterations)
    dt = min(dts)

    images_per_sec = batch_size / dt
    peak = guess_peak(jax.devices()[0])
    mfu = (flops_per_step / dt) / peak if np.isfinite(flops_per_step) else float("nan")
    vs_baseline = mfu / 0.4 if np.isfinite(mfu) else 1.0

    # measured achievable roofline on THIS chip/runtime (an 8192^3 bf16
    # matmul chain) — contextualizes MFU when the runtime can't reach the
    # datasheet peak (e.g. relay-attached chips)
    a = jnp.asarray(np.random.RandomState(1).randn(8192, 8192) * 0.01, jnp.bfloat16)
    mm = jax.jit(lambda v: (v @ a).astype(jnp.bfloat16) * 0.001)
    z = mm(a)
    float(jnp.sum(z).astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(10):
        z = mm(z)
    float(jnp.sum(z).astype(jnp.float32))
    roofline_tfs = 2 * 8192 ** 3 / ((time.perf_counter() - t0) / 10) / 1e12

    print(json.dumps({
        "metric": "images/sec/chip (Inception-v1 bs%d sync-SGD train)" % batch_size,
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(vs_baseline, 4),
        "detail": {
            "step_time_ms": round(dt * 1e3, 3),
            "mfu": round(mfu, 4) if np.isfinite(mfu) else None,
            "measured_matmul_roofline_tflops": round(roofline_tfs, 1),
            "step_tflops": round(flops_per_step / dt / 1e12, 1),
            "flops_per_step": flops_per_step,
            "device": jax.devices()[0].device_kind,
            "loss": last_loss,
        },
    }))


if __name__ == "__main__":
    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    main(batch_size=bs)
