"""PARITY.md must not rot: every `file:line` reference resolves."""
import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parity_references_resolve():
    text = open(os.path.join(ROOT, "PARITY.md")).read()
    refs = re.findall(r"`((?:bigdl_tpu|examples|scripts)/[\w/]+\.py)(?::(\d+))?`", text)
    assert len(refs) > 150, f"expected a full inventory, found {len(refs)} refs"
    for path, line in refs:
        full = os.path.join(ROOT, path)
        assert os.path.exists(full), f"PARITY.md references missing file {path}"
        if line:
            n_lines = sum(1 for _ in open(full))
            assert int(line) <= n_lines, (
                f"PARITY.md points at {path}:{line} but the file has "
                f"{n_lines} lines — regenerate PARITY.md")


def test_parity_names_match_inventory_test():
    """The names PARITY.md lists are exactly the resolvable exports."""
    import bigdl_tpu.nn as nn
    text = open(os.path.join(ROOT, "PARITY.md")).read()
    section = text.split("## §2.3")[1].split("\n## ")[0]
    names = re.findall(r"^\| (\w+) \|", section, re.M)
    assert len(names) > 120
    missing = [n for n in names if n != "Component" and not hasattr(nn, n)]
    assert not missing, f"PARITY.md lists unresolvable nn names: {missing}"
