"""Elastic training + async sharded checkpointing (ISSUE 8,
docs/resilience.md "Elastic training").

Fast, in-process coverage of every recovery building block:

- watchdog policy hook: ``on_peer_death="recover"`` hands the trip to
  the elastic layer and keeps beating; the ``"exit"`` default keeps the
  historical fail-fast contract (exit-43 back-compat)
- the reform protocol's file handshake (join/plan/quorum/abort) — pure
  files + callbacks, no jax.distributed needed
- the host AnchorKeeper (background snapshot-to-host) and guarded_sync
  (abandonable host syncs)
- the ``recover`` obs event schema and the obs_report recovery timeline
- async sharded checkpointing: shard split/assemble round trip, the
  background writer, keep-last-N retention with a corrupt-newest layout,
  and the corrupt-shard fallback in ``load_latest_checkpoint``
- world-size-agnostic zero1 restore: save under dp=4, restore under
  dp=2 and dp=1, post-restore trajectory matches a never-killed oracle
- dataset world re-keying: ``ShardedDataSet.reshard`` and
  ``SampleToBatch(global_batch_size=...)``

The 4-process kill→recover→converge drill lives in
``tests/test_multiprocess.py`` (slow + chaos + elastic);
``scripts/chaos_drill.sh`` runs the full matrix.
"""
import json
import os
import time

import numpy as np
import pytest
import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.dataset.transformer import SampleToBatch
from bigdl_tpu.obs.events import validate_event
from bigdl_tpu.optim import (DistriOptimizer, load_latest_checkpoint,
                             max_iteration, several_iteration)
from bigdl_tpu.optim.optimizer import (list_checkpoints, prune_checkpoints,
                                       snapshot_files, snapshot_valid)
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.resilience import Watchdog, elastic
from bigdl_tpu.resilience import checkpoint as ckpt_mod
from bigdl_tpu.resilience.checkpoint import (AsyncCheckpointWriter,
                                             ShardRef,
                                             assemble_sharded_state,
                                             shard_file,
                                             split_sharded_state)
from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.random import set_seed
from bigdl_tpu.utils.table import T

pytestmark = pytest.mark.elastic


@pytest.fixture(autouse=True)
def _clean_elastic():
    elastic.reset()
    yield
    elastic.reset()


def _data(n=16, d=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes) * 2
    xs = rng.randn(n, d).astype(np.float32)
    ys = (xs @ w).argmax(1) + 1.0
    return [Sample(x, np.asarray([y])) for x, y in zip(xs, ys)]


def _model(d=6, classes=3):
    return nn.Sequential(nn.Linear(d, 8), nn.Tanh(),
                         nn.Linear(8, classes), nn.LogSoftMax())


def _params_vec(model):
    return np.concatenate([np.asarray(p).ravel()
                           for p in jax.tree_util.tree_leaves(
                               model.params())])


# ---------------------------------------------------------------------------
# Watchdog policy hook
# ---------------------------------------------------------------------------

class TestWatchdogPolicy:
    def test_invalid_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="on_peer_death"):
            Watchdog(str(tmp_path), 0, 2, on_peer_death="retry")

    def test_default_policy_is_exit(self, tmp_path):
        dog = Watchdog(str(tmp_path), 0, 2)
        assert dog.on_peer_death == "exit"
        assert dog.on_stale == dog._default_on_stale

    def test_explicit_on_stale_overrides_policy(self, tmp_path):
        def custom(stale):
            pass

        dog = Watchdog(str(tmp_path), 0, 2, on_stale=custom,
                       on_peer_death="recover")
        assert dog.on_stale is custom

    def test_recover_policy_defers_and_keeps_beating(self, tmp_path):
        dog = Watchdog(str(tmp_path), process_index=0, n_processes=2,
                       interval=0.05, timeout=0.2,
                       on_peer_death="recover")
        # the heartbeat dir doubles as the reform dir
        assert elastic.runtime().reform_dir == str(tmp_path)
        assert elastic.runtime().watchdog is dog
        dog.start()
        try:
            deadline = time.time() + 5.0
            while elastic.tripped() is None and time.time() < deadline:
                time.sleep(0.02)
            # peer 1 never beat: trip recorded, process still alive
            assert elastic.tripped() == frozenset([1])
            assert elastic.trip_age() is not None
            # this process's OWN heartbeat keeps advancing (survivors'
            # monitors must not read a recovering peer as dead)
            hb = os.path.join(str(tmp_path), "hb.0")
            m0 = os.path.getmtime(hb)
            time.sleep(0.15)
            assert os.path.getmtime(hb) > m0
        finally:
            dog.stop()

    def test_rebind_narrows_the_monitored_peers(self, tmp_path):
        dog = Watchdog(str(tmp_path), process_index=0, n_processes=3,
                       interval=0.05, timeout=0.1)
        for i in range(3):
            open(os.path.join(str(tmp_path), f"hb.{i}"), "w").close()
        time.sleep(0.25)
        assert sorted(dog.stale_peers()) == [1, 2]
        dog.rebind(peers=[0, 1])
        assert sorted(dog.stale_peers()) == [1]

    def test_check_raises_recovery_signal(self):
        elastic.note_trip([2])
        with pytest.raises(elastic.PeerLossRecovery) as ei:
            elastic.check()
        assert ei.value.stale == frozenset([2])
        elastic.clear_trip()
        elastic.check()   # no trip pending: no raise


# ---------------------------------------------------------------------------
# Reform protocol (files + callbacks; no jax.distributed)
# ---------------------------------------------------------------------------

class TestReformProtocol:
    def _join(self, d, gen, orig):
        open(os.path.join(str(d), f"rf.{gen}.join.{orig}"), "w").close()

    def test_plan_round_trip(self, tmp_path):
        d = str(tmp_path)
        for o in (0, 2, 3):
            self._join(d, 1, o)
        plan = elastic.publish_plan(d, 1, stale=[1], orig_index=0,
                                    n_orig=4, settle=0.1, timeout=5.0)
        assert plan["survivors"] == [0, 2, 3]
        assert plan["gen"] == 1
        host, port = plan["addr"].rsplit(":", 1)
        assert int(port) > 0
        # non-coordinators read the identical plan back
        assert elastic.await_plan(d, 1, timeout=2.0) == plan

    def test_plan_waits_for_joiners_to_settle(self, tmp_path):
        import threading
        d = str(tmp_path)
        self._join(d, 1, 0)

        def late_join():
            time.sleep(0.2)
            self._join(d, 1, 1)

        t = threading.Thread(target=late_join)
        t.start()
        plan = elastic.publish_plan(d, 1, stale=[2], orig_index=0,
                                    n_orig=3, settle=0.6, timeout=10.0)
        t.join()
        assert plan["survivors"] == [0, 1]

    def test_quorum_floor_aborts(self, tmp_path):
        d = str(tmp_path)
        self._join(d, 1, 0)
        with pytest.raises(elastic.ReformAbort, match="quorum"):
            elastic.publish_plan(d, 1, stale=[1, 2, 3], orig_index=0,
                                 n_orig=4, settle=0.1, timeout=5.0,
                                 min_survivors=2)

    def test_live_probe_excludes_freshly_dead(self, tmp_path):
        d = str(tmp_path)
        for o in (0, 1, 2):
            self._join(d, 1, o)
        # peer 2 joined, then went silent before the plan was cut
        plan = elastic.publish_plan(d, 1, stale=[3], orig_index=0,
                                    n_orig=4, settle=0.1, timeout=5.0,
                                    live_probe=lambda: [2])
        assert plan["survivors"] == [0, 1]

    def test_await_plan_times_out(self, tmp_path):
        with pytest.raises(elastic.ReformAbort, match="no plan"):
            elastic.await_plan(str(tmp_path), 1, timeout=0.3)

    def test_reform_unarmed_aborts(self):
        with pytest.raises(elastic.ReformAbort, match="not armed"):
            elastic.reform([1])

    def test_coordinator_death_is_unrecoverable(self, tmp_path):
        rt = elastic.runtime()
        rt.armed = True
        rt.reform_dir = str(tmp_path)
        rt.orig_index, rt.n_orig = 1, 4
        with pytest.raises(elastic.ReformAbort, match="process 0"):
            elastic.reform([0])

    def test_finalize_is_noop_without_recovery(self):
        elastic.finalize(0)   # must return, not exit


# ---------------------------------------------------------------------------
# AnchorKeeper + guarded_sync
# ---------------------------------------------------------------------------

def _payload(neval=3, count=8):
    return {"state": T(neval=neval), "neval": neval, "epoch": 1,
            "count": count, "rng": {"seed": 1}}


class TestAnchorKeeper:
    def test_offer_then_latest(self):
        k = elastic.AnchorKeeper()
        trees = ({"w": np.ones((2, 2))}, {}, {"v": np.zeros(3)})
        k.offer(trees, _payload(neval=5))
        a = k.latest(grace=5.0)
        assert a.neval == 5 and a.count == 8
        np.testing.assert_array_equal(a.params["w"], np.ones((2, 2)))

    def test_latest_returns_newest_complete(self):
        k = elastic.AnchorKeeper()
        for ne in (1, 2, 3):
            k.offer(({"w": np.full(2, ne)}, {}, {}), _payload(neval=ne))
            k.latest(grace=5.0)   # let each land before the next offer
        a = k.latest(grace=5.0)
        assert a.neval == 3
        np.testing.assert_array_equal(a.params["w"], np.full(2, 3))

    def test_no_anchor_aborts(self):
        k = elastic.AnchorKeeper()
        with pytest.raises(elastic.ReformAbort, match="no complete"):
            k.latest(grace=0.1)

    def test_capture_sync_seeds_immediately(self):
        k = elastic.AnchorKeeper()
        k.capture_sync(({"w": np.ones(1)}, {}, {}), _payload(neval=9))
        assert k.latest(grace=0.0).neval == 9

    def test_device_trees_materialize_to_host(self):
        import jax.numpy as jnp
        k = elastic.AnchorKeeper()
        k.offer(({"w": jnp.arange(4.0)}, {}, {}), _payload())
        a = k.latest(grace=5.0)
        assert isinstance(a.params["w"], np.ndarray)


class TestGuardedSync:
    def test_passthrough_value_and_error(self):
        assert elastic.guarded_sync(lambda: 42) == 42
        with pytest.raises(KeyError):
            elastic.guarded_sync(lambda: {}["missing"])

    def test_pending_trip_raises_before_running(self):
        elastic.note_trip([1])
        ran = []
        with pytest.raises(elastic.PeerLossRecovery):
            elastic.guarded_sync(lambda: ran.append(1))
        assert not ran

    def test_trip_mid_sync_abandons_the_block(self):
        import threading

        release = threading.Event()

        def blocked():
            release.wait(timeout=30.0)
            return "late"

        def trip_soon():
            time.sleep(0.2)
            elastic.note_trip([2])

        t = threading.Thread(target=trip_soon)
        t.start()
        t0 = time.time()
        with pytest.raises(elastic.PeerLossRecovery):
            elastic.guarded_sync(blocked, poll=0.05)
        assert time.time() - t0 < 5.0
        t.join()
        release.set()


# ---------------------------------------------------------------------------
# recover obs events + report section
# ---------------------------------------------------------------------------

class TestRecoverEvents:
    def _env(self, **kw):
        e = {"v": 2, "ts": 0.0, "proc": 0, "type": "recover"}
        e.update(kw)
        return e

    def test_kinds_validate(self):
        validate_event(self._env(kind="trip", stale=[1]))
        validate_event(self._env(kind="quiesce", step=7))
        validate_event(self._env(kind="reform", world_before=4,
                                 world_after=3))
        validate_event(self._env(kind="reshard", world_after=3))
        validate_event(self._env(kind="resume", step=7, world_before=4,
                                 world_after=3, pause_s=1.25))
        validate_event(self._env(kind="abort", reason="below quorum"))

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown recover kind"):
            validate_event(self._env(kind="reboot"))

    def test_missing_fields_rejected(self):
        with pytest.raises(ValueError, match="missing"):
            validate_event(self._env(kind="resume", step=7))

    def test_obs_report_renders_recovery_timeline(self, obs_run_dir):
        from bigdl_tpu.obs import events
        from tools.obs_report import load_run, render
        events.emit("recover", kind="trip", stale=[2])
        events.emit("recover", kind="quiesce", step=11, stale=[2])
        events.emit("recover", kind="reform", world_before=4,
                    world_after=3, generation=1)
        events.emit("recover", kind="reshard", world_after=3, step=11)
        events.emit("recover", kind="resume", step=11, world_before=4,
                    world_after=3, pause_s=2.5)
        evs, bad, bundles = load_run(obs_run_dir)
        assert not bad
        md = render(evs, bad, bundles)
        assert "Recovery timeline" in md
        assert "4 → 3" in md
        assert "2.50s" in md
        assert "resume" in md


# ---------------------------------------------------------------------------
# Async sharded checkpointing: split/assemble, writer, retention
# ---------------------------------------------------------------------------

class TestShardedState:
    def test_single_process_state_has_no_cross_process_shards(self):
        # everything addressable on one process: the classic whole-tree
        # path stays in charge (and split returns no slices)
        tree = {"v": jax.numpy.zeros((8, 3)), "step": jax.numpy.int32(4)}
        marked, slices = split_sharded_state(tree)
        assert slices == {}
        assert not any(isinstance(l, ShardRef)
                       for l in jax.tree_util.tree_leaves(marked))

    def test_assemble_round_trip(self):
        full = np.arange(24, dtype=np.float32).reshape(8, 3)
        marked = {"v": ShardRef("['v']", (8, 3), "float32"),
                  "step": np.int32(4)}
        blobs = [{"rank": 0, "world": 2,
                  "slices": {"['v']": [(((0, 4), (0, 3)), full[:4])]}},
                 {"rank": 1, "world": 2,
                  "slices": {"['v']": [(((4, 8), (0, 3)), full[4:])]}}]
        out = assemble_sharded_state(marked, blobs)
        np.testing.assert_array_equal(out["v"], full)
        assert out["step"] == 4

    def test_assemble_dedups_replicated_rows(self):
        # two processes covering the same rows (within-process replication)
        full = np.arange(8, dtype=np.float32).reshape(4, 2)
        spec = lambda r0, r1: ((r0, r1), (0, 2))
        marked = {"v": ShardRef("['v']", (4, 2), "float32")}
        blobs = [{"slices": {"['v']": [(spec(0, 2), full[:2]),
                                       (spec(2, 4), full[2:])]}},
                 {"slices": {"['v']": [(spec(2, 4), full[2:])]}}]
        np.testing.assert_array_equal(
            assemble_sharded_state(marked, blobs)["v"], full)

    def test_assemble_non_dim0_sharding(self):
        # zero1_tp_rule shards TP'd leaves over dim 1 (P(model, data)):
        # the spec round-trips ANY layout, not just row blocks
        full = np.arange(24, dtype=np.float32).reshape(4, 6)
        marked = {"w": ShardRef("['w']", (4, 6), "float32")}
        blobs = [{"slices": {"['w']": [(((0, 2), (0, 3)), full[:2, :3]),
                                       (((2, 4), (0, 3)), full[2:, :3])]}},
                 {"slices": {"['w']": [(((0, 2), (3, 6)), full[:2, 3:]),
                                       (((2, 4), (3, 6)), full[2:, 3:])]}}]
        np.testing.assert_array_equal(
            assemble_sharded_state(marked, blobs)["w"], full)

    def test_missing_rows_fail_loudly(self):
        marked = {"v": ShardRef("['v']", (8, 3), "float32")}
        blobs = [{"slices": {"['v']": [(((0, 4), (0, 3)),
                                        np.zeros((4, 3), np.float32))]}}]
        with pytest.raises(ValueError, match="cover only"):
            assemble_sharded_state(marked, blobs)
        with pytest.raises(ValueError, match="missing"):
            assemble_sharded_state(marked, [{"slices": {}}])

    def test_shardref_survives_file_save(self, tmp_path):
        # File.save's numpy duck test must not flatten the placeholder
        p = str(tmp_path / "state.1")
        File.save({"opt_state": {"v": ShardRef("['v']", (4,), "float32")},
                   "opt_shards": 2}, p)
        back = File.load(p)
        ref = back["opt_state"]["v"]
        assert isinstance(ref, ShardRef)
        assert ref.shape == (4,) and ref.path == "['v']"


class TestAsyncWriter:
    def test_writes_files_with_sidecars(self, tmp_path):
        w = AsyncCheckpointWriter()
        files = [(str(tmp_path / "state.2"), {"neval": 2}),
                 (str(tmp_path / "state.2.shard0of1"), {"slices": {}})]
        w.submit(files)
        assert w.flush(timeout=30.0)
        assert w.written == 1 and w.failed == 0
        for p, _ in files:
            assert os.path.exists(p) and os.path.exists(p + ".crc32")
            assert File.verify(p)
        assert File.load(str(tmp_path / "state.2"))["neval"] == 2

    def test_failure_is_contained(self, tmp_path):
        w = AsyncCheckpointWriter()
        # unpicklable blob: the write fails, the writer survives
        w.submit([(str(tmp_path / "state.0"), {"fn": lambda: None})])
        w.submit([(str(tmp_path / "state.1"), {"ok": 1})])
        assert w.flush(timeout=30.0)
        assert w.failed == 1 and w.written == 1
        assert File.load(str(tmp_path / "state.1"))["ok"] == 1

    def test_emits_checkpoint_event_and_prunes(self, tmp_path,
                                               obs_run_dir):
        from bigdl_tpu.obs import events
        d = tmp_path / "ckpt"
        d.mkdir()
        for n in (1, 2):
            File.save({"n": n}, str(d / f"model.{n}"))
            File.save({"n": n}, str(d / f"state.{n}"))
        w = AsyncCheckpointWriter()
        w.submit([(str(d / "model.3"), {"n": 3}),
                  (str(d / "state.3"), {"n": 3})],
                 meta={"event_path": str(d / "model.3"), "step": 3,
                       "shards": 0, "keep": 1, "ckpt_dir": str(d)})
        assert w.flush(timeout=30.0)
        assert events.get() is not None
        assert list_checkpoints(str(d)) == [3]
        with open(os.path.join(obs_run_dir,
                               "events.p0.jsonl")) as fh:
            evs = [json.loads(l) for l in fh if l.strip()]
        ck = [e for e in evs if e["type"] == "checkpoint"
              and e.get("mode") == "async"]
        assert ck and ck[0]["step"] == 3


class TestRetention:
    def _snap(self, d, n, shards=0):
        File.save({"n": n}, str(d / f"model.{n}"))
        File.save({"n": n}, str(d / f"state.{n}"))
        for r in range(shards):
            File.save({"r": r}, shard_file(str(d), n, r, shards))

    def test_keep_last_n(self, tmp_path):
        for n in (2, 4, 6):
            self._snap(tmp_path, n)
        prune_checkpoints(str(tmp_path), keep=2)
        assert list_checkpoints(str(tmp_path)) == [6, 4]
        assert not os.path.exists(str(tmp_path / "model.2.crc32"))

    def test_zero_keep_is_unlimited(self, tmp_path):
        for n in (1, 2, 3):
            self._snap(tmp_path, n)
        assert prune_checkpoints(str(tmp_path), keep=0) == []
        assert list_checkpoints(str(tmp_path)) == [3, 2, 1]

    def test_never_deletes_newest_valid_with_corrupt_newest(self,
                                                            tmp_path):
        for n in (2, 4, 6):
            self._snap(tmp_path, n, shards=2)
        # corrupt the NEWEST snapshot's payload (sidecar now disagrees)
        p = str(tmp_path / "state.6")
        with open(p, "r+b") as fh:
            fh.seek(0)
            fh.write(b"\xde\xad\xbe\xef")
        assert not snapshot_valid(str(tmp_path), 6)
        assert snapshot_valid(str(tmp_path), 4)
        prune_checkpoints(str(tmp_path), keep=1)
        # 6 is in the keep window (corrupt, but retention is not repair);
        # 4 is the newest CRC-valid snapshot and MUST survive the prune
        labels = list_checkpoints(str(tmp_path))
        assert 4 in labels and 6 in labels and 2 not in labels
        # the resume scan lands on 4, skipping the corrupt 6
        got = load_latest_checkpoint(str(tmp_path))
        assert got is None   # these stubs are not real module blobs

    def test_shard_files_ride_their_snapshot(self, tmp_path):
        self._snap(tmp_path, 1, shards=2)
        self._snap(tmp_path, 2, shards=2)
        files = snapshot_files(str(tmp_path), 1)
        assert f"state.1.shard0of2" in files
        prune_checkpoints(str(tmp_path), keep=1)
        left = sorted(os.listdir(str(tmp_path)))
        assert not any(f.startswith(("model.1", "state.1")) for f in left)
        assert any(f.startswith("state.2.shard") for f in left)


class TestShardedResumeScan:
    def _write_sharded_snapshot(self, d, neval, nshards, value):
        model = _model()
        File.save_module(model, str(d / f"model.{neval}"))
        full = np.full((8, 3), value, np.float32)
        rows = 8 // nshards
        for r in range(nshards):
            spec = ((r * rows, (r + 1) * rows), (0, 3))
            File.save({"rank": r, "world": nshards,
                       "slices": {"['v']": [
                           (spec, full[r * rows:(r + 1) * rows])]}},
                      shard_file(str(d), neval, r, nshards))
        File.save({"state": T(neval=neval), "neval": neval,
                   "opt_state": {"v": ShardRef("['v']", (8, 3),
                                               "float32")},
                   "opt_shards": nshards, "rng": None},
                  str(d / f"state.{neval}"))

    def test_reassembles_full_tree(self, tmp_path):
        self._write_sharded_snapshot(tmp_path, 3, 4, 7.0)
        module, blob, neval = load_latest_checkpoint(str(tmp_path))
        assert neval == 3
        v = blob["opt_state"]["v"]
        assert not isinstance(v, ShardRef)
        np.testing.assert_array_equal(np.asarray(v),
                                      np.full((8, 3), 7.0))

    def test_corrupt_shard_falls_back_to_older_pair(self, tmp_path):
        self._write_sharded_snapshot(tmp_path, 2, 2, 1.0)
        self._write_sharded_snapshot(tmp_path, 5, 2, 9.0)
        p = shard_file(str(tmp_path), 5, 1, 2)
        with open(p, "r+b") as fh:
            fh.write(b"\x00\x00\x00\x00")
        module, blob, neval = load_latest_checkpoint(str(tmp_path))
        assert neval == 2
        np.testing.assert_array_equal(np.asarray(blob["opt_state"]["v"]),
                                      np.full((8, 3), 1.0))

    def test_missing_shard_falls_back(self, tmp_path):
        self._write_sharded_snapshot(tmp_path, 2, 2, 1.0)
        self._write_sharded_snapshot(tmp_path, 5, 2, 9.0)
        os.remove(shard_file(str(tmp_path), 5, 0, 2))
        os.remove(shard_file(str(tmp_path), 5, 0, 2) + ".crc32")
        module, blob, neval = load_latest_checkpoint(str(tmp_path))
        assert neval == 2


# ---------------------------------------------------------------------------
# World-size-agnostic zero1 restore (dp=4 save -> dp=2 / dp=1 restore)
# ---------------------------------------------------------------------------

def _zero1_run(dp, iters, ckpt=None, ckpt_every=None, resume=None,
               compression=None, seed=7):
    """Full-batch zero1 training on a ``dp``-device mesh; momentum makes
    the optimizer state matter.  ``resume=(module, blob)`` continues a
    checkpointed run (neval rides the state)."""
    samples = _data()
    set_seed(seed)
    if resume is None:
        model = _model()
    else:
        model = resume[0]
    ds = DataSet.array(samples) >> SampleToBatch(len(samples))
    mesh = make_mesh({"data": dp}, jax.devices()[:dp])
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh,
                          zero1=True, gradient_compression=compression)
    st = T(learningRate=0.2, momentum=0.9)
    if resume is not None:
        st.update(resume[1]["state"])
    opt.set_state(st)
    if resume is not None and resume[1].get("opt_state") is not None:
        opt.set_optim_state(resume[1]["opt_state"])
    opt.set_end_when(max_iteration(iters))
    if ckpt:
        opt.set_checkpoint(str(ckpt), several_iteration(ckpt_every))
    opt.optimize()
    return opt, model


@pytest.mark.serial
class TestWorldSizeAgnosticRestore:
    @pytest.mark.parametrize("dp_restore", [2, 1])
    def test_zero1_dp4_checkpoint_restores_at_smaller_dp(self, tmp_path,
                                                         dp_restore):
        # oracle: 6 uninterrupted steps at dp=4
        _, oracle = _zero1_run(4, 6)
        ref = _params_vec(oracle)
        # killed run: checkpoint at step 3, then restore at dp_restore
        _zero1_run(4, 3, ckpt=tmp_path, ckpt_every=3)
        got = load_latest_checkpoint(str(tmp_path), restore_rng=True)
        assert got is not None
        module, blob, neval = got
        assert neval == 3
        # the snapshot's optimizer state is the FULL logical tree
        for leaf in jax.tree_util.tree_leaves(blob["opt_state"]):
            assert not isinstance(leaf, ShardRef)
        opt2, m2 = _zero1_run(dp_restore, 6, resume=(module, blob))
        # post-restore trajectory matches the never-killed oracle: the
        # restored state re-partitioned over the smaller mesh is the
        # same math (mesh layout is data placement, not semantics)
        np.testing.assert_allclose(_params_vec(m2), ref,
                                   rtol=1e-4, atol=1e-5)
        assert int(opt2.state["neval"]) == 7

    def test_z1c_flat_state_restores_at_smaller_dp(self, tmp_path):
        # the compressed-ZeRO-1 flat mirrors carry dp=4 padding; restore
        # at dp=2 must trim + re-pad (bf16 wire: loose tolerance)
        _, oracle = _zero1_run(4, 6, compression="bf16")
        ref_loss = None
        _zero1_run(4, 3, ckpt=tmp_path, ckpt_every=3,
                   compression="bf16")
        module, blob, neval = load_latest_checkpoint(str(tmp_path),
                                                     restore_rng=True)
        opt2, m2 = _zero1_run(2, 6, resume=(module, blob),
                              compression="bf16")
        final = _params_vec(m2)
        assert np.all(np.isfinite(final))
        np.testing.assert_allclose(final, _params_vec(oracle),
                                   rtol=0.05, atol=0.05)


# ---------------------------------------------------------------------------
# Dataset world re-keying
# ---------------------------------------------------------------------------

class TestDatasetReshard:
    def test_sharded_dataset_reshard_repartitions(self, monkeypatch):
        monkeypatch.setenv("BIGDL_ELASTIC", "1")
        data = list(range(12))
        ds = ShardedDataSet(data, n_shards=4, shard_index=1)
        assert ds._shard == data[1::4]
        assert ds.size() == 12
        ds.reshard(n_shards=3, shard_index=2)
        assert ds._shard == data[2::3]
        assert ds.size() == 12

    def test_reshard_covers_every_record_exactly_once(self, monkeypatch):
        monkeypatch.setenv("BIGDL_ELASTIC", "1")
        data = list(range(10))
        shards = [ShardedDataSet(data, n_shards=4, shard_index=i)
                  .reshard(n_shards=3, shard_index=i)._shard
                  for i in range(3)]
        flat = sorted(x for s in shards for x in s)
        assert flat == data

    def test_fail_fast_runs_do_not_retain_other_shards(self, monkeypatch):
        # the N-times resident-memory cost is paid only under the flag
        monkeypatch.delenv("BIGDL_ELASTIC", raising=False)
        ds = ShardedDataSet(list(range(12)), n_shards=4, shard_index=1)
        assert ds._data is None
        assert ds._shard == list(range(12))[1::4]
        with pytest.raises(RuntimeError, match="BIGDL_ELASTIC"):
            ds.reshard(n_shards=3, shard_index=1)

    def test_global_batch_with_reuse_buffers(self):
        # the preallocated ring must size itself from the RESOLVED local
        # batch (batch_size is None in global mode)
        samples = _data(n=16)
        tb = SampleToBatch(global_batch_size=8, reuse_buffers=2)
        batches = list(tb(iter(samples)))
        assert [b.data.shape[0] for b in batches] == [8, 8]
        assert tb._ring is not None

    def test_sample_to_batch_needs_exactly_one_size(self):
        with pytest.raises(ValueError, match="exactly one"):
            SampleToBatch()
        with pytest.raises(ValueError, match="exactly one"):
            SampleToBatch(4, global_batch_size=8)

    def test_global_batch_size_resolves_against_live_world(self):
        samples = _data(n=16)
        tb = SampleToBatch(global_batch_size=8)
        # single test process: local == global
        batches = list(tb(iter(samples)))
        assert [b.data.shape[0] for b in batches] == [8, 8]

    def test_global_batch_divisibility_enforced(self):
        tb = SampleToBatch(global_batch_size=7)
        import unittest.mock as mock
        with mock.patch.object(jax, "process_count", return_value=2):
            with pytest.raises(ValueError, match="divided"):
                list(tb(iter(_data(n=14))))


# ---------------------------------------------------------------------------
# Elastic session arming on the optimizer
# ---------------------------------------------------------------------------

class TestElasticArming:
    def test_single_process_run_ignores_the_flag(self, monkeypatch):
        monkeypatch.setenv("BIGDL_ELASTIC", "1")
        opt, model = _zero1_run(2, 2)
        # trained fine, no session armed (process_count == 1)
        assert opt._elastic is None
        assert np.isfinite(opt.state["loss"])

    def test_env_knob_parsing(self, monkeypatch):
        monkeypatch.delenv("BIGDL_ELASTIC", raising=False)
        assert not elastic.enabled()
        monkeypatch.setenv("BIGDL_ELASTIC", "1")
        assert elastic.enabled()
        monkeypatch.setenv("BIGDL_ELASTIC_QUORUM", "3")
        assert elastic.quorum() == 3
        monkeypatch.setenv("BIGDL_ELASTIC_QUORUM", "bogus")
        assert elastic.quorum() == 2

    def test_ckpt_knob_parsing(self, monkeypatch):
        monkeypatch.delenv("BIGDL_CKPT_ASYNC", raising=False)
        assert not ckpt_mod.async_enabled()
        monkeypatch.setenv("BIGDL_CKPT_ASYNC", "1")
        assert ckpt_mod.async_enabled()
        monkeypatch.setenv("BIGDL_CKPT_KEEP", "5")
        assert ckpt_mod.keep_count() == 5
        monkeypatch.setenv("BIGDL_CKPT_KEEP", "junk")
        assert ckpt_mod.keep_count() == 0

    def test_async_checkpoint_single_process_end_to_end(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv("BIGDL_CKPT_ASYNC", "1")
        monkeypatch.setenv("BIGDL_CKPT_KEEP", "1")
        opt, model = _zero1_run(2, 6, ckpt=tmp_path, ckpt_every=2)
        # writer flushed at run end: every snapshot durable, pruned to 1
        labels = list_checkpoints(str(tmp_path))
        assert labels == [6]
        assert snapshot_valid(str(tmp_path), 6)
        got = load_latest_checkpoint(str(tmp_path))
        assert got is not None and got[2] == 6


# ---------------------------------------------------------------------------
# Async checkpoint acceptance: off-critical-path + kill-during-write
# ---------------------------------------------------------------------------

class TestAsyncOffCriticalPath:
    def test_checkpoint_step_cost_is_copy_plus_enqueue(self, tmp_path,
                                                       monkeypatch):
        """The acceptance claim: with the async writer, a checkpoint-
        cadence step pays a device copy + enqueue, not the write.  With
        File.save slowed to 0.25s/file, the sync path blocks the loop
        >= 0.5s (model + state) while the async path returns in a small
        fraction of that."""
        import bigdl_tpu.utils.file as file_mod
        opt, model = _zero1_run(2, 1)
        opt.checkpoint_path = str(tmp_path)
        params = model.params()
        net_state = model.state()
        opt_state = opt.optim_method.init_state(params)
        state = T(neval=5, epoch=1)

        real_save = file_mod.save

        def slow_save(obj, path, **kw):
            time.sleep(0.25)
            return real_save(obj, path, **kw)

        monkeypatch.setattr(file_mod, "save", slow_save)

        monkeypatch.setenv("BIGDL_CKPT_ASYNC", "0")
        t0 = time.perf_counter()
        opt._emit_checkpoint(params, net_state, opt_state, state, 5,
                             asynchronous=False)
        sync_wall = time.perf_counter() - t0
        assert sync_wall >= 0.5

        monkeypatch.setenv("BIGDL_CKPT_ASYNC", "1")
        t0 = time.perf_counter()
        opt._emit_checkpoint(params, net_state, opt_state, state, 6,
                             asynchronous=True)
        async_wall = time.perf_counter() - t0
        assert async_wall < 0.2, \
            f"async checkpoint blocked the loop {async_wall:.3f}s"
        assert opt._ckpt_writer.flush(timeout=30.0)
        monkeypatch.setattr(file_mod, "save", real_save)
        assert snapshot_valid(str(tmp_path), 5)
        assert snapshot_valid(str(tmp_path), 6)


class TestKillDuringAsyncWrite:
    def test_previous_checkpoint_survives_a_mid_write_kill(self,
                                                           tmp_path):
        """A process killed while the background writer is mid-snapshot
        must leave the PREVIOUS checkpoint loadable: the half-written
        snapshot is an unpaired/invalid set the resume scan skips."""
        import subprocess
        import sys as _sys
        import textwrap

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = textwrap.dedent("""
            import os, sys, time
            sys.path.insert(0, %r)
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            import jax
            jax.config.update("jax_platforms", "cpu")
            import bigdl_tpu.utils.file as file_mod
            from bigdl_tpu.resilience.checkpoint import (
                AsyncCheckpointWriter)
            d = %r
            # snapshot 3: complete and durable
            file_mod.save({"ok": 3}, os.path.join(d, "model.3"))
            file_mod.save({"ok": 3}, os.path.join(d, "state.3"))
            # snapshot 6 rides the async writer with a slowed save; the
            # process dies while state.6 is still in flight
            real = file_mod.save
            def slow(obj, path, **kw):
                real(obj, path, **kw)
                time.sleep(1.0)
            file_mod.save = slow
            w = AsyncCheckpointWriter()
            w.submit([(os.path.join(d, "model.6"), {"ok": 6}),
                      (os.path.join(d, "state.6"), {"ok": 6})])
            time.sleep(0.5)   # inside snapshot 6: model written, state not
            os._exit(9)       # the kill
        """) % (repo, str(tmp_path))
        p = subprocess.run([_sys.executable, "-c", script], timeout=120)
        assert p.returncode == 9
        files = sorted(os.listdir(str(tmp_path)))
        assert "model.6" in files and "state.6" not in files, files
        # the scan must fall back past the unpaired snapshot 6
        from bigdl_tpu.optim.optimizer import list_checkpoints
        assert list_checkpoints(str(tmp_path)) == [3]
        assert File.load(str(tmp_path / "state.3"))["ok"] == 3


# ---------------------------------------------------------------------------
# Review-round regressions: unconsumed-trip fallback, worker reuse,
# orphan-shard sweep
# ---------------------------------------------------------------------------

class TestUnconsumedTripFallback:
    def test_recover_policy_downgrades_when_nobody_consumes(self,
                                                            tmp_path):
        """recover policy with no armed elastic consumer must NOT turn
        peer death into an unbounded fleet hang: after the fallback
        window the watchdog delivers the fail-fast contract."""
        dog = Watchdog(str(tmp_path), process_index=0, n_processes=2,
                       interval=0.05, timeout=0.2,
                       on_peer_death="recover")
        dog.trip_fallback = 0.6
        fell_back = []
        dog._default_on_stale = lambda stale: fell_back.append(stale)
        dog.start()
        try:
            deadline = time.time() + 10.0
            while not fell_back and time.time() < deadline:
                time.sleep(0.05)
            assert fell_back and 1 in fell_back[0]
        finally:
            dog.stop()

    def test_consumed_trip_stands_the_fallback_down(self, tmp_path):
        dog = Watchdog(str(tmp_path), process_index=0, n_processes=2,
                       interval=0.05, timeout=0.2,
                       on_peer_death="recover")
        dog.trip_fallback = 1.5
        fell_back = []
        dog._default_on_stale = lambda stale: fell_back.append(stale)
        dog.start()
        try:
            deadline = time.time() + 10.0
            while elastic.tripped() is None and time.time() < deadline:
                time.sleep(0.02)
            # a recovery owner claims the trip (what raising
            # PeerLossRecovery does in the training loop)
            elastic.PeerLossRecovery(elastic.tripped())
            assert elastic.runtime().recovering
            time.sleep(2.0)
            assert not fell_back
        finally:
            dog.stop()


class TestGuardedWorkerReuse:
    def test_healthy_calls_reuse_one_thread(self):
        assert elastic.guarded_sync(lambda: 1) == 1
        w = elastic._SYNC_WORKER
        assert w is not None
        assert elastic.guarded_sync(lambda: 2) == 2
        assert elastic._SYNC_WORKER is w

    def test_abandoned_worker_is_replaced(self):
        import threading
        elastic.guarded_sync(lambda: 0)
        w = elastic._SYNC_WORKER
        release = threading.Event()

        def trip_soon():
            time.sleep(0.2)
            elastic.note_trip([1])

        t = threading.Thread(target=trip_soon)
        t.start()
        with pytest.raises(elastic.PeerLossRecovery):
            elastic.guarded_sync(lambda: release.wait(30.0), poll=0.05)
        t.join()
        elastic.clear_trip()
        release.set()
        assert elastic.guarded_sync(lambda: 3) == 3
        assert elastic._SYNC_WORKER is not w


class TestOrphanShardSweep:
    def test_pairless_shards_are_swept(self, tmp_path):
        for n in (4, 6):
            File.save({"n": n}, str(tmp_path / f"model.{n}"))
            File.save({"n": n}, str(tmp_path / f"state.{n}"))
        # label 1: its pair was pruned earlier but a shard survived a
        # failed delete (or a lagging rank's async writer)
        File.save({"r": 0}, shard_file(str(tmp_path), 1, 0, 2))
        prune_checkpoints(str(tmp_path), keep=2)
        left = sorted(os.listdir(str(tmp_path)))
        assert not any(".shard" in f and f.startswith("state.1.")
                       for f in left), left

    def test_in_flight_newer_shard_is_not_swept(self, tmp_path):
        for n in (4, 6):
            File.save({"n": n}, str(tmp_path / f"model.{n}"))
            File.save({"n": n}, str(tmp_path / f"state.{n}"))
        # label 8: a rank's shard landed before rank 0's state.8 — newer
        # than every pair, must survive the sweep
        File.save({"r": 1}, shard_file(str(tmp_path), 8, 1, 2))
        prune_checkpoints(str(tmp_path), keep=1)
        left = sorted(os.listdir(str(tmp_path)))
        assert any(f.startswith("state.8.shard") for f in left), left


# ---------------------------------------------------------------------------
# Review round 3: shard-set-aware retention, reform batch validation,
# elastic bring-up fail-fast
# ---------------------------------------------------------------------------

class TestShardAwareRetention:
    def _pair(self, d, n):
        File.save({"n": n}, str(d / f"model.{n}"))
        File.save({"n": n}, str(d / f"state.{n}"))

    def test_incomplete_shard_set_invalidates_snapshot(self, tmp_path):
        from bigdl_tpu.optim.optimizer import shard_set_complete
        self._pair(tmp_path, 4)
        File.save({"r": 0}, shard_file(str(tmp_path), 4, 0, 3))
        File.save({"r": 1}, shard_file(str(tmp_path), 4, 1, 3))
        # shard 2 of 3 never landed (its rank died mid-write)
        assert not shard_set_complete(str(tmp_path), 4)
        assert not snapshot_valid(str(tmp_path), 4)
        for r in (2,):
            File.save({"r": r}, shard_file(str(tmp_path), 4, r, 3))
        assert shard_set_complete(str(tmp_path), 4)
        assert snapshot_valid(str(tmp_path), 4)

    def test_prune_keeps_last_complete_when_newest_lacks_a_shard(
            self, tmp_path):
        """just_written vouches only for the writing rank's files: a
        newest snapshot missing another rank's shard must not anchor
        retention — the older COMPLETE snapshot survives keep=1."""
        self._pair(tmp_path, 2)
        for r in range(2):
            File.save({"r": r}, shard_file(str(tmp_path), 2, r, 2))
        self._pair(tmp_path, 6)
        File.save({"r": 0}, shard_file(str(tmp_path), 6, 0, 2))
        # rank 1 died before state.6.shard1of2 landed
        prune_checkpoints(str(tmp_path), keep=1, just_written=6)
        labels = list_checkpoints(str(tmp_path))
        assert 2 in labels, labels


class TestReformBatchValidation:
    def test_indivisible_global_batch_aborts_recovery(self, monkeypatch):
        opt, _ = _zero1_run(2, 1)
        samples = _data(n=16)
        opt.dataset = (DataSet.array(samples)
                       >> SampleToBatch(global_batch_size=16))
        import unittest.mock as mock
        with mock.patch.object(jax, "process_count", return_value=3):
            with pytest.raises(elastic.ReformAbort, match="divided"):
                opt._reshard_dataset()

    def test_divisible_global_batch_passes(self):
        opt, _ = _zero1_run(2, 1)
        samples = _data(n=16)
        opt.dataset = (DataSet.array(samples)
                       >> SampleToBatch(global_batch_size=16))
        opt._reshard_dataset()   # process_count() == 1: divides


class TestElasticBringUpFailFast:
    def test_metadata_path_with_flag_raises(self, monkeypatch):
        from bigdl_tpu.utils.engine import Engine
        monkeypatch.setenv("BIGDL_ELASTIC", "1")
        with pytest.raises(ValueError, match="BIGDL_ELASTIC"):
            Engine.init_distributed()
