"""Straggler mitigation (SURVEY.md §5.3) — the reference's drop-slowest
machinery (DistriOptimizer.scala:154-172 timeout drop, :245-278 threshold)
re-designed as gradient masking on the 8-device virtual CPU mesh.

Policy unit tests mirror the reference's threshold arithmetic; the
integration tests inject synthetic per-task time schedules through
``time_source`` and check the masked aggregation against a hand-rolled
oracle (psum(w*g)/sum(w) == gradient of the mean loss over the kept
replicas' examples)."""
import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.optim.straggler import StragglerPolicy
from bigdl_tpu.utils.table import T


class TestStragglerPolicy:
    def test_warmup_and_first_threshold(self):
        pol = StragglerPolicy(n_tasks=4, drop_percentage=0.25,
                              max_drop_percentage=0.5,
                              compute_threshold_batch_size=2,
                              warmup_iteration=0)
        # not armed yet: all-ones masks (ref :154 — iteration must exceed
        # warmup + batchSize - 1)
        m = pol.mask()
        np.testing.assert_array_equal(m, np.ones(4))
        pol.record([1.0, 1.0, 1.0, 5.0], m)
        assert not pol.armed
        pol.record([1.0, 1.0, 1.0, 6.0], pol.mask())
        # window boundary at iteration 2: k = int(0.25*2*4) = 2, 2nd
        # largest of [1,1,1,5,1,1,1,6] is 5 (Util.kthLargest role)
        assert pol.armed
        assert pol.threshold == pytest.approx(5.0)
        # the mask now drops the task whose LAST time exceeded 5
        np.testing.assert_array_equal(pol.mask(), [1, 1, 1, 0])

    def test_relax_when_window_already_dropped_share(self):
        pol = StragglerPolicy(n_tasks=4, drop_percentage=0.25,
                              max_drop_percentage=0.5,
                              compute_threshold_batch_size=2,
                              warmup_iteration=0)
        pol.record([1.0, 1.0, 1.0, 5.0], pol.mask())
        pol.record([1.0, 1.0, 1.0, 6.0], pol.mask())
        assert pol.threshold == pytest.approx(5.0)
        # two masked iterations: window drop count reaches k, so the
        # boundary relaxes the threshold by 1% instead (ref :259)
        m3 = pol.mask()
        np.testing.assert_array_equal(m3, [1, 1, 1, 0])
        pol.record([1.0, 1.0, 1.0, 7.0], m3)
        pol.record([1.0, 1.0, 1.0, 1.0], pol.mask())
        assert pol.threshold == pytest.approx(5.0 * 1.01)

    def test_window_is_one_batch_sized(self):
        # ref moduleTimeList is a FIXED batchSize*n circular buffer: a
        # long warmup must not inflate the first threshold's sample set
        pol = StragglerPolicy(n_tasks=2, drop_percentage=0.5,
                              max_drop_percentage=0.5,
                              compute_threshold_batch_size=3,
                              warmup_iteration=6)
        for _ in range(5):
            pol.record([1.0, 1.0], pol.mask())
        assert len(pol._window) == 3 * 2
        # slow times older than one window are forgotten
        pol.record([9.0, 9.0], pol.mask())
        for _ in range(3):
            pol.record([1.0, 1.0], pol.mask())
        # boundary at iteration 9 (> warmup 6, % 3 == 0): k =
        # int(0.5*3*2) = 3, window = last 3 iterations, all 1.0
        assert pol.iteration == 9
        assert pol.threshold == pytest.approx(1.0)

    def test_accepts_max_drop_guard(self):
        pol = StragglerPolicy(n_tasks=8, drop_percentage=0.1,
                              max_drop_percentage=0.25)
        assert pol.accepts(np.asarray([1, 1, 1, 1, 1, 1, 0, 0], np.float32))
        assert not pol.accepts(
            np.asarray([1, 1, 1, 1, 1, 0, 0, 0], np.float32))

    def test_uniform_slowness_masks_nobody(self):
        # the threshold is a quantile over TIME: a uniformly slow
        # iteration (GC pause — every task identical) has no straggler
        # to drop; the fastest cohort always survives
        pol = StragglerPolicy(n_tasks=4, drop_percentage=0.25,
                              max_drop_percentage=0.5,
                              compute_threshold_batch_size=2,
                              warmup_iteration=0)
        pol.record([1.0, 1.0, 1.0, 1.0], pol.mask())
        pol.record([1.0, 1.0, 1.0, 1.0], pol.mask())
        assert pol.threshold == pytest.approx(1.0)
        pol.record([7.0, 7.0, 7.0, 7.0], pol.mask())
        np.testing.assert_array_equal(pol.mask(), np.ones(4))

    def test_never_accepts_empty_mask(self):
        # max_drop_percentage=1.0 makes the reference guard vacuous
        # (0 >= 0); a zero finished-count would NaN the masked mean, so
        # at least one task must survive
        pol = StragglerPolicy(4, drop_percentage=1.0,
                              max_drop_percentage=1.0)
        assert not pol.accepts(np.zeros(4, np.float32))
        assert pol.accepts(np.asarray([1, 0, 0, 0], np.float32))

    def test_validates_percentages(self):
        with pytest.raises(ValueError):
            StragglerPolicy(4, drop_percentage=0.5, max_drop_percentage=0.2)
        with pytest.raises(ValueError):
            StragglerPolicy(4, drop_percentage=-0.1,
                            max_drop_percentage=0.5)


def _make_data(n=64, d=8, classes=4):
    from bigdl_tpu.dataset import Sample
    rng = np.random.RandomState(0)
    w = rng.randn(d, classes)
    xs = rng.randn(n, d).astype(np.float32)
    ys = (xs @ w).argmax(1) + 1.0
    return [Sample(x, np.asarray([y])) for x, y in zip(xs, ys)]


def _model():
    from bigdl_tpu.utils.random import set_seed
    set_seed(7)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                         nn.LogSoftMax())


def _run_distri(time_source=None, iters=4, drop_kw=None, n_samples=64,
                **kw):
    from bigdl_tpu.dataset import DataSet, SampleToBatch
    from bigdl_tpu.optim import DistriOptimizer, max_iteration
    from bigdl_tpu.utils.random import set_seed

    samples = _make_data(n=n_samples)
    set_seed(3)
    model = _model()
    ds = DataSet.array(samples) >> SampleToBatch(32)
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), **kw)
    if drop_kw is not None:
        opt.set_drop_module_property(time_source=time_source, **drop_kw)
    opt.set_state(T(learningRate=0.1))
    opt.set_end_when(max_iteration(iters))
    return opt.optimize()


def _collect_batches(n_batches, n_samples=64):
    """Materialize the exact batch sequence the seeded run sees.  Valid
    only within ONE epoch: the optimizer's boundary reshuffle draws RNG
    this single continuous iterator does not."""
    from bigdl_tpu.dataset import DataSet, SampleToBatch
    from bigdl_tpu.utils.random import set_seed
    samples = _make_data(n=n_samples)
    set_seed(3)
    _model()  # consume the init draws exactly like _run_distri does
    ds = DataSet.array(samples) >> SampleToBatch(32)
    it = iter(ds.data(train=True))
    out = []
    for _ in range(n_batches):
        b = next(it)
        out.append((np.asarray(b.data), np.asarray(b.labels)))
    return out


class TestStragglerIntegration:
    N = 8  # virtual CPU mesh size (conftest)

    def test_no_skew_matches_plain_dp(self):
        """With a uniform time schedule the threshold never bites: the
        straggler path must train like plain DP (mean-of-replica-means
        == global mean; fp reassociation only)."""
        m_plain = _run_distri()
        m_strag = _run_distri(
            time_source=lambda wall: np.ones(self.N),
            drop_kw=dict(drop_percentage=0.25, max_drop_percentage=0.5,
                         batch_size=2, warmup_iteration=0))
        for wp, ws in zip(m_plain.parameters()[0], m_strag.parameters()[0]):
            np.testing.assert_allclose(np.asarray(wp), np.asarray(ws),
                                       rtol=1e-4, atol=1e-5)

    def test_drops_slow_replica_matches_masked_oracle(self):
        """Replica 3 is persistently slow: after the first threshold
        window (2 iterations) its gradient is masked out.  The masked
        aggregation psum(w*g)/sum(w) must equal the gradient of the mean
        loss over the 7 kept replicas' 28 examples — the reference's
        zero-the-cancelled-gradients + div(finishedModelNum)
        (DistriOptimizer.scala:203-234)."""
        times = np.ones(self.N)
        times[3] = 9.0
        m_strag = _run_distri(
            time_source=lambda wall: times, n_samples=256,
            drop_kw=dict(drop_percentage=0.2, max_drop_percentage=0.5,
                         batch_size=2, warmup_iteration=0))
        # k = int(0.2*2*8) = 3; window holds two 9.0 slots and fourteen
        # 1.0 -> 3rd largest = 1.0 -> threshold 1.0 -> replica 3 dropped
        # from iteration 3 on.  256 samples: 4 iterations stay inside
        # one epoch, so the oracle's batch collection is exact.

        # ---- oracle: manual SGD over the same batch sequence
        batches = _collect_batches(4, n_samples=256)
        from bigdl_tpu.nn.module import Context
        model = _model()
        params = model.params()
        net_state = model.state()
        crit = nn.ClassNLLCriterion()

        def loss_fn(p, x, y):
            out, _ = model.apply(p, jnp.asarray(x), net_state,
                                 Context(training=True,
                                         key=jax.random.PRNGKey(0)))
            return crit.apply_loss(out, jnp.asarray(y))

        g_fn = jax.jit(jax.grad(loss_fn))
        shard = 32 // self.N
        for it, (x, y) in enumerate(batches, start=1):
            if it <= 2:
                g = g_fn(params, x, y)
            else:
                keep = np.ones(32, bool)
                keep[3 * shard:(3 + 1) * shard] = False
                g = g_fn(params, x[keep], y[keep])
            params = jax.tree_util.tree_map(
                lambda p, gg: p - 0.1 * gg, params, g)

        model.load_params(jax.device_get(params))  # align parameters() order
        got = m_strag.parameters()[0]
        want = model.parameters()[0]
        assert len(got) == len(want)
        for ws, wo in zip(got, want):
            np.testing.assert_allclose(np.asarray(ws), np.asarray(wo),
                                       rtol=1e-4, atol=1e-5)

    def test_rejection_skips_update_and_consumes_batch(self, caplog):
        """An iteration whose survivors fall below n*(1-maxDrop) is
        rejected: no update, batch consumed, next dispatch re-measures
        unmasked (ref DistriOptimizer.scala:224)."""
        calls = {"n": 0}

        def schedule(wall):
            calls["n"] += 1
            t = np.ones(self.N)
            if calls["n"] == 2:   # iteration 2: three slow tasks
                t[:3] = 9.0
            return t

        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
            m = _run_distri(
                time_source=schedule, iters=4,
                drop_kw=dict(drop_percentage=0.3, max_drop_percentage=0.3,
                             batch_size=2, warmup_iteration=0))
        # k = int(0.3*2*8) = 4; window after it2 = five 9.0?? no: three
        # 9.0 and thirteen 1.0 -> 4th largest 1.0 -> threshold 1.0 ->
        # iteration 3's mask keeps 5 < 8*(1-0.3)=5.6 -> REJECTED
        assert any("REJECTED" in r.message for r in caplog.records)
        assert m is not None

    def test_uniform_spike_never_rejects(self, caplog):
        """A globally slow iteration (every replica's wall spikes
        together) must not reject anything — the all-or-none failure a
        time-quantile threshold would otherwise produce single-host."""
        calls = {"n": 0}

        def schedule(wall):
            calls["n"] += 1
            return np.full(self.N, 9.0 if calls["n"] == 3 else 1.0)

        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
            m_strag = _run_distri(
                time_source=schedule,
                drop_kw=dict(drop_percentage=0.25, max_drop_percentage=0.5,
                             batch_size=2, warmup_iteration=0))
        assert not any("REJECTED" in r.message for r in caplog.records)
        m_plain = _run_distri()
        for wp, ws in zip(m_plain.parameters()[0], m_strag.parameters()[0]):
            np.testing.assert_allclose(np.asarray(wp), np.asarray(ws),
                                       rtol=1e-4, atol=1e-5)

    def test_all_ones_compression_matches_compressed(self):
        """Straggler armed but never dropping must not perturb the bf16
        wire path (w == 1 multiplications and /sum(w) vs /n are
        exact)."""
        m_comp = _run_distri(gradient_compression="bf16")
        m_both = _run_distri(
            time_source=lambda wall: np.ones(self.N),
            gradient_compression="bf16",
            drop_kw=dict(drop_percentage=0.25, max_drop_percentage=0.5,
                         batch_size=2, warmup_iteration=0))
        for wc, wb in zip(m_comp.parameters()[0], m_both.parameters()[0]):
            np.testing.assert_allclose(np.asarray(wc), np.asarray(wb),
                                       rtol=1e-6, atol=1e-7)

    def test_all_ones_composes_with_zero1_compression(self):
        """drop + bf16 wire + ZeRO-1 owner-partition update — the
        reference's single mechanism (AllReduceParameter.scala:162-235)
        with the finished-count division layered on."""
        m_z1c = _run_distri(gradient_compression="bf16", zero1=True)
        m_all = _run_distri(
            time_source=lambda wall: np.ones(self.N),
            gradient_compression="bf16", zero1=True,
            drop_kw=dict(drop_percentage=0.25, max_drop_percentage=0.5,
                         batch_size=2, warmup_iteration=0))
        for wz, wa in zip(m_z1c.parameters()[0], m_all.parameters()[0]):
            np.testing.assert_allclose(np.asarray(wz), np.asarray(wa),
                                       rtol=1e-6, atol=1e-7)

    def test_invalid_combinations_raise(self):
        from bigdl_tpu.dataset import DataSet, SampleToBatch
        from bigdl_tpu.optim import DistriOptimizer

        samples = _make_data()
        ds = DataSet.array(samples) >> SampleToBatch(32)
        opt = DistriOptimizer(_model(), ds, nn.ClassNLLCriterion(),
                              pipeline_stages=4)
        with pytest.raises(ValueError, match="composes with DP"):
            opt.set_drop_module_property(0.1, 0.2)

        opt2 = DistriOptimizer(_model(), ds, nn.ClassNLLCriterion(),
                               drop_percentage=0.1)
        opt2.set_iterations_per_dispatch(4)
        with pytest.raises(ValueError, match="iterations_per_dispatch"):
            opt2._build_step()
