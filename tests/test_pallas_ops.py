"""Pallas kernel tests (interpret mode on the CPU mesh)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.ops.pallas_kernels import fused_sgd


@pytest.mark.perf
def test_fused_sgd_matches_reference():
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(300, 37), jnp.float32),
              "b": jnp.asarray(rs.randn(5), jnp.float32)}
    grads = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.1), params)
    vel = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.2), params)
    p2, v2 = fused_sgd(params, grads, vel, lr=0.5, momentum=0.9,
                       weight_decay=0.01)
    for k in params:
        v_ref = 0.9 * 0.2 + (0.1 + 0.01 * np.asarray(params[k]))
        p_ref = np.asarray(params[k]) - 0.5 * v_ref
        np.testing.assert_allclose(np.asarray(p2[k]), p_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(v2[k]), v_ref, rtol=1e-5, atol=1e-6)


@pytest.mark.perf
def test_fused_sgd_optim_method_equivalence():
    """SGD(fused=True).update == SGD().update across momentum/dampening/
    nesterov combinations (the Pallas kernel runs interpreted off-TPU)."""
    from bigdl_tpu.optim import SGD
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(130, 7), jnp.float32),
              "b": jnp.asarray(rng.randn(7), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(130, 7), jnp.float32),
             "b": jnp.asarray(rng.randn(7), jnp.float32)}
    for hyper in (
        {"lr": 0.1},
        {"lr": 0.1, "dampening": 0.9},  # mom==0: dampening must be ignored
        {"lr": 0.1, "momentum": 0.9},
        {"lr": 0.1, "momentum": 0.9, "dampening": 0.9},
        {"lr": 0.1, "momentum": 0.9, "nesterov": True},
        {"lr": 0.1, "momentum": 0.9, "weight_decay": 1e-3},
    ):
        plain, fused = SGD(), SGD(fused=True)
        s_p = plain.init_state(params)
        s_f = fused.init_state(params)
        p_p, p_f = params, params
        for _ in range(3):
            p_p, s_p = plain.update(grads, s_p, p_p, hyper)
            p_f, s_f = fused.update(grads, s_f, p_f, hyper)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_p[k]), np.asarray(p_f[k]),
                                       rtol=1e-5, atol=1e-6)
            # velocity state must also agree (checkpoint handoff between
            # fused and unfused paths)
            np.testing.assert_allclose(np.asarray(s_p["velocity"][k]),
                                       np.asarray(s_f["velocity"][k]),
                                       rtol=1e-5, atol=1e-6)


@pytest.mark.perf
def test_fused_sgd_nonaligned_size():
    """Sizes that do not divide the kernel block must round-trip exactly."""
    p = {"x": jnp.arange(100.0)}
    g = {"x": jnp.ones(100)}
    v = {"x": jnp.zeros(100)}
    p2, v2 = fused_sgd(p, g, v, lr=1.0)
    np.testing.assert_allclose(np.asarray(p2["x"]), np.arange(100.0) - 1.0)


@pytest.mark.perf
class TestPallasMaxPool:
    """Stride-1 Pallas maxpool (ops/pallas_kernels.maxpool2d): exact
    forward + first-max-wins gradient vs reduce_window/select-and-scatter
    autodiff, including tie positions (coarsely quantized inputs).  Kept
    as measured evidence — NOT wired into nn/pooling.py (10-50x slower
    than the XLA emitter on TPU, PERF_NOTES round 3)."""

    @pytest.mark.parametrize("shape,win,pads", [
        ((2, 4, 14, 14), (3, 3), ((1, 1), (1, 1))),
        ((1, 2, 8, 8), (3, 3), ((1, 1), (1, 1))),
        ((2, 3, 10, 12), (2, 2), ((0, 1), (1, 0))),
    ])
    def test_fwd_bwd_vs_xla(self, shape, win, pads):
        from bigdl_tpu.ops.pallas_kernels import maxpool2d
        interpret = jax.devices()[0].platform != "tpu"

        def ref_pool(x):
            return lax.reduce_window(
                x, -jnp.inf, lax.max, (1, 1) + win, (1, 1, 1, 1),
                ((0, 0), (0, 0)) + pads)

        rs = np.random.RandomState(0)
        x = jnp.asarray(np.round(rs.randn(*shape) * 2) / 2, jnp.float32)
        y_ref = ref_pool(x)
        y = maxpool2d(x, win, (1, 1), pads, interpret)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref))

        g = jnp.asarray(rs.randn(*y_ref.shape).astype(np.float32))
        d_ref = jax.grad(lambda v: (ref_pool(v) * g).sum())(x)
        d = jax.grad(
            lambda v: (maxpool2d(v, win, (1, 1), pads, interpret) * g).sum())(x)
        np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.perf
class TestPallasLRN:
    """Fused cross-channel LRN kernel (ops/pallas_kernels.lrn_channel):
    forward + closed-form backward vs the XLA reduce_window formulation,
    incl. ragged H*W not divisible by 128.  Evidence kernel — measured
    slower than XLA's path on v5e, so SpatialCrossMapLRN keeps
    _PALLAS=False (see the class comment + PERF_NOTES round 3)."""

    @pytest.mark.parametrize("shape,pars", [
        ((2, 8, 16, 8), (5, 1.0, 0.75, 1.0)),
        ((2, 6, 16, 16), (3, 2e-4, 0.9, 2.0)),
        ((2, 8, 7, 9), (5, 1.0, 0.75, 1.0)),      # ragged lanes
        ((2, 8, 16, 8), (4, 1.0, 0.75, 1.0)),     # EVEN size: asymmetric
    ])                                             # adjoint window in bwd
    def test_fwd_bwd_vs_xla(self, shape, pars):
        from bigdl_tpu.ops.pallas_kernels import lrn_channel
        size, alpha, beta, k = pars
        interpret = jax.devices()[0].platform != "tpu"

        def ref_lrn(x):
            lo = (size - 1) // 2
            hi = size - 1 - lo
            sq = lax.reduce_window(x * x, 0.0, lax.add, (1, size, 1, 1),
                                   (1, 1, 1, 1),
                                   ((0, 0), (lo, hi), (0, 0), (0, 0)))
            return x / (k + alpha / size * sq) ** beta

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(*shape), jnp.float32)
        y = lrn_channel(x, size, alpha, beta, k, interpret)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref_lrn(x)),
                                   rtol=1e-5, atol=1e-6)
        g = jnp.asarray(rs.randn(*shape), jnp.float32)
        d_ref = jax.grad(lambda v: (ref_lrn(v) * g).sum())(x)
        d = jax.grad(
            lambda v: (lrn_channel(v, size, alpha, beta, k, interpret)
                       * g).sum())(x)
        np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.perf
class TestMosaicMaxPool:
    """Round-6 Mosaic maxpool pair (ops/pallas_kernels.mosaic_maxpool2d):
    argmax-storing forward + scatter-free gather backward vs the XLA
    oracle (reduce_window forward, select_and_scatter autodiff
    backward), overlapping STRIDED windows and tie positions included
    (coarsely quantized inputs).  Off by default in nn/pooling.py
    (_PALLAS_POOL) pending the device-clock A/B — these tests are the
    interpreter-mode equivalence half of the adoption contract."""

    CASES = [
        # Inception stem/transition geometry (3x3 stride 2, overlap)
        ((2, 5, 13, 17), (3, 3), (2, 2), ((1, 1), (1, 1))),
        # Inception in-block pool branches (3x3 stride 1, overlap)
        ((2, 3, 10, 12), (3, 3), (1, 1), ((1, 1), (1, 1))),
        # non-overlapping, asymmetric Torch ceil-mode style pads
        ((1, 4, 9, 11), (2, 2), (2, 2), ((0, 1), (1, 0))),
        # window larger than stride on both dims, fat pads
        ((1, 2, 12, 8), (5, 3), (3, 2), ((2, 2), (1, 1))),
        # non-tile-aligned batch (B=37) and tiny W
        ((37, 1, 13, 7), (3, 3), (2, 2), ((1, 1), (1, 1))),
        # non-tile-aligned channel count (C=100: ragged lanes)
        ((1, 100, 8, 8), (3, 3), (1, 1), ((0, 0), (0, 0))),
    ]

    @pytest.mark.parametrize("shape,win,st,pads", CASES)
    def test_fwd_bwd_vs_xla(self, shape, win, st, pads):
        from bigdl_tpu.ops.pallas_kernels import mosaic_maxpool2d
        interpret = jax.devices()[0].platform != "tpu"

        def ref_pool(v):
            return lax.reduce_window(v, -jnp.inf, lax.max, (1, 1) + win,
                                     (1, 1) + st,
                                     ((0, 0), (0, 0)) + pads)

        rs = np.random.RandomState(0)
        # quantized values force exact ties: the first-max rule must
        # match select_and_scatter's bit for bit
        x = jnp.asarray(np.round(rs.randn(*shape) * 2) / 2, jnp.float32)
        y_ref = ref_pool(x)
        y = mosaic_maxpool2d(x, win, st, pads, interpret)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref))

        g = jnp.asarray(rs.randn(*y_ref.shape).astype(np.float32))
        d_ref = jax.grad(lambda v: (ref_pool(v) * g).sum())(x)
        d = jax.grad(lambda v: (mosaic_maxpool2d(v, win, st, pads,
                                                 interpret) * g).sum())(x)
        np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_and_pooling_layer_route(self):
        """The nn/pooling.py _PALLAS_POOL='interpret' route produces the
        XLA path's output on the module's real geometry, bf16 included."""
        from bigdl_tpu.nn import pooling

        rs = np.random.RandomState(1)
        x = jnp.asarray(np.round(rs.randn(2, 6, 14, 14) * 2) / 2,
                        jnp.float32)
        m = pooling.SpatialMaxPooling(3, 3, 2, 2, 1, 1).ceil()
        y_ref = m.forward(x)
        old = pooling._PALLAS_POOL
        pooling._PALLAS_POOL = "interpret"
        try:
            y = pooling.SpatialMaxPooling(3, 3, 2, 2, 1, 1).ceil().forward(x)
        finally:
            pooling._PALLAS_POOL = old
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref))
        # bf16 input through the raw kernel (the policy-cast pool path)
        from bigdl_tpu.ops.pallas_kernels import mosaic_maxpool2d
        xb = x.astype(jnp.bfloat16)
        yb = mosaic_maxpool2d(xb, (3, 3), (2, 2), ((1, 1), (1, 1)), True)
        ref = lax.reduce_window(xb, -jnp.inf, lax.max, (1, 1, 3, 3),
                                (1, 1, 2, 2),
                                ((0, 0), (0, 0), (1, 1), (1, 1)))
        assert yb.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(yb, np.float32),
                                   np.asarray(ref, np.float32))


@pytest.mark.perf
class TestBlockedRecurrence:
    """Round-6 multi-timestep blocking: block_t > 1 must reproduce the
    block_t=1 kernels exactly (outputs) and up to f32 weight-grad
    summation order (gradients), including T not divisible by the
    block.  Non-tile-aligned shapes on purpose (B=37, T=13, H=100 where
    cheap enough)."""

    @pytest.mark.parametrize("block_t", [3, 8])
    def test_bilstm_blocked(self, block_t):
        from bigdl_tpu.ops.pallas_kernels import bilstm_recurrence
        rs = np.random.RandomState(0)
        t, nd, b, h = 13, 2, 37, 4
        zx = jnp.asarray(rs.randn(t, nd, b, 4 * h), jnp.float32)
        wht = jnp.asarray(rs.randn(nd, h, 4 * h) * 0.3, jnp.float32)
        go = jnp.asarray(rs.randn(t, nd, b, h), jnp.float32)
        y1 = bilstm_recurrence(zx, wht, True, 1)
        yk = bilstm_recurrence(zx, wht, True, block_t)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(yk),
                                   rtol=1e-6, atol=1e-6)
        g1 = jax.grad(lambda a, w: (bilstm_recurrence(a, w, True, 1)
                                    * go).sum(), argnums=(0, 1))(zx, wht)
        gk = jax.grad(lambda a, w: (bilstm_recurrence(a, w, True, block_t)
                                    * go).sum(), argnums=(0, 1))(zx, wht)
        for a, b_ in zip(g1, gk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("block_t", [3, 8])
    def test_gru_blocked(self, block_t):
        from bigdl_tpu.ops.pallas_kernels import gru_recurrence
        rs = np.random.RandomState(1)
        t, nd, b, h = 13, 1, 5, 100
        zrz = jnp.asarray(rs.randn(t, nd, b, 2 * h), jnp.float32)
        zn = jnp.asarray(rs.randn(t, nd, b, h), jnp.float32)
        wrz = jnp.asarray(rs.randn(nd, h, 2 * h) * 0.1, jnp.float32)
        wh = jnp.asarray(rs.randn(nd, h, h) * 0.1, jnp.float32)
        go = jnp.asarray(rs.randn(t, nd, b, h), jnp.float32)
        y1 = gru_recurrence(zrz, zn, wrz, wh, True, 1)
        yk = gru_recurrence(zrz, zn, wrz, wh, True, block_t)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(yk),
                                   rtol=1e-6, atol=1e-6)
        g1 = jax.grad(lambda *a: (gru_recurrence(*a, True, 1) * go).sum(),
                      argnums=(0, 1, 2, 3))(zrz, zn, wrz, wh)
        gk = jax.grad(lambda *a: (gru_recurrence(*a, True, block_t)
                                  * go).sum(),
                      argnums=(0, 1, 2, 3))(zrz, zn, wrz, wh)
        for a, b_ in zip(g1, gk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("block_t", [4])
    def test_rnn_blocked(self, block_t):
        from bigdl_tpu.ops.pallas_kernels import rnn_recurrence
        rs = np.random.RandomState(2)
        t, nd, b, h = 9, 2, 3, 6
        zx = jnp.asarray(rs.randn(t, nd, b, h), jnp.float32)
        wht = jnp.asarray(rs.randn(nd, h, h) * 0.3, jnp.float32)
        go = jnp.asarray(rs.randn(t, nd, b, h), jnp.float32)
        y1 = rnn_recurrence(zx, wht, True, 1)
        yk = rnn_recurrence(zx, wht, True, block_t)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(yk),
                                   rtol=1e-6, atol=1e-6)
        g1 = jax.grad(lambda *a: (rnn_recurrence(*a, True, 1) * go).sum(),
                      argnums=(0, 1))(zx, wht)
        gk = jax.grad(lambda *a: (rnn_recurrence(*a, True, block_t)
                                  * go).sum(), argnums=(0, 1))(zx, wht)
        for a, b_ in zip(g1, gk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-5, atol=1e-6)
