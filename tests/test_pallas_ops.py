"""Pallas kernel tests (interpret mode on the CPU mesh)."""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu.ops.pallas_kernels import fused_sgd


def test_fused_sgd_matches_reference():
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(300, 37), jnp.float32),
              "b": jnp.asarray(rs.randn(5), jnp.float32)}
    grads = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.1), params)
    vel = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.2), params)
    p2, v2 = fused_sgd(params, grads, vel, lr=0.5, momentum=0.9,
                       weight_decay=0.01)
    for k in params:
        v_ref = 0.9 * 0.2 + (0.1 + 0.01 * np.asarray(params[k]))
        p_ref = np.asarray(params[k]) - 0.5 * v_ref
        np.testing.assert_allclose(np.asarray(p2[k]), p_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(v2[k]), v_ref, rtol=1e-5, atol=1e-6)


def test_fused_sgd_nonaligned_size():
    """Sizes that do not divide the kernel block must round-trip exactly."""
    p = {"x": jnp.arange(100.0)}
    g = {"x": jnp.ones(100)}
    v = {"x": jnp.zeros(100)}
    p2, v2 = fused_sgd(p, g, v, lr=1.0)
    np.testing.assert_allclose(np.asarray(p2["x"]), np.arange(100.0) - 1.0)
