"""Pallas kernel tests (interpret mode on the CPU mesh)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.ops.pallas_kernels import fused_sgd


def test_fused_sgd_matches_reference():
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(300, 37), jnp.float32),
              "b": jnp.asarray(rs.randn(5), jnp.float32)}
    grads = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.1), params)
    vel = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.2), params)
    p2, v2 = fused_sgd(params, grads, vel, lr=0.5, momentum=0.9,
                       weight_decay=0.01)
    for k in params:
        v_ref = 0.9 * 0.2 + (0.1 + 0.01 * np.asarray(params[k]))
        p_ref = np.asarray(params[k]) - 0.5 * v_ref
        np.testing.assert_allclose(np.asarray(p2[k]), p_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(v2[k]), v_ref, rtol=1e-5, atol=1e-6)


def test_fused_sgd_optim_method_equivalence():
    """SGD(fused=True).update == SGD().update across momentum/dampening/
    nesterov combinations (the Pallas kernel runs interpreted off-TPU)."""
    from bigdl_tpu.optim import SGD
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(130, 7), jnp.float32),
              "b": jnp.asarray(rng.randn(7), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(130, 7), jnp.float32),
             "b": jnp.asarray(rng.randn(7), jnp.float32)}
    for hyper in (
        {"lr": 0.1},
        {"lr": 0.1, "dampening": 0.9},  # mom==0: dampening must be ignored
        {"lr": 0.1, "momentum": 0.9},
        {"lr": 0.1, "momentum": 0.9, "dampening": 0.9},
        {"lr": 0.1, "momentum": 0.9, "nesterov": True},
        {"lr": 0.1, "momentum": 0.9, "weight_decay": 1e-3},
    ):
        plain, fused = SGD(), SGD(fused=True)
        s_p = plain.init_state(params)
        s_f = fused.init_state(params)
        p_p, p_f = params, params
        for _ in range(3):
            p_p, s_p = plain.update(grads, s_p, p_p, hyper)
            p_f, s_f = fused.update(grads, s_f, p_f, hyper)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_p[k]), np.asarray(p_f[k]),
                                       rtol=1e-5, atol=1e-6)
            # velocity state must also agree (checkpoint handoff between
            # fused and unfused paths)
            np.testing.assert_allclose(np.asarray(s_p["velocity"][k]),
                                       np.asarray(s_f["velocity"][k]),
                                       rtol=1e-5, atol=1e-6)


def test_fused_sgd_nonaligned_size():
    """Sizes that do not divide the kernel block must round-trip exactly."""
    p = {"x": jnp.arange(100.0)}
    g = {"x": jnp.ones(100)}
    v = {"x": jnp.zeros(100)}
    p2, v2 = fused_sgd(p, g, v, lr=1.0)
    np.testing.assert_allclose(np.asarray(p2["x"]), np.arange(100.0) - 1.0)


class TestPallasMaxPool:
    """Stride-1 Pallas maxpool (ops/pallas_kernels.maxpool2d): exact
    forward + first-max-wins gradient vs reduce_window/select-and-scatter
    autodiff, including tie positions (coarsely quantized inputs).  Kept
    as measured evidence — NOT wired into nn/pooling.py (10-50x slower
    than the XLA emitter on TPU, PERF_NOTES round 3)."""

    @pytest.mark.parametrize("shape,win,pads", [
        ((2, 4, 14, 14), (3, 3), ((1, 1), (1, 1))),
        ((1, 2, 8, 8), (3, 3), ((1, 1), (1, 1))),
        ((2, 3, 10, 12), (2, 2), ((0, 1), (1, 0))),
    ])
    def test_fwd_bwd_vs_xla(self, shape, win, pads):
        from bigdl_tpu.ops.pallas_kernels import maxpool2d
        interpret = jax.devices()[0].platform != "tpu"

        def ref_pool(x):
            return lax.reduce_window(
                x, -jnp.inf, lax.max, (1, 1) + win, (1, 1, 1, 1),
                ((0, 0), (0, 0)) + pads)

        rs = np.random.RandomState(0)
        x = jnp.asarray(np.round(rs.randn(*shape) * 2) / 2, jnp.float32)
        y_ref = ref_pool(x)
        y = maxpool2d(x, win, (1, 1), pads, interpret)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref))

        g = jnp.asarray(rs.randn(*y_ref.shape).astype(np.float32))
        d_ref = jax.grad(lambda v: (ref_pool(v) * g).sum())(x)
        d = jax.grad(
            lambda v: (maxpool2d(v, win, (1, 1), pads, interpret) * g).sum())(x)
        np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                                   rtol=1e-5, atol=1e-5)


class TestPallasLRN:
    """Fused cross-channel LRN kernel (ops/pallas_kernels.lrn_channel):
    forward + closed-form backward vs the XLA reduce_window formulation,
    incl. ragged H*W not divisible by 128.  Evidence kernel — measured
    slower than XLA's path on v5e, so SpatialCrossMapLRN keeps
    _PALLAS=False (see the class comment + PERF_NOTES round 3)."""

    @pytest.mark.parametrize("shape,pars", [
        ((2, 8, 16, 8), (5, 1.0, 0.75, 1.0)),
        ((2, 6, 16, 16), (3, 2e-4, 0.9, 2.0)),
        ((2, 8, 7, 9), (5, 1.0, 0.75, 1.0)),      # ragged lanes
        ((2, 8, 16, 8), (4, 1.0, 0.75, 1.0)),     # EVEN size: asymmetric
    ])                                             # adjoint window in bwd
    def test_fwd_bwd_vs_xla(self, shape, pars):
        from bigdl_tpu.ops.pallas_kernels import lrn_channel
        size, alpha, beta, k = pars
        interpret = jax.devices()[0].platform != "tpu"

        def ref_lrn(x):
            lo = (size - 1) // 2
            hi = size - 1 - lo
            sq = lax.reduce_window(x * x, 0.0, lax.add, (1, size, 1, 1),
                                   (1, 1, 1, 1),
                                   ((0, 0), (lo, hi), (0, 0), (0, 0)))
            return x / (k + alpha / size * sq) ** beta

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(*shape), jnp.float32)
        y = lrn_channel(x, size, alpha, beta, k, interpret)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref_lrn(x)),
                                   rtol=1e-5, atol=1e-6)
        g = jnp.asarray(rs.randn(*shape), jnp.float32)
        d_ref = jax.grad(lambda v: (ref_lrn(v) * g).sum())(x)
        d = jax.grad(
            lambda v: (lrn_channel(v, size, alpha, beta, k, interpret)
                       * g).sum())(x)
        np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-5)
