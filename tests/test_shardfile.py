"""Shard-file (SequenceFile role) tests."""
import numpy as np

from bigdl_tpu.dataset import shardfile


def test_roundtrip(tmp_path):
    records = [(float(i % 10 + 1), bytes([i % 256]) * (i + 1)) for i in range(37)]
    paths = shardfile.write_shards(records, str(tmp_path), n_shards=4)
    assert len(paths) == 4
    ds = shardfile.ShardFolder(str(tmp_path))
    assert ds.size() == 37
    got = list(ds.data(train=False))
    assert len(got) == 37
    lens = sorted(len(r.data) for r in got)
    assert lens == sorted(i + 1 for i in range(37))


def test_train_loops(tmp_path):
    records = [(1.0, b"x")] * 5
    shardfile.write_shards(records, str(tmp_path), n_shards=2)
    ds = shardfile.ShardFolder(str(tmp_path))
    it = ds.data(train=True)
    assert len([next(it) for _ in range(12)]) == 12
