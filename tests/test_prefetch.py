"""Asynchronous host pipeline tests (ISSUE 4): prefetch-to-device input
path + cadenced host sync.

The contract under test, in order of importance:

1. bit-identical loss trajectory with ``BIGDL_PREFETCH`` on vs off —
   same seed, same per-step losses, same final params — for
   LocalOptimizer and DistriOptimizer, single-step and chunked dispatch,
   including an RNG-bearing pipeline (random crop + flip) across epoch
   boundaries;
2. no per-step device→host sync outside cadence boundaries (the
   ``_HostSyncWindow`` audit trail), and the train step stays ONE jitted
   dispatch with prefetch on;
3. overlap is real: with an artificially slow transform the wall clock
   lands strictly below the serial fetch+train sum;
4. chaos hooks stay keyed by the CONSUMING step, and checkpoint/resume
   replays the serial trajectory (the runner pins the RNG payload to the
   last consumed batch).
"""
import os
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.dataset import prefetch as pf
from bigdl_tpu.dataset.image import (HFlip, ImgRdmCropper, ImgToBatch,
                                     LabeledImage)
from bigdl_tpu.dataset.transformer import FuncTransformer, SampleToBatch
from bigdl_tpu.obs import events as obs_events
from bigdl_tpu.optim import (DistriOptimizer, LocalOptimizer, Top1Accuracy,
                             max_iteration, several_iteration)
from bigdl_tpu.optim.local_optimizer import validate
from bigdl_tpu.utils.random import RNG, set_seed
from bigdl_tpu.utils.table import T

pytestmark = pytest.mark.perf


@pytest.fixture
def ring_log():
    """Fresh in-memory event ring per test (step events carry the
    per-step losses the trajectory assertions read)."""
    log = obs_events.configure(None)
    yield log
    obs_events.reset()


def _samples(n=24, d=5, seed=0):
    rs = np.random.RandomState(seed)
    xs = rs.randn(n, d).astype(np.float32)
    ys = (rs.randint(0, 3, n) + 1).astype(np.float32)
    return [Sample(x, np.asarray([y])) for x, y in zip(xs, ys)]


def _mlp(d=5):
    return nn.Sequential(nn.Linear(d, 8), nn.Tanh(), nn.Linear(8, 3),
                         nn.LogSoftMax())


def _grey_images(n=16, hw=8, seed=1):
    rs = np.random.RandomState(seed)
    return [LabeledImage(rs.rand(hw, hw).astype(np.float32),
                         float(i % 3 + 1)) for i in range(n)]


def _step_events(log):
    return [e for e in log.ring_events() if e["type"] == "step"]


def _losses(log):
    return [e["loss"] for e in _step_events(log)]


def _params_vec(model):
    return np.concatenate([np.asarray(l).reshape(-1) for l in
                           jax.tree_util.tree_leaves(model.params())])


def _train(make_opt, steps, seed=5, dropout=False):
    set_seed(seed)
    opt = make_opt(dropout)
    opt.set_end_when(max_iteration(steps))
    opt.optimize()
    return opt


# ---------------------------------------------------------------------------
# 1. bit-identical trajectories, prefetch on vs off
# ---------------------------------------------------------------------------

class TestTrajectoryParity:
    def _run_mlp(self, monkeypatch, ring_log, prefetch_on, n_disp=1,
                 steps=8, distri=False, dropout=False):
        monkeypatch.setenv(pf.ENV_PREFETCH, "1" if prefetch_on else "0")
        obs_events.configure(None)

        def make(dropout):
            layers = [nn.Linear(5, 8), nn.Tanh()]
            if dropout:
                layers.append(nn.Dropout(0.5))
            layers += [nn.Linear(8, 3), nn.LogSoftMax()]
            model = nn.Sequential(*layers)
            ds = DataSet.array(_samples()) >> SampleToBatch(8)
            cls = DistriOptimizer if distri else LocalOptimizer
            opt = cls(model, ds, nn.ClassNLLCriterion())
            opt.set_state(T(learningRate=0.2, momentum=0.9))
            if n_disp > 1:
                opt.set_iterations_per_dispatch(n_disp)
            return opt

        opt = _train(make, steps, dropout=dropout)
        return _losses(obs_events.get()), _params_vec(opt.model), opt

    @pytest.mark.parametrize("n_disp", [1, 2])
    def test_local(self, monkeypatch, ring_log, n_disp):
        # 8 iterations over a 24-sample epoch (3 steps/epoch): the
        # trajectory crosses epoch shuffles with dropout keys live
        l_on, p_on, _ = self._run_mlp(monkeypatch, ring_log, True,
                                      n_disp=n_disp, dropout=True)
        l_off, p_off, _ = self._run_mlp(monkeypatch, ring_log, False,
                                        n_disp=n_disp, dropout=True)
        assert l_on == l_off
        np.testing.assert_array_equal(p_on, p_off)

    @pytest.mark.parametrize("n_disp", [1, 2])
    def test_distri(self, monkeypatch, ring_log, n_disp):
        l_on, p_on, _ = self._run_mlp(monkeypatch, ring_log, True,
                                      n_disp=n_disp, distri=True,
                                      dropout=True)
        l_off, p_off, _ = self._run_mlp(monkeypatch, ring_log, False,
                                        n_disp=n_disp, distri=True,
                                        dropout=True)
        assert l_on == l_off
        np.testing.assert_array_equal(p_on, p_off)

    def _run_image(self, monkeypatch, prefetch_on, steps=7):
        """RNG-bearing pipeline: random crop + flip draw from the seed
        stream per record — the draws must come off the producer thread
        in the exact serial order (16 images / batch 8 = 2 steps per
        epoch, so 7 steps cross three epoch shuffles)."""
        monkeypatch.setenv(pf.ENV_PREFETCH, "1" if prefetch_on else "0")
        obs_events.configure(None)

        def make(_):
            ds = (DataSet.array(_grey_images())
                  >> ImgRdmCropper(6, 6) >> HFlip() >> ImgToBatch(8))
            model = nn.Sequential(nn.Reshape([36]), nn.Linear(36, 3),
                                  nn.LogSoftMax())
            opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
            opt.set_state(T(learningRate=0.1))
            return opt

        opt = _train(make, steps)
        return _losses(obs_events.get()), _params_vec(opt.model)

    def test_rng_bearing_image_pipeline(self, monkeypatch, ring_log):
        l_on, p_on = self._run_image(monkeypatch, True)
        l_off, p_off = self._run_image(monkeypatch, False)
        assert len(l_on) == 7
        assert l_on == l_off
        np.testing.assert_array_equal(p_on, p_off)

    def test_rng_state_after_run_matches_serial(self, monkeypatch,
                                                ring_log):
        """close() must leave the process stream where a serial run
        would: the ahead-draws of merely-prefetched batches are erased,
        so back-to-back optimize() calls stay on the serial trajectory
        (the parity runs above call optimize once per process state)."""
        def end_state(prefetch_on):
            self._run_image(monkeypatch, prefetch_on, steps=5)
            snap = RNG.snapshot()
            return snap["key_counter"], np.asarray(snap["np_state"][1]), \
                snap["np_state"][2]

        kc_on, key_on, pos_on = end_state(True)
        kc_off, key_off, pos_off = end_state(False)
        assert kc_on == kc_off
        assert pos_on == pos_off
        np.testing.assert_array_equal(key_on, key_off)


# ---------------------------------------------------------------------------
# 2. cadenced host sync: no per-step device→host sync, one jit dispatch
# ---------------------------------------------------------------------------

class TestCadencedSync:
    def _opt(self, cadence=None):
        ds = DataSet.array(_samples(n=64)) >> SampleToBatch(8)
        opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion())
        opt.set_state(T(learningRate=0.2))
        if cadence is not None:
            opt.set_taps(enabled=True, cadence=cadence)
        return opt

    def test_sync_only_at_cadence_boundaries(self, ring_log):
        """The sync-count probe: the window's audit trail shows host
        materializations at cadence boundaries and run end, nowhere else
        (64-sample epoch = 8 steps, so no epoch flush inside 7 steps)."""
        set_seed(5)
        opt = self._opt(cadence=3)
        opt.set_end_when(max_iteration(7))
        opt.optimize()
        assert list(opt._window.flush_steps) == [3, 6, 7]
        assert list(opt._window.flush_reasons) == ["cadence", "cadence",
                                                  "run-end"]
        # the taps monitor synced at the same boundaries (one host-wait
        # covers both), and every step still produced its event
        assert list(opt._taps_monitor.materialized_steps) == [3, 6, 7]
        assert len(_step_events(obs_events.get())) == 7

    def test_sync_every_step_escape_hatch(self, monkeypatch, ring_log):
        monkeypatch.setenv(pf.ENV_SYNC_EVERY_STEP, "1")
        set_seed(5)
        opt = self._opt(cadence=10)
        opt.set_end_when(max_iteration(4))
        opt.optimize()
        assert list(opt._window.flush_steps) == [1, 2, 3, 4]

    def test_cadenced_losses_match_every_step_sync(self, monkeypatch,
                                                   ring_log):
        def run(sync_env):
            monkeypatch.setenv(pf.ENV_SYNC_EVERY_STEP, sync_env)
            obs_events.configure(None)
            set_seed(5)
            opt = self._opt(cadence=4)
            opt.set_end_when(max_iteration(9))
            opt.optimize()
            return _losses(obs_events.get()), _params_vec(opt.model)

        l_cad, p_cad = run("0")
        l_sync, p_sync = run("1")
        assert len(l_cad) == 9
        assert l_cad == l_sync
        np.testing.assert_array_equal(p_cad, p_sync)

    def test_trigger_and_epoch_boundaries_force_flush(self, ring_log,
                                                      tmp_path):
        set_seed(5)
        ds = DataSet.array(_samples(n=24)) >> SampleToBatch(8)
        opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion())
        opt.set_state(T(learningRate=0.2))
        opt.set_taps(enabled=True, cadence=100)   # cadence never fires
        opt.set_checkpoint(str(tmp_path), several_iteration(5))
        opt.set_end_when(max_iteration(7))
        opt.optimize()
        # 24-sample epoch = 3 steps: epoch flushes at 3 and 6; the
        # checkpoint trigger fires once neval reaches 5 (after step 4 —
        # neval is the NEXT iteration index, the historical semantics)
        # and forces its own flush; run-end covers 7
        assert list(opt._window.flush_steps) == [3, 4, 6, 7]
        assert list(opt._window.flush_reasons) == ["epoch", "trigger",
                                                   "epoch", "run-end"]
        assert os.path.exists(tmp_path / "model.5")

    def test_unwind_flushes_pending_steps(self, ring_log):
        """A crash between cadence boundaries must not lose the already-
        dispatched steps: the unwind flush emits their events (the
        postmortem needs the steps nearest the failure)."""
        def boom(batch):
            boom.n += 1
            if boom.n > 4:
                raise RuntimeError("source died")
            return batch
        boom.n = 0

        set_seed(5)
        ds = (DataSet.array(_samples(n=64)) >> SampleToBatch(8)
              >> FuncTransformer(boom))
        opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion())
        opt.set_state(T(learningRate=0.2))
        opt.set_taps(enabled=True, cadence=100)  # cadence never fires
        opt.set_end_when(max_iteration(50))
        with pytest.raises(RuntimeError, match="source died"):
            opt.optimize()
        assert [e["step"] for e in _step_events(obs_events.get())] == \
            [1, 2, 3, 4]
        assert list(opt._window.flush_reasons) == ["exception"]

    def test_single_jit_dispatch_with_prefetch(self, monkeypatch,
                                               ring_log):
        """The jit-count invariant extended to the prefetch path: the
        whole optimize() run — prefetcher, H2D thread, cadence window —
        builds exactly ONE jitted program."""
        calls = []
        real_jit = jax.jit

        def counting_jit(fn, *a, **kw):
            calls.append(fn)
            return real_jit(fn, *a, **kw)

        monkeypatch.setattr(jax, "jit", counting_jit)
        set_seed(5)
        opt = self._opt()
        opt.set_end_when(max_iteration(5))
        opt.optimize()
        assert len(calls) == 1

    def test_queue_depth_in_step_events(self, ring_log):
        set_seed(5)
        opt = self._opt(cadence=2)
        opt.set_end_when(max_iteration(5))
        opt.optimize()
        steps = _step_events(obs_events.get())
        assert steps and all("queue_depth" in e for e in steps)


# ---------------------------------------------------------------------------
# 3. overlap: wall clock strictly below the serial fetch+train sum
# ---------------------------------------------------------------------------

class TestOverlap:
    DELAY = 0.05
    STEPS = 8

    def _run(self, monkeypatch, prefetch_on, steps=None):
        from bigdl_tpu.resilience import faults
        monkeypatch.setenv(pf.ENV_PREFETCH, "1" if prefetch_on else "0")

        def slow(batch):                      # producer-side stall
            time.sleep(self.DELAY)            # per BATCH (after assembly)
            return batch

        set_seed(5)
        ds = (DataSet.array(_samples(n=64)) >> SampleToBatch(8)
              >> FuncTransformer(slow))
        opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion())
        opt.set_state(T(learningRate=0.2))
        opt.set_end_when(max_iteration(steps or self.STEPS))
        # consumer-side work the producer can hide behind: the
        # slow_worker chaos site sleeps at CONSUME time every step
        faults.configure(f"slow_worker@every=1,delay={self.DELAY}")
        t0 = time.perf_counter()
        try:
            opt.optimize()
        finally:
            faults.clear()
        return time.perf_counter() - t0, opt

    def test_stall_injection_overlap(self, monkeypatch, ring_log):
        # warm the persistent XLA cache so both timed runs pay the same
        # (small) compile cost — the sleeps dominate, not the compiler
        self._run(monkeypatch, False, steps=2)
        wall_on, opt_on = self._run(monkeypatch, True)
        wall_off, _ = self._run(monkeypatch, False)
        # serial pays DELAY (producer) + DELAY (consumer) per step; the
        # pipeline hides the producer sleep behind the consumer's work,
        # so at least ~STEPS*DELAY of wall must disappear
        assert wall_on < wall_off - 0.15, (wall_on, wall_off)
        assert wall_on < 0.85 * wall_off, (wall_on, wall_off)
        # the spans tell the same story from the prefetch run alone: the
        # producer paid the transform wall (data-load/fetch), the
        # consumer's data-load wait stayed a fraction of it
        fetch_total, fetch_n = opt_on.metrics.get("span: data-load/fetch")
        wait_total, _ = opt_on.metrics.get("span: data-load")
        assert fetch_n >= self.STEPS
        assert wait_total < 0.6 * fetch_total, (wait_total, fetch_total)
        # wall < this same run's serial fetch+train sum (the components
        # it would have paid back-to-back without overlap)
        disp_total, _ = opt_on.metrics.get("span: dispatch")
        hw_total, _ = opt_on.metrics.get("span: host-wait")
        chaos_total = self.STEPS * self.DELAY
        assert wall_on < fetch_total + disp_total + hw_total \
            + chaos_total, (wall_on, fetch_total, disp_total, hw_total)

    def test_stall_events_emitted(self, monkeypatch, ring_log):
        """A producer slower than the consumer must surface as
        prefetch_stall events keyed by the waiting step."""
        def slow(batch):
            time.sleep(0.1)
            return batch

        monkeypatch.setenv(pf.ENV_PREFETCH, "1")
        set_seed(5)
        ds = (DataSet.array(_samples(n=64)) >> SampleToBatch(8)
              >> FuncTransformer(slow))
        opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion())
        opt.set_state(T(learningRate=0.2))
        opt.set_end_when(max_iteration(6))
        opt.optimize()
        stalls = [e for e in obs_events.get().ring_events()
                  if e["type"] == "prefetch_stall"]
        assert stalls
        assert all(e["seconds"] > 0 and e["step"] >= 1 for e in stalls)


# ---------------------------------------------------------------------------
# 4. chaos keyed by consuming step + checkpoint/resume
# ---------------------------------------------------------------------------

class TestChaosAndResume:
    def test_fault_keyed_by_consuming_step(self, ring_log):
        """nan_grad@at=3 must poison the batch CONSUMED at iteration 3,
        not the batch fetched third — with prefetch on, those differ by
        the queue depth.  The taps ledger pins it."""
        from bigdl_tpu.resilience import faults
        faults.configure("nan_grad@at=3")
        try:
            set_seed(5)
            ds = DataSet.array(_samples(n=64)) >> SampleToBatch(8)
            opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion())
            opt.set_state(T(learningRate=0.2))
            opt.set_taps(enabled=True, cadence=1)
            opt.set_nonfinite_policy(0)
            opt.set_end_when(max_iteration(5))
            opt.optimize()
        finally:
            faults.clear()
        hist = dict(opt._taps_monitor.history)
        assert hist[3]["nonfinite_grads"] > 0
        assert hist[3]["update_ratio"] == 0.0
        assert hist[2]["nonfinite_grads"] == 0.0
        assert hist[4]["nonfinite_grads"] == 0.0
        ev = obs_events.get().ring_events()
        assert any(e["type"] == "fault" and e["site"] == "nan_grad"
                   and e["step"] == 3 for e in ev)

    def test_resume_replays_serial_trajectory(self, tmp_path, ring_log):
        """The checkpoint RNG payload is pinned to the last CONSUMED
        batch (not the prefetch head): resuming replays the exact
        uninterrupted trajectory — crop/flip draws and dropout keys
        included.  Scenario shape follows the resilience resume test:
        the pipeline decodes fresh records from bytes each epoch, all
        records are identical (the dataset's shuffled list order is not
        part of a checkpoint), and batch == dataset so every checkpoint
        lands on an epoch boundary (a mid-epoch permutation is not
        replayable, with or without prefetch)."""
        from bigdl_tpu.dataset import ByteRecord
        from bigdl_tpu.dataset.image import BytesToGreyImg, ImgNormalizer
        raw = np.random.RandomState(2).randint(
            0, 255, 64, dtype=np.uint8).tobytes()
        records = [ByteRecord(raw, 1.0) for _ in range(16)]

        def make_ds():
            return (DataSet.array(list(records)) >> BytesToGreyImg(8, 8)
                    >> ImgNormalizer(128.0, 128.0)
                    >> ImgRdmCropper(6, 6) >> HFlip() >> ImgToBatch(16))

        def build(seed):
            set_seed(seed)
            model = nn.Sequential(nn.Reshape([36]), nn.Dropout(0.5),
                                  nn.Linear(36, 3), nn.LogSoftMax())
            opt = LocalOptimizer(model, make_ds(), nn.ClassNLLCriterion())
            opt.set_state(T(learningRate=0.05))
            return opt

        opt_a = build(7)
        opt_a.set_checkpoint(str(tmp_path), several_iteration(2))
        opt_a.set_end_when(max_iteration(5))
        opt_a.optimize()
        assert opt_a.state["loss"] > 0    # gradients stayed live
        final = _params_vec(opt_a.model)

        from bigdl_tpu.optim import load_latest_checkpoint
        # corrupt the newer snapshots (several_iteration(2) fired at
        # neval 2, 4 and 6) so resume falls back to neval 2 — mid-run,
        # where the prefetch head had drawn past the consumed batches
        (tmp_path / "model.4").write_bytes(b"rot")
        (tmp_path / "model.6").write_bytes(b"rot")

        def resume(restore_rng):
            set_seed(12345 if restore_rng else 999)
            module, blob, neval = load_latest_checkpoint(
                str(tmp_path), restore_rng=restore_rng)
            assert neval == 2
            opt_b = LocalOptimizer(module, make_ds(),
                                   nn.ClassNLLCriterion())
            opt_b.set_state(blob["state"])
            opt_b.set_optim_state(blob["opt_state"])
            opt_b.set_end_when(max_iteration(5))
            opt_b.optimize()
            return _params_vec(opt_b.model)

        np.testing.assert_array_equal(resume(restore_rng=True), final)
        # negative control: without the rng payload the crops/flips and
        # dropout masks of steps 2-5 differ and the trajectory forks
        assert not np.array_equal(resume(restore_rng=False), final)


# ---------------------------------------------------------------------------
# PipelineRunner / satellite units
# ---------------------------------------------------------------------------

class TestPipelineRunner:
    def test_matches_serial_iterator(self):
        # no epoch_size: compares against the RAW looped iterator (the
        # rollover-shuffle parity is covered by the trajectory tests)
        ds = DataSet.array(_samples(n=32)) >> SampleToBatch(8)
        set_seed(11)
        serial = [np.array(b.data) for b, _ in
                  zip(ds.data(train=True), range(6))]
        set_seed(11)
        runner = pf.PipelineRunner(ds, train=True)
        got = [np.array(runner.get()[0].x) for _ in range(6)]
        runner.close()
        for a, b in zip(serial, got):
            np.testing.assert_array_equal(a, b)

    def test_close_restores_consumed_rng_state(self):
        def make_ds():
            # fresh images per pass: the croppers mutate records in
            # place, and a reused (already-cropped) image changes the
            # randint RANGES and with them the words-per-draw
            return (DataSet.array(_grey_images(n=16))
                    >> ImgRdmCropper(6, 6) >> HFlip() >> ImgToBatch(8))

        set_seed(13)
        it = make_ds().data(train=True)
        for _ in range(3):      # exactly 3 batches (zip would pull a 4th)
            next(it)
        serial_state = RNG.snapshot()["np_state"]
        set_seed(13)
        runner = pf.PipelineRunner(make_ds(), train=True,
                                   epoch_size=10 ** 9)
        for _ in range(3):
            runner.get()
        runner.close()          # producer drew ahead; close rewinds
        got_state = RNG.snapshot()["np_state"]
        np.testing.assert_array_equal(np.asarray(serial_state[1]),
                                      np.asarray(got_state[1]))
        assert serial_state[2] == got_state[2]
        assert RNG.seed_stream_owner() is not None

    def test_producer_error_propagates(self):
        def boom(sample):
            raise RuntimeError("decode failed")

        ds = DataSet.array(_samples()) >> FuncTransformer(boom) \
            >> SampleToBatch(8)
        runner = pf.PipelineRunner(ds, train=True, epoch_size=24)
        with pytest.raises(RuntimeError, match="decode failed"):
            runner.get()
        runner.close()

    def test_worker_fanout_preserves_order_and_trajectory(self):
        """Pure per-record stages fan out across workers; the record
        order and the stochastic stages' draw sequence are unchanged."""
        from bigdl_tpu.dataset.image import ImgNormalizer

        def run(n_workers):
            ds = (DataSet.array(_grey_images(n=16))
                  >> ImgNormalizer(0.5, 2.0)      # pure: fans out
                  >> ImgRdmCropper(6, 6) >> HFlip()   # stochastic: stays
                  >> ImgToBatch(8))
            set_seed(17)
            runner = pf.PipelineRunner(ds, train=True, epoch_size=16,
                                       n_workers=n_workers)
            out = [np.array(runner.get()[0].x) for _ in range(5)]
            runner.close()
            return out

        fanout = run(4)
        serial = run(0)
        for a, b in zip(serial, fanout):
            np.testing.assert_array_equal(a, b)

    def test_eval_background_prefetch_one_pass(self):
        ds = DataSet.array(_samples(n=20)) >> SampleToBatch(8)
        serial = [np.array(b.data) for b in ds.data(train=False)]
        got = [np.array(b.data) for b in
               pf.background(ds.data(train=False), 2)]
        assert len(got) == len(serial) == 3   # 8 + 8 + 4 tail
        for a, b in zip(serial, got):
            np.testing.assert_array_equal(a, b)

    def test_validate_results_match_serial(self, monkeypatch):
        ds = DataSet.array(_samples(n=40)) >> SampleToBatch(8)
        set_seed(3)
        model = _mlp()

        def run(on):
            monkeypatch.setenv(pf.ENV_PREFETCH, "1" if on else "0")
            res = validate(model, model.params(), model.state(), ds,
                           [Top1Accuracy()])
            return res[0][1]

        assert run(True) == run(False)


class TestSatellites:
    def test_stack_chunk_converts_once_and_checks_shapes(self):
        from bigdl_tpu.dataset.sample import MiniBatch
        a = MiniBatch(np.ones((4, 3), np.float32), np.ones((4,)))
        b = MiniBatch(np.zeros((4, 3), np.float32), np.zeros((4,)))
        xs, ys = pf.stack_chunk([a, b])
        assert xs.shape == (2, 4, 3) and ys.shape == (2, 4)
        bad = MiniBatch(np.ones((5, 3), np.float32), np.ones((5,)))
        with pytest.raises(ValueError, match="uniform batch shapes"):
            pf.stack_chunk([a, bad])

    def test_eval_iteration_is_snapshot_free(self):
        from bigdl_tpu.dataset.dataset import (LocalArrayDataSet,
                                               ShardedDataSet)
        for cls in (LocalArrayDataSet,
                    lambda d: ShardedDataSet(d, n_shards=1, shard_index=0)):
            ds = cls(list(range(10)))
            assert list(ds.data(train=False)) == list(range(10))
            # the view is lazy: a shuffle between passes is visible to
            # the NEXT iterator without any per-call list copy
            it = ds.data(train=False)
            assert not isinstance(it, list)
            set_seed(4)
            ds.shuffle()
            assert sorted(ds.data(train=False)) == list(range(10))

    def test_sampletobatch_reuse_buffers_ring(self):
        samples = _samples(n=32)
        plain = list(SampleToBatch(8)(iter(samples)))
        ring = SampleToBatch(8, reuse_buffers=2)
        reused = []
        ids = []
        for b in ring(iter(samples)):
            reused.append(np.array(b.data))    # copy before reuse
            ids.append(id(b.data))
        assert len(reused) == 4
        for a, b in zip(plain, reused):
            np.testing.assert_array_equal(a.data, b)
        # the ring really recycles: slot 0 backs batches 0 and 2
        assert ids[0] == ids[2] and ids[1] == ids[3]
        assert ids[0] != ids[1]

    def test_sampletobatch_reuse_tail_falls_back(self):
        samples = _samples(n=20)               # 8 + 8 + 4 tail
        ring = SampleToBatch(8, reuse_buffers=2)
        batches = list(ring(iter(samples)))
        assert [b.data.shape[0] for b in batches] == [8, 8, 4]
        with pytest.raises(ValueError, match="ring of >= 2"):
            SampleToBatch(8, reuse_buffers=1)

    def test_transformer_purity_attrs(self):
        from bigdl_tpu.dataset.image import (BytesToImg, ColorJitter,
                                             ImgCropper, ImgNormalizer,
                                             Lighting)
        assert BytesToImg().pure_per_record
        assert ImgNormalizer(0.0, 1.0).pure_per_record
        assert not ImgNormalizer(0.0, 1.0).stochastic
        for t in (HFlip(), ColorJitter(), Lighting(),
                  ImgRdmCropper(2, 2), ImgCropper(2, 2, "random")):
            assert t.stochastic, type(t).__name__
        assert ImgCropper(2, 2, "center").pure_per_record
